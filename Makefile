# Developer entry points. The test suite needs no hardware (virtual CPU
# mesh via tests/conftest.py); bench probes the pinned device and falls
# back to a labeled CPU measurement when it is unreachable.

.PHONY: fast test evidence bench dryrun cache-smoke pipeline-smoke resilience-smoke hetero-smoke obs-smoke race-smoke spmd-smoke serve-smoke fleet-smoke bem-smoke lint lint-budgets

fast:            ## fast test tier (< 8 min on one core)
	python -m pytest tests/ -q -m "not slow"

lint:            ## graftlint: static rules vs baseline + trace audit + compiled-artifact budget gate
	python -m raft_tpu.lint --audit

lint-budgets:    ## refresh lint/budgets.json after an INTENTIONAL compiled-artifact change
	python -m raft_tpu.lint --write-budgets   # review the diff like code

cache-smoke:     ## warm-start proof: tiny sweep twice in fresh processes,
	python -m raft_tpu.cache smoke   # 2nd run's compile must be < 50% of 1st

pipeline-smoke:  ## fused-kernel + dispatch-ahead + donation proof (CPU, < 60 s)
	python -c "from raft_tpu.parallel.pipeline import _smoke; raise SystemExit(_smoke())"

resilience-smoke:  ## kill/resume + NaN-quarantine + ladder-salvage proof (CPU, < 60 s)
	python -m raft_tpu.resilience

hetero-smoke:    ## shape-bucket proof: mixed OC3+VolturnUS+OC4 stream compiles
	python -m raft_tpu.build.smoke   # once per BUCKET (< designs), cross-process

obs-smoke:       ## observability proof: RAFT_TPU_OBS-armed sweep emits valid
	python -m raft_tpu.obs           # JSONL + Chrome trace + p50/p99, bounded overhead

race-smoke:      ## deterministic N-thread race proof: single-flight AOT compile,
	python -m raft_tpu.lint.race     # exact metric/ckpt/fault counters (< 60 s CPU)

spmd-smoke:      ## deterministic 2-process SPMD proof: design axis sharded over a
	python -m raft_tpu.parallel.spmd_smoke   # global mesh == unsharded oracle; one shared cache root, per-process-salted exports, no torn files (< 90 s CPU)

serve-smoke:     ## resident-daemon proof: compiles == buckets, solo parity, warm
	python -m raft_tpu.serve smoke   # restart 0 compiles; armed obs leg: request traces/SLO/flight/ledger

fleet-smoke:     ## fault-tolerant fleet proof: 2 replicas, kill_replica:1 mid-stream,
	python -m raft_tpu.serve fleet-smoke   # zero lost/dup + bit-identical rows, warm zero-compile restart, deterministic typed shed + recover

bem-smoke:       ## on-device BEM proof: novel geometry solves with g++ POISONED
	python -m raft_tpu.hydro.bem_smoke   # (no host solver), oracle parity, warm/novel zero compiles; pallas-interpret leg: cross-route parity, zero compiles warm

test:            ## full suite (nightly tier, ~35 min on one core)
	python -m pytest tests/ -q

dryrun:          ## 8-device multi-chip dry run (the driver's check)
	python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

bench:           ## benchmark; prints one JSON line
	python bench.py

evidence:        ## fast tier + lint + dryrun + bench -> EVIDENCE.json
	python -m raft_tpu.evidence
