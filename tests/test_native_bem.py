"""Native C++ BEM solver tests.

Oracles:
  * exact single-layer identities on a deep sphere (added mass 0.5 rho V,
    zero damping far from the free surface);
  * mpmath evaluation of the dimensionless PV wave integral I0;
  * the published HAMS outputs for the 1008-panel cylinder example on the
    identical mesh — fixtures vendored in tests/data/cylinder (HullMesh.pnl
    + WAMIT-format Buoy.1/.3, the upstream HAMS verification case shipped
    by the reference at raft/data/cylinder), so the golden regression runs
    everywhere, CI included.
"""
import os

import numpy as np
import pytest

from raft_tpu.hydro.native_bem import solve_bem, wave_integral

DATA = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "data", "cylinder")


def sphere_mesh(a=1.0, zc=-10.0, nth=20, naz=40):
    th = np.linspace(0, np.pi, nth + 1)
    pans = []
    for i in range(nth):
        for j in range(naz):
            p0, p1 = th[i], th[i + 1]
            a0, a1 = 2 * np.pi * j / naz, 2 * np.pi * (j + 1) / naz
            pt = lambda pp, aa: [
                a * np.sin(pp) * np.cos(aa),
                a * np.sin(pp) * np.sin(aa),
                zc + a * np.cos(pp),
            ]
            pans.append([pt(p0, a0), pt(p1, a0), pt(p1, a1), pt(p0, a1)])
    return np.asarray(pans)


def test_wave_integral_against_quadrature():
    # table vs the independent pole-subtracted quadrature path
    for X, Y in [(0.5, -0.5), (5.0, -0.1), (10.0, -2.0), (2.0, -20.0)]:
        t0, t1 = wave_integral(X, Y)
        d0, d1 = wave_integral(X, Y, direct=True)
        assert t0 == pytest.approx(d0, rel=2e-3, abs=2e-4)
        assert t1 == pytest.approx(d1, rel=2e-3, abs=2e-4)


def test_deep_sphere_added_mass():
    p = sphere_mesh()
    A, B, F = solve_bem(p, np.array([1.0]), rho=1000.0, g=9.81, cache=False)
    rhoV = 1000.0 * 4.0 / 3.0 * np.pi
    for d in range(3):
        assert A[d, d, 0] == pytest.approx(0.5 * rhoV, rel=0.05)
    # far from the surface: no radiated waves, no excitation to speak of
    assert abs(B[2, 2, 0]) < 0.01 * A[2, 2, 0]
    # symmetry of the radiation matrix
    assert A[0, 4, 0] == pytest.approx(A[4, 0, 0], abs=0.02 * rhoV)


def test_result_cache_atomic_and_corruption_tolerant(tmp_path,
                                                     monkeypatch):
    """The panel-solver result cache is published atomically and a
    truncated/garbage artifact is a MISS (deleted, recomputed) — it used
    to be a direct np.savez whose torn file crashed every later run with
    the same geometry (GL202)."""
    from raft_tpu.cache import config

    monkeypatch.setenv("RAFT_TPU_CACHE_DIR", str(tmp_path))
    config.disable()                       # force env re-resolution
    p = sphere_mesh(nth=6, naz=10)         # tiny: sub-second solve
    w = np.array([1.0])
    A1, B1, F1 = solve_bem(p, w, rho=1000.0, g=9.81, cache=True)
    bem_dir = os.path.join(str(tmp_path), "bem")
    (art,) = os.listdir(bem_dir)
    path = os.path.join(bem_dir, art)
    assert not art.endswith(".tmp")        # atomic publish left no tmp
    # served from cache: bit-identical
    A2, _, _ = solve_bem(p, w, rho=1000.0, g=9.81, cache=True)
    np.testing.assert_array_equal(A1, A2)
    # corrupt it: recompute (never crash, never serve garbage), re-publish
    with open(path, "wb") as f:
        f.write(b"\x00not-an-npz")
    A3, _, _ = solve_bem(p, w, rho=1000.0, g=9.81, cache=True)
    np.testing.assert_allclose(A3, A1, rtol=1e-12)
    with np.load(path) as z:               # rewritten artifact is whole
        np.testing.assert_allclose(z["A"], A1, rtol=1e-12)


@pytest.mark.slow
def test_model_with_native_bem_runs():
    from raft_tpu.model import Model, load_design

    m = Model(load_design("raft_tpu/designs/OC3spar.yaml"), BEM="native",
              w=np.arange(0.1, 2.0, 0.1))
    m.setEnv(Hs=8.0, Tp=12.0, V=10.0, Fthrust=800e3)
    m.calcSystemProps()
    assert m.bem is not None
    A, B, F = m.bem
    assert A.shape == (6, 6, 19)
    # spar surge added mass from potential flow: order rho*V
    assert 0.2e7 < A[0, 0, 0] < 2e7
    # radiation damping nonnegative-ish diagonals at all freqs
    assert (np.asarray(B[2, 2, :]) > -1e3).all()
    m.solveEigen()
    m.calcMooringAndOffsets()
    m.solveDynamics()
    resp = m.results["response"]
    assert resp["converged"]
    assert np.isfinite(resp["std dev"]).all()
    # surge/pitch modes still in the published ballpark with BEM added mass
    fns = m.results["eigen"]["frequencies"]
    assert 0.004 < fns[0] < 0.015
    assert 0.02 < fns[2] < 0.04


@pytest.mark.slow
def test_cylinder_matches_hams():
    from raft_tpu.hydro.bem_io import read_wamit1, read_wamit3
    from raft_tpu.hydro.mesh import read_pnl

    panels = read_pnl(os.path.join(DATA, "HullMesh.pnl"))
    w_h, A_h, B_h = read_wamit1(os.path.join(DATA, "Buoy.1"))
    _, _, mod, _, _, _ = read_wamit3(os.path.join(DATA, "Buoy.3"))
    rho, g = 1000.0, 9.80665
    wsel = np.array([0.2, 2.0, 4.0])
    A, B, F = solve_bem(panels, wsel, rho=rho, g=g, cache=False)
    for i, wv in enumerate(wsel):
        ih = int(np.argmin(np.abs(w_h - wv)))
        assert A[0, 0, i] == pytest.approx(rho * A_h[0, 0, ih], rel=0.04)
        assert A[2, 2, i] == pytest.approx(rho * A_h[2, 2, ih], rel=0.04)
        assert A[4, 4, i] == pytest.approx(rho * A_h[4, 4, ih], rel=0.04)
        assert B[2, 2, i] == pytest.approx(rho * wv * B_h[2, 2, ih], rel=0.05, abs=0.02)
        assert abs(F[0, i]) == pytest.approx(rho * g * mod[0, ih], rel=0.04)
        assert abs(F[2, i]) == pytest.approx(rho * g * mod[2, ih], rel=0.04)
