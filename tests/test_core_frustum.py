"""Frustum kernels vs independent closed-form oracles.

Oracles below are the standard closed forms for frustum volume/centroid and
for solid cylinder / tapered frustum moments of inertia (the same physics the
reference encodes at raft/raft.py:251-332, 873-900), written independently.
"""
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import frustum


def vcv(dA, dB, H, circ=True):
    dA2 = jnp.asarray([dA, dA] if np.isscalar(dA) else dA, dtype=float)
    dB2 = jnp.asarray([dB, dB] if np.isscalar(dB) else dB, dtype=float)
    V, hc = frustum.frustum_vcv(dA2, dB2, jnp.asarray(float(H)), jnp.asarray(circ))
    return float(V), float(hc)


def moi(dA, dB, H, rho, circ=True):
    dA2 = jnp.asarray([dA, dA] if np.isscalar(dA) else dA, dtype=float)
    dB2 = jnp.asarray([dB, dB] if np.isscalar(dB) else dB, dtype=float)
    out = frustum.frustum_moi(dA2, dB2, jnp.asarray(float(H)), jnp.asarray(rho), jnp.asarray(circ))
    return tuple(float(v) for v in out)


def test_cylinder_volume_centroid():
    V, hc = vcv(2.0, 2.0, 10.0)
    np.testing.assert_allclose(V, np.pi * 10.0, rtol=1e-12)
    np.testing.assert_allclose(hc, 5.0, rtol=1e-12)


def test_cone_volume_centroid():
    # full cone tapering to zero: V = (1/3) A H, centroid at H/4 from base
    V, hc = vcv(4.0, 0.0, 9.0)
    np.testing.assert_allclose(V, np.pi / 4 * 16 * 9 / 3, rtol=1e-12)
    np.testing.assert_allclose(hc, 9.0 / 4, rtol=1e-12)


def test_frustum_volume_formula():
    # conical frustum closed form: V = pi H/12 (dA^2 + dA dB + dB^2)
    dA, dB, H = 9.4, 6.5, 8.0
    V, hc = vcv(dA, dB, H)
    np.testing.assert_allclose(V, np.pi * H / 12 * (dA**2 + dA * dB + dB**2), rtol=1e-12)
    # centroid (pyramidal frustum): hc = H/4 (A1 + 2 Am + 3 A2)/(A1+Am+A2) with Am=pi/4 dA dB
    A1, A2, Am = np.pi / 4 * dA**2, np.pi / 4 * dB**2, np.pi / 4 * dA * dB
    np.testing.assert_allclose(hc, H / 4 * (A1 + 2 * Am + 3 * A2) / (A1 + Am + A2), rtol=1e-12)


def test_box_volume_centroid():
    V, hc = vcv([2.0, 3.0], [2.0, 3.0], 5.0, circ=False)
    np.testing.assert_allclose(V, 30.0, rtol=1e-12)
    np.testing.assert_allclose(hc, 2.5, rtol=1e-12)


def test_rect_proportional_taper_matches_pyramid_formula():
    # proportional taper: geometric-mean mid-area form is exact -> must agree
    slA, slB, H = [4.0, 2.0], [2.0, 1.0], 6.0
    V, hc = vcv(slA, slB, H, circ=False)
    A1, A2 = 8.0, 2.0
    Am = np.sqrt(A1 * A2)
    np.testing.assert_allclose(V, (A1 + A2 + Am) * H / 3, rtol=1e-12)
    np.testing.assert_allclose(hc, H / 4 * (A1 + 2 * Am + 3 * A2) / (A1 + Am + A2), rtol=1e-12)


def test_rect_general_taper_exact_integral():
    # non-proportional taper: check against numerical integration
    La, Wa, Lb, Wb, H = 4.0, 2.0, 3.0, 2.5, 7.0
    xi = np.linspace(0, 1, 200001)
    L = La + (Lb - La) * xi
    W = Wa + (Wb - Wa) * xi
    A = L * W
    V_num = H * np.trapezoid(A, xi)
    hc_num = H * H * np.trapezoid(A * xi, xi) / V_num
    V, hc = vcv([La, Wa], [Lb, Wb], H, circ=False)
    np.testing.assert_allclose(V, V_num, rtol=1e-8)
    np.testing.assert_allclose(hc, hc_num, rtol=1e-8)


def test_zero_height_and_zero_size():
    V, hc = vcv(3.0, 3.0, 0.0)
    assert V == 0.0 and hc == 0.0
    I = moi(0.0, 0.0, 5.0, 8500.0)
    assert all(v == 0.0 for v in I)


def test_cylinder_moi_closed_form():
    d, H, rho = 3.0, 12.0, 8500.0
    r = d / 2
    Ixx, Iyy, Izz = moi(d, d, H, rho)
    m = rho * np.pi * r**2 * H
    # about end node: I = m r^2/4 + m H^2/3 ; axial: m r^2 / 2
    np.testing.assert_allclose(Ixx, m * r**2 / 4 + m * H**2 / 3, rtol=1e-12)
    np.testing.assert_allclose(Iyy, Ixx, rtol=1e-12)
    np.testing.assert_allclose(Izz, m * r**2 / 2, rtol=1e-12)


def test_tapered_moi_closed_form():
    # reference closed forms (raft/raft.py:266-267):
    # I_rad_end = (1/20) p pi H (r2^5 - r1^5)/(r2-r1) + (1/30) p pi H^3 (r1^2 + 3 r1 r2 + 6 r2^2)
    # I_ax      = (1/10) p pi H (r2^5 - r1^5)/(r2-r1)
    dA, dB, H, rho = 9.4, 6.5, 8.0, 1860.0
    r1, r2 = dA / 2, dB / 2
    Ixx, Iyy, Izz = moi(dA, dB, H, rho)
    I_rad = (1 / 20) * rho * np.pi * H * (r2**5 - r1**5) / (r2 - r1) + (
        1 / 30
    ) * rho * np.pi * H**3 * (r1**2 + 3 * r1 * r2 + 6 * r2**2)
    I_ax = (1 / 10) * rho * np.pi * H * (r2**5 - r1**5) / (r2 - r1)
    np.testing.assert_allclose(Ixx, I_rad, rtol=1e-12)
    np.testing.assert_allclose(Izz, I_ax, rtol=1e-12)


def test_box_moi_closed_form():
    # cuboid about end node (reference raft/raft.py:289-291):
    # Ixx = (1/12) M (W^2 + 4 H^2), Iyy = (1/12) M (L^2 + 4 H^2), Izz = (1/12) M (L^2+W^2)
    L, W, H, rho = 4.0, 2.0, 6.0, 1025.0
    M = rho * L * W * H
    Ixx, Iyy, Izz = moi([L, W], [L, W], H, rho, circ=False)
    np.testing.assert_allclose(Ixx, M * (W**2 + 4 * H**2) / 12, rtol=1e-12)
    np.testing.assert_allclose(Iyy, M * (L**2 + 4 * H**2) / 12, rtol=1e-12)
    np.testing.assert_allclose(Izz, M * (L**2 + W**2) / 12, rtol=1e-12)


def test_batched_shapes():
    dA = jnp.ones((7, 2)) * 3.0
    dB = jnp.ones((7, 2)) * 2.0
    H = jnp.linspace(1.0, 7.0, 7)
    circ = jnp.ones(7, dtype=bool)
    V, hc = frustum.frustum_vcv(dA, dB, H, circ)
    assert V.shape == (7,) and hc.shape == (7,)
    I = frustum.frustum_moi(dA, dB, H, jnp.asarray(1000.0), circ)
    assert all(v.shape == (7,) for v in I)
