"""Float32 numerics pin for the benched device path.

bench.py runs the real TPU chip at default float32, while every other test
here runs CPU float64 (tests/conftest.py).  These tests pin the float32
response of the two benched workloads (OC3 strip, VolturnUS-S + staged BEM)
against the float64 oracle across the full 200-bin grid *including the
resonance bins*, and assert the while-loop driver converges at float32 —
so the benched number is tested physics, not just throughput.

Error metric: complex response difference normalized by the dominant
amplitude of the unit group (translations 0-2 [m], rotations 3-5 [rad]).
Per-DOF self-relative error is meaningless for the symmetry-suppressed DOFs
(sway/roll/yaw under beta=0 on a symmetric platform), whose amplitudes are
pure cancellation noise at any precision.

Measured float32 errors on this host (CPU, same code path as TPU):
OC3 ~5e-6, VolturnUS+BEM excited DOFs ~3e-6; pins carry ~30x margin.
"""
import numpy as np
import pytest
import jax


def _flagship_oc3(x64: bool, nw: int = 200):
    jax.config.update("jax_enable_x64", x64)
    try:
        import jax.numpy as jnp

        import __graft_entry__ as ge
        from raft_tpu.mooring import mooring_stiffness, parse_mooring
        from raft_tpu.parallel import forward_response

        design, members, rna, env, wave = ge._base(nw=nw)
        moor = parse_mooring(
            design["mooring"], yaw_stiffness=design["turbine"]["yaw_stiffness"]
        )
        C_moor = mooring_stiffness(moor, jnp.zeros(6))
        out = forward_response(
            members, rna, env, wave, C_moor, n_iter=40, method="while"
        )
        Xi = np.asarray(out.Xi.re) + 1j * np.asarray(out.Xi.im)
        return Xi, bool(out.converged), int(out.n_iter)
    finally:
        jax.config.update("jax_enable_x64", True)


def _flagship_volturn(x64: bool):
    jax.config.update("jax_enable_x64", x64)
    try:
        import bench
        from raft_tpu.parallel import forward_response

        _, members, rna, env, wave, C_moor, bem = bench._volturn_setup(nw=200)
        out = forward_response(
            members, rna, env, wave, C_moor, bem=bem, n_iter=40, method="while"
        )
        Xi = np.asarray(out.Xi.re) + 1j * np.asarray(out.Xi.im)
        return Xi, bool(out.converged), int(out.n_iter)
    finally:
        jax.config.update("jax_enable_x64", True)


def _pin(Xi32, Xi64, tol_trans, tol_rot):
    amp64 = np.abs(Xi64)
    err = np.abs(Xi32 - Xi64)
    scale_t = amp64[:, :3].max()
    scale_r = amp64[:, 3:].max()
    assert err[:, :3].max() / scale_t < tol_trans, (
        f"translation err {err[:, :3].max() / scale_t:.2e}"
    )
    assert err[:, 3:].max() / scale_r < tol_rot, (
        f"rotation err {err[:, 3:].max() / scale_r:.2e}"
    )


def test_oc3_float32_fast_pin():
    """Per-push (fast tier) guard on the property the benched number
    depends on: the float32 device path matches the float64 oracle.  One
    workload at nw=40 keeps it cheap; the full 200-bin pins on both benched
    workloads stay in the nightly tier below."""
    Xi64, c64, n64 = _flagship_oc3(True, nw=40)
    Xi32, c32, n32 = _flagship_oc3(False, nw=40)
    assert Xi32.dtype == np.complex64 and Xi64.dtype == np.complex128
    assert c32, "float32 while-driver failed to converge"
    assert abs(n32 - n64) <= 2
    _pin(Xi32, Xi64, tol_trans=2e-4, tol_rot=2e-4)


@pytest.mark.slow
def test_oc3_float32_matches_float64_oracle():
    Xi64, c64, n64 = _flagship_oc3(True)
    Xi32, c32, n32 = _flagship_oc3(False)
    assert Xi32.dtype == np.complex64 and Xi64.dtype == np.complex128
    assert c32, "float32 while-driver failed to converge"
    assert abs(n32 - n64) <= 2
    _pin(Xi32, Xi64, tol_trans=2e-4, tol_rot=2e-4)


@pytest.mark.slow
def test_volturn_bem_float32_matches_float64_oracle():
    Xi64, c64, n64 = _flagship_volturn(True)
    Xi32, c32, n32 = _flagship_volturn(False)
    assert c32, "float32 while-driver failed to converge"
    assert abs(n32 - n64) <= 2
    _pin(Xi32, Xi64, tol_trans=2e-4, tol_rot=2e-4)
