"""CLI, WEIS adapter, profiling, and design-variant regression tests."""
import numpy as np
import pytest

from raft_tpu.model import Model, load_design


@pytest.mark.slow
def test_oc4_split_variant_matches_single_member():
    """OC4semi_2 (split-column decomposition) must reproduce OC4semi statics
    to machine precision — same platform, different member decomposition."""
    a = Model(load_design("raft_tpu/designs/OC4semi.yaml"))
    b = Model(load_design("raft_tpu/designs/OC4semi_2.yaml"))
    a.setEnv()
    b.setEnv()
    a.calcSystemProps()
    b.calcSystemProps()
    pa, pb = a.results["properties"], b.results["properties"]
    for key in ("substructure mass", "displacement", "ballast mass", "total mass"):
        assert pa[key] == pytest.approx(pb[key], rel=1e-9)
    np.testing.assert_allclose(pa["substructure CG"], pb["substructure CG"], atol=1e-6)
    np.testing.assert_allclose(pa["C_stiffness"], pb["C_stiffness"], rtol=1e-9, atol=1e-3)


@pytest.mark.slow
def test_cli_json(capsys):
    import json

    from raft_tpu.cli import main

    main(["oc3", "--wmin", "0.2", "--wmax", "1.2", "--dw", "0.2", "--json"])
    out = capsys.readouterr().out
    data = json.loads(out.strip().splitlines()[-1])
    assert "eigen" in data and "response" in data


@pytest.mark.slow
def test_cli_sweep_json(capsys):
    import json

    from raft_tpu.cli import main

    rows = main(["sweep", "oc3", "--param", "draft", "--lo", "0.95",
                 "--hi", "1.05", "-n", "4",
                 "--wmin", "0.2", "--wmax", "1.4", "--dw", "0.2"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["param"] == "draft"
    assert len(out["theta"]) == 4 and len(out["std dev"]) == 4
    sig = np.asarray(rows["std dev"])
    assert np.isfinite(sig).all() and (sig[:, 0] > 0).all()


@pytest.mark.slow
def test_cli_dlc_json(capsys, tmp_path):
    import json

    from raft_tpu.cli import main

    f = tmp_path / "cases.csv"
    # comment lines AND a bare spreadsheet header must be tolerated,
    # including a header that follows a comment
    f.write_text("# DLC set 1\nHs, Tp, beta_deg\n6, 10, 0\n6, 10, 40\n"
                 "8, 12, 40\n")
    res = main(["dlc", "oc3", "--cases", str(f),
                "--wmin", "0.2", "--wmax", "1.4", "--dw", "0.2"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["columns"] == ["Hs", "Tp", "beta_rad"]
    sig = np.asarray(res["std dev"])
    assert sig.shape == (3, 6) and np.isfinite(sig).all()
    # heading moved energy into sway between the two (6, 10) cases
    assert sig[1, 1] > sig[0, 1] + 1e-9
    # bad column mix and a non-numeric BODY row are clean errors
    g = tmp_path / "bad.csv"
    g.write_text("6, 10\n6, 10, 40\n")
    with pytest.raises(SystemExit, match="column"):
        main(["dlc", "oc3", "--cases", str(g),
              "--wmin", "0.2", "--wmax", "1.4", "--dw", "0.2"])
    h = tmp_path / "worse.csv"
    h.write_text("6, 10, 0\nsix, ten, forty\n")
    with pytest.raises(SystemExit, match="non-numeric"):
        main(["dlc", "oc3", "--cases", str(h),
              "--wmin", "0.2", "--wmax", "1.4", "--dw", "0.2"])


def test_interp_heading_f32_roundtrip_endpoint():
    """A grid-endpoint heading that round-tripped through a float32 device
    array (7th-decimal overshoot) is the same physical heading — it must
    interpolate, not raise 'outside staged grid'."""
    from raft_tpu.model import interp_heading_excitation

    betas = np.array([0.0, np.deg2rad(30.0)])
    F_all = np.zeros((2, 6, 4), complex)
    F_all[1] += 1.0
    b32 = float(np.float32(betas[1]))
    assert b32 > betas[1]                  # the overshoot this guards
    F = interp_heading_excitation(betas, F_all, b32)
    np.testing.assert_allclose(F, F_all[1], atol=1e-6)
    with pytest.raises(ValueError, match="outside staged grid"):
        interp_heading_excitation(betas, F_all, betas[1] + 1e-3)


@pytest.mark.slow
def test_cli_dlc_bem_heading_grid(capsys, tmp_path):
    """--bem stages ONE native heading-grid solve; per-case excitation is
    interpolated to each row's heading."""
    import json

    from raft_tpu.cli import main

    f = tmp_path / "cases.csv"
    f.write_text("6, 10, 0\n6, 10, 40\n")
    res = main(["dlc", "oc3", "--cases", str(f), "--bem",
                "--dz-max", "6", "--da-max", "6",
                "--wmin", "0.3", "--wmax", "1.4", "--dw", "0.3"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    sig = np.asarray(res["std dev"])
    assert sig.shape == (2, 6) and np.isfinite(sig).all()
    assert out["columns"] == ["Hs", "Tp", "beta_rad"]
    # identical sea state, different heading -> different response split
    assert abs(sig[0, 0] - sig[1, 0]) > 1e-9 or sig[1, 1] > sig[0, 1]


@pytest.mark.slow
def test_cli_optimize_json(capsys):
    import json

    from raft_tpu.cli import main

    res = main(["optimize", "oc3", "--params", "diameter", "draft",
                "--steps", "2", "--wmin", "0.2", "--wmax", "1.4",
                "--dw", "0.2"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["params"] == ["diameter", "draft"]
    assert len(out["theta"]) == 2
    assert len(res.history if hasattr(res, "history") else res["history"]) == 3
    assert res["history"][-1] <= res["history"][0] + 1e-12


@pytest.mark.slow
def test_print_report(capsys):
    m = Model(load_design("raft_tpu/designs/OC3spar.yaml"),
              w=np.arange(0.2, 1.2, 0.2))
    m.setEnv(Fthrust=800e3)
    m.calcSystemProps()
    m.solveEigen()
    m.print_report()
    out = capsys.readouterr().out
    assert "natural frequencies" in out
    assert "total mass" in out


def test_profiling_phases():
    from raft_tpu.utils import profiling

    profiling.reset()
    m = Model(load_design("raft_tpu/designs/OC3spar.yaml"),
              w=np.arange(0.2, 1.2, 0.2))
    m.setEnv()
    m.calcSystemProps()
    s = profiling.summary()
    assert "statics" in s
    assert "hydro-strip" in s


@pytest.mark.slow
def test_weis_adapter_end_to_end():
    from raft_tpu.io.weis import design_from_weis, member_from_arrays, mooring_from_arrays

    spar = member_from_arrays(
        "spar", [0, 0, -120], [0, 0, 10], [9.4, 9.4, 6.5, 6.5], [0.027],
        stations=[-120, -12, -4, 10], potMod=False, Cd=0.8, Ca=1.0,
        rho_shell=8500, l_fill=[52.0, 0, 0], rho_fill=[1860.0, 0, 0],
    )
    tower = member_from_arrays(
        "tower", [0, 0, 10], [0, 0, 87.6], [6.5, 3.87], [0.027, 0.019],
        mtype=1, Cd=0.0, Ca=0.0,
    )
    ang = np.deg2rad([0, 120, 240])
    moor = mooring_from_arrays(
        320.0,
        np.stack([853.87 * np.cos(ang), 853.87 * np.sin(ang), np.full(3, -320.0)], -1),
        np.stack([5.2 * np.cos(ang), 5.2 * np.sin(ang), np.full(3, -70.0)], -1),
        [902.2] * 3,
        diameter=0.09, mass_density=77.7066, stiffness=384.243e6,
    )
    design = design_from_weis(
        [spar], tower,
        {"mRNA": 350000, "IxRNA": 3.5e7, "IrRNA": 2.6e7, "xCG_RNA": 0,
         "hHub": 90.0, "Fthrust": 800e3, "yaw_stiffness": 9.834e7},
        moor,
    )
    m = Model(design, w=np.arange(0.2, 1.4, 0.2))
    m.setEnv(Fthrust=800e3)
    m.calcSystemProps()
    m.solveEigen()
    m.calcMooringAndOffsets()
    m.solveDynamics()
    assert m.results["response"]["converged"]
    # same spar as the bundled OC3 design: displacement should agree ~2%
    oc3 = Model(load_design("raft_tpu/designs/OC3spar.yaml"))
    oc3.setEnv()
    oc3.calcSystemProps()
    assert m.results["properties"]["displacement"] == pytest.approx(
        oc3.results["properties"]["displacement"], rel=0.02
    )


def test_run_raft_env_file(tmp_path):
    """run_raft honors the environment YAML (the reference accepts the
    argument but never opens it, raft/runRAFT.py:68)."""
    import yaml

    from raft_tpu.model import run_raft

    envf = tmp_path / "env.yaml"
    envf.write_text(yaml.safe_dump({"Hs": 3.0, "Tp": 9.0, "V": 5.0,
                                    "beta": 0.0, "Fthrust": 2e5}))
    w = np.arange(0.1, 2.5, 0.4)
    res = run_raft("raft_tpu/designs/OC3spar.yaml", str(envf), w=w)
    res8 = run_raft("raft_tpu/designs/OC3spar.yaml", w=w)
    # milder sea state + less thrust: smaller offsets and responses
    assert res["means"]["platform offset"][0] < res8["means"]["platform offset"][0]
    assert res["response"]["std dev"][0] < res8["response"]["std dev"][0]
