"""Warm-start subsystem tests (raft_tpu/cache): keying, corruption
tolerance, staging invalidation, off-path identity, cross-process smoke.

The suite-wide conftest pins ``RAFT_TPU_CACHE_DIR=off`` so every other
test runs the plain uncached paths; each test here opts in with an
explicit tmp cache dir (an explicit ``enable(dir)`` argument overrides
the env pin) and restores the disabled state on teardown.  Everything
runs under ``JAX_PLATFORMS=cpu`` — the subsystem is backend-agnostic by
construction (backend/device-kind are key salts, not requirements).
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from raft_tpu import cache
from raft_tpu.cache import aot, config, staging, stats

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def warm(tmp_path):
    """Cache armed at a fresh dir; disabled + reset after the test."""
    root = cache.enable(str(tmp_path / "cache"))
    stats.reset()
    aot.clear_memory()
    yield root
    cache.disable()
    aot.clear_memory()
    stats.reset()


# ------------------------------------------------------------- enablement


def test_resolve_dir_spellings(monkeypatch):
    for off in ("off", "OFF", "0", "none", "disabled", "Disabled", "no"):
        assert config.resolve_dir(off) is None
        monkeypatch.setenv("RAFT_TPU_CACHE_DIR", off)
        assert config.resolve_dir() is None
    # empty env means UNSET (default dir), matching the RAFT_TPU_PALLAS
    # empty-knob convention
    monkeypatch.setenv("RAFT_TPU_CACHE_DIR", "")
    assert config.resolve_dir() == os.path.abspath(config.default_dir())
    monkeypatch.setenv("RAFT_TPU_CACHE_DIR", "/some/where")
    assert config.resolve_dir() == "/some/where"
    # the explicit argument wins over the env pin
    assert config.resolve_dir("/else/where") == "/else/where"


def test_enable_off_is_noop(monkeypatch):
    monkeypatch.setenv("RAFT_TPU_CACHE_DIR", "off")
    assert cache.enable() is None
    assert not cache.is_enabled()


# ---------------------------------------------------------------- staging


def test_staging_roundtrip_hit_and_key(warm):
    calls = []

    def compute():
        calls.append(1)
        return (np.arange(6.0), np.ones((2, 3)) * (1 + 2j))

    parts = ("tag", np.arange(4.0), 2.5, 7, None)
    a1, c1 = staging.cached_arrays("t", parts, compute)
    a2, c2 = staging.cached_arrays("t", parts, compute)       # disk hit
    assert len(calls) == 1
    np.testing.assert_array_equal(a1, a2)
    np.testing.assert_array_equal(c1, c2)
    assert c2.dtype == np.complex128                # complex round-trips
    rep = stats.report()["staging"]
    assert rep["disk_hits"] == 1 and rep["misses"] == 1
    # any changed key part is a different artifact
    staging.cached_arrays("t", ("tag", np.arange(4.0), 2.5, 8, None), compute)
    assert len(calls) == 2
    assert staging.staging_key("t", *parts) != staging.staging_key(
        "t", "tag", np.arange(4.0), 2.5, 8, None)


def test_staging_corruption_tolerance(warm):
    calls = []

    def compute():
        calls.append(1)
        return (np.full(3, 7.0),)

    (out,) = staging.cached_arrays("c", ("k",), compute)
    d = os.path.join(warm, "staging")
    (art,) = [f for f in os.listdir(d) if f.startswith("c-")]
    with open(os.path.join(d, art), "wb") as f:
        f.write(b"truncated garbage")                # corrupt the artifact
    (out2,) = staging.cached_arrays("c", ("k",), compute)    # silent recompute
    assert len(calls) == 2
    np.testing.assert_array_equal(out, out2)
    assert stats.report()["staging"]["errors"] == 1
    (out3,) = staging.cached_arrays("c", ("k",), compute)    # healed: hits again
    assert len(calls) == 2
    np.testing.assert_array_equal(out, out3)


def test_wamit_staging_invalidates_on_file_change(warm, tmp_path):
    from test_bem_io import synth_wamit

    from raft_tpu.hydro.bem_io import load_wamit_coeffs

    w, A, B, Xre, Xim, p1, p3 = synth_wamit(tmp_path)
    grid = np.linspace(0.25, 0.95, 8)
    A1, B1, F1 = load_wamit_coeffs(p1, p3, grid)
    A2, B2, F2 = load_wamit_coeffs(p1, p3, grid)         # content hit
    np.testing.assert_array_equal(A1, A2)
    np.testing.assert_array_equal(F1, F2)
    assert stats.report()["staging"]["disk_hits"] == 1
    # rewrite the .1 file with scaled coefficients: the content hash (not
    # mtime) must invalidate and the fresh parse must see the new values
    txt = open(p1).read().splitlines()
    with open(p1, "w") as f:
        for ln in txt:
            c = ln.split()
            f.write(f"{c[0]} {c[1]} {c[2]} {float(c[3]) * 2:.12E} {c[4]}\n")
    A3, B3, F3 = load_wamit_coeffs(p1, p3, grid)
    np.testing.assert_allclose(A3, 2 * A1, rtol=1e-9)
    np.testing.assert_array_equal(B3, B1)
    assert stats.report()["staging"]["misses"] == 2


# -------------------------------------------------------------------- aot


def test_aot_keying_shape_dtype_consts_mesh(warm):
    x32 = jnp.zeros((4, 3), jnp.float32)
    x64 = jnp.zeros((4, 3), jnp.float64)
    y = jnp.zeros((8, 3), jnp.float32)
    k = aot.aot_key("t", (x32,))
    assert k == aot.aot_key("t", (x32,))                 # deterministic
    assert k != aot.aot_key("u", (x32,))                 # tag
    assert k != aot.aot_key("t", (y,))                   # shape
    assert k != aot.aot_key("t", (x64,))                 # dtype
    assert k != aot.aot_key("t", (x32,), consts=(np.ones(3),))   # consts
    assert (aot.aot_key("t", (x32,), consts=(np.ones(3),))
            != aot.aot_key("t", (x32,), consts=(2 * np.ones(3),)))  # content
    from raft_tpu.parallel import make_mesh

    assert k != aot.aot_key("t", (x32,), mesh=make_mesh(2))      # topology
    assert (aot.aot_key("t", (x32,), mesh=make_mesh(2))
            != aot.aot_key("t", (x32,), mesh=make_mesh(4)))


def test_callable_salt_sees_closure_values():
    """Two instances of the same factory-made hook differ only in the
    captured value — the salt must distinguish them, or a warm process
    would reuse an executable with the WRONG constant baked in."""
    def make_apply(alpha):
        def apply(m, t):
            return m * alpha * t
        return apply

    assert aot.callable_salt(make_apply(0.5)) != aot.callable_salt(
        make_apply(2.0))
    assert aot.callable_salt(make_apply(0.5)) == aot.callable_salt(
        make_apply(0.5))

    def make_arr(a):
        def f(x):
            return x + a
        return f

    assert aot.callable_salt(make_arr(np.ones(3))) != aot.callable_salt(
        make_arr(np.zeros(3)))


def test_callable_salt_stable_for_hooks_containing_lambdas():
    """The salt must be process-stable for hooks whose code objects nest
    lambdas/comprehensions: code-object repr embeds a memory address, so
    hashing repr(co_consts) would give every process a different salt and
    silently defeat the cross-process AOT disk layer.  Re-exec'ing the
    same source twice (fresh code objects at fresh addresses, no
    retrievable source — exec-defined) simulates two processes."""
    src = ("def hook(x):\n"
           "    return sum(y * 2 for y in x) + (lambda z: z + 1)(0)\n")

    def build():
        ns: dict = {}
        exec(src, ns)          # noqa: S102 - test-local source
        return ns["hook"]

    assert aot.callable_salt(build()) == aot.callable_salt(build())


def test_callable_salt_stable_across_hash_seeds():
    """frozenset constants (compiled from `x in {...}` membership tests)
    iterate in PYTHONHASHSEED order — the salt must canonicalize them or
    every process computes a different key and the AOT disk layer never
    hits.  Two subprocesses with different seeds must agree."""
    code = ("import sys; sys.path.insert(0, %r)\n"
            "from raft_tpu.cache import aot\n"
            "def hook(x):\n"
            "    return x if x in {'alpha', 'beta', 'gamma'} else 0\n"
            "print(aot.callable_salt(hook)[1])\n") % REPO

    def salt_under(seed):
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=60, env={**os.environ, "PYTHONHASHSEED": seed},
        )
        assert r.returncode == 0, r.stderr[-800:]
        return r.stdout.strip()

    assert salt_under("1") == salt_under("2")


def test_bench_stderr_tail_redaction():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_mod_redact", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    for s in ("Authorization: Bearer sk-ant-SECRET123",
              "Bearer tok_abc123",
              "api_key=XYZ999",
              "oops sk-ant-api03-longsecret99 trace"):
        out = bench._stderr_tail(s)
        assert "SECRET" not in out and "tok_abc" not in out \
            and "XYZ999" not in out and "longsecret" not in out, (s, out)
    assert bench._stderr_tail("plain diagnostic line") == \
        "plain diagnostic line"
    # a credential whose key prefix sits before the 300-char cut must
    # still be caught (redaction happens before truncation)
    s = "x" * 500 + "Authorization: Bearer " + "A" * 290
    assert "AAAA" not in bench._stderr_tail(s)


def test_disable_unwires_compile_cache(tmp_path):
    cache.enable(str(tmp_path / "c"))
    assert jax.config.jax_compilation_cache_dir is not None
    cache.disable()
    assert jax.config.jax_compilation_cache_dir is None
    # enable with an off spelling after a prior enable must un-wire too
    cache.enable(str(tmp_path / "c"))
    assert cache.enable("off") is None
    assert jax.config.jax_compilation_cache_dir is None
    assert not cache.is_enabled()


def test_keys_salted_by_package_source(warm, monkeypatch):
    """Editing ANY in-repo source must invalidate both registries — a
    developer iterating on physics code can never be served a pre-edit
    executable or pre-edit staged arrays."""
    x = jnp.zeros(3)
    k_aot = aot.aot_key("t", (x,))
    k_stage = staging.staging_key("t", np.arange(3.0))
    monkeypatch.setattr(config, "_code_salt", ["deadbeefdeadbeef"])
    assert aot.aot_key("t", (x,)) != k_aot
    assert staging.staging_key("t", np.arange(3.0)) != k_stage


def test_aot_key_version_salted(warm, monkeypatch):
    x = jnp.zeros(3)
    k = aot.aot_key("t", (x,))
    monkeypatch.setattr(aot, "_version_salts",
                        lambda: ("jax=9.9.9", "jaxlib=9.9.9", "raft_tpu=x"))
    assert aot.aot_key("t", (x,)) != k       # a jax upgrade invalidates


def test_aot_mem_disk_and_corruption(warm):
    x = jnp.arange(8.0)

    def f(v):
        return (v * 3 + 1).sum()

    c1 = aot.cached_compile("toy", f, (x,))
    ref = c1(x)
    assert stats.report()["aot"]["misses"] == 1
    assert aot.cached_compile("toy", f, (x,))(x) == ref          # mem hit
    assert stats.report()["aot"]["mem_hits"] == 1
    aot.clear_memory()
    c2 = aot.cached_compile("toy", f, (x,))                      # disk hit
    assert stats.report()["aot"]["disk_hits"] == 1
    assert c2(x) == ref
    # corrupt the stored executable: silent recompile, never a crash
    aot.clear_memory()
    d = os.path.join(warm, "aot")
    (art,) = os.listdir(d)
    with open(os.path.join(d, art), "wb") as f2:
        f2.write(b"\x00garbage")
    c3 = aot.cached_compile("toy", f, (x,))
    assert c3(x) == ref
    rep = stats.report()["aot"]
    assert rep["errors"] == 1 and rep["misses"] == 2


def test_donation_salt_distinguishes_signatures():
    """An executable compiled with donation must never be served to a
    call site compiled without it (the donating one invalidates inputs
    the other still holds): the donation signature is a key component."""
    s_none = aot.donation_salt(None)
    s_empty = aot.donation_salt({})
    s_num = aot.donation_salt({"donate_argnums": (0,)})
    s_num2 = aot.donation_salt({"donate_argnums": (0, 1)})
    s_int = aot.donation_salt({"donate_argnums": 0})
    s_name = aot.donation_salt({"donate_argnames": ("x",)})
    assert s_none == s_empty
    assert len({s_none, s_num, s_num2, s_name}) == 4
    assert s_int == s_num                      # int normalizes to tuple
    x = jnp.arange(4.0)
    k_plain = aot.aot_key("t", (x,), extra=(s_none,))
    k_donate = aot.aot_key("t", (x,), extra=(s_num,))
    assert k_plain != k_donate


def test_cached_compile_keys_on_donation(warm):
    """Flipping donate_argnums compiles a SECOND executable (no stale
    reuse across the aliasing flip), and the donating one really
    invalidates its input buffer."""
    def f(v):
        return v * 2.0

    x = jnp.arange(16.0)
    plain = aot.cached_compile("don", f, (x,))
    assert stats.report()["aot"]["misses"] == 1
    donating = aot.cached_compile("don", f, (x,),
                                  jit_kwargs={"donate_argnums": (0,)})
    assert stats.report()["aot"]["misses"] == 2    # distinct key: recompiled
    # same key on repeat: served from memory
    aot.cached_compile("don", f, (x,), jit_kwargs={"donate_argnums": (0,)})
    assert stats.report()["aot"]["mem_hits"] == 1
    y = jnp.arange(16.0) + 1.0
    ref = np.asarray(plain(y))
    out = np.asarray(donating(y))
    np.testing.assert_array_equal(out, ref)
    assert y.is_deleted()                          # donation was real
    assert not x.is_deleted()


def test_compile_events_counted_and_reset(warm):
    """Real compiles land in both the ordered event log and the exact
    per-tag counters; reset zeroes them (phase boundaries of long-lived
    processes)."""
    aot.reset_compile_events()
    x = jnp.arange(4.0)
    aot.cached_compile("evt_a", lambda v: v + 1, (x,))
    aot.cached_compile("evt_b", lambda v: v * 2, (x,))
    aot.cached_compile("evt_a", lambda v: v + 1, (x,))     # mem hit: no event
    assert aot.compile_events("evt_a") == ["evt_a"]
    assert aot.compile_count("evt_a") == 1
    assert aot.compile_count() == 2
    aot.reset_compile_events()
    assert aot.compile_events() == [] and aot.compile_count() == 0


def test_compile_event_log_is_bounded_counters_exact():
    """The ordered log is a ring (a daemon or multi-phase bench cannot
    grow it without limit) while compile_count stays exact past the
    wrap.  Events are injected exactly as cached_compile records them."""
    aot.reset_compile_events()
    try:
        n = aot._COMPILE_EVENTS_MAX + 50
        for i in range(n):
            aot._record_compile("ring")
        assert len(aot.compile_events()) == aot._COMPILE_EVENTS_MAX
        assert aot.compile_count("ring") == n
    finally:
        aot.reset_compile_events()


def test_compile_count_reset_consistent_under_threads():
    """Ring and counter move under ONE lock: a reset racing appends can
    never leave a negative or torn window, and an uncontended phase
    counts exactly (test_test_cache-style threaded pin of the PR's
    events-lock fix)."""
    import threading

    aot.reset_compile_events()
    writers, per_writer = 4, 2000
    stop = threading.Event()
    bad: list = []

    def writer():
        for _ in range(per_writer):
            aot._record_compile("thr_evt")

    def resetter():
        while not stop.is_set():
            aot.reset_compile_events()
            # tear invariant (single resetter, so no clear lands between
            # these two reads): every ring event carried its increment
            # atomically, and the count is monotone between resets, so a
            # count read AFTER the ring read can never be smaller.  The
            # pre-fix non-atomic reset orphaned the events appended
            # between ring.clear() and counts.clear(), making
            # count < len(ring) observable.
            n_ring = len(aot.compile_events("thr_evt"))
            c = aot.compile_count("thr_evt")
            if c < n_ring:
                bad.append((c, n_ring))

    old = sys.getswitchinterval()
    sys.setswitchinterval(1e-6)
    try:
        rt = threading.Thread(target=resetter)
        ts = [threading.Thread(target=writer) for _ in range(writers)]
        rt.start()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        stop.set()
        rt.join()
        assert not bad, f"torn compile counts observed: {bad[:5]}"
        aot.reset_compile_events()
        assert aot.compile_count() == 0 and aot.compile_events() == []
        # no concurrent reset: the count must be exact
        ts = [threading.Thread(target=writer) for _ in range(writers)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert aot.compile_count("thr_evt") == writers * per_writer
        assert len(aot.compile_events("thr_evt")) == min(
            writers * per_writer, aot._COMPILE_EVENTS_MAX)
    finally:
        sys.setswitchinterval(old)
        aot.reset_compile_events()


def test_cached_compile_single_flight_under_contention(warm):
    """N threads requesting one AOT key compile it exactly once (the
    single-flight discipline): every caller gets the SAME executable
    object and compile_count stays 1."""
    import threading

    args = (jnp.arange(6, dtype=jnp.float32),)

    def fn(x):
        return x * 3.0 - 1.0

    n = 6
    results = [None] * n
    barrier = threading.Barrier(n)

    def worker(i):
        barrier.wait(timeout=30)
        results[i] = aot.cached_compile("thr_single_flight", fn, args)

    old = sys.getswitchinterval()
    sys.setswitchinterval(1e-6)
    try:
        ts = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    finally:
        sys.setswitchinterval(old)
    assert all(r is not None for r in results)
    assert len({id(r) for r in results}) == 1, "threads got distinct executables"
    assert aot.compile_count("thr_single_flight") == 1
    out = np.asarray(results[0](*args))
    np.testing.assert_allclose(out, np.arange(6, dtype=np.float32) * 3.0 - 1.0)


def test_cached_compile_single_flight_leader_failure_retries(warm):
    """A leader whose build raises must not poison the key: the event is
    set without a publish and a waiter retries as the new leader."""
    import threading
    import time

    args = (jnp.arange(4, dtype=jnp.float32),)
    calls = {"n": 0}
    leading = threading.Event()

    def flaky(x):
        calls["n"] += 1
        if calls["n"] == 1:
            # hold single-flight leadership until the follower is queued
            leading.set()
            time.sleep(0.3)
            raise RuntimeError("injected trace failure")
        return x + 1.0

    errors: list = []

    def leader():
        try:
            aot.cached_compile("thr_flaky", flaky, args)
        except RuntimeError as e:
            errors.append(str(e))

    results: list = []
    lt = threading.Thread(target=leader)
    lt.start()
    assert leading.wait(timeout=30)     # leader is inside its build now
    ft = threading.Thread(
        target=lambda: results.append(
            aot.cached_compile("thr_flaky", flaky, args)))
    ft.start()
    lt.join()
    ft.join()
    assert errors == ["injected trace failure"]
    assert len(results) == 1 and results[0] is not None
    np.testing.assert_allclose(np.asarray(results[0](*args)),
                               np.arange(4, dtype=np.float32) + 1.0)


def test_cached_callable_off_is_plain_jit():
    cache.disable()
    x = jnp.ones(4)
    fn = aot.cached_callable("t", lambda v: v + 1, (x,))
    # the disabled path must be today's exact dispatch path: a jitted
    # function (re-traceable on new shapes), NOT a shape-locked executable
    np.testing.assert_array_equal(fn(jnp.ones(9)), np.full(9, 2.0))


# ------------------------------------------------- end-to-end sweep paths


def _tiny_sweep():
    import __graft_entry__ as ge
    from raft_tpu.mooring import mooring_stiffness, parse_mooring
    from raft_tpu.parallel import sweep

    design, members, rna, env, wave = ge._base(nw=16)
    moor = parse_mooring(
        design["mooring"], yaw_stiffness=design["turbine"]["yaw_stiffness"]
    )
    C_moor = mooring_stiffness(moor, jnp.zeros(6))
    return sweep(members, rna, env, wave, C_moor,
                 jnp.linspace(0.97, 1.03, 2), n_iter=20)


def test_sweep_cache_on_equals_off(warm):
    on1 = _tiny_sweep()
    on2 = _tiny_sweep()                       # mem hit, same executable
    cache.disable()
    off = _tiny_sweep()
    np.testing.assert_array_equal(on1["std dev"], off["std dev"])
    np.testing.assert_array_equal(on1["std dev"], on2["std dev"])
    np.testing.assert_array_equal(on1["Xi_abs2"], off["Xi_abs2"])


def test_sweep_sea_states_cache_on_equals_off(warm):
    import __graft_entry__ as ge
    from raft_tpu.mooring import mooring_stiffness, parse_mooring
    from raft_tpu.parallel import make_wave_states, sweep_sea_states

    design, members, rna, env, wave = ge._base(nw=16)
    moor = parse_mooring(
        design["mooring"], yaw_stiffness=design["turbine"]["yaw_stiffness"]
    )
    C_moor = mooring_stiffness(moor, jnp.zeros(6))
    waves = make_wave_states(np.asarray(wave.w), [[6, 10], [8, 12]],
                             float(env.depth))
    on = sweep_sea_states(members, rna, env, waves, C_moor, n_iter=20)
    cache.disable()
    off = sweep_sea_states(members, rna, env, waves, C_moor, n_iter=20)
    np.testing.assert_array_equal(on["std dev"], off["std dev"])
    np.testing.assert_array_equal(on["Xi_abs2"], off["Xi_abs2"])
    cache.enable(warm)
    on2 = sweep_sea_states(members, rna, env, waves, C_moor, n_iter=20)
    np.testing.assert_array_equal(on["std dev"], on2["std dev"])
    assert stats.report()["aot"]["mem_hits"] >= 1


def _oc3_inputs(nw=16):
    import __graft_entry__ as ge
    from raft_tpu.mooring import mooring_stiffness, parse_mooring

    design, members, rna, env, wave = ge._base(nw=nw)
    moor = parse_mooring(
        design["mooring"], yaw_stiffness=design["turbine"]["yaw_stiffness"]
    )
    return members, rna, env, wave, mooring_stiffness(moor, jnp.zeros(6))


def test_freq_sharded_and_dp_sp_cache_paths(warm):
    """The sharded forwards' AOT path: deterministic across repeat calls
    (committed placement + stored executable) and matching the plain
    eager-shard_map path to reduction-order tolerance — the extra jit
    wrapper the registry needs reassociates at float-eps level, exactly
    the tolerance the sharded==unsharded docstring already grants."""
    from jax.sharding import Mesh

    members, rna, env, wave, C_moor = _oc3_inputs()
    from raft_tpu.parallel import (
        forward_response_dp_sp, forward_response_freq_sharded, make_mesh,
    )

    mesh_f = make_mesh(8, axis="freq")
    on1 = forward_response_freq_sharded(members, rna, env, wave, C_moor,
                                        mesh_f, n_iter=30)
    on2 = forward_response_freq_sharded(members, rna, env, wave, C_moor,
                                        mesh_f, n_iter=30)
    np.testing.assert_array_equal(np.asarray(on1.Xi.re),
                                  np.asarray(on2.Xi.re))
    cache.disable()
    off = forward_response_freq_sharded(members, rna, env, wave, C_moor,
                                        mesh_f, n_iter=30)
    np.testing.assert_allclose(np.asarray(on1.Xi.re), np.asarray(off.Xi.re),
                               rtol=1e-10, atol=1e-12)
    cache.enable(warm)

    mesh2 = Mesh(np.array(jax.devices()).reshape(2, 4), ("designs", "freq"))
    th = jnp.linspace(0.97, 1.03, 2)
    on_dp = forward_response_dp_sp(members, rna, env, wave, C_moor, th,
                                   mesh2, n_iter=30)
    cache.disable()
    off_dp = forward_response_dp_sp(members, rna, env, wave, C_moor, th,
                                    mesh2, n_iter=30)
    np.testing.assert_allclose(np.asarray(on_dp.Xi.re),
                               np.asarray(off_dp.Xi.re),
                               rtol=1e-10, atol=1e-12)


def test_optimize_val_grad_cache_on_equals_off(warm):
    """optimize_design's value-and-grad step compiles from the SAME trace
    either way (plain jit off, registry executable on) — results must be
    bit-identical, and the registry must log the executable."""
    members, rna, env, wave, C_moor = _oc3_inputs(nw=12)
    from raft_tpu.parallel import optimize_design

    kw = dict(theta0=jnp.ones(1), steps=2, learning_rate=0.02, n_iter=12)
    on = optimize_design(members, rna, env, wave, C_moor, **kw)
    assert stats.report()["aot"]["misses"] >= 1
    cache.disable()
    off = optimize_design(members, rna, env, wave, C_moor, **kw)
    np.testing.assert_array_equal(on.history, off.history)
    np.testing.assert_array_equal(on.thetas, off.thetas)


# ------------------------------------------------------ cross-process smoke


def test_cache_smoke_two_processes(tmp_path):
    """The ``make cache-smoke`` check, smallest workload: a second PROCESS
    must load the stored executable (disk hit) and spend well under the
    cold process's compile time — the acceptance-criteria warm start,
    verified in the driver's regime (fresh subprocesses)."""
    r = subprocess.run(
        [sys.executable, "-m", "raft_tpu.cache", "smoke",
         "--n", "2", "--nw", "12", "--threshold", "0.6",
         "--dir", str(tmp_path / "smoke")],
        capture_output=True, text=True, timeout=420, cwd=REPO,
        env={**os.environ, "RAFT_TPU_CACHE_DIR": ""},
    )
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["ok"] and out["warm_aot_disk_hits"] >= 1
    assert out["results_identical"]
    assert out["warm_compile_s"] < 0.6 * out["cold_compile_s"]
