"""Fault-tolerant serving fleet: config snapshot, deterministic
routing/admission on a virtual clock, failover resubmission with
bit-identical rows and trace continuity, shed-then-recover, and the
supervisor's restart-storm bound.

The robustness contract under test (docs/serving.rst, docs/robustness.rst):

* routing is a pure function of replica state — bucket affinity by
  design label, least-loaded (ties -> lowest index) on a miss, re-pin
  when the pinned replica is down or saturated;
* admission is deterministic: capacity (``queue_max x healthy``) and the
  windowed error budget shed with the typed ``Overloaded`` frame, and
  the budget recovers as the window slides (virtual clock);
* a request orphaned by a replica death is resubmitted to a survivor
  and answered EXACTLY once, with the original trace id and rows
  bit-identical to an uninterrupted run (solves are pure);
* the supervisor restarts dead children at most ``restart_max`` times
  per ``restart_window_s`` sliding window, visibly suppressed beyond.

The cross-process half (real daemon children, SIGKILL mid-stream, warm
zero-compile restarts) is ``make fleet-smoke``; these tests pin the
same machinery deterministically in-process.
"""
import os
import socket
import threading
import time

import pytest

from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.resilience import faults
from raft_tpu.serve import protocol
from raft_tpu.serve.client import (ServeConnectionLost, ServeTimeout,
                                   SolveClient)
from raft_tpu.serve.fleet import Fleet, FleetConfig
from raft_tpu.serve.router import FleetRouter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class VirtualClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _counter(name):
    return obs_metrics.counter(f"fleet.{name}").value


def _mk_router(tmp_path, clock=None, replicas=2, injector=None, **cfg_kw):
    """A router over nonexistent replica sockets (the unit tests drive
    its state directly; nothing is started unless the test says so)."""
    cfg_kw.setdefault("probe_interval_s", 0.0)
    cfg = FleetConfig(replicas=replicas, **cfg_kw)
    paths = [str(tmp_path / f"r{i}.sock") for i in range(replicas)]
    return FleetRouter(cfg, paths, socket_path=str(tmp_path / "front.sock"),
                       clock=clock or VirtualClock(), injector=injector,
                       sleep=lambda s: None)


def _mark_up(router, *idxs):
    class _NullLink:
        def send(self, obj):
            return True

        def close(self):
            pass

    for i in idxs:
        st = router._replicas[i]
        st.healthy = True
        st.link = _NullLink()


# --------------------------------------------------------------------------
# FleetConfig: env snapshot, overrides, loud failures
# --------------------------------------------------------------------------
def test_fleet_config_defaults_and_env(monkeypatch):
    for k in list(os.environ):
        if k.startswith("RAFT_TPU_FLEET_"):
            monkeypatch.delenv(k)
    cfg = FleetConfig.from_env()
    assert (cfg.replicas, cfg.queue_max) == (2, 32)
    assert cfg.probe_interval_s == pytest.approx(0.5)
    monkeypatch.setenv("RAFT_TPU_FLEET_REPLICAS", "4")
    monkeypatch.setenv("RAFT_TPU_FLEET_PROBE_MS", "250")
    monkeypatch.setenv("RAFT_TPU_FLEET_QUEUE_MAX", "7")
    monkeypatch.setenv("RAFT_TPU_FLEET_SHED_ERROR_RATE", "0.25")
    monkeypatch.setenv("RAFT_TPU_FLEET_RESTART_MAX", "5")
    monkeypatch.setenv("RAFT_TPU_FLEET_SOCKET", "/tmp/fleet-test.sock")
    cfg = FleetConfig.from_env()
    assert cfg.replicas == 4
    assert cfg.probe_interval_s == pytest.approx(0.25)
    assert cfg.queue_max == 7
    assert cfg.shed_error_rate == pytest.approx(0.25)
    assert cfg.restart_max == 5
    assert cfg.socket_path == "/tmp/fleet-test.sock"
    # explicit overrides (CLI flags, fixtures) win over the environment
    assert FleetConfig.from_env(replicas=1).replicas == 1


def test_fleet_config_malformed_is_loud(monkeypatch):
    monkeypatch.setenv("RAFT_TPU_FLEET_REPLICAS", "two")
    with pytest.raises(ValueError, match="RAFT_TPU_FLEET_REPLICAS"):
        FleetConfig.from_env()
    monkeypatch.delenv("RAFT_TPU_FLEET_REPLICAS")
    monkeypatch.setenv("RAFT_TPU_FLEET_SHED_ERROR_RATE", "1.5")
    with pytest.raises(ValueError, match="SHED_ERROR_RATE"):
        FleetConfig.from_env()
    monkeypatch.delenv("RAFT_TPU_FLEET_SHED_ERROR_RATE")
    with pytest.raises(ValueError, match="REPLICAS"):
        FleetConfig.from_env(replicas=0)


# --------------------------------------------------------------------------
# counted replica faults (the chaos hand the router/smoke drive)
# --------------------------------------------------------------------------
def test_replica_fault_kinds_counted(monkeypatch):
    assert {"kill_replica", "stall_replica",
            "refuse_connect"} <= faults.KINDS
    monkeypatch.setenv("RAFT_TPU_FAULT_INJECT",
                       "kill_replica:2,stall_replica:1,refuse_connect:1")
    faults.reset_counts()
    try:
        assert faults.consume("kill_replica")
        assert faults.consume("kill_replica")
        assert not faults.consume("kill_replica")   # exactly K
        assert faults.consume("stall_replica")
        assert not faults.consume("stall_replica")
        assert faults.consume("refuse_connect")
        assert not faults.consume("refuse_connect")
    finally:
        faults.reset_counts()


def test_unknown_fault_kind_warns(monkeypatch):
    monkeypatch.setenv("RAFT_TPU_FAULT_INJECT", "explode_rack:1")
    with pytest.warns(UserWarning, match="explode_rack"):
        assert faults.specs() == {}


# --------------------------------------------------------------------------
# routing: affinity + least-loaded, pure function of replica state
# --------------------------------------------------------------------------
def test_pick_least_loaded_then_affinity_pins(tmp_path):
    r = _mk_router(tmp_path)
    _mark_up(r, 0, 1)
    with r._lock:
        assert r._pick_locked("OC3spar").idx == 0     # tie -> lowest idx
        r._replicas[0].inflight = 3
        assert r._pick_locked("OC4semi").idx == 1     # least loaded
        # the pin follows even when loads later invert
        r._replicas[1].inflight = 9
        assert r._pick_locked("OC4semi").idx == 1
    assert r.telemetry()["affinity"] == {"OC3spar": 0, "OC4semi": 1}


def test_pick_repins_on_saturation_and_death(tmp_path):
    r = _mk_router(tmp_path, queue_max=2)
    _mark_up(r, 0, 1)
    with r._lock:
        assert r._pick_locked("OC3spar").idx == 0
        r._replicas[0].inflight = 2                   # == queue_max
        assert r._pick_locked("OC3spar").idx == 1     # saturated -> re-pin
        assert r._affinity["OC3spar"] == 1
        r._replicas[1].healthy = False                # pinned replica dies
        r._replicas[0].inflight = 0
        assert r._pick_locked("OC3spar").idx == 0
        r._replicas[0].healthy = False
        assert r._pick_locked("OC3spar") is None      # nobody left


# --------------------------------------------------------------------------
# admission: capacity + windowed error budget on a virtual clock
# --------------------------------------------------------------------------
def test_admission_capacity_and_recovery(tmp_path):
    clk = VirtualClock()
    r = _mk_router(tmp_path, clock=clk, queue_max=2)
    assert "no healthy replica" in r._admit()
    _mark_up(r, 0, 1)
    assert r._admit() is None
    r._replicas[0].inflight = 2
    r._replicas[1].inflight = 2                       # 4 == 2 x 2 healthy
    assert "capacity" in r._admit()
    r._replicas[1].inflight = 1
    assert r._admit() is None                         # headroom again
    r._replicas[1].healthy = False                    # 3 > 2 x 1 healthy
    assert "capacity" in r._admit()


def test_admission_error_budget_sheds_then_recovers(tmp_path):
    clk = VirtualClock(t=100.0)
    r = _mk_router(tmp_path, clock=clk, shed_error_rate=0.5,
                   shed_min_events=8)
    _mark_up(r, 0)
    # 7 errors: below min events, the budget must NOT latch shut
    for _ in range(7):
        r._slo.error(now=clk.t)
    assert r._admit() is None
    r._slo.error(now=clk.t)                           # 8th: rate 1.0 > 0.5
    reason = r._admit()
    assert reason is not None and "error budget" in reason
    # successes dilute the windowed rate back under the threshold
    for _ in range(9):
        r._slo.observe(0.01, now=clk.t)
    assert r._admit() is None
    # ... and a slid window forgets entirely (shed-then-recover)
    for _ in range(16):
        r._slo.error(now=clk.t)
    assert "error budget" in r._admit()
    clk.t += 2 * r.slo_window_s
    assert r._admit() is None


def test_overloaded_response_is_typed():
    resp = protocol.overloaded_response("req-1", 50.0, detail="capacity")
    assert resp["ok"] is False and resp["shed"] is True
    assert resp["id"] == "req-1"
    assert resp["retry_after_ms"] == 50.0
    assert resp["error"]["class"] == "Overloaded"
    assert "capacity" in resp["error"]["detail"]


# --------------------------------------------------------------------------
# forward deadline: an expired in-flight request fails over (virtual clock)
# --------------------------------------------------------------------------
def test_probe_once_expires_overdue_forwards(tmp_path):
    clk = VirtualClock()
    r = _mk_router(tmp_path, clock=clk, request_timeout_s=5.0,
                   resubmit_retries=1, resubmit_backoff_s=0.0)
    # a stalled-but-pingable replica: heartbeats pass, the frame never
    # comes back — exactly the hole the forward deadline exists to plug
    r._probe = lambda st: True

    class _Conn:
        def __init__(self):
            self.sent = []

        def send(self, obj):
            self.sent.append(obj)
            return True

    _mark_up(r, 0)
    conn = _Conn()
    r._dispatch(conn, {"op": "solve", "id": "x", "trace": "t-1",
                       "lanes": [("d", "OC3spar", 6.0, 10.0)]},
                {"op": "solve", "id": "x", "design": "oc3",
                 "Hs": 6.0, "Tp": 10.0})
    assert r._replicas[0].inflight == 1
    c_to = _counter("timeouts")
    c_re = _counter("resubmitted")
    clk.t = 4.0
    assert r.probe_once()["expired"] == 0             # not overdue yet
    clk.t = 6.0
    summary = r.probe_once()
    assert summary["expired"] == 1
    assert _counter("timeouts") - c_to == 1
    # the replica is still in rotation, so the expired forward is
    # RESUBMITTED (re-registered, resubmits bumped), not failed
    assert _counter("resubmitted") - c_re == 1
    assert conn.sent == []
    (fwd,) = r._replicas[0].outstanding.values()
    assert fwd.resubmits == 1
    # now the only replica is gone too: the ladder exhausts and the
    # client is answered LOUDLY with a typed error frame, never dropped
    with r._lock:
        r._replicas[0].healthy = False
        r._replicas[0].link = None
    clk.t = 12.0
    assert r.probe_once()["expired"] == 1
    assert len(conn.sent) == 1
    assert conn.sent[0]["ok"] is False and conn.sent[0]["id"] == "x"
    assert r._replicas[0].outstanding == {}


# --------------------------------------------------------------------------
# scripted replicas: live failover, trace continuity, shed-then-recover
# --------------------------------------------------------------------------
class FakeReplica:
    """Scripted stand-in for a daemon child: answers the admission ping,
    then echoes solve frames with a deterministic per-replica payload.
    ``hold()`` parks responses (a busy replica); ``die()`` is a real
    mid-stream death — path unlinked, accepted streams torn down — which
    is what makes the router's link reader see EOF (closing only the
    listener would leave kernel-backlogged connects alive)."""

    def __init__(self, path, tag):
        self.path = path
        self.tag = tag
        self.seen = []                       # (fid, trace) per solve frame
        self._release = threading.Event()
        self._release.set()
        self._conns = []
        self._ls = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._ls.bind(path)
        self._ls.listen(8)
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while True:
            try:
                conn, _ = self._ls.accept()
            except OSError:
                return
            self._conns.append(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                obj = protocol.recv_msg(conn)
                if obj.get("op") in ("ping", "stats", "refresh"):
                    protocol.send_msg(conn, {"id": obj.get("id"),
                                             "ok": True, "op": obj["op"]})
                    continue
                self.seen.append((obj.get("id"), obj.get("trace")))
                self._release.wait(30.0)
                protocol.send_msg(conn, {
                    "id": obj.get("id"), "ok": True, "op": "solve",
                    "results": [{"design": obj.get("design"),
                                 "std_dev": [self.tag] * 6}]})
        except (protocol.PeerClosed, protocol.ProtocolError, OSError):
            pass

    def hold(self):
        self._release.clear()

    def release(self):
        self._release.set()

    def die(self):
        try:
            os.unlink(self.path)
        except OSError:
            pass
        for c in self._conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            c.close()
        self._ls.close()


@pytest.fixture()
def fake_fleet(tmp_path):
    """A started router over two scripted replicas (no probe thread; the
    tests drive health sweeps explicitly)."""
    cfg = FleetConfig(replicas=2, probe_interval_s=0.0,
                      resubmit_backoff_s=0.0)
    paths = [str(tmp_path / "fr0.sock"), str(tmp_path / "fr1.sock")]
    reps = [FakeReplica(paths[i], tag=float(i)) for i in range(2)]
    router = FleetRouter(cfg, paths,
                         socket_path=str(tmp_path / "front.sock"))
    router.start()
    yield router, reps
    router.stop()
    for rep in reps:
        rep.die()


def test_routed_end_to_end_with_affinity_split(fake_fleet):
    router, reps = fake_fleet
    with SolveClient(router.socket_path) as cl:
        for rep in reps:
            rep.hold()
        # dispatch is sequential on the client's conn reader, so the
        # second label sees the first's in-flight and splits off — but
        # only release once BOTH frames have landed on a replica, else
        # the first relay drains the in-flight count mid-routing
        f_a = cl.submit({"op": "solve", "design": "oc3",
                         "Hs": 6.0, "Tp": 10.0})
        f_b = cl.submit({"op": "solve", "design": "oc4",
                         "Hs": 6.0, "Tp": 10.0})
        deadline = time.monotonic() + 5.0
        while (len(reps[0].seen) + len(reps[1].seen) < 2
               and time.monotonic() < deadline):
            time.sleep(0.005)
        for rep in reps:
            rep.release()
        ra, rb = f_a.result(10.0), f_b.result(10.0)
    assert (ra["replica"], rb["replica"]) == (0, 1)
    assert ra["results"][0]["std_dev"] == [0.0] * 6
    assert rb["results"][0]["std_dev"] == [1.0] * 6
    tel = router.telemetry()
    assert tel["affinity"] == {"OC3spar": 0, "OC4semi": 1}
    assert tel["replicas"][0]["heat"] == {"OC3spar": 1}


def test_failover_answers_once_with_original_trace(fake_fleet):
    router, reps = fake_fleet
    c0 = {k: _counter(k) for k in ("failover", "resubmitted", "relayed")}
    with SolveClient(router.socket_path) as cl:
        # pin the label to replica 0, then kill it with the request in
        # flight: the link EOF must fail the request over to replica 1
        reps[0].hold()
        fut = cl.submit({"op": "solve", "design": "oc3",
                         "Hs": 6.0, "Tp": 10.0, "trace": "t-abc"})
        deadline = time.monotonic() + 5.0
        while (not reps[0].seen) and time.monotonic() < deadline:
            time.sleep(0.005)
        assert reps[0].seen, "request never reached replica 0"
        reps[0].die()
        resp = fut.result(10.0)
    assert resp["ok"] is True
    assert resp["replica"] == 1
    assert resp["resubmits"] == 1
    # exactly once: replica 0 never answered, replica 1 answered once
    assert [t for _, t in reps[1].seen] == ["t-abc"]   # trace continuity
    assert _counter("failover") - c0["failover"] == 1
    assert _counter("resubmitted") - c0["resubmitted"] == 1
    assert _counter("relayed") - c0["relayed"] == 1
    # the dead replica is out of rotation until re-admitted
    assert router.telemetry()["healthy"] == 1


def test_shed_then_recover_under_load_step(tmp_path):
    cfg = FleetConfig(replicas=1, probe_interval_s=0.0, queue_max=1,
                      resubmit_backoff_s=0.0)
    path = str(tmp_path / "sr0.sock")
    rep = FakeReplica(path, tag=7.0)
    router = FleetRouter(cfg, [path],
                         socket_path=str(tmp_path / "front.sock"))
    router.start()
    try:
        c0 = _counter("shed")
        with SolveClient(router.socket_path) as cl:
            rep.hold()                     # wedge the replica mid-request
            first = cl.submit({"op": "solve", "design": "oc3",
                               "Hs": 6.0, "Tp": 10.0})
            burst = [cl.submit({"op": "solve", "design": "oc3",
                                "Hs": 6.0 + i, "Tp": 10.0})
                     for i in range(3)]
            shed = [f.result(10.0) for f in burst]
            # the step over capacity sheds DETERMINISTICALLY: typed
            # frames with a retry hint, nothing queued unboundedly
            assert all(r["ok"] is False and r["shed"] is True
                       and r["error"]["class"] == "Overloaded"
                       and r["retry_after_ms"] > 0 for r in shed)
            assert _counter("shed") - c0 == 3
            rep.release()                  # load step passes
            assert first.result(10.0)["ok"] is True
            redo = [cl.call({"op": "solve", "design": "oc3",
                             "Hs": 6.0 + i, "Tp": 10.0}, timeout=10.0)
                    for i in range(3)]
            assert all(r["ok"] for r in redo)   # degrade, never lose
    finally:
        router.stop()
        rep.die()


def test_dead_replica_readmitted_by_probe(fake_fleet, tmp_path):
    router, reps = fake_fleet
    reps[0].die()
    # ... the next health sweep notices (heartbeat on a one-shot conn)
    summary = router.probe_once()
    assert 0 in summary["failed"]
    assert router.telemetry()["healthy"] == 1
    # replica 0 comes back on its ORIGINAL socket path, warm
    reps[0] = FakeReplica(router._replicas[0].socket_path, tag=0.5)
    summary = router.probe_once()
    assert summary["admitted"] == [0]
    tel = router.telemetry()
    assert tel["healthy"] == 2
    assert tel["replicas"][0]["admissions"] == 2


def test_refuse_connect_blocks_readmission(fake_fleet, monkeypatch):
    router, reps = fake_fleet
    reps[0].die()
    router.probe_once()
    reps[0] = FakeReplica(router._replicas[0].socket_path, tag=0.5)
    monkeypatch.setenv("RAFT_TPU_FAULT_INJECT", "refuse_connect:3")
    faults.reset_counts()
    try:
        # all 3 connect attempts of the admission ladder are refused:
        # the replica stays OUT of rotation (never half-admitted)
        assert router.probe_once()["admitted"] == []
        assert router.telemetry()["healthy"] == 1
    finally:
        monkeypatch.delenv("RAFT_TPU_FAULT_INJECT")
        faults.reset_counts()
    assert router.probe_once()["admitted"] == [0]


# --------------------------------------------------------------------------
# real solver: bit-identical rows across replicas and across a failover
# --------------------------------------------------------------------------
def test_failover_rows_bit_identical_real_solver(tmp_path, monkeypatch):
    """Rows are BIT-identical whichever replica solves the lane, and a
    failover mid-flight (stalled forward -> replica failed -> resubmitted
    to the survivor) answers with those same bits."""
    from raft_tpu.serve.config import ServeConfig
    from raft_tpu.serve.server import SolverServer

    servers = []
    for i in range(2):
        cfg = ServeConfig(batch_deadline_s=0.02, batch_max=2, nw=8,
                          w_min=0.3, w_max=2.1, n_iter=8, escalate=False,
                          socket_path=str(tmp_path / f"sv{i}.sock"))
        srv = SolverServer(cfg)
        srv.start()
        servers.append(srv)
    fcfg = FleetConfig(replicas=2, probe_interval_s=0.0,
                       resubmit_backoff_s=0.0)
    router = FleetRouter(fcfg, [s.socket_path for s in servers],
                         socket_path=str(tmp_path / "front.sock"))
    router.start()
    try:
        with SolveClient(router.socket_path) as cl:
            req = {"op": "solve", "design": "oc3", "Hs": 6.0, "Tp": 10.0}
            ref = cl.call(dict(req), timeout=120.0)
            assert ref["ok"] and ref["replica"] == 0
            rows_ref = ref["results"][0]["std_dev"]
            # same lane, forced onto the OTHER replica: same bits
            with router._lock:
                router._affinity["OC3spar"] = 1
            other = cl.call(dict(req), timeout=120.0)
            assert other["ok"] and other["replica"] == 1
            assert other["results"][0]["std_dev"] == rows_ref
            # failover leg: the forward to replica 0 is withheld
            # (stall_replica), then the replica is failed under it —
            # the resubmission lands on replica 1, bits unchanged
            with router._lock:
                router._affinity["OC3spar"] = 0
            monkeypatch.setenv("RAFT_TPU_FAULT_INJECT", "stall_replica:1")
            faults.reset_counts()
            try:
                fut = cl.submit(dict(req))
                deadline = time.monotonic() + 5.0
                while (not router._replicas[0].outstanding
                       and time.monotonic() < deadline):
                    time.sleep(0.005)
                assert router._replicas[0].outstanding
            finally:
                monkeypatch.delenv("RAFT_TPU_FAULT_INJECT")
                faults.reset_counts()
            router._fail_replica(router._replicas[0], "test kill")
            resp = fut.result(120.0)
            assert resp["ok"] is True
            assert resp["replica"] == 1
            assert resp["resubmits"] == 1
            assert resp["results"][0]["std_dev"] == rows_ref
    finally:
        router.stop()
        for s in servers:
            s.stop()


# --------------------------------------------------------------------------
# supervisor: restart-storm bound on a virtual clock
# --------------------------------------------------------------------------
class _DeadHandle:
    """A child that exits the instant it is spawned (crash loop)."""

    def poll(self):
        return 1

    def terminate(self):
        pass

    def kill(self):
        pass

    def wait(self, timeout=None):
        return 1


class _AliveHandle(_DeadHandle):
    def poll(self):
        return None


def _mk_fleet(tmp_path, spawn, **cfg_kw):
    cfg_kw.setdefault("probe_interval_s", 0.0)
    cfg = FleetConfig(replicas=1,
                      socket_path=str(tmp_path / "front.sock"), **cfg_kw)
    return Fleet(cfg, spawn_fn=spawn, run_dir=str(tmp_path / "run"),
                 clock=VirtualClock())


def test_restart_storm_is_bounded_per_window(tmp_path):
    spawns = []

    def spawn(idx, path):
        spawns.append(path)
        return _DeadHandle(), {"ready": True, "compiles_at_ready": 0}

    fleet = _mk_fleet(tmp_path, spawn, restart_max=3, restart_window_s=30.0)
    c_restart, c_supp = _counter("restart"), _counter("restart_suppressed")
    rep = fleet._replicas[0]
    rep.handle = _DeadHandle()                # "died" before any sweep
    assert fleet._babysit_once(now=0.0) == [0]
    assert fleet._babysit_once(now=1.0) == [0]
    assert fleet._babysit_once(now=2.0) == [0]
    # window full: the crash loop is suppressed, visibly, exactly once
    assert fleet._babysit_once(now=3.0) == []
    assert fleet._babysit_once(now=4.0) == []
    assert rep.suppressed is True
    assert rep.restarts == 3
    assert _counter("restart") - c_restart == 3
    assert _counter("restart_suppressed") - c_supp == 1
    assert fleet.telemetry()["supervisor"]["replicas"][0]["suppressed"]
    # the SLIDING window re-arms the budget once the old restarts age out
    assert fleet._babysit_once(now=33.5) == [0]
    assert rep.suppressed is False
    assert rep.restarts == 4
    assert len(spawns) == 4
    # every respawn kept the replica's ORIGINAL socket path (identity
    # is the index; the router's routing table never changes shape)
    assert set(spawns) == {rep.socket_path}


def test_babysit_leaves_live_children_alone(tmp_path):
    calls = []

    def spawn(idx, path):
        calls.append(idx)
        return _AliveHandle(), {"ready": True}

    fleet = _mk_fleet(tmp_path, spawn)
    fleet._spawn(fleet._replicas[0])
    assert calls == [0]
    assert fleet._babysit_once(now=0.0) == []
    assert fleet._babysit_once(now=10.0) == []
    assert calls == [0]                       # no gratuitous respawn
    assert fleet._replicas[0].restarts == 0


def test_failed_respawn_consumes_budget_and_retries(tmp_path):
    attempts = []

    def spawn(idx, path):
        attempts.append(idx)
        raise RuntimeError("ready line never came")

    fleet = _mk_fleet(tmp_path, spawn, restart_max=2, restart_window_s=30.0)
    rep = fleet._replicas[0]
    rep.handle = _DeadHandle()
    assert fleet._babysit_once(now=0.0) == []     # spawn raised
    assert rep.handle is None                     # retried next sweep...
    assert fleet._babysit_once(now=1.0) == []
    assert fleet._babysit_once(now=2.0) == []     # ...within the budget
    assert len(attempts) == 2
    assert rep.suppressed is True


# --------------------------------------------------------------------------
# client deadlines (the failure typing the router's failover keys on)
# --------------------------------------------------------------------------
def _silent_server(tmp_path, name="silent.sock"):
    """Accepts and reads but never answers (a wedged daemon)."""
    path = str(tmp_path / name)
    ls = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    ls.bind(path)
    ls.listen(4)
    conns = []

    def accept():
        while True:
            try:
                c, _ = ls.accept()
            except OSError:
                return
            conns.append(c)

    threading.Thread(target=accept, daemon=True).start()
    return path, ls, conns


def test_client_read_deadline_types_serve_timeout(tmp_path):
    path, ls, conns = _silent_server(tmp_path)
    try:
        with SolveClient(path, read_timeout=0.2) as cl:
            fut = cl.submit({"op": "ping"})
            with pytest.raises(ServeTimeout):
                fut.result(5.0)
    finally:
        ls.close()
        for c in conns:
            c.close()


def test_client_connection_loss_fails_pending(tmp_path):
    path, ls, conns = _silent_server(tmp_path, "dying.sock")
    cl = SolveClient(path)
    try:
        fut = cl.submit({"op": "ping"})
        deadline = time.monotonic() + 5.0
        while not conns and time.monotonic() < deadline:
            time.sleep(0.005)
        for c in conns:                      # the daemon dies mid-request
            c.shutdown(socket.SHUT_RDWR)
            c.close()
        with pytest.raises(ServeConnectionLost):
            fut.result(5.0)
    finally:
        ls.close()
        cl.close()


def test_client_connect_ladder_exhaustion_is_typed(tmp_path):
    with pytest.raises(ServeConnectionLost):
        SolveClient(str(tmp_path / "nowhere.sock"), connect_timeout=0.2,
                    retry_interval=0.05)


# --------------------------------------------------------------------------
# knobs: the RAFT_TPU_FLEET_* surface is registered and documented
# --------------------------------------------------------------------------
def test_fleet_knobs_registered_and_documented():
    from raft_tpu.lint import knobs

    expected = {
        "RAFT_TPU_FLEET_REPLICAS", "RAFT_TPU_FLEET_PROBE_MS",
        "RAFT_TPU_FLEET_PROBE_TIMEOUT_MS", "RAFT_TPU_FLEET_QUEUE_MAX",
        "RAFT_TPU_FLEET_SHED_ERROR_RATE", "RAFT_TPU_FLEET_RESTART_MAX",
        "RAFT_TPU_FLEET_RESTART_WINDOW_S", "RAFT_TPU_FLEET_SOCKET",
    }
    names = {k.name for k in knobs.KNOBS}
    assert expected <= names
    assert expected <= set(knobs.serve_knob_names())
    with open(os.path.join(REPO, "docs", "serving.rst")) as f:
        rst = f.read()
    for name in sorted(expected):
        assert name in rst, f"{name} missing from docs/serving.rst"
