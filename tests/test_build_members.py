"""Member-builder tests: orientation parity and cap/bulkhead edge cases."""
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.build.members import _orientation, build_member_set
from raft_tpu.core.transforms import member_orientation


def test_orientation_numpy_jnp_parity():
    """The host (numpy) and device (jnp) orientation code must agree exactly."""
    rng = np.random.default_rng(42)
    for _ in range(20):
        rA = rng.standard_normal(3) * 30
        rB = rA + rng.standard_normal(3) * 20
        gamma = float(rng.uniform(-180, 180))
        q_np, p1_np, p2_np, R_np = _orientation(rA, rB, gamma)
        q_j, p1_j, p2_j, R_j = member_orientation(
            jnp.asarray(rA), jnp.asarray(rB), jnp.deg2rad(gamma)
        )
        np.testing.assert_allclose(q_np, np.asarray(q_j), atol=1e-12)
        np.testing.assert_allclose(p1_np, np.asarray(p1_j), atol=1e-12)
        np.testing.assert_allclose(p2_np, np.asarray(p2_j), atol=1e-12)
        np.testing.assert_allclose(R_np, np.asarray(R_j), atol=1e-12)


def _spar(cap_stations, cap_t):
    return {
        "platform": {
            "members": [
                {
                    "name": "spar",
                    "type": 2,
                    "rA": [0, 0, -90.0],
                    "rB": [0, 0, 10.0],
                    "shape": "circ",
                    "stations": [-90, 10],
                    "d": 9.0,
                    "t": 0.05,
                    "cap_stations": cap_stations,
                    "cap_t": cap_t,
                    "cap_d_in": [0.0] * len(cap_stations),
                }
            ]
        },
    }


def test_near_end_bulkhead_skipped():
    # bulkhead 0.1 m above the bottom with 0.5 m thickness -> interior-cap
    # interpolation would reach past end A; must be skipped (DEVIATIONS.md #9)
    ms_near = build_member_set(_spar([-89.9], [0.5]))
    ms_none = build_member_set(_spar([], []))
    n_caps_near = int(np.asarray(ms_near.seg_is_cap & ms_near.seg_mask).sum())
    n_caps_none = int(np.asarray(ms_none.seg_is_cap & ms_none.seg_mask).sum())
    assert n_caps_near == n_caps_none == 0

    # near the top end likewise (the reference's always-false clause)
    ms_top = build_member_set(_spar([9.9], [0.5]))
    assert int(np.asarray(ms_top.seg_is_cap & ms_top.seg_mask).sum()) == 0


def test_end_and_interior_caps_kept():
    ms = build_member_set(_spar([-90.0, -50.0, 10.0], [0.5, 0.5, 0.5]))
    assert int(np.asarray(ms.seg_is_cap & ms.seg_mask).sum()) == 3


def test_cap_hole_pair_conventions():
    from raft_tpu.build.members import _cap_hole_pairs

    # rect: a [len,wid] pair broadcasts to all caps, even when ncap == 2
    np.testing.assert_array_equal(
        _cap_hole_pairs(np.array([2.0, 1.0]), 2, circ=False),
        [[2.0, 1.0], [2.0, 1.0]],
    )
    # rect single cap with a pair hole must not crash
    np.testing.assert_array_equal(
        _cap_hole_pairs(np.array([2.0, 1.0]), 1, circ=False), [[2.0, 1.0]]
    )
    # circ: per-cap hole diameters
    np.testing.assert_array_equal(
        _cap_hole_pairs(np.array([2.0, 1.0]), 2, circ=True), [[2.0, 2.0], [1.0, 1.0]]
    )
    np.testing.assert_array_equal(_cap_hole_pairs(np.array(3.0), 2, circ=True),
                                  [[3.0, 3.0], [3.0, 3.0]])
    with pytest.raises(ValueError):
        _cap_hole_pairs(np.array([1.0, 2.0, 3.0]), 2, circ=True)


@pytest.mark.slow
def test_waterline_station_no_double_count():
    """A station exactly at z=0 must not double-count waterplane terms."""
    import jax
    from raft_tpu.core.types import Env, RNA
    from raft_tpu.statics import assemble_statics

    def spar(stations):
        return {
            "platform": {
                "members": [
                    {
                        "name": "cyl", "type": 2,
                        "rA": [0, 0, -80.0], "rB": [0, 0, 20.0],
                        "shape": "circ", "stations": stations,
                        "d": 10.0, "t": 0.05,
                    }
                ]
            },
        }

    rna = RNA(mRNA=0.0, IxRNA=0.0, IrRNA=0.0, xCG_RNA=0.0, hHub=0.0)
    s1 = jax.jit(assemble_statics)(build_member_set(spar([-80, 20])), rna, Env())
    s2 = jax.jit(assemble_statics)(build_member_set(spar([-80, 0, 20])), rna, Env())
    np.testing.assert_allclose(np.asarray(s2.AWP), np.asarray(s1.AWP), rtol=1e-9)
    np.testing.assert_allclose(
        np.asarray(s2.C_hydro), np.asarray(s1.C_hydro), rtol=1e-9, atol=1e-3
    )
    np.testing.assert_allclose(np.asarray(s2.V), np.asarray(s1.V), rtol=1e-9)
