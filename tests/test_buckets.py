"""Shape-bucket ladder + mixed-design megabatch (raft_tpu/build/buckets.py,
model.stage_designs, parallel.sweep.sweep_designs).

Fast tier: ladder/bucketize/promotion host logic, frequency-padding
invariants, and one tiny padded==unpadded compile.  Slow tier: the full
parity matrix (all four shipped designs x multiple bucket classes), mixed
sweep_designs vs per-design solo solves, health verdicts on padded lanes,
chunked execution, and BEM-staged buckets.
"""
import copy
import os

import numpy as np
import pytest
import jax.numpy as jnp

from raft_tpu.build import buckets
from raft_tpu.build.members import build_member_set, member_counts
from raft_tpu.model import (
    _staged_wave,
    load_design,
    stage_design_base,
    stage_designs,
)

HERE = os.path.dirname(os.path.abspath(__file__))
DESIGN_DIR = os.path.join(HERE, "..", "raft_tpu", "designs")
ALL_DESIGNS = ["OC3spar", "VolturnUS-S", "OC4semi", "OC4semi_2"]


def _path(name):
    return os.path.join(DESIGN_DIR, name + ".yaml")


KW = dict(nw=10, Hs=8.0, Tp=12.0, w_min=0.05, w_max=2.95)


# --------------------------------------------------------------- ladder


def test_ladder_default_and_env_override(monkeypatch):
    ld = buckets.ladder()
    assert ld == buckets.DEFAULT_LADDER
    monkeypatch.setenv(buckets.ENV_VAR, "segments=8,24; nw=12,48")
    ld = buckets.ladder()
    assert ld["segments"] == (8, 24)
    assert ld["nw"] == (12, 48)
    assert ld["nodes"] == buckets.DEFAULT_LADDER["nodes"]  # untouched axis
    # the salt must follow the override (AOT keys track the ladder)
    assert "segments=8,24" in buckets.ladder_salt()[1]
    monkeypatch.delenv(buckets.ENV_VAR)
    assert "segments=8,24" not in buckets.ladder_salt()[1]


@pytest.mark.parametrize("spec", [
    "segments=8,4",              # not increasing
    "segments=0,4",              # non-positive
    "bogus=4",                   # unknown axis
    "segments=a,b",              # non-integer
    "segments 4",                # malformed entry
])
def test_ladder_rejects_bad_spec(monkeypatch, spec):
    monkeypatch.setenv(buckets.ENV_VAR, spec)
    with pytest.raises(ValueError):
        buckets.ladder()


def test_round_up_and_overflow():
    ld = {"segments": (16, 48), "nodes": (64,), "nw": (16,)}
    assert buckets.round_up(1, "segments", ld) == 16
    assert buckets.round_up(16, "segments", ld) == 16
    assert buckets.round_up(17, "segments", ld) == 48
    with pytest.raises(buckets.BucketOverflow):
        buckets.round_up(49, "segments", ld)


def test_member_counts_match_unpadded_build():
    for name in ALL_DESIGNS:
        design = load_design(_path(name))
        S, N = member_counts(design)
        m = build_member_set(design)
        assert m.seg_l.shape == (S,)
        assert m.node_dls.shape == (N,)


def test_bucketize_shipped_designs_share_classes():
    # the default ladder is sized so the four shipped designs collapse to
    # TWO buckets: OC3 + VolturnUS share the small class, the OC4s the
    # medium one — the compile-collapse claim of the hetero smoke/bench
    sigs = [buckets.bucketize(load_design(_path(n)), nw=100)
            for n in ALL_DESIGNS]
    assert sigs[0] == sigs[1]
    assert sigs[2] == sigs[3]
    assert sigs[0] != sigs[2]
    assert all(s.nw == 128 for s in sigs)


# ------------------------------------------------------------ promotion


def test_promotion_self_heals_undersized_class():
    design = load_design(_path("OC4semi"))       # 36 seg, 114 nodes
    buckets.reset_promotions()
    too_small = buckets.BucketSig(segments=16, nodes=64, nw=16)
    m, sig = buckets.build_bucketed_member_set(design, too_small)
    assert sig.segments >= 36 and sig.nodes >= 114
    assert sig.nw == 16                           # untouched by promotion
    assert m.seg_l.shape == (sig.segments,)
    assert buckets.promotion_count() == 2         # both member axes bumped
    # exact-fit class: no promotion
    m2, sig2 = buckets.build_bucketed_member_set(design, sig)
    assert sig2 == sig and buckets.promotion_count() == 2
    buckets.reset_promotions()


def test_stage_designs_promotions_are_per_call_not_cumulative():
    """DesignBatch.promotions (and so the sweep's buckets stats block)
    records THIS staging's promotions as a delta, not the process-wide
    counter: promotions performed outside the call must not leak in."""
    buckets.reset_promotions()
    staged = stage_designs([_path("OC3spar")], with_mooring=False, **KW)
    assert all(b.promotions == 0 for b in staged.values())
    # promote outside any staging call (stale undersized class)
    buckets.build_bucketed_member_set(
        load_design(_path("OC4semi")),
        buckets.BucketSig(segments=16, nodes=64, nw=16))
    assert buckets.promotion_count() == 2
    staged = stage_designs([_path("OC3spar")], with_mooring=False, **KW)
    assert all(b.promotions == 0 for b in staged.values())
    buckets.reset_promotions()


def test_promotion_raises_past_ladder_top(monkeypatch):
    monkeypatch.setenv(buckets.ENV_VAR, "segments=16;nodes=64")
    design = load_design(_path("OC4semi"))
    with pytest.raises(buckets.BucketOverflow):
        buckets.build_bucketed_member_set(
            design, buckets.BucketSig(segments=16, nodes=64, nw=None))


# ------------------------------------------------- frequency-grid padding


def test_staged_wave_padding_invariants():
    w0 = _staged_wave(10, 0.05, 2.95, 300.0, 8.0, 12.0)
    wp = _staged_wave(10, 0.05, 2.95, 300.0, 8.0, 12.0, nw_pad=16)
    assert w0.freq_mask is None                   # unbucketed: old pytree
    assert wp.w.shape == (16,)
    np.testing.assert_array_equal(np.asarray(wp.freq_mask),
                                  np.arange(16) < 10)
    # physical bins identical, padded bins: same spacing, zero amplitude
    np.testing.assert_allclose(np.asarray(wp.w[:10]), np.asarray(w0.w))
    np.testing.assert_allclose(np.diff(np.asarray(wp.w)),
                               float(w0.w[1] - w0.w[0]), rtol=1e-12)
    np.testing.assert_array_equal(np.asarray(wp.zeta[10:]), 0.0)
    np.testing.assert_allclose(np.asarray(wp.zeta[:10]),
                               np.asarray(w0.zeta))
    with pytest.raises(ValueError):
        _staged_wave(10, 0.05, 2.95, 300.0, 8.0, 12.0, nw_pad=8)


def test_padded_forward_parity_fast():
    """One tiny compile: bucket-padded OC3 (members + frequency grid)
    reproduces the unpadded solve exactly — same iteration count, padded
    bins exactly zero, physical bins at float eps."""
    from raft_tpu.parallel import forward_response

    fn = _path("OC3spar")
    _, m0, rna, env, w0, C = stage_design_base(fn, **KW)
    _, mp, _, _, wp, _ = stage_design_base(fn, bucket=True, **KW)
    assert wp.w.shape[0] == 16 and w0.w.shape[0] == 10
    o0 = forward_response(m0, rna, env, w0, C, n_iter=20, method="while")
    op = forward_response(mp, rna, env, wp, C, n_iter=20, method="while")
    assert int(o0.n_iter) == int(op.n_iter)
    a0 = np.asarray(o0.Xi.abs2())
    ap = np.asarray(op.Xi.abs2())
    np.testing.assert_array_equal(ap[10:], 0.0)   # padded bins exactly 0
    np.testing.assert_allclose(ap[:10], a0, rtol=1e-9, atol=1e-12)


# ------------------------------------------------------- staging/grouping


def test_stage_designs_groups_and_stacks():
    staged = stage_designs([_path(n) for n in ALL_DESIGNS],
                           with_mooring=False, **KW)
    assert len(staged) == 2
    D = 0
    for sig, b in staged.items():
        B = len(b.fnames)
        D += B
        assert b.members.seg_rA.shape == (B, sig.segments, 3)
        assert b.wave.w.shape == (B, sig.nw)
        assert b.wave.freq_mask.shape == (B, sig.nw)
        assert b.C_moor is None                   # with_mooring=False
        assert np.asarray(b.env.depth).shape == (B,)
        assert b.nw == KW["nw"]
    idx = sorted(i for b in staged.values() for i in b.indices)
    assert idx == list(range(4)) and D == 4


def test_stage_designs_accepts_dicts_and_validates_bems():
    d = load_design(_path("OC3spar"))
    staged = stage_designs([d, copy.deepcopy(d)], with_mooring=False, **KW)
    (b,) = staged.values()
    assert len(b.fnames) == 2
    with pytest.raises(ValueError, match="bems"):
        stage_designs([d, d], bems=[None], with_mooring=False, **KW)
    with pytest.raises(ValueError, match="every design"):
        stage_designs([d, d], bems=[None, None], with_mooring=False, **KW)


# ----------------------------------------------------- slow parity matrix


@pytest.mark.slow
@pytest.mark.parametrize("name", ALL_DESIGNS)
def test_padded_parity_multi_bucket_sizes(name):
    """Padded == unpadded at FLOAT EPS for every shipped design, at its
    natural bucket class AND one class larger on every axis — the masking
    invariant must hold regardless of how much padding the ladder adds."""
    from raft_tpu.parallel import forward_response, response_std

    fn = _path(name)
    design = load_design(fn)
    _, m0, rna, env, w0, C = stage_design_base(fn, **KW)
    o0 = forward_response(m0, rna, env, w0, C, n_iter=30, method="while")
    s0 = np.asarray(response_std(o0.Xi.abs2(), w0.w))
    scale = np.max(np.abs(s0))

    ld = buckets.ladder()
    nat = buckets.bucketize(design, nw=KW["nw"], ld=ld)

    def next_class(axis, v):
        classes = ld[axis]
        i = classes.index(v)
        return classes[min(i + 1, len(classes) - 1)]

    bigger = buckets.BucketSig(
        segments=next_class("segments", nat.segments),
        nodes=next_class("nodes", nat.nodes),
        nw=next_class("nw", nat.nw))
    for sig in (nat, bigger):
        _, mp, _, _, wp, _ = stage_design_base(fn, bucket=sig, **KW)
        op = forward_response(mp, rna, env, wp, C, n_iter=30,
                              method="while")
        assert int(op.n_iter) == int(o0.n_iter)
        sp = np.asarray(response_std(op.Xi.abs2(), wp.w))
        # scale-relative: unexcited symmetric DOFs are exact/noise zeros
        assert np.max(np.abs(sp - s0)) / scale < 1e-9
        np.testing.assert_array_equal(
            np.asarray(op.Xi.abs2())[KW["nw"]:], 0.0)


@pytest.mark.slow
def test_sweep_designs_mixed_vs_solo_with_health():
    """The megabatch contract: a mixed 4-platform batch solves per-design
    identically to solo sweeps (iteration counts included), and health
    verdicts hold on the padded lanes."""
    from raft_tpu.parallel import forward_response, response_std, sweep_designs

    fnames = [_path(n) for n in ALL_DESIGNS]
    out = sweep_designs(fnames, n_iter=30, health=True, **KW)
    assert out["buckets"]["n_buckets"] == 2
    assert out["converged"].all() and out["finite"].all()
    assert out["health"]["n_quarantined"] == 0
    for i, fn in enumerate(fnames):
        _, m, rna, env, wv, C = stage_design_base(fn, **KW)
        o = forward_response(m, rna, env, wv, C, n_iter=30)
        s = np.asarray(response_std(o.Xi.abs2(), wv.w))
        assert int(out["iterations"][i]) == int(o.n_iter)
        assert np.max(np.abs(out["std dev"][i] - s)) / np.max(np.abs(s)) < 1e-9
    # Xi_abs2 trimmed to the physical grid in design order
    assert out["Xi_abs2"].shape == (4, KW["nw"], 6)


@pytest.mark.slow
def test_sweep_designs_bad_lane_quarantined_mates_untouched():
    """Per-lane resilience inside a bucket: a NaN design (bad drag
    coefficient) is quarantined and reported unsalvaged, while its
    bucket-mates' results are BITWISE those of a clean batch."""
    from raft_tpu.parallel import sweep_designs

    d0, dv = _path("OC3spar"), _path("VolturnUS-S")
    bad = copy.deepcopy(load_design(d0))
    bad["platform"]["members"][0]["Cd"] = float("nan")
    ref = sweep_designs([d0, dv], n_iter=30, **KW)
    out = sweep_designs([d0, bad, dv], n_iter=30, health=True,
                        escalate=True, **KW)
    assert list(out["health"]["quarantined"]) == [1]
    assert list(out["health"]["unsalvaged"]) == [1]
    assert not out["finite"][1]
    assert out["converged"][[0, 2]].all() and out["finite"][[0, 2]].all()
    np.testing.assert_array_equal(out["std dev"][0], ref["std dev"][0])
    np.testing.assert_array_equal(out["std dev"][2], ref["std dev"][1])


@pytest.mark.slow
def test_sweep_designs_starved_lanes_salvaged():
    """Iteration-starved lanes walk the escalation ladder to the
    full-budget fixed point — per design, inside the padded batch."""
    from raft_tpu.parallel import sweep_designs

    fnames = [_path("OC3spar"), _path("VolturnUS-S")]
    ref = sweep_designs(fnames, n_iter=30, **KW)
    out = sweep_designs(fnames, n_iter=2, health=True, **KW)
    assert out["health"]["n_quarantined"] == 2
    assert out["health"]["salvaged"] == 2
    assert out["converged"].all()
    scale = np.max(np.abs(ref["std dev"]))
    assert np.max(np.abs(out["std dev"] - ref["std dev"])) / scale < 1e-6


@pytest.mark.slow
def test_sweep_designs_chunked_matches_unchunked():
    from raft_tpu.parallel import sweep_designs

    fnames = [_path("OC3spar"), _path("VolturnUS-S")] * 2
    ref = sweep_designs(fnames, n_iter=30, **KW)
    out = sweep_designs(fnames, n_iter=30, chunk=2, **KW)
    np.testing.assert_array_equal(out["std dev"], ref["std dev"])
    assert out["pipeline"]                        # per-bucket stats present
    # bucket sizes are emergent, so an awkward chunk request CLAMPS to a
    # divisor per bucket instead of failing: 3 + 1 lanes with chunk=2
    # degrades to lane-sized chunks, same results
    mix = fnames[:3] + [_path("OC4semi")]
    ref2 = sweep_designs(mix, n_iter=30, **KW)
    out2 = sweep_designs(mix, chunk=2, n_iter=30, **KW)
    np.testing.assert_array_equal(out2["std dev"], ref2["std dev"])


@pytest.mark.slow
def test_sweep_designs_with_staged_bem_parity():
    """Synthetic per-design BEM tuples staged batch-leading: the padded
    mixed batch matches solo forward_response with stage_bem."""
    from raft_tpu.parallel import (
        forward_response, response_std, stage_bem, sweep_designs,
    )

    fnames = [_path("OC3spar"), _path("VolturnUS-S")]
    nw = KW["nw"]

    def synth(seed):
        r = np.random.default_rng(seed)
        A = r.normal(size=(6, 6, nw)) * 1e5
        A = A + A.transpose(1, 0, 2)              # symmetric-ish
        B = np.abs(r.normal(size=(6, 6, nw))) * 1e4
        B = B + B.transpose(1, 0, 2)
        F = (r.normal(size=(6, nw)) + 1j * r.normal(size=(6, nw))) * 1e4
        return A, B, F

    bems = [synth(i) for i in range(len(fnames))]
    out = sweep_designs(fnames, bems=bems, n_iter=30, **KW)
    for i, fn in enumerate(fnames):
        _, m, rna, env, wv, C = stage_design_base(fn, **KW)
        o = forward_response(m, rna, env, wv, C,
                             bem=stage_bem(bems[i], wv), n_iter=30)
        s = np.asarray(response_std(o.Xi.abs2(), wv.w))
        assert np.max(np.abs(out["std dev"][i] - s)) / np.max(np.abs(s)) < 1e-9
    # chunk + bems compose: the BEM batch must be sliced with the lanes
    out2 = sweep_designs(fnames * 2, bems=bems * 2, n_iter=30, chunk=2, **KW)
    np.testing.assert_array_equal(out2["std dev"][:2], out["std dev"])
