"""Smoke tests for the runnable examples: every script's main() executes
end-to-end and prints sane output.  Sizes are reduced where the signature
allows; analyze/array use their (already small) defaults."""
import importlib.util
import os
import sys

import pytest

pytestmark = pytest.mark.slow

HERE = os.path.dirname(os.path.abspath(__file__))
EXAMPLES = os.path.join(HERE, "..", "examples")


def _load(name):
    spec = importlib.util.spec_from_file_location(
        f"examples_{name}", os.path.join(EXAMPLES, f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def test_sweep_designs_example(capsys):
    _load("sweep_designs").main(batch=8, nw=16)
    out = capsys.readouterr().out
    assert "8 designs x 16 bins" in out
    assert "best pitch response" in out
    # the example exercises the REAL mixed-design path: four platform
    # topologies bucketized into fewer compiled dispatches than designs
    line = [ln for ln in out.splitlines() if "shape buckets" in ln][0]
    n_buckets = int(line.split("->")[1].split()[0])
    assert 1 <= n_buckets < 8


def test_codesign_example(capsys):
    _load("codesign_opt").main(steps=2, nw=12)
    out = capsys.readouterr().out
    assert "optimized:" in out and "sigma_nac" in out


def test_dlc_table_example(capsys):
    _load("dlc_table").main(nw=12)
    out = capsys.readouterr().out
    lines = [ln for ln in out.splitlines() if "|" in ln]
    assert len(lines) == 9                      # header + 8 cases
    # the table varies heading alongside (Hs, Tp), so the severity-monotone
    # quantity is the horizontal response magnitude, not surge alone
    horiz = []
    for ln in lines[1:]:
        cols = ln.split("|")[1].split()
        horiz.append(float(cols[0]) ** 2 + float(cols[1]) ** 2)
    assert horiz == sorted(horiz)               # monotone in severity
    # headings actually act: the off-axis cases put energy into sway
    sway = [float(ln.split("|")[1].split()[1]) for ln in lines[1:]]
    assert sway[0] < 1e-6 < sway[-1]
    # and the short-crested demo ran with nonzero spread sway
    sc = [ln for ln in out.splitlines() if ln.startswith("short-crested")]
    assert len(sc) == 1 and float(sc[0].split("sway std ")[1]) > 1e-6


def test_design_checks_example(capsys):
    _load("design_checks").main(nw=16)
    out = capsys.readouterr().out
    assert "slack line margin" in out and "air gap" in out
    # OC3 with a 12 m deck in 10 m seas screens OK on every check
    assert "RISK" not in out and "EXCEEDED" not in out
    assert "critical deck point" in out


def test_analyze_example(capsys):
    _load("analyze_oc3").main()
    out = capsys.readouterr().out
    assert "natural frequencies" in out
    assert "surge RAO peak" in out


def test_array_farm_example(capsys):
    _load("array_farm").main()
    out = capsys.readouterr().out
    assert "3 turbines, nDOF 18" in out
    assert "phase" in out