"""Interpret-mode validation of the Pallas 6x6 complex-solve kernel.

The Mosaic (TPU) compiler is unavailable on this CPU host, so these tests
run the kernel through the Pallas interpreter — same kernel code, same
lane-major layout, bit-compared against the XLA implementation
(:mod:`raft_tpu.core.linalg6`) that the solver uses on non-TPU
backends.  On TPU the kernel is ON by default — a measured decision
(18x end-to-end on the north star, see ``core/pallas6.py``); on the
pinned-CPU test backend :func:`pallas6.enabled`'s auto mode stays off,
so these tests exercise the kernel explicitly via interpret mode and
the RAFT_TPU_PALLAS=1 force-on knob.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from raft_tpu.core.cplx import Cx
from raft_tpu.core.linalg6 import assemble_impedance, solve_cx, solve_cx_fused
from raft_tpu.core.pallas6 import (
    solve_cx_pallas,
    solve_cx_pallas_ad,
    solve_rao_pallas,
    solve_rao_pallas_ad,
)


def _random_systems(B, rng):
    Ar = rng.normal(size=(B, 6, 6)) + 6 * np.eye(6)
    Ai = rng.normal(size=(B, 6, 6))
    br = rng.normal(size=(B, 6))
    bi = rng.normal(size=(B, 6))
    return (Cx(jnp.asarray(Ar), jnp.asarray(Ai)),
            Cx(jnp.asarray(br), jnp.asarray(bi)))


def test_matches_linalg6_including_padding():
    """700 systems (not a block multiple, so the pad lanes engage) agree
    with the unrolled XLA elimination to machine epsilon."""
    A, b = _random_systems(700, np.random.default_rng(0))
    x_ref = solve_cx(A, b)
    x_pal = solve_cx_pallas(A, b)
    np.testing.assert_allclose(np.asarray(x_pal.re), np.asarray(x_ref.re),
                               rtol=0, atol=1e-13)
    np.testing.assert_allclose(np.asarray(x_pal.im), np.asarray(x_ref.im),
                               rtol=0, atol=1e-13)


@pytest.mark.slow
def test_pivot_permutation_exact():
    """A permutation matrix has a zero first pivot: only the lane-wise
    one-hot pivoting path solves it (exactly)."""
    rng = np.random.default_rng(1)
    P = np.zeros((6, 6))
    P[np.arange(6), (np.arange(6) + 1) % 6] = 1.0
    A = Cx(jnp.asarray(np.broadcast_to(P, (4, 6, 6)).copy()),
           jnp.zeros((4, 6, 6)))
    b = Cx(jnp.asarray(rng.normal(size=(4, 6))),
           jnp.asarray(rng.normal(size=(4, 6))))
    x = solve_cx_pallas(A, b)
    res = np.einsum("ij,bj->bi", P, np.asarray(x.to_complex()))
    np.testing.assert_allclose(res, np.asarray(b.to_complex()), atol=1e-15)


@pytest.mark.slow
def test_vmap_composes():
    """The kernel batches under vmap (the design-sweep usage pattern)."""
    A, b = _random_systems(4 * 96, np.random.default_rng(2))
    A4 = Cx(A.re.reshape(4, 96, 6, 6), A.im.reshape(4, 96, 6, 6))
    b4 = Cx(b.re.reshape(4, 96, 6), b.im.reshape(4, 96, 6))
    x_v = jax.vmap(lambda a, c: solve_cx_pallas(a, c, block=128))(A4, b4)
    x_ref = solve_cx(A, b)
    np.testing.assert_allclose(np.asarray(x_v.re).reshape(-1, 6),
                               np.asarray(x_ref.re), rtol=0, atol=1e-13)


def test_adjoint_grad_matches_xla():
    """Reverse-mode through ``solve_cx_pallas_ad`` (the analytic
    ``A^H lam = xbar`` adjoint rule) must equal reverse-mode through the
    XLA elimination itself, for BOTH the matrix and RHS cotangents.  The
    loss weights re and im asymmetrically so a conjugation or re/im swap
    in the hand-derived pair algebra cannot cancel out."""
    A, b = _random_systems(96, np.random.default_rng(3))

    def make_loss(solver):
        def loss(A, b):
            x = solver(A, b)
            return jnp.sum(x.re ** 2 + 0.7 * x.im ** 2 + 0.3 * x.re * x.im)
        return loss

    gA_p, gb_p = jax.grad(make_loss(solve_cx_pallas_ad), argnums=(0, 1))(A, b)
    gA_r, gb_r = jax.grad(make_loss(solve_cx), argnums=(0, 1))(A, b)
    for got, ref in ((gA_p.re, gA_r.re), (gA_p.im, gA_r.im),
                     (gb_p.re, gb_r.re), (gb_p.im, gb_r.im)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-9, atol=1e-11)


@pytest.mark.slow
def test_scan_grad_pallas_matches_xla(monkeypatch):
    """The full differentiable fixed point (``method="scan"``) produces
    the same gradient with the Pallas path (custom_vjp adjoint inside
    every scan step, through the remat wrapper) as with the XLA path."""
    from test_solve import setup
    from raft_tpu.solve import solve_dynamics

    m, kin, wave, env, lin = setup()

    def loss(scale):
        lin2 = lin.replace(F=Cx(lin.F.re * scale, lin.F.im * scale))
        o = solve_dynamics(m, kin, wave, env, lin2, method="scan")
        return jnp.sum(o.Xi.abs2())

    monkeypatch.setenv("RAFT_TPU_PALLAS", "0")
    g_xla = float(jax.grad(loss)(jnp.asarray(1.0)))
    monkeypatch.setenv("RAFT_TPU_PALLAS", "1")
    g_pal = float(jax.grad(loss)(jnp.asarray(1.0)))
    assert np.isfinite(g_pal)
    np.testing.assert_allclose(g_pal, g_xla, rtol=1e-8)


@pytest.mark.slow
def test_solver_flag_switches_both_drivers(monkeypatch):
    """RAFT_TPU_PALLAS=1 routes the while-loop driver's solves through the
    kernel (same answer) — the flag is read outside the jitted core, so
    toggling it mid-process takes effect without any cache clearing; the
    scan driver's gradients flow through the kernel's adjoint rule."""
    from test_solve import setup
    from raft_tpu.solve import solve_dynamics

    m, kin, wave, env, lin = setup()
    base = solve_dynamics(m, kin, wave, env, lin, method="while")
    monkeypatch.setenv("RAFT_TPU_PALLAS", "1")
    out = solve_dynamics(m, kin, wave, env, lin, method="while")
    np.testing.assert_allclose(np.asarray(out.Xi.re),
                               np.asarray(base.Xi.re), rtol=1e-12)
    assert int(out.n_iter) == int(base.n_iter)

    def loss(scale):
        lin2 = lin.replace(F=Cx(lin.F.re * scale, lin.F.im * scale))
        o = solve_dynamics(m, kin, wave, env, lin2, method="scan")
        return jnp.sum(o.Xi.abs2())

    g = jax.grad(loss)(jnp.asarray(1.0))
    assert np.isfinite(float(g)) and float(g) != 0.0


def _random_rao_systems(nw, rng, batch=()):
    """Well-conditioned fused-representation systems (Z0, w, B_drag, F)."""
    lead = batch + (nw,)
    Z0 = Cx(jnp.asarray(rng.normal(size=lead + (6, 6)) + 8 * np.eye(6)),
            jnp.asarray(0.3 * rng.normal(size=lead + (6, 6))))
    w = jnp.asarray(rng.uniform(0.1, 3.0, lead))
    Bd = jnp.asarray(rng.normal(size=batch + (6, 6)))
    F = Cx(jnp.asarray(rng.normal(size=lead + (6,))),
           jnp.asarray(rng.normal(size=lead + (6,))))
    return Z0, w, Bd, F


def test_fused_kernel_matches_unfused_bitwise():
    """Interpreter-mode ``solve_rao_pallas`` equals the UNFUSED pipeline
    (explicit Z assembly -> ``solve_cx``) to machine epsilon on random
    well-conditioned systems — including a lane count that engages the
    pad path — and equals the fused XLA fallback the same way."""
    Z0, w, Bd, F = _random_rao_systems(173, np.random.default_rng(10))
    x_unfused = solve_cx(assemble_impedance(Z0, w, Bd), F)
    x_xla = solve_cx_fused(Z0, w, Bd, F)
    x_pal = solve_rao_pallas(Z0, w, Bd, F)
    # the XLA fallback IS the unfused expression (same assembly, fused
    # only by the compiler): bit-identical
    np.testing.assert_array_equal(np.asarray(x_xla.re),
                                  np.asarray(x_unfused.re))
    np.testing.assert_array_equal(np.asarray(x_xla.im),
                                  np.asarray(x_unfused.im))
    for got, ref in ((x_pal.re, x_unfused.re), (x_pal.im, x_unfused.im)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=0, atol=1e-13)


def test_fused_kernel_pivoting_stressed():
    """A permutation-matrix ``Z0`` with zero drag has a zero first pivot:
    only the lane-wise one-hot pivot path inside the fused kernel solves
    it (exactly) — the assembly fusion must not bypass pivoting."""
    rng = np.random.default_rng(11)
    P = np.zeros((6, 6))
    P[np.arange(6), (np.arange(6) + 1) % 6] = 1.0
    nw = 4
    Z0 = Cx(jnp.asarray(np.broadcast_to(P, (nw, 6, 6)).copy()),
            jnp.zeros((nw, 6, 6)))
    w = jnp.zeros((nw,))                   # zero drag term: Z == P exactly
    Bd = jnp.asarray(rng.normal(size=(6, 6)))
    F = Cx(jnp.asarray(rng.normal(size=(nw, 6))),
           jnp.asarray(rng.normal(size=(nw, 6))))
    x = solve_rao_pallas(Z0, w, Bd, F)
    res = np.einsum("ij,bj->bi", P, np.asarray(x.to_complex()))
    np.testing.assert_allclose(res, np.asarray(F.to_complex()), atol=1e-15)


def test_fused_adjoint_grad_matches_xla():
    """Reverse-mode through ``solve_rao_pallas_ad`` (the fused-
    representation adjoint: same kernel on ``(Z0^H, w, -B_drag^T)``)
    equals reverse-mode through the XLA fused expression for ALL four
    cotangents — including the frequency and drag-matrix ones that only
    exist in the fused representation."""
    Z0, w, Bd, F = _random_rao_systems(96, np.random.default_rng(12))

    def make_loss(solver):
        def loss(Z0, w, Bd, F):
            x = solver(Z0, w, Bd, F)
            return jnp.sum(x.re ** 2 + 0.7 * x.im ** 2 + 0.3 * x.re * x.im)
        return loss

    g_p = jax.grad(make_loss(solve_rao_pallas_ad), argnums=(0, 1, 2, 3))(
        Z0, w, Bd, F)
    g_r = jax.grad(make_loss(solve_cx_fused), argnums=(0, 1, 2, 3))(
        Z0, w, Bd, F)
    for got, ref, name in (
            (g_p[0].re, g_r[0].re, "Z0.re"), (g_p[0].im, g_r[0].im, "Z0.im"),
            (g_p[1], g_r[1], "w"), (g_p[2], g_r[2], "B_drag"),
            (g_p[3].re, g_r[3].re, "F.re"), (g_p[3].im, g_r[3].im, "F.im")):
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-9, atol=1e-11, err_msg=name)


@pytest.mark.slow
def test_fused_kernel_vmap_composes():
    """The fused kernel batches under vmap (the design-sweep pattern:
    per-lane Z0/F/B_drag, shared w)."""
    Z0, w, Bd, F = _random_rao_systems(24, np.random.default_rng(13),
                                       batch=(5,))
    w1 = w[0]                                # shared frequency grid
    x_v = jax.vmap(lambda z, bd, f: solve_rao_pallas(z, w1, bd, f))(Z0, Bd, F)
    x_ref = jax.vmap(lambda z, bd, f: solve_cx_fused(z, w1, bd, f))(Z0, Bd, F)
    np.testing.assert_allclose(np.asarray(x_v.re), np.asarray(x_ref.re),
                               rtol=0, atol=1e-13)
    np.testing.assert_allclose(np.asarray(x_v.im), np.asarray(x_ref.im),
                               rtol=0, atol=1e-13)


def test_enabled_knob_parsing(monkeypatch):
    """Affirmative spellings force the kernel on, negative spellings force
    it off, and a malformed value degrades to auto (with a warning) rather
    than silently opting out of the measured TPU default."""
    import warnings
    from raft_tpu.core import pallas6

    for v in ("1", "true", "ON", "Yes"):
        monkeypatch.setenv("RAFT_TPU_PALLAS", v)
        assert pallas6.enabled() is True
    for v in ("0", "false", "OFF", "no"):
        monkeypatch.setenv("RAFT_TPU_PALLAS", v)
        assert pallas6.enabled() is False
    auto = jax.default_backend() == "tpu"
    monkeypatch.setenv("RAFT_TPU_PALLAS", "maybe")
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        assert pallas6.enabled() is auto
    assert any("RAFT_TPU_PALLAS" in str(r.message) for r in rec)
    # empty means SET-but-malformed: auto, with a warning (the pre-round-5
    # rule forced the kernel off for "", so the flip must be visible)
    monkeypatch.setenv("RAFT_TPU_PALLAS", "")
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        assert pallas6.enabled() is auto
    assert any("empty" in str(r.message) for r in rec)
    monkeypatch.delenv("RAFT_TPU_PALLAS")
    assert pallas6.enabled() is auto
