"""Mooring tests.

Oracles:
  * independent numerical integration of the elastic catenary ODE in NumPy —
    given the solved (H, V), integrating dx/ds, dz/ds over unstretched
    arclength from anchor to fairlead must recover the imposed spans;
  * taut-line limit: tension ~ EA * strain along the chord;
  * the published OC3-Hywind mooring system: surge stiffness at zero offset
    ~41.2 kN/m (Jonkman, NREL/TP-500-47535, Table 7-2 equivalent), symmetric
    3-line geometry force balance;
  * finite-difference check of the autodiff stiffness.
"""
import pytest
import jax
import jax.numpy as jnp
import numpy as np
import yaml

from raft_tpu.mooring import (
    LineProps,
    mooring_force,
    mooring_stiffness,
    parse_mooring,
    solve_catenary,
    solve_equilibrium,
)

RHO, G = 1025.0, 9.81


def integrate_catenary(H, V, L, w, EA, n=200_000):
    """NumPy ODE oracle: spans from anchor to fairlead for given (H, V)."""
    s = np.linspace(0.0, L, n)                 # unstretched arclength
    Vv = V - w * (L - s)                       # vertical tension (suspended)
    hanging = Vv > 0.0
    Vv = np.maximum(Vv, 0.0)
    T = np.sqrt(H * H + Vv * Vv)
    dxds = np.where(hanging, (1.0 + T / EA) * H / T, 1.0 + H / EA)
    dzds = np.where(hanging, (1.0 + T / EA) * Vv / T, 0.0)
    return np.trapezoid(dxds, s), np.trapezoid(dzds, s)


def check_roundtrip(xf, zf, L, w, EA, tol=1e-3):
    p = LineProps(L=jnp.asarray(L), w=jnp.asarray(w), EA=jnp.asarray(EA))
    st = solve_catenary(jnp.asarray(xf), jnp.asarray(zf), p)
    assert float(st.residual) < 1e-6 * max(xf, zf)
    x_ode, z_ode = integrate_catenary(float(st.H), float(st.V), L, w, EA)
    np.testing.assert_allclose(x_ode, xf, rtol=tol)
    np.testing.assert_allclose(z_ode, zf, rtol=tol)


def test_catenary_slack_with_touchdown():
    # OC3-like chain: large span, much of the line on the seabed
    check_roundtrip(xf=848.67, zf=250.0, L=902.2, w=698.1, EA=384.243e6)


def test_catenary_fully_suspended():
    check_roundtrip(xf=650.0, zf=300.0, L=730.0, w=698.1, EA=384.243e6)


def test_catenary_taut_limit():
    L, w, EA = 400.0, 100.0, 1e9
    xf, zf = 350.0, 220.0                      # chord 413.6 m > L: taut
    p = LineProps(L=jnp.asarray(L), w=jnp.asarray(w), EA=jnp.asarray(EA))
    st = solve_catenary(jnp.asarray(xf), jnp.asarray(zf), p)
    chord = np.hypot(xf, zf)
    T_est = EA * (chord - L) / L
    assert abs(float(st.Tf) - T_est) / T_est < 0.1


def test_catenary_batch_matches_scalar():
    xs = jnp.array([848.67, 650.0, 700.0])
    zs = jnp.array([250.0, 300.0, 280.0])
    p = LineProps(
        L=jnp.array([902.2, 730.0, 800.0]),
        w=jnp.full(3, 698.1),
        EA=jnp.full(3, 384.243e6),
    )
    st = solve_catenary(xs, zs, p)
    for i in range(3):
        pi = LineProps(L=p.L[i], w=p.w[i], EA=p.EA[i])
        sti = solve_catenary(xs[i], zs[i], pi)
        np.testing.assert_allclose(float(st.H[i]), float(sti.H), rtol=1e-8)


# ------------------------------------------------------------- OC3 system


def oc3_system():
    with open("raft_tpu/designs/OC3spar.yaml") as f:
        design = yaml.safe_load(f)
    return parse_mooring(
        design["mooring"],
        yaw_stiffness=design["turbine"]["yaw_stiffness"],
    )


def test_oc3_zero_offset_balance():
    sys = oc3_system()
    F = mooring_force(sys, jnp.zeros(6))
    # symmetric 3-line layout: horizontal forces and x/y moments cancel
    assert abs(float(F[0])) < 1e3
    assert abs(float(F[1])) < 1e3
    # net vertical line pull is downward, order of the total wet line weight
    assert float(F[2]) < 0
    assert 0.3e6 < -float(F[2]) < 3e6


def test_oc3_surge_stiffness_matches_published():
    sys = oc3_system()
    C = mooring_stiffness(sys, jnp.zeros(6))
    # published OC3-Hywind effective surge stiffness ~41.2 kN/m about zero
    assert 30e3 < float(C[0, 0]) < 55e3
    # symmetry: surge and sway stiffness equal for the 120-degree layout
    np.testing.assert_allclose(float(C[0, 0]), float(C[1, 1]), rtol=0.05)
    # yaw spring folded in
    C_no = mooring_stiffness(sys.replace(yaw_stiffness=0.0), jnp.zeros(6))
    np.testing.assert_allclose(
        float(C[5, 5] - C_no[5, 5]), 98340000.0, rtol=1e-6
    )


@pytest.mark.slow
def test_stiffness_matches_finite_difference():
    sys = oc3_system()
    r6 = jnp.array([5.0, 1.0, -0.5, 0.01, 0.02, 0.005])
    C = np.asarray(mooring_stiffness(sys.replace(yaw_stiffness=0.0), r6))
    h = 1e-4
    C_fd = np.zeros((6, 6))
    for j in range(6):
        e = np.zeros(6)
        e[j] = h
        Fp = np.asarray(mooring_force(sys, r6 + jnp.asarray(e)))
        Fm = np.asarray(mooring_force(sys, r6 - jnp.asarray(e)))
        C_fd[:, j] = -(Fp - Fm) / (2 * h)
    np.testing.assert_allclose(C, C_fd, rtol=5e-3, atol=20.0)


@pytest.mark.slow
def test_equilibrium_under_thrust():
    sys = oc3_system()
    # body restoring: plausible OC3 hydrostatic + gravity stiffness
    C_body = jnp.diag(jnp.array([0.0, 0.0, 3.3e5, 1.3e9, 1.3e9, 0.0]))
    thrust = 800e3
    F_const = jnp.array([thrust, 0.0, 0.0, 0.0, thrust * 90.0, 0.0])
    # cancel the mean vertical line pull so heave stays near zero
    F0 = mooring_force(sys, jnp.zeros(6))
    F_const = F_const.at[2].add(-float(F0[2]))
    r6, res = solve_equilibrium(sys, F_const, C_body)
    # residual small relative to applied load
    assert float(res) < 1e-3 * thrust
    # surge offset tens of meters against ~41 kN/m net surge stiffness
    assert 10.0 < float(r6[0]) < 40.0
    assert abs(float(r6[1])) < 1.0


@pytest.mark.slow
def test_equilibrium_gradient_flows():
    sys = oc3_system()
    C_body = jnp.diag(jnp.array([0.0, 0.0, 3.3e5, 1.3e9, 1.3e9, 0.0]))

    def surge_offset(thrust):
        F_const = jnp.array([thrust, 0.0, 0.0, 0.0, thrust * 90.0, 0.0])
        F0 = mooring_force(sys, jnp.zeros(6))
        F_const = F_const.at[2].add(-F0[2])
        r6, _ = solve_equilibrium(sys, F_const, C_body)
        return r6[0]

    g = jax.grad(surge_offset)(800e3)
    h = 1e2
    fd = (surge_offset(800e3 + h) - surge_offset(800e3 - h)) / (2 * h)
    np.testing.assert_allclose(float(g), float(fd), rtol=1e-3)


def test_catenary_seabed_friction_roundtrip():
    """Forward-generate (xf, zf) from known (H, V) with CB > 0 via the
    closed-form profile, then check solve_catenary recovers (H, V) — and
    that friction reduces the anchor tension by CB*w*LB."""
    from raft_tpu.mooring.catenary import _profile_residual

    p = LineProps(
        L=jnp.asarray(900.0), w=jnp.asarray(1000.0), EA=jnp.asarray(1e9),
        CB=jnp.asarray(1.0),
    )
    H0, V0 = jnp.asarray(2.0e5), jnp.asarray(5.0e5)     # touchdown: V < w L
    rx, rz = _profile_residual(H0, V0, 0.0, 0.0, p)     # residual at (0,0)
    xf, zf = rx, rz                                      # = closed-form spans
    st = solve_catenary(xf, zf, p)
    assert float(st.residual) < 1e-6
    np.testing.assert_allclose(float(st.H), 2.0e5, rtol=1e-8)
    np.testing.assert_allclose(float(st.V), 5.0e5, rtol=1e-8)
    LB = 900.0 - 5.0e5 / 1000.0
    np.testing.assert_allclose(
        float(st.Ta), max(2.0e5 - 1.0 * 1000.0 * LB, 0.0), rtol=1e-8
    )
    # same spans with CB=0: friction reduces the grounded-portion stretch,
    # so the frictional line needs (slightly) more H to span the same xf
    st0 = solve_catenary(xf, zf, LineProps(L=p.L, w=p.w, EA=p.EA))
    assert float(st0.residual) < 1e-6
    assert float(st.H) > float(st0.H)


def test_catenary_friction_slack_anchor():
    """CB large enough that tension hits zero before the anchor: anchor
    tension is exactly zero and the solve still converges."""
    p = LineProps(
        L=jnp.asarray(900.0), w=jnp.asarray(1000.0), EA=jnp.asarray(1e9),
        CB=jnp.asarray(2.0),
    )
    from raft_tpu.mooring.catenary import _profile_residual

    H0, V0 = jnp.asarray(1.0e5), jnp.asarray(4.0e5)
    LB = 900.0 - 4.0e5 / 1000.0                          # 500 m grounded
    assert 1.0e5 - 2.0 * 1000.0 * LB < 0                 # slack before anchor
    rx, rz = _profile_residual(H0, V0, 0.0, 0.0, p)
    st = solve_catenary(rx, rz, p)
    assert float(st.residual) < 1e-6
    np.testing.assert_allclose(float(st.H), 1.0e5, rtol=1e-7)
    assert float(st.Ta) == 0.0
