"""Worker for tests/test_multihost.py — NOT a test module.

Each of the two coordinated processes runs this same program (SPMD):
join the distributed runtime, build the identical OC3 model, solve the
RAO with the frequency axis sharded over the GLOBAL 8-device mesh
(2 processes x 4 virtual CPU devices; the psum/pmax collectives cross
the process boundary), gather the result, and print it from rank 0 for
the parent test to compare against the single-process solve.
"""
import sys

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)      # match the test oracle

pid = int(sys.argv[1])
port = sys.argv[2]

from raft_tpu.parallel.multihost import global_mesh, init_multihost  # noqa: E402

init_multihost(f"localhost:{port}", num_processes=2, process_id=pid)

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.experimental import multihost_utils  # noqa: E402

import __graft_entry__ as ge  # noqa: E402
from raft_tpu.mooring import mooring_stiffness, parse_mooring  # noqa: E402
from raft_tpu.parallel import forward_response_freq_sharded  # noqa: E402

assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()

design, members, rna, env, wave = ge._base(nw=8)
moor = parse_mooring(design["mooring"],
                     yaw_stiffness=design["turbine"]["yaw_stiffness"])
C_moor = mooring_stiffness(moor, jnp.zeros(6))

mesh = global_mesh(("freq",))
out = forward_response_freq_sharded(members, rna, env, wave, C_moor,
                                    mesh=mesh, method="while")
Xi_re = multihost_utils.process_allgather(out.Xi.re, tiled=True)
Xi_im = multihost_utils.process_allgather(out.Xi.im, tiled=True)
if pid == 0:
    flat = np.stack([np.asarray(Xi_re), np.asarray(Xi_im)]).ravel()
    print("XI", " ".join(f"{v:.17e}" for v in flat), flush=True)
    print("NITER", int(out.n_iter), flush=True)
