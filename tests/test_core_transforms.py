import jax.numpy as jnp
import numpy as np

from raft_tpu.core import transforms as tf


def test_alternator_cross_identity():
    rng = np.random.default_rng(0)
    r = rng.normal(size=3)
    f = rng.normal(size=3)
    H = np.asarray(tf.alternator(jnp.asarray(r)))
    # H(r) @ f == f x r  and  H.T @ f == r x f
    np.testing.assert_allclose(H @ f, np.cross(f, r), atol=1e-12)
    np.testing.assert_allclose(H.T @ f, np.cross(r, f), atol=1e-12)


def test_translate_force_moment():
    r = np.array([1.0, -2.0, 3.0])
    f = np.array([10.0, 0.0, -5.0])
    out = np.asarray(tf.translate_force_3to6(jnp.asarray(r), jnp.asarray(f)))
    np.testing.assert_allclose(out[:3], f)
    np.testing.assert_allclose(out[3:], np.cross(r, f))


def test_translate_matrix_3to6_point_mass():
    # A point mass m at r must produce the standard 6x6: inertia m*(|r|^2 I - r r^T)
    m = 7.5
    r = np.array([2.0, 1.0, -3.0])
    M3 = m * np.eye(3)
    M6 = np.asarray(tf.translate_matrix_3to6(jnp.asarray(r), jnp.asarray(M3)))
    I_expect = m * ((r @ r) * np.eye(3) - np.outer(r, r))
    np.testing.assert_allclose(M6[:3, :3], M3)
    np.testing.assert_allclose(M6[3:, 3:], I_expect, rtol=1e-12)
    # Coupling block: J' = m H(r); check against moment of a unit acceleration
    # force: (M6 @ [a,0]) moments = r x (m a)
    a = np.array([1.0, 0.0, 0.0, 0.0, 0.0, 0.0])
    np.testing.assert_allclose((M6 @ a)[3:], np.cross(r, m * a[:3]), atol=1e-12)


def test_translate_matrix_6to6_roundtrip():
    rng = np.random.default_rng(1)
    # build a random symmetric 6x6 about CG, translate out and back
    A = rng.normal(size=(6, 6))
    M = A + A.T + 12 * np.eye(6)
    r = rng.normal(size=3)
    M1 = tf.translate_matrix_6to6(jnp.asarray(r), jnp.asarray(M))
    M2 = np.asarray(tf.translate_matrix_6to6(jnp.asarray(-r), M1))
    np.testing.assert_allclose(M2, M, rtol=1e-9, atol=1e-9)


def test_translate_matrix_6to6_agrees_with_3to6():
    m = 3.0
    r = np.array([0.5, -1.5, 2.0])
    M6 = np.zeros((6, 6))
    M6[:3, :3] = m * np.eye(3)
    out6 = np.asarray(tf.translate_matrix_6to6(jnp.asarray(r), jnp.asarray(M6)))
    out3 = np.asarray(tf.translate_matrix_3to6(jnp.asarray(r), jnp.asarray(m * np.eye(3))))
    np.testing.assert_allclose(out6, out3, atol=1e-12)


def test_member_orientation_vertical():
    rA = jnp.array([0.0, 0.0, -120.0])
    rB = jnp.array([0.0, 0.0, 10.0])
    q, p1, p2, R = tf.member_orientation(rA, rB, jnp.asarray(0.0))
    np.testing.assert_allclose(np.asarray(q), [0, 0, 1], atol=1e-12)
    # R maps local z to global q
    np.testing.assert_allclose(np.asarray(R @ jnp.array([0.0, 0.0, 1.0])), np.asarray(q), atol=1e-12)
    # orthonormal triad
    np.testing.assert_allclose(np.asarray(jnp.cross(q, p1)), np.asarray(p2), atol=1e-12)


def test_member_orientation_inclined_triad():
    rng = np.random.default_rng(2)
    rA = rng.normal(size=3)
    rB = rA + rng.normal(size=3)
    q, p1, p2, R = tf.member_orientation(jnp.asarray(rA), jnp.asarray(rB), jnp.asarray(0.3))
    q, p1, p2, R = map(np.asarray, (q, p1, p2, R))
    np.testing.assert_allclose(q, (rB - rA) / np.linalg.norm(rB - rA), atol=1e-12)
    for v in (q, p1, p2):
        np.testing.assert_allclose(np.linalg.norm(v), 1.0, atol=1e-12)
    np.testing.assert_allclose(p1 @ q, 0.0, atol=1e-12)
    np.testing.assert_allclose(R @ R.T, np.eye(3), atol=1e-12)
    # R columns are images of the local basis; local z -> q
    np.testing.assert_allclose(R[:, 2], q, atol=1e-12)


def test_small_rotation_displacement():
    r = np.array([1.0, 2.0, 3.0])
    th = np.array([0.01, -0.02, 0.005])
    out = np.asarray(tf.small_rotation_displacement(jnp.asarray(r), jnp.asarray(th)))
    np.testing.assert_allclose(out, np.cross(th, r), atol=1e-15)


def test_heading_rotation_pattern():
    # 120-degree pattern of a point must form an equilateral triangle set
    p = np.array([10.0, 0.0, -5.0])
    Rz = np.asarray(tf.heading_rotation(jnp.asarray(120.0)))
    p2 = Rz @ p
    assert abs(np.linalg.norm(p2[:2]) - 10.0) < 1e-12
    assert abs(p2[2] - p[2]) < 1e-12
    # three applications come back around
    p3 = Rz @ Rz @ Rz @ p
    np.testing.assert_allclose(p3, p, atol=1e-9)


def test_batched_broadcasting():
    rng = np.random.default_rng(3)
    r = rng.normal(size=(5, 3))
    M = rng.normal(size=(5, 6, 6))
    out = tf.translate_matrix_6to6(jnp.asarray(r), jnp.asarray(M))
    assert out.shape == (5, 6, 6)
    for i in range(5):
        one = tf.translate_matrix_6to6(jnp.asarray(r[i]), jnp.asarray(M[i]))
        np.testing.assert_allclose(np.asarray(out[i]), np.asarray(one), atol=1e-12)
