"""Co-design optimization: objective correctness, exact gradients, descent.

The capability under test is BASELINE.json configs[4] — "jax.grad of
nacelle-accel std-dev w.r.t. platform geometry params" driving a WEIS-style
inner loop.  Gradients are checked against central finite differences of
the same pipeline; the optimizer is checked to actually descend its
objective on the OC3 spar.
"""
import numpy as np
import pytest
import jax.numpy as jnp

import __graft_entry__ as ge
from raft_tpu.mooring import mooring_stiffness, parse_mooring
from raft_tpu.parallel import (
    forward_response,
    grad_nacelle_accel_std,
    nacelle_accel_std,
    optimize_design,
)


@pytest.fixture(scope="module")
def oc3():
    design, members, rna, env, wave = ge._base(nw=24)
    moor = parse_mooring(
        design["mooring"], yaw_stiffness=design["turbine"]["yaw_stiffness"]
    )
    C_moor = mooring_stiffness(moor, jnp.zeros(6))
    return members, rna, env, wave, C_moor


def _sigma_nac(oc3, s):
    members, rna, env, wave, C_moor = oc3
    from raft_tpu.parallel import scale_diameters

    out = forward_response(
        scale_diameters(members, jnp.asarray(s)), rna, env, wave, C_moor,
        n_iter=25, method="scan",
    )
    return float(nacelle_accel_std(out.Xi, wave, rna))


def test_nacelle_objective_matches_manual_sum(oc3):
    members, rna, env, wave, C_moor = oc3
    out = forward_response(members, rna, env, wave, C_moor, n_iter=25)
    sigma = float(nacelle_accel_std(out.Xi, wave, rna))
    Xi = np.asarray(out.Xi.to_complex())
    w = np.asarray(wave.w)
    a = -(w**2) * (Xi[:, 0] + float(rna.hHub) * Xi[:, 4])
    dw = float(w[1] - w[0])
    assert sigma == pytest.approx(np.sqrt((np.abs(a) ** 2).sum() * dw), rel=1e-10)
    assert sigma > 0.01                      # Hs=8 seas excite the nacelle


def test_grad_matches_finite_difference(oc3):
    members, rna, env, wave, C_moor = oc3
    g = float(grad_nacelle_accel_std(members, rna, env, wave, C_moor, 1.0))
    h = 1e-4
    fd = (_sigma_nac(oc3, 1.0 + h) - _sigma_nac(oc3, 1.0 - h)) / (2 * h)
    assert g == pytest.approx(fd, rel=2e-3)


@pytest.mark.slow
def test_optimizer_descends(oc3):
    members, rna, env, wave, C_moor = oc3
    res = optimize_design(
        members, rna, env, wave, C_moor, theta0=1.0,
        steps=6, learning_rate=0.02, bounds=(0.8, 1.25), n_iter=25,
    )
    assert res.history[-1] < res.history[0] - 1e-4, res.history
    assert 0.8 <= float(res.theta) <= 1.25
    assert np.isfinite(res.history).all()
    # trajectory bookkeeping is consistent
    assert res.thetas.shape[0] == res.history.shape[0] == 7
    assert res.objective == pytest.approx(res.history[-1])


def test_grad_with_staged_bem_matches_fd(oc3):
    """Co-design gradient with potential-flow coefficients staged: the BEM
    terms are held constant (nominal hull), the statics/Morison/drag
    dependence differentiates exactly."""
    from raft_tpu.parallel import stage_bem

    members, rna, env, wave, C_moor = oc3
    nw = int(wave.w.shape[0])
    rng = np.random.default_rng(2)
    A = np.tile(np.eye(6)[:, :, None] * 4e6, (1, 1, nw))
    B = np.tile(np.eye(6)[:, :, None] * 2e5, (1, 1, nw))
    F = (rng.normal(size=(6, nw)) + 1j * rng.normal(size=(6, nw))) * 2e5
    bem = stage_bem((A, B, F), wave)

    def f(s):
        from raft_tpu.parallel import scale_diameters

        out = forward_response(
            scale_diameters(members, jnp.asarray(s)), rna, env, wave, C_moor,
            bem=bem, n_iter=25, method="scan",
        )
        return float(nacelle_accel_std(out.Xi, wave, rna))

    g = float(grad_nacelle_accel_std(members, rna, env, wave, C_moor, 1.0,
                                     bem=bem))
    h = 1e-4
    fd = (f(1.0 + h) - f(1.0 - h)) / (2 * h)
    assert np.isfinite(g)
    assert g == pytest.approx(fd, rel=2e-3)


@pytest.mark.slow
def test_robust_dlc_objective_and_descent(oc3):
    """Batched-wave (DLC-table) optimization: the worst-case objective
    reduces correctly, its gradient matches finite differences, and the
    optimizer descends it."""
    from raft_tpu.parallel import make_wave_states
    from raft_tpu.parallel.optimize import _make_loss
    from raft_tpu.parallel import scale_diameters

    members, rna, env, wave, C_moor = oc3
    w = np.asarray(wave.w)
    waves = make_wave_states(w, [[4.0, 9.0], [8.0, 12.0]], float(env.depth))

    loss = _make_loss(members, rna, env, waves, C_moor, nacelle_accel_std,
                      scale_diameters, None, 25, False)
    # worst case == max of the per-case single-wave objectives
    per_case = []
    for i in range(2):
        from raft_tpu.core.types import WaveState

        wv = WaveState(w=waves.w[i], k=waves.k[i], zeta=waves.zeta[i])
        out = forward_response(members, rna, env, wv, C_moor, n_iter=25)
        per_case.append(float(nacelle_accel_std(out.Xi, wv, rna)))
    assert float(loss(jnp.asarray(1.0))) == pytest.approx(max(per_case), rel=1e-10)

    import jax

    g = float(jax.grad(loss)(jnp.asarray(1.0)))
    h = 1e-4
    fd = (float(loss(jnp.asarray(1.0 + h))) - float(loss(jnp.asarray(1.0 - h)))) / (2 * h)
    assert g == pytest.approx(fd, rel=2e-3)

    res = optimize_design(members, rna, env, waves, C_moor, theta0=1.0,
                          steps=4, learning_rate=0.02, bounds=(0.85, 1.2))
    assert res.history[-1] < res.history[0]


@pytest.mark.slow
def test_short_crested_codesign(oc3):
    """Optimization over a directionally-spread sea: the energy_sum reduce
    equals the RSS of per-direction objectives (each lane's heading carried
    through the loss), the gradient matches finite differences, and the
    optimizer descends it."""
    import jax
    from raft_tpu.core.types import WaveState
    from raft_tpu.parallel import scale_diameters, spread_sea_state
    from raft_tpu.parallel.optimize import _make_loss, energy_sum

    members, rna, env, wave, C_moor = oc3
    w = np.asarray(wave.w)
    waves = spread_sea_state(w, 8.0, 12.0, float(env.depth), n_dir=3, s=2.0)

    loss = _make_loss(members, rna, env, waves, C_moor, nacelle_accel_std,
                      scale_diameters, None, 25, False,
                      case_reduce=energy_sum)
    var = 0.0
    for j in range(3):
        wv = WaveState(w=waves.w[j], k=waves.k[j], zeta=waves.zeta[j])
        out = forward_response(members, rna,
                               env.replace(beta=float(waves.beta[j])),
                               wv, C_moor, n_iter=25)
        var += float(nacelle_accel_std(out.Xi, wv, rna)) ** 2
    assert float(loss(jnp.asarray(1.0))) == pytest.approx(np.sqrt(var), rel=1e-9)

    g = float(jax.grad(loss)(jnp.asarray(1.0)))
    h = 1e-4
    fd = (float(loss(jnp.asarray(1.0 + h)))
          - float(loss(jnp.asarray(1.0 - h)))) / (2 * h)
    assert g == pytest.approx(fd, rel=2e-3)

    res = optimize_design(members, rna, env, waves, C_moor, theta0=1.0,
                          steps=3, learning_rate=0.02, bounds=(0.85, 1.2),
                          case_reduce=energy_sum)
    assert res.history[-1] < res.history[0]


def test_short_crested_codesign_with_bem_heading_grid(oc3):
    """Short-crested optimization with potential-flow coefficients: each
    direction lane's BEM excitation is interpolated to its own heading
    from the staged grid (exactly as sweep_sea_states does); a raw
    single-heading tuple under heading-varying lanes is rejected."""
    from raft_tpu.core.types import WaveState
    from raft_tpu.model import interp_heading_excitation
    from raft_tpu.parallel import spread_sea_state, stage_bem
    from raft_tpu.parallel import scale_diameters
    from raft_tpu.parallel.optimize import _make_loss, energy_sum

    members, rna, env, wave, C_moor = oc3
    w = np.asarray(wave.w)
    nw = len(w)
    waves = spread_sea_state(w, 8.0, 12.0, float(env.depth), n_dir=3, s=2.0)
    rng = np.random.default_rng(5)
    A = np.tile(np.eye(6)[:, :, None] * 5e6, (1, 1, nw))
    Bh = np.tile(np.eye(6)[:, :, None] * 1e5, (1, 1, nw))
    bgrid = np.array([-1.1, 1.1])          # covers the +-pi/3 lane offsets
    F_all = (rng.normal(size=(2, 6, nw))
             + 1j * rng.normal(size=(2, 6, nw))) * 1e5

    loss = _make_loss(members, rna, env, waves, C_moor, nacelle_accel_std,
                      scale_diameters, (bgrid, F_all, A, Bh), 25, False,
                      case_reduce=energy_sum)
    var = 0.0
    for j in range(3):
        beta_j = float(waves.beta[j])
        wv = WaveState(w=waves.w[j], k=waves.k[j], zeta=waves.zeta[j])
        F_j = interp_heading_excitation(bgrid, F_all, beta_j)
        out = forward_response(members, rna, env.replace(beta=beta_j), wv,
                               C_moor, bem=stage_bem((A, Bh, F_j), wv),
                               n_iter=25)
        var += float(nacelle_accel_std(out.Xi, wv, rna)) ** 2
    assert float(loss(jnp.asarray(1.0))) == pytest.approx(np.sqrt(var), rel=1e-9)

    with pytest.raises(ValueError, match="heading"):
        _make_loss(members, rna, env, waves, C_moor, nacelle_accel_std,
                   scale_diameters, (A, Bh, F_all[0]), 25, False)


@pytest.mark.slow
def test_robust_dlc_with_raw_bem_matches_per_case(oc3):
    """Batched waves + BEM: the per-case zeta re-staging inside the robust
    loss equals staging each case by hand; stage_bem output is rejected
    with a clear error."""
    from raft_tpu.core.types import WaveState
    from raft_tpu.parallel import make_wave_states, stage_bem
    from raft_tpu.parallel.optimize import _make_loss
    from raft_tpu.parallel import scale_diameters

    members, rna, env, wave, C_moor = oc3
    nw = int(wave.w.shape[0])
    rng = np.random.default_rng(3)
    A = np.tile(np.eye(6)[:, :, None] * 4e6, (1, 1, nw))
    B = np.tile(np.eye(6)[:, :, None] * 2e5, (1, 1, nw))
    F = (rng.normal(size=(6, nw)) + 1j * rng.normal(size=(6, nw))) * 2e5
    waves = make_wave_states(np.asarray(wave.w), [[4.0, 9.0], [8.0, 12.0]],
                             float(env.depth))

    loss = _make_loss(members, rna, env, waves, C_moor, nacelle_accel_std,
                      scale_diameters, (A, B, F), 25, False)
    per_case = []
    for i in range(2):
        wv = WaveState(w=waves.w[i], k=waves.k[i], zeta=waves.zeta[i])
        out = forward_response(members, rna, env, wv, C_moor,
                               bem=stage_bem((A, B, F), wv), n_iter=25)
        per_case.append(float(nacelle_accel_std(out.Xi, wv, rna)))
    assert float(loss(jnp.asarray(1.0))) == pytest.approx(max(per_case), rel=1e-10)
    import jax

    assert np.isfinite(float(jax.grad(loss)(jnp.asarray(1.0))))

    # staged tuple with batched waves is a clear error, not a shape bomb
    with pytest.raises(ValueError, match="raw"):
        _make_loss(members, rna, env, waves, C_moor, nacelle_accel_std,
                   scale_diameters, stage_bem((A, B, F), wave), 25, False)

    # raw tuple with a SINGLE wave is accepted (staged internally)
    loss1 = _make_loss(members, rna, env, wave, C_moor, nacelle_accel_std,
                       scale_diameters, (A, B, F), 25, False)
    out1 = forward_response(members, rna, env, wave, C_moor,
                            bem=stage_bem((A, B, F), wave), n_iter=25)
    assert float(loss1(jnp.asarray(1.0))) == pytest.approx(
        float(nacelle_accel_std(out1.Xi, wave, rna)), rel=1e-10)


@pytest.mark.slow
def test_optimizer_remat_matches(oc3):
    """remat only changes the backward-pass schedule, not values/grads."""
    members, rna, env, wave, C_moor = oc3
    a = optimize_design(members, rna, env, wave, C_moor, theta0=1.0,
                        steps=2, learning_rate=0.02)
    b = optimize_design(members, rna, env, wave, C_moor, theta0=1.0,
                        steps=2, learning_rate=0.02, remat=True)
    np.testing.assert_allclose(a.history, b.history, rtol=1e-12)
    np.testing.assert_allclose(a.thetas, b.thetas, rtol=1e-12)


@pytest.mark.slow
def test_mooring_knobs_grad_matches_fd():
    """Line length / anchor radius / EA as differentiable co-design knobs:
    the exact gradient through the catenary stack matches central finite
    differences of the same loss, component by component."""
    import jax

    from raft_tpu.mooring import scale_mooring
    from raft_tpu.parallel import scale_diameters
    from raft_tpu.parallel.optimize import _make_loss

    design, members, rna, env, wave = ge._base(nw=16)
    moor = parse_mooring(
        design["mooring"], yaw_stiffness=design["turbine"]["yaw_stiffness"]
    )
    # theta = [diam_scale, L_scale, R_scale, EA_scale]
    loss = _make_loss(
        members, rna, env, wave, None, nacelle_accel_std,
        lambda m, t: scale_diameters(m, t[0]), None, 20, False,
        moor=moor, moor_apply_fn=lambda s, t: scale_mooring(s, t[1:4]),
    )
    lj = jax.jit(loss)
    g = np.asarray(jax.jit(jax.grad(loss))(jnp.ones(4)))
    assert np.isfinite(g).all()
    # every mooring knob moves the objective (gradient nonzero)...
    assert (np.abs(g[1:]) > 1e-12).all(), g
    # ...and matches finite differences of the identical loss
    h = 1e-4
    for i in range(4):
        e = np.zeros(4)
        e[i] = h
        fd = (float(lj(jnp.asarray(1.0 + e))) -
              float(lj(jnp.asarray(1.0 - e)))) / (2 * h)
        assert g[i] == pytest.approx(fd, rel=5e-3, abs=1e-10), f"knob {i}"


@pytest.mark.slow
def test_mooring_codesign_descends():
    """optimize_design with hull + mooring knobs: objective decreases and
    the mooring parameters move off their initial values."""
    from raft_tpu.mooring import scale_mooring
    from raft_tpu.parallel import scale_diameters

    design, members, rna, env, wave = ge._base(nw=16)
    moor = parse_mooring(
        design["mooring"], yaw_stiffness=design["turbine"]["yaw_stiffness"]
    )
    res = optimize_design(
        members, rna, env, wave, None, theta0=np.ones(4),
        apply_fn=lambda m, t: scale_diameters(m, t[0]),
        moor=moor, moor_apply_fn=lambda s, t: scale_mooring(s, t[1:4]),
        steps=5, learning_rate=0.02, bounds=(0.8, 1.25), n_iter=20,
    )
    assert res.history[-1] < res.history[0] - 1e-6, res.history
    assert np.isfinite(res.history).all()
    assert (res.theta != 1.0).any()
