"""Co-design optimization: objective correctness, exact gradients, descent.

The capability under test is BASELINE.json configs[4] — "jax.grad of
nacelle-accel std-dev w.r.t. platform geometry params" driving a WEIS-style
inner loop.  Gradients are checked against central finite differences of
the same pipeline; the optimizer is checked to actually descend its
objective on the OC3 spar.
"""
import numpy as np
import pytest
import jax.numpy as jnp

import __graft_entry__ as ge
from raft_tpu.mooring import mooring_stiffness, parse_mooring
from raft_tpu.parallel import (
    forward_response,
    grad_nacelle_accel_std,
    nacelle_accel_std,
    optimize_design,
)


@pytest.fixture(scope="module")
def oc3():
    design, members, rna, env, wave = ge._base(nw=24)
    moor = parse_mooring(
        design["mooring"], yaw_stiffness=design["turbine"]["yaw_stiffness"]
    )
    C_moor = mooring_stiffness(moor, jnp.zeros(6))
    return members, rna, env, wave, C_moor


def _sigma_nac(oc3, s):
    members, rna, env, wave, C_moor = oc3
    from raft_tpu.parallel import scale_diameters

    out = forward_response(
        scale_diameters(members, jnp.asarray(s)), rna, env, wave, C_moor,
        n_iter=25, method="scan",
    )
    return float(nacelle_accel_std(out.Xi, wave, rna))


def test_nacelle_objective_matches_manual_sum(oc3):
    members, rna, env, wave, C_moor = oc3
    out = forward_response(members, rna, env, wave, C_moor, n_iter=25)
    sigma = float(nacelle_accel_std(out.Xi, wave, rna))
    Xi = np.asarray(out.Xi.to_complex())
    w = np.asarray(wave.w)
    a = -(w**2) * (Xi[:, 0] + float(rna.hHub) * Xi[:, 4])
    dw = float(w[1] - w[0])
    assert sigma == pytest.approx(np.sqrt((np.abs(a) ** 2).sum() * dw), rel=1e-10)
    assert sigma > 0.01                      # Hs=8 seas excite the nacelle


def test_grad_matches_finite_difference(oc3):
    members, rna, env, wave, C_moor = oc3
    g = float(grad_nacelle_accel_std(members, rna, env, wave, C_moor, 1.0))
    h = 1e-4
    fd = (_sigma_nac(oc3, 1.0 + h) - _sigma_nac(oc3, 1.0 - h)) / (2 * h)
    assert g == pytest.approx(fd, rel=2e-3)


def test_optimizer_descends(oc3):
    members, rna, env, wave, C_moor = oc3
    res = optimize_design(
        members, rna, env, wave, C_moor, theta0=1.0,
        steps=6, learning_rate=0.02, bounds=(0.8, 1.25), n_iter=25,
    )
    assert res.history[-1] < res.history[0] - 1e-4, res.history
    assert 0.8 <= float(res.theta) <= 1.25
    assert np.isfinite(res.history).all()
    # trajectory bookkeeping is consistent
    assert res.thetas.shape[0] == res.history.shape[0] == 7
    assert res.objective == pytest.approx(res.history[-1])


def test_grad_with_staged_bem_matches_fd(oc3):
    """Co-design gradient with potential-flow coefficients staged: the BEM
    terms are held constant (nominal hull), the statics/Morison/drag
    dependence differentiates exactly."""
    from raft_tpu.parallel import stage_bem

    members, rna, env, wave, C_moor = oc3
    nw = int(wave.w.shape[0])
    rng = np.random.default_rng(2)
    A = np.tile(np.eye(6)[:, :, None] * 4e6, (1, 1, nw))
    B = np.tile(np.eye(6)[:, :, None] * 2e5, (1, 1, nw))
    F = (rng.normal(size=(6, nw)) + 1j * rng.normal(size=(6, nw))) * 2e5
    bem = stage_bem((A, B, F), wave)

    def f(s):
        from raft_tpu.parallel import scale_diameters

        out = forward_response(
            scale_diameters(members, jnp.asarray(s)), rna, env, wave, C_moor,
            bem=bem, n_iter=25, method="scan",
        )
        return float(nacelle_accel_std(out.Xi, wave, rna))

    g = float(grad_nacelle_accel_std(members, rna, env, wave, C_moor, 1.0,
                                     bem=bem))
    h = 1e-4
    fd = (f(1.0 + h) - f(1.0 - h)) / (2 * h)
    assert np.isfinite(g)
    assert g == pytest.approx(fd, rel=2e-3)


def test_optimizer_remat_matches(oc3):
    """remat only changes the backward-pass schedule, not values/grads."""
    members, rna, env, wave, C_moor = oc3
    a = optimize_design(members, rna, env, wave, C_moor, theta0=1.0,
                        steps=2, learning_rate=0.02)
    b = optimize_design(members, rna, env, wave, C_moor, theta0=1.0,
                        steps=2, learning_rate=0.02, remat=True)
    np.testing.assert_allclose(a.history, b.history, rtol=1e-12)
    np.testing.assert_allclose(a.thetas, b.thetas, rtol=1e-12)
