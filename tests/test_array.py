"""Multi-turbine array tests: stacked FOWTs reproduce single-turbine runs.

The array system is block-diagonal (no hull-to-hull hydrodynamic coupling,
matching the reference architecture at raft/raft.py:1292-1298 which never
couples FOWTs either), so:

* N co-located identical turbines must reproduce N copies of the single-
  turbine response exactly (block-diagonality).
* A turbine offset down-wave by d must respond with the same amplitude and
  an extra phase lag exp(-i k d) (linearity + incident-wave phasing).
* Mixed-design arrays (different pad dims, different mooring) must match
  each design's own single-turbine eigenfrequencies.
"""
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from raft_tpu.array import ArrayModel
from raft_tpu.model import Model, load_design

OC3 = "raft_tpu/designs/OC3spar.yaml"
OC4 = "raft_tpu/designs/OC4semi.yaml"

W = np.arange(0.05, 3.0, 0.25)          # coarse grid keeps the test fast


@pytest.fixture(scope="module")
def single():
    m = Model(load_design(OC3), w=W)
    m.setEnv(Hs=8.0, Tp=12.0, Fthrust=800e3)
    m.calcSystemProps()
    m.solveEigen()
    m.calcMooringAndOffsets()
    m.solveDynamics()
    return m


@pytest.fixture(scope="module")
def pair():
    a = Model(load_design(OC3), w=W, nTurbines=2)
    assert isinstance(a, ArrayModel)
    a.setEnv(Hs=8.0, Tp=12.0, Fthrust=800e3)
    a.calcSystemProps()
    a.solveEigen()
    a.calcMooringAndOffsets()
    a.solveDynamics()
    return a


def test_model_constructor_routes_to_array(pair):
    assert pair.nT == 2
    assert pair.results["properties"]["nDOF"] == 12


def test_array_eigen_matches_single(single, pair):
    f1 = single.results["eigen"]["frequencies"]
    fa = pair.results["eigen"]["frequencies"]
    assert fa.shape == (2, 6)
    np.testing.assert_allclose(fa[0], f1, rtol=1e-8)
    np.testing.assert_allclose(fa[1], f1, rtol=1e-8)


def test_array_offsets_match_single(single, pair):
    r1 = single.results["means"]["platform offset"]
    ra = pair.results["means"]["platform offset"]
    np.testing.assert_allclose(ra[0], r1, rtol=1e-6, atol=1e-9)
    np.testing.assert_allclose(ra[1], r1, rtol=1e-6, atol=1e-9)


def test_array_response_block_diagonal(single, pair):
    """Co-located identical turbines = two copies of the single response."""
    Xi1 = single.results["response"]["Xi"]                # (nw, 6)
    Xa = pair.results["response"]["Xi per turbine"]       # (2, nw, 6)
    assert pair.results["response"]["converged"].all()
    np.testing.assert_allclose(Xa[0], Xi1, rtol=1e-6, atol=1e-10)
    np.testing.assert_allclose(Xa[1], Xi1, rtol=1e-6, atol=1e-10)
    # stacked 6N layout interleaves turbines on the DOF axis
    flat = pair.results["response"]["Xi"]                 # (nw, 12)
    np.testing.assert_allclose(flat[:, :6], Xi1, rtol=1e-6, atol=1e-10)
    np.testing.assert_allclose(flat[:, 6:], Xi1, rtol=1e-6, atol=1e-10)


def test_array_downwave_phase_lag(single):
    """Turbine at (d, 0) in beta=0 waves: same |Xi|, phase lag k*d."""
    d = 800.0
    a = ArrayModel(load_design(OC3), positions=[[0.0, 0.0], [d, 0.0]], w=W)
    a.setEnv(Hs=8.0, Tp=12.0, Fthrust=800e3)
    a.calcSystemProps()
    a.calcMooringAndOffsets()
    a.solveDynamics(tol=1e-4)
    Xa = a.results["response"]["Xi per turbine"]
    k = np.asarray(a.wave.k)
    expect = Xa[0] * np.exp(-1j * k[:, None] * d)
    # same drag linearization fixed point => exact phase relation
    np.testing.assert_allclose(Xa[1], expect, rtol=2e-3, atol=1e-8)
    np.testing.assert_allclose(np.abs(Xa[1]), np.abs(Xa[0]), rtol=2e-3, atol=1e-8)


def test_mixed_design_array_eigen():
    """OC3 + OC4 in one array: each block matches its own single model."""
    d3, d4 = load_design(OC3), load_design(OC4)
    a = ArrayModel([d3, d4], w=W)
    a.setEnv(Hs=8.0, Tp=12.0)
    a.calcSystemProps()
    a.solveEigen()
    fa = a.results["eigen"]["frequencies"]

    for i, d in enumerate((d3, d4)):
        m = Model(d, w=W)
        m.setEnv(Hs=8.0, Tp=12.0)
        m.calcSystemProps()
        m.solveEigen()
        np.testing.assert_allclose(
            fa[i], m.results["eigen"]["frequencies"], rtol=1e-6
        )


def test_array_plot_raos_smoke(pair):
    import matplotlib

    matplotlib.use("Agg", force=True)
    import matplotlib.pyplot as plt

    axes = pair.plot_raos()
    flat = np.asarray(axes).ravel()
    assert flat.shape[0] == 6
    assert all(len(a.lines) == pair.nT for a in flat)   # one curve/turbine
    plt.close("all")


def test_array_outputs_nacelle_accel(pair):
    out = pair.calcOutputs()
    a_nac = out["response"]["nacelle acceleration"]
    assert a_nac.shape == (2, len(W))
    assert np.isfinite(a_nac).all()
    np.testing.assert_allclose(a_nac[0], a_nac[1], rtol=1e-6, atol=1e-12)
    # per-turbine constraint margins: identical co-located turbines agree
    cons = out["constraints"]
    assert cons["slack line margin"].shape == (2,)
    assert cons["dynamic pitch"].shape == (2,)
    np.testing.assert_allclose(cons["slack line margin"][0],
                               cons["slack line margin"][1], rtol=1e-6)
    assert (cons["dynamic pitch"] > 0).all()
    assert (cons["dynamic pitch"] < cons["dynamic pitch limit"]).all()


def test_array_with_staged_bem_matches_single():
    """Two co-located turbines with staged BEM coefficients reproduce the
    single-turbine BEM solve; a down-wave turbine's BEM excitation carries
    the incident phase lag."""
    design = load_design(OC3)
    nw = len(W)
    rng = np.random.default_rng(3)
    A = np.zeros((6, 6, nw))
    for i in range(6):
        A[i, i] = 5e6 * (1e3 if i >= 3 else 1.0) / (1 + W**2)
    B = np.zeros((6, 6, nw))
    F = (rng.normal(size=(6, nw)) + 1j * rng.normal(size=(6, nw))) * 1e5

    m1 = Model(design, w=W, BEM=(A, B, F))
    m1.setEnv(Hs=8.0, Tp=12.0)
    m1.calcSystemProps()
    m1.calcMooringAndOffsets()
    m1.solveDynamics(tol=1e-4)
    Xi1 = np.asarray(m1.rao.Xi.to_complex())

    d = 500.0
    a = Model(design, w=W, nTurbines=2, BEM=(A, B, F),
              positions=[[0.0, 0.0], [d, 0.0]])
    a.setEnv(Hs=8.0, Tp=12.0)
    a.calcSystemProps()
    a.calcMooringAndOffsets()
    a.solveDynamics(tol=1e-4)
    Xa = a.results["response"]["Xi per turbine"]
    np.testing.assert_allclose(Xa[0], Xi1, rtol=1e-5, atol=1e-9)
    k = np.asarray(a.wave.k)
    np.testing.assert_allclose(
        Xa[1], Xi1 * np.exp(-1j * k[:, None] * d), rtol=2e-3, atol=1e-8
    )


def test_array_eigen_with_staged_bem_matches_single():
    """With BEM staged the potMod strip added mass is gated out of
    A_morison, so the array eigen assembly must fold in the staged
    A_bem(w_n) per mode exactly as the single model does."""
    design = load_design(OC3)
    nw = len(W)
    A = np.zeros((6, 6, nw))
    for i in range(6):
        A[i, i] = 5e6 * (1e3 if i >= 3 else 1.0) / (1 + W**2)
    B = np.zeros((6, 6, nw))
    F = np.zeros((6, nw), dtype=complex)

    m1 = Model(design, w=W, BEM=(A, B, F))
    m1.setEnv(Hs=8.0, Tp=12.0)
    m1.calcSystemProps()
    m1.solveEigen()
    f1 = m1.results["eigen"]["frequencies"]

    a = Model(design, w=W, nTurbines=2, BEM=(A, B, F))
    a.setEnv(Hs=8.0, Tp=12.0)
    a.calcSystemProps()
    a.solveEigen()
    fa = a.results["eigen"]["frequencies"]
    assert fa.shape == (2, 6)
    np.testing.assert_allclose(fa[0], f1, rtol=1e-7)
    np.testing.assert_allclose(fa[1], f1, rtol=1e-7)
    assert a.results["eigen"]["estimates"].shape == (2, 6)

    # and the staged added mass really enters the assembly (not a no-op):
    # the frequency-dependent A shifts the modes vs the Morison-only solve
    m0 = Model(design, w=W)
    m0.setEnv(Hs=8.0, Tp=12.0)
    m0.calcSystemProps()
    m0.solveEigen()
    f0 = m0.results["eigen"]["frequencies"]
    assert np.abs(f1 - f0).max() / np.abs(f0).max() > 1e-3


def test_array_history_diagnostic():
    """history=True surfaces each turbine's per-iteration convergence error."""
    a = ArrayModel(load_design(OC3), nT=2, w=W)
    a.setEnv(Hs=8.0, Tp=12.0)
    a.calcSystemProps()
    a.calcMooringAndOffsets()
    a.solveDynamics(history=True)
    h = a.results["response"]["iteration error history"]
    n = a.results["response"]["iterations"]
    assert h.shape == (2, 40)
    for t in range(2):
        assert np.isfinite(h[t, : int(n[t])]).all()
        assert np.isnan(h[t, int(n[t]):]).all()


def test_mixed_design_array_with_bem_raises():
    d3, d4 = load_design(OC3), load_design(OC4)
    with pytest.raises(NotImplementedError):
        ArrayModel([d3, d4], w=W, BEM="native")


def test_add_fowt_grows_array():
    """addFOWT rebuilds the stacked axes (cf. raft/raft.py:1292-1298, which
    grows fowtList but never solves the extra turbines)."""
    d = load_design(OC3)
    a = ArrayModel(d, w=W)
    assert a.nT == 1
    a.addFOWT(d, position=(600.0, 0.0))
    assert a.nT == 2
    a.setEnv(Hs=8.0, Tp=12.0)
    a.calcSystemProps()
    a.solveEigen()
    f = a.results["eigen"]["frequencies"]
    assert f.shape == (2, 6)
    np.testing.assert_allclose(f[0], f[1], rtol=1e-8)


def test_array_mesh_sharded_matches_unsharded():
    """Wind-farm data parallelism: the turbine axis sharded over a 4-device
    mesh reproduces the unsharded farm exactly (no cross-turbine coupling,
    so no collectives — pure placement)."""
    import jax
    from jax.sharding import Mesh

    a = ArrayModel(load_design(OC3), positions=[[0, 0], [400, 0],
                                                [800, 0], [1200, 0]], w=W)
    a.setEnv(Hs=8.0, Tp=12.0, Fthrust=800e3)
    a.calcSystemProps()
    a.calcMooringAndOffsets()
    a.solveDynamics()
    Xi_ref = np.asarray(a.rao.Xi.to_complex())

    mesh = Mesh(np.array(jax.devices()[:4]), axis_names=("turbines",))
    a.solveDynamics(mesh=mesh)
    Xi_sh = np.asarray(a.rao.Xi.to_complex())
    np.testing.assert_allclose(Xi_sh, Xi_ref, rtol=1e-12, atol=1e-14)

    with pytest.raises(ValueError, match="not a multiple"):
        ArrayModel(load_design(OC3), nT=3, w=W).solveDynamics(mesh=mesh)


@pytest.mark.slow
def test_array_heading_grid_restages_without_resolve(monkeypatch):
    """calcBEM(headings=[...]) on an array: setEnv(beta) re-stages the
    excitation by interpolation with NO second native solve, and staleness
    of the phased staging is honored."""
    from raft_tpu.hydro import native_bem

    design = load_design(OC3)
    a = ArrayModel(design, positions=[[0, 0], [500, 0]], w=np.arange(0.2, 1.4, 0.3))
    a.setEnv(Hs=8.0, Tp=12.0, beta=0.0)
    calls = {"n": 0}
    real = native_bem.solve_bem

    def counting(*args, **kw):
        calls["n"] += 1
        return real(*args, **kw)

    monkeypatch.setattr(native_bem, "solve_bem", counting)
    betas = np.deg2rad([0.0, 30.0])
    a.calcBEM(dz_max=6.0, da_max=6.0, headings=betas)
    assert calls["n"] == 1
    a.calcSystemProps()
    a.solveDynamics()
    Xi0 = np.asarray(a.rao.Xi.to_complex())

    a.setEnv(Hs=8.0, Tp=12.0, beta=float(betas[1]))   # re-stage, no re-solve
    assert calls["n"] == 1
    assert a.kin is None and a._bem_staged is None     # staleness honored
    a.calcSystemProps()
    a.solveDynamics()
    Xi1 = np.asarray(a.rao.Xi.to_complex())
    assert np.abs(Xi0 - Xi1).max() > 1e-6              # heading changed response
    # out-of-grid heading raises BEFORE mutating any state
    with pytest.raises(ValueError, match="outside staged grid"):
        a.setEnv(beta=1.0)
    assert float(a.env.beta) == pytest.approx(float(betas[1]))
    assert a.kin is not None                            # staging untouched


def test_model_solvestatics_alias():
    m = Model(load_design(OC3), w=W)
    m.setEnv(Hs=8.0, Tp=12.0, Fthrust=800e3)
    m.calcSystemProps()
    m.solveStatics()
    assert "means" in m.results
    assert 10.0 < m.results["means"]["platform offset"][0] < 40.0


def test_farm16_batched_matches_loop(monkeypatch):
    """Farm-scale array: 16 turbines solve eigen + mooring equilibrium in
    ONE compiled call each (eigen_with_bem_batched / _moor_solve_batch),
    and the batched results match the sequential per-turbine loop."""
    design = load_design(OC3)
    nw = len(W)
    A = np.zeros((6, 6, nw))
    for i in range(6):
        A[i, i] = 5e6 * (1e3 if i >= 3 else 1.0) / (1 + W**2)
    B = np.zeros((6, 6, nw))
    F = np.zeros((6, nw), dtype=complex)

    a = Model(design, w=W, nTurbines=16, BEM=(A, B, F))
    a.setEnv(Hs=8.0, Tp=12.0, Fthrust=800e3)
    a.calcSystemProps()
    assert a._moor_batchable()          # identical farm -> batched fast path
    a.solveEigen()
    a.calcMooringAndOffsets()
    fa = a.results["eigen"]["frequencies"]
    r6_b = np.asarray(a.r6_eq)
    C_b = np.asarray(a.C_moor)
    T_b = np.stack([np.asarray(t)
                    for t in a.results["means"]["fairlead tensions"]])
    assert fa.shape == (16, 6) and r6_b.shape == (16, 6)

    # identical co-located turbines: every row equals row 0
    for arr in (fa, r6_b, C_b, T_b):
        np.testing.assert_allclose(arr, np.broadcast_to(arr[:1], arr.shape),
                                   rtol=1e-6, atol=1e-9)

    # the sequential per-turbine loop gives the same physics
    a2 = Model(design, w=W, nTurbines=16, BEM=(A, B, F))
    a2.setEnv(Hs=8.0, Tp=12.0, Fthrust=800e3)
    a2.calcSystemProps()
    monkeypatch.setattr(a2, "_moor_batchable", lambda: False)
    a2.calcMooringAndOffsets()
    np.testing.assert_allclose(r6_b, np.asarray(a2.r6_eq),
                               rtol=1e-6, atol=1e-8)
    np.testing.assert_allclose(C_b, np.asarray(a2.C_moor), rtol=1e-5)
    T_l = np.stack([np.asarray(t)
                    for t in a2.results["means"]["fairlead tensions"]])
    np.testing.assert_allclose(T_b, T_l, rtol=1e-6)

    # eigen matches the single-turbine solve with the same staged BEM
    m1 = Model(design, w=W, BEM=(A, B, F))
    m1.setEnv(Hs=8.0, Tp=12.0)
    m1.calcSystemProps()
    m1.solveEigen()
    np.testing.assert_allclose(
        fa[0], m1.results["eigen"]["frequencies"], rtol=1e-6)
