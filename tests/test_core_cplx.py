"""Cx (re,im)-pair complex arithmetic vs numpy complex oracle."""
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import cplx
from raft_tpu.core.cplx import Cx

rng = np.random.default_rng(42)


def _rand(shape):
    return rng.normal(size=shape) + 1j * rng.normal(size=shape)


def test_arithmetic_matches_numpy():
    a = _rand((4, 5))
    b = _rand((4, 5))
    A, B = Cx.of(a), Cx.of(b)
    np.testing.assert_allclose(np.asarray((A + B).to_complex()), a + b, rtol=1e-12)
    np.testing.assert_allclose(np.asarray((A - B).to_complex()), a - b, rtol=1e-12)
    np.testing.assert_allclose(np.asarray((A * B).to_complex()), a * b, rtol=1e-12)
    np.testing.assert_allclose(np.asarray((A / B).to_complex()), a / b, rtol=1e-12)
    np.testing.assert_allclose(np.asarray((-A).to_complex()), -a, rtol=1e-12)
    np.testing.assert_allclose(np.asarray(A.conj().to_complex()), np.conj(a), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(A.mul_i().to_complex()), 1j * a, rtol=1e-12)
    np.testing.assert_allclose(np.asarray(A.abs()), np.abs(a), rtol=1e-12)
    np.testing.assert_allclose(np.asarray((A * 2.5).to_complex()), a * 2.5, rtol=1e-12)
    np.testing.assert_allclose(np.asarray((A + 1.0).to_complex()), a + 1.0, rtol=1e-12)


def test_expi():
    th = rng.normal(size=7)
    np.testing.assert_allclose(
        np.asarray(Cx.expi(jnp.asarray(th)).to_complex()), np.exp(1j * th), rtol=1e-12
    )


def test_einsum_two_complex():
    a = _rand((3, 4))
    b = _rand((4, 5))
    out = cplx.einsum("ij,jk->ik", Cx.of(a), Cx.of(b))
    np.testing.assert_allclose(np.asarray(out.to_complex()), a @ b, rtol=1e-12)


def test_einsum_mixed_real_complex():
    a = rng.normal(size=(3, 4))
    b = _rand((4,))
    out = cplx.einsum("ij,j->i", jnp.asarray(a), Cx.of(b))
    np.testing.assert_allclose(np.asarray(out.to_complex()), a @ b, rtol=1e-12)


def test_matmul():
    a = _rand((6, 6))
    b = _rand((6, 2))
    out = cplx.matmul(Cx.of(a), Cx.of(b))
    np.testing.assert_allclose(np.asarray(out.to_complex()), a @ b, rtol=1e-12)


def test_pytree_through_jit_vmap():
    import jax

    a = _rand((8, 3))

    @jax.jit
    def f(z: Cx):
        return (z * z + z.conj()).abs2()

    out = np.asarray(f(Cx.of(a)))
    np.testing.assert_allclose(out, np.abs(a * a + np.conj(a)) ** 2, rtol=1e-10)
