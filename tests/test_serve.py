"""Resident solver service: protocol, deterministic batching, padded-batch
bit-identity, socket end-to-end, knob snapshot, executor refresh.

The determinism contract under test (docs/serving.rst):

* same arrival schedule + knobs -> IDENTICAL batch compositions
  (:class:`MicroBatcher` on a virtual clock — deadline-close,
  capacity-close, and mixed-bucket interleave cases);
* a request's results are BIT-IDENTICAL whatever batch it rode in
  (fixed-capacity padding + value-independent vmapped lanes), pinned by
  solving the same lane solo and in mixed company;
* a NaN lane is quarantined without perturbing batch-mates' bits.
"""
import os
import socket
import threading

import numpy as np
import pytest

from raft_tpu.build.buckets import BucketSig
from raft_tpu.serve import protocol
from raft_tpu.serve.batcher import Lane, MicroBatcher
from raft_tpu.serve.config import ServeConfig
from raft_tpu.serve.solver import SolverCore, design_key, solve_batch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OC3 = os.path.join(REPO, "raft_tpu", "designs", "OC3spar.yaml")
OC4 = os.path.join(REPO, "raft_tpu", "designs", "OC4semi.yaml")


# --------------------------------------------------------------------------
# protocol: framing + request validation
# --------------------------------------------------------------------------
def test_protocol_frame_round_trip():
    a, b = socket.socketpair()
    try:
        msg = {"op": "ping", "id": "x", "payload": list(range(50))}
        protocol.send_msg(a, msg)
        assert protocol.recv_msg(b) == msg
    finally:
        a.close()
        b.close()


def test_protocol_peer_close_and_oversize():
    a, b = socket.socketpair()
    a.close()
    with pytest.raises(protocol.PeerClosed):
        protocol.recv_msg(b)
    b.close()
    a, b = socket.socketpair()
    try:
        # an announced frame length past the cap must refuse BEFORE
        # allocating/reading the body
        import struct

        a.sendall(struct.pack(">I", protocol.MAX_FRAME + 1))
        with pytest.raises(protocol.ProtocolError):
            protocol.recv_msg(b)
    finally:
        a.close()
        b.close()


def test_parse_request_kinds_and_errors():
    one = protocol.parse_request(
        {"op": "solve", "id": "a", "design": "oc3", "Hs": 6, "Tp": 10})
    assert len(one["lanes"]) == 1
    assert one["lanes"][0][0].endswith("OC3spar.yaml")
    dlc = protocol.parse_request(
        {"op": "dlc", "id": "b", "design": OC4,
         "cases": [[6, 10], [8, 12]]})
    assert len(dlc["lanes"]) == 2
    sw = protocol.parse_request(
        {"op": "sweep", "id": "c", "designs": ["oc3", "volturnus"],
         "Hs": 7, "Tp": 11})
    assert [l[1] for l in sw["lanes"]] == ["OC3spar", "VolturnUS-S"]
    assert protocol.parse_request({"op": "ping"})["lanes"] == []
    for bad in (
        {"op": "nope"},
        {"op": "solve", "design": "oc3", "Hs": 6, "Tp": 10},   # no id
        {"op": "solve", "id": "x", "design": "mystery", "Hs": 6, "Tp": 10},
        {"op": "solve", "id": "x", "design": "oc3", "Hs": "wide", "Tp": 1},
        {"op": "dlc", "id": "x", "design": "oc3", "cases": []},
        {"op": "dlc", "id": "x", "design": "oc3", "cases": [[1, 2, 3]]},
        {"op": "sweep", "id": "x", "designs": [], "Hs": 6, "Tp": 10},
        [1, 2],
    ):
        with pytest.raises(protocol.ProtocolError):
            protocol.parse_request(bad)


def test_parse_request_trace_passthrough():
    r = protocol.parse_request(
        {"op": "solve", "id": "a", "design": "oc3", "Hs": 6, "Tp": 10,
         "trace": "abc-1"})
    assert r["trace"] == "abc-1"
    r2 = protocol.parse_request(
        {"op": "solve", "id": "a", "design": "oc3", "Hs": 6, "Tp": 10})
    assert r2["trace"] is None
    with pytest.raises(protocol.ProtocolError, match="trace"):
        protocol.parse_request(
            {"op": "solve", "id": "a", "design": "oc3", "Hs": 6,
             "Tp": 10, "trace": 7})


def test_design_key_dict_content_hash():
    d1 = {"a": 1, "b": [1, 2]}
    d2 = {"b": [1, 2], "a": 1}          # key order must not matter
    assert design_key(d1) == design_key(d2)
    assert design_key(d1) != design_key({"a": 2, "b": [1, 2]})
    assert design_key("/p/x.yaml") == "/p/x.yaml"


# --------------------------------------------------------------------------
# micro-batcher: deterministic deadline/capacity composition
# --------------------------------------------------------------------------
class VirtualClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


SIG_A = BucketSig(16, 64, 32)
SIG_B = BucketSig(48, 128, 32)


def _lane(i):
    return Lane(request_id=i, seq=0, label=f"l{i}", staged=None)


def _run_schedule(events, deadline=10.0, cap=3):
    """Replay [(t, sig, lane_id) ...] arrivals plus ('advance', t) steps
    on a virtual clock; after each step drain every closeable batch.
    Returns the closed compositions [(sig, [lane ids]) ...]."""
    clk = VirtualClock()
    mb = MicroBatcher(batch_deadline_s=deadline, batch_max=cap, clock=clk)
    out = []

    def drain_ready():
        while True:
            got = mb.next_batch(timeout=0.0)
            if got is None:
                return
            out.append((tuple(got[0]), [ln.request_id for ln in got[1]]))

    for ev in events:
        if ev[0] == "advance":
            clk.t = ev[1]
        else:
            t, sig, lid = ev
            clk.t = t
            mb.submit(sig, _lane(lid))
        drain_ready()
    return out


def test_batcher_capacity_close_fifo_and_remainder():
    events = [(0.0, SIG_A, i) for i in range(5)]     # cap 3: one close
    got = _run_schedule(events, deadline=100.0, cap=3)
    assert got == [(tuple(SIG_A), [0, 1, 2])]
    # the remainder keeps its ORIGINAL arrival: deadline measured from
    # t=0, so advancing to 100 closes [3, 4]
    got2 = _run_schedule(events + [("advance", 100.0)],
                         deadline=100.0, cap=3)
    assert got2 == [(tuple(SIG_A), [0, 1, 2]), (tuple(SIG_A), [3, 4])]


def test_batcher_deadline_close():
    events = [(0.0, SIG_A, 0), (2.0, SIG_A, 1), ("advance", 9.9)]
    assert _run_schedule(events, deadline=10.0) == []   # not yet
    events += [("advance", 10.0)]
    assert _run_schedule(events, deadline=10.0) == [
        (tuple(SIG_A), [0, 1])]


def test_batcher_mixed_bucket_interleave_deterministic():
    events = [
        (0.0, SIG_A, 0), (1.0, SIG_B, 1), (2.0, SIG_A, 2),
        (3.0, SIG_B, 3), (4.0, SIG_A, 4),          # A capacity-closes
        (5.0, SIG_B, 5), ("advance", 11.5),        # B deadline-closes
        (12.0, SIG_A, 6), ("advance", 30.0),
    ]
    expect = [
        (tuple(SIG_A), [0, 2, 4]),                 # capacity at t=4
        (tuple(SIG_B), [1, 3, 5]),                 # deadline at 1+10
        (tuple(SIG_A), [6]),                       # deadline at 12+10
    ]
    runs = [_run_schedule(events, deadline=10.0, cap=3) for _ in range(3)]
    assert runs[0] == expect
    assert runs[1] == runs[0] and runs[2] == runs[0]


def test_batcher_simultaneous_deadlines_tie_break_stable():
    # both buckets deadline-expire at the same instant: equal oldest
    # arrivals fall through to the sorted-signature tie break (SIG_A <
    # SIG_B) — a total order, same composition every run
    events = [(0.0, SIG_B, 0), (0.0, SIG_A, 1), ("advance", 10.0)]
    got = _run_schedule(events, deadline=10.0)
    assert got == [(tuple(SIG_A), [1]), (tuple(SIG_B), [0])]


def test_batcher_close_drains_then_signals_exit():
    clk = VirtualClock()
    mb = MicroBatcher(batch_deadline_s=100.0, batch_max=8, clock=clk)
    mb.submit(SIG_A, _lane(0))
    mb.submit(SIG_B, _lane(1))
    mb.close()
    sigs = {tuple(mb.next_batch()[0]) for _ in range(2)}
    assert sigs == {tuple(SIG_A), tuple(SIG_B)}
    assert mb.next_batch() is None
    with pytest.raises(RuntimeError):
        mb.submit(SIG_A, _lane(2))
    assert mb.counters() == {"submitted": 2, "popped": 2, "pending": 0}


# --------------------------------------------------------------------------
# config snapshot (GL303: env read once, at arm time)
# --------------------------------------------------------------------------
def test_config_from_env_snapshot(monkeypatch):
    monkeypatch.setenv("RAFT_TPU_SERVE_BATCH_DEADLINE_MS", "40")
    monkeypatch.setenv("RAFT_TPU_SERVE_BATCH_MAX", "5")
    monkeypatch.setenv("RAFT_TPU_SERVE_SOCKET", "/tmp/x.sock")
    cfg = ServeConfig.from_env(nw=8)
    assert (cfg.batch_deadline_s, cfg.batch_max, cfg.socket_path,
            cfg.nw) == (0.040, 5, "/tmp/x.sock", 8)
    # a mid-process env change must not reach the snapshot
    monkeypatch.setenv("RAFT_TPU_SERVE_BATCH_MAX", "99")
    assert cfg.batch_max == 5
    # overrides win over env
    assert ServeConfig.from_env(batch_max=2).batch_max == 2
    monkeypatch.setenv("RAFT_TPU_SERVE_BATCH_MAX", "zero")
    with pytest.raises(ValueError):
        ServeConfig.from_env()
    monkeypatch.setenv("RAFT_TPU_SERVE_BATCH_MAX", "0")
    with pytest.raises(ValueError):
        ServeConfig.from_env()


# --------------------------------------------------------------------------
# solver: staging memo + padded-batch bit-identity
# --------------------------------------------------------------------------
@pytest.fixture(scope="module")
def core3():
    """One warm SolverCore shared by the solver tests (tiny program:
    nw=8 physical -> 16 padded, 4 iterations, capacity 3)."""
    cfg = ServeConfig(batch_deadline_s=0.01, batch_max=3, nw=8,
                      w_min=0.3, w_max=2.1, n_iter=8, escalate=False)
    return SolverCore(cfg)


def _mk_lane(core, design, Hs, Tp, rid="r"):
    sig, staged = core.stage_lane(design, Hs, Tp)
    return sig, Lane(request_id=rid, seq=0, label=str(rid), staged=staged)


def test_stage_lane_memo_and_routing(core3):
    sig_a, st = core3.stage_lane(OC3, 6.0, 10.0)
    sig_a2, st2 = core3.stage_lane(OC3, 6.0, 10.0)
    assert st is st2, "repeated (design, sea state) must hit the memo"
    sig_b, _ = core3.stage_lane(OC4, 6.0, 10.0)
    assert sig_a == sig_a2
    assert sig_a != sig_b, "OC3 and OC4 must route to different buckets"
    # different sea state = different staging, same bucket
    sig_a3, st3 = core3.stage_lane(OC3, 7.0, 11.0)
    assert sig_a3 == sig_a and st3 is not st


def test_solve_batch_rows_and_occupancy(core3):
    sig, lane = _mk_lane(core3, OC3, 6.0, 10.0)
    rows, info = solve_batch(core3, sig, [lane])
    assert len(rows) == 1
    r = rows[0]
    assert r["converged"] and r["finite"] and not r["quarantined"]
    assert len(r["std_dev"]) == 6 and np.isfinite(r["std_dev"]).all()
    assert info["lanes"] == 1 and info["capacity"] == 3
    assert info["occupancy"] == pytest.approx(1 / 3)


def test_lane_results_batch_composition_independent(core3):
    """THE serving determinism pin: one lane's row is bit-identical
    solo (padded with copies of itself) and in mixed company."""
    sig, lane_a = _mk_lane(core3, OC3, 6.0, 10.0, "a")
    solo_rows, _ = solve_batch(core3, sig, [lane_a])
    # mixed company: a different sea state of the same bucket, twice
    _, lane_b = _mk_lane(core3, OC3, 7.5, 11.0, "b")
    _, lane_c = _mk_lane(core3, OC3, 9.0, 12.5, "c")
    mixed_rows, info = solve_batch(core3, sig, [lane_a, lane_b, lane_c])
    assert info["occupancy"] == 1.0
    assert mixed_rows[0]["std_dev"] == solo_rows[0]["std_dev"], \
        "batch-mates changed a lane's bits"
    assert mixed_rows[0]["iterations"] == solo_rows[0]["iterations"]
    # and the mixed order is respected: b/c rows differ from a's
    assert mixed_rows[1]["std_dev"] != mixed_rows[0]["std_dev"]
    # b solo must equal b-in-mixed too (capacity-close vs deadline-close
    # compositions can never change results)
    solo_b, _ = solve_batch(core3, sig, [_mk_lane(core3, OC3, 7.5, 11.0)[1]])
    assert solo_b[0]["std_dev"] == mixed_rows[1]["std_dev"]


def test_solve_batch_parity_vs_sweep_designs(core3):
    """The serve path IS sweep_designs + padding: a serve row must match
    the plain mixed-design API at float eps (different batch size, same
    per-lane program)."""
    from raft_tpu.parallel.sweep import sweep_designs

    sig, lane = _mk_lane(core3, OC3, 6.0, 10.0)
    rows, _ = solve_batch(core3, sig, [lane])
    ref = sweep_designs([OC3], nw=8, Hs=6.0, Tp=10.0, w_min=0.3,
                        w_max=2.1, n_iter=8, return_xi=False)
    got = np.asarray(rows[0]["std_dev"])
    want = np.asarray(ref["std dev"][0])
    scale = np.max(np.abs(want))
    assert np.max(np.abs(got - want)) <= 1e-9 * scale


def test_nan_lane_quarantined_mates_bitwise(core3):
    """One client's Tp=0 lane (NaN JONSWAP spectrum) is quarantined;
    its batch-mate's bits do not move."""
    sig, good = _mk_lane(core3, OC3, 6.0, 10.0, "good")
    solo_rows, _ = solve_batch(core3, sig, [good])
    _, bad = _mk_lane(core3, OC3, 6.0, 0.0, "bad")
    rows, info = solve_batch(core3, sig, [good, bad])
    assert rows[0]["finite"] and not rows[0]["quarantined"]
    assert rows[0]["std_dev"] == solo_rows[0]["std_dev"]
    assert rows[1]["quarantined"] and not rows[1]["finite"]
    assert not rows[1]["salvaged"]          # escalate=False in core3
    assert 1 in info["quarantined_real"]


def test_solver_refresh_drops_memo(core3):
    core3.stage_lane(OC3, 6.0, 10.0)
    info = core3.refresh()
    assert info["staged_lanes_dropped"] >= 1
    _sig, st = core3.stage_lane(OC3, 6.0, 10.0)
    assert st is core3.stage_lane(OC3, 6.0, 10.0)[1]


# --------------------------------------------------------------------------
# end-to-end over the real socket: two concurrent clients
# --------------------------------------------------------------------------
@pytest.fixture()
def server(tmp_path):
    from raft_tpu.serve.server import SolverServer

    cfg = ServeConfig(batch_deadline_s=0.02, batch_max=3, nw=8,
                      w_min=0.3, w_max=2.1, n_iter=8, escalate=False,
                      socket_path=str(tmp_path / "serve.sock"))
    srv = SolverServer(cfg)
    srv.start()
    yield srv
    srv.stop()


def test_two_clients_concurrent_submit(server):
    from raft_tpu.serve.client import SolveClient

    sock = server.socket_path
    results = {}
    errors = []

    def client_run(name, Hs):
        try:
            with SolveClient(sock) as cl:
                futs = [cl.submit({"op": "solve", "design": "oc3",
                                   "Hs": Hs + 0.5 * j, "Tp": 10.0})
                        for j in range(3)]
                dlc = cl.submit({"op": "dlc", "design": "oc3",
                                 "cases": [[Hs, 10.0], [Hs + 1.0, 12.0]]})
                rs = [f.result(180.0) for f in futs] + [dlc.result(180.0)]
                results[name] = rs
        except Exception as e:          # surfaced by the join below
            errors.append(f"{name}: {type(e).__name__}: {e}")

    t1 = threading.Thread(target=client_run, args=("c1", 6.0))
    t2 = threading.Thread(target=client_run, args=("c2", 8.0))
    t1.start()
    t2.start()
    t1.join(300)
    t2.join(300)
    assert not errors, errors
    assert set(results) == {"c1", "c2"}
    for name, rs in results.items():
        for r in rs[:3]:
            assert r["ok"], r
            assert len(r["results"]) == 1
            assert r["results"][0]["converged"]
        dlc = rs[3]
        assert dlc["ok"] and len(dlc["results"]) == 2
        assert len(dlc["t_queue_s"]) == 2
    # distinct sea states must produce distinct rows (no cross-request
    # result mixing under concurrent submits)
    c1_first = results["c1"][0]["results"][0]["std_dev"]
    c2_first = results["c2"][0]["results"][0]["std_dev"]
    assert c1_first != c2_first


def test_server_stats_refresh_and_bad_request(server):
    from raft_tpu.serve.client import SolveClient

    with SolveClient(server.socket_path) as cl:
        assert cl.ping()["ok"]
        r = cl.solve("oc3", 6.0, 10.0)
        assert r["ok"]
        st = cl.stats()
        assert st["ok"] and st["solver"]["buckets"]
        assert st["solver"]["batch_max"] == 3
        # malformed request: error response, connection stays usable
        bad = cl.call({"op": "solve", "design": "mystery",
                       "Hs": 6, "Tp": 10})
        assert not bad["ok"] and "mystery" in bad["error"]["detail"]
        assert cl.ping()["ok"]
        # refresh with operator-carried knob values
        rf = cl.call({"op": "refresh", "deadline_ms": 5, "batch_max": 2})
        assert rf["ok"] and rf["batch_max"] == 2
        assert server.batcher.batch_max == 2
        assert server.core.config.batch_max == 2
        r2 = cl.solve("oc3", 6.0, 10.0)      # new capacity still solves
        assert r2["ok"]


def test_partial_batch_failure_poisons_whole_request(server, monkeypatch):
    """A sweep spanning two buckets where ONE bucket's batch fails must
    answer ok:false — never ok:true with null rows for the failed
    lanes."""
    from raft_tpu.serve import server as server_mod
    from raft_tpu.serve.client import SolveClient

    real = server_mod.solve_batch
    oc4_sig = server.core.stage_lane(OC4, 6.0, 10.0)[0]

    def flaky(core, sig, lanes):
        if sig == oc4_sig:
            raise RuntimeError("injected bucket failure")
        return real(core, sig, lanes)

    monkeypatch.setattr(server_mod, "solve_batch", flaky)
    with SolveClient(server.socket_path) as cl:
        r = cl.call({"op": "sweep", "designs": ["oc3", "oc4"],
                     "Hs": 6.0, "Tp": 10.0}, timeout=180.0)
        assert not r["ok"]
        assert "injected bucket failure" in r["error"]["detail"]
        # the connection survives and healthy buckets still serve
        ok = cl.solve("oc3", 6.0, 10.0, timeout=180.0)
        assert ok["ok"] and ok["results"][0]["converged"]
    # the poisoned request fed the error budget and the flight recorder
    assert server.flight.counts()["errors"] >= 1
    bad = [rec for rec in server.flight.snapshot()
           if rec["outcome"].startswith("error:")]
    assert bad and bad[0]["op"] == "sweep"
    assert server.telemetry()["error_budget"]["errors"] >= 1


def test_refresh_rejects_malformed_values(server):
    """Malformed refresh values answer with an error response; they must
    not kill the reader thread (which would drop the connection)."""
    from raft_tpu.serve.client import SolveClient

    with SolveClient(server.socket_path) as cl:
        r = cl.call({"op": "refresh", "deadline_ms": "abc"})
        assert not r["ok"] and r["error"]["class"] == "ValueError"
        r2 = cl.call({"op": "refresh", "batch_max": 0})
        assert not r2["ok"]
        assert cl.ping()["ok"]          # connection still alive
        # server state untouched by the rejected values
        assert server.batcher.batch_max == 3


def test_shutdown_op_drains(tmp_path):
    from raft_tpu.serve.client import SolveClient
    from raft_tpu.serve.server import SolverServer

    cfg = ServeConfig(batch_deadline_s=0.02, batch_max=2, nw=8,
                      w_min=0.3, w_max=2.1, n_iter=8, escalate=False,
                      socket_path=str(tmp_path / "s.sock"))
    srv = SolverServer(cfg)
    srv.start()
    with SolveClient(cfg.socket_path) as cl:
        fut = cl.submit({"op": "solve", "design": "oc3",
                         "Hs": 6.0, "Tp": 10.0})
        ack = cl.shutdown()
        assert ack["ok"]
        # the queued request is answered before the daemon exits
        r = fut.result(180.0)
        assert r["ok"] and r["results"][0]["converged"]
    assert srv.wait(60.0)
    # stop() unlinks the socket just after the solver drain signals —
    # poll out the last few milliseconds of the stop thread
    import time as _time

    deadline = _time.monotonic() + 10.0
    while os.path.exists(cfg.socket_path) and _time.monotonic() < deadline:
        _time.sleep(0.02)
    assert not os.path.exists(cfg.socket_path)


# --------------------------------------------------------------------------
# request-scoped tracing + live SLO telemetry
# --------------------------------------------------------------------------
def test_request_trace_tree_spans_client_reader_solver(server, tmp_path):
    """THE request-tracing pin: one request's spans — recorded on the
    client reader thread, the server connection reader, and the solver
    loop — share ONE trace id, form one path tree, and the exported
    Chrome trace keeps valid per-track time containment (metadata
    thread names included)."""
    import json as _json

    from raft_tpu.obs import trace
    from raft_tpu.obs.smoke import _validate_chrome_trace
    from raft_tpu.serve.client import SolveClient

    trace.reset()        # a clean ring: this test validates ITS request
    with SolveClient(server.socket_path) as cl:
        r = cl.solve("oc3", 6.0, 10.0, timeout=180.0)
    assert r["ok"]
    tid = r["trace"]
    assert tid
    spans = [s for s in trace.spans() if s.trace == tid]
    paths = {s.name for s in spans}
    # client root + server root + the reader and solver-loop stages
    assert {"request", "request/server", "request/server/stage",
            "request/server/queue_wait", "request/server/solve"} <= paths
    # the tree really CROSSES threads: the reader-side stage span and
    # the synthetic-track spans record on distinct tids
    assert len({s.tid for s in spans}) >= 3
    # tree containment in ns terms: every server-side span lies inside
    # the client root's interval
    by = {s.name: s for s in spans}
    root = by["request"]
    for name in ("request/server", "request/server/queue_wait",
                 "request/server/solve"):
        s = by[name]
        assert root.t0_us <= s.t0_us
        assert s.t0_us + s.dur_us <= root.t0_us + root.dur_us
    # and the full export passes the Perfetto containment validator
    p = tmp_path / "trace.json"
    p.write_text(_json.dumps(trace.chrome_trace()))
    info = _validate_chrome_trace(str(p))
    assert info["events"] >= 5


def test_queue_wait_exact_under_virtual_clock(tmp_path):
    """Queue wait is EXACTLY batch-close minus submit on the batcher's
    clock: the response's t_queue_s, the flight-recorder breakdown, and
    the windowed SLO quantiles are all hand-computable from a virtual
    schedule."""
    from raft_tpu.obs import metrics
    from raft_tpu.serve import server as server_mod

    clk = VirtualClock()
    cfg = ServeConfig(batch_deadline_s=1.0, batch_max=4, nw=8,
                      socket_path=str(tmp_path / "x.sock"))
    srv = server_mod.SolverServer(cfg, clock=clk)   # never started: the
    # delivery path is exercised directly on hand-built lanes

    class _FakeConn:
        def __init__(self):
            self.sent = []

        def send(self, obj):
            self.sent.append(obj)
            return True

    conn = _FakeConn()
    clk.t = 5.0
    pend = server_mod._PendingRequest(conn, "r1", 2, clk, op="dlc",
                                      trace="t-virt")
    lanes = [Lane(request_id=pend, seq=0, label="a", staged=None,
                  trace="t-virt", t_submit=6.0),
             Lane(request_id=pend, seq=1, label="b", staged=None,
                  trace="t-virt", t_submit=6.5)]
    clk.t = 7.25                               # the batch closes here
    srv._deliver(lanes, [{"lane": 0}, {"lane": 1}], 7.25)
    resp = conn.sent[0]
    assert resp["ok"] and resp["trace"] == "t-virt"
    # EXACT equality, not approx: close minus submit on the same clock
    assert resp["t_queue_s"] == [1.25, 0.75]
    assert resp["t_total_s"] == 2.25           # deliver at 7.25, t0 5.0
    rec = srv.flight.snapshot()[0]
    assert rec["queue_wait_s"] == [1.25, 0.75]
    assert rec["outcome"] == "ok" and rec["trace"] == "t-virt"
    # windowed SLO: one request of latency 2.25 s -> p50 == p99 == the
    # covering log-bucket upper edge
    win = srv._slo_latency.window(now=clk.t)
    edges = metrics.Histogram.edges
    import bisect

    expect = edges[bisect.bisect_left(edges, 2.25)]
    assert win["count"] == 1
    # the window reports 6-significant-digit JSON-safe floats
    assert win["p50"] == win["p99"] == pytest.approx(expect, rel=1e-5)
    tel = srv.telemetry()
    assert tel["error_budget"] == {"requests": 1, "errors": 0,
                                   "error_rate": 0.0}
    assert tel["latency"]["count"] == 1
    assert tel["flight"]["recorded"] == 1


def test_stats_op_returns_telemetry_block(server):
    from raft_tpu.serve.client import SolveClient

    with SolveClient(server.socket_path) as cl:
        r = cl.solve("oc3", 6.0, 10.0, timeout=180.0)
        assert r["ok"]
        st = cl.stats()
    tel = st["telemetry"]
    assert {"uptime_s", "window_s", "latency", "queue_wait", "occupancy",
            "queue_depth", "error_budget", "compiles", "flight",
            "ledger"} <= set(tel)
    lat = tel["latency"]
    assert lat["count"] >= 1 and 0 < lat["p50"] <= lat["p99"]
    assert lat["error_rate"] == 0.0
    assert tel["error_budget"]["requests"] >= 1
    # per-bucket queue-wait windows carry the same shape
    assert tel["queue_wait"]
    for w in tel["queue_wait"].values():
        assert {"count", "p50", "p99", "error_rate"} <= set(w)
    assert tel["flight"]["recorded"] >= 1


def test_reset_telemetry_is_a_window_boundary(tmp_path):
    from raft_tpu.serve import server as server_mod

    clk = VirtualClock()
    cfg = ServeConfig(socket_path=str(tmp_path / "y.sock"))
    srv = server_mod.SolverServer(cfg, clock=clk)
    srv._slo_latency.observe(0.1, now=0.0)
    with srv._lock:
        srv._req_done = 5
    srv.reset_telemetry()
    assert srv._slo_latency.window(now=0.0)["count"] == 0
    assert srv.telemetry()["error_budget"]["requests"] == 0


# --------------------------------------------------------------------------
# loadgen: closed-form schedule + deterministic quantiles
# --------------------------------------------------------------------------
def test_loadgen_schedule_closed_form():
    from raft_tpu.serve import loadgen

    a = [loadgen.schedule(i, 50.0) for i in range(20)]
    b = [loadgen.schedule(i, 50.0) for i in range(20)]
    assert a == b
    designs = {d for d, *_ in a}
    assert designs == set(loadgen.DEFAULT_DESIGNS)
    assert a[0][3] == 0.0 and a[10][3] == pytest.approx(0.2)


def test_loadgen_quantile_rank_statistic():
    from raft_tpu.serve.loadgen import quantile

    xs = [5.0, 1.0, 3.0, 2.0, 4.0]
    assert quantile(xs, 0.5) == 3.0
    assert quantile(xs, 0.99) == 5.0
    assert quantile(xs, 0.0) == 1.0
    assert quantile([7.0], 0.99) == 7.0
    assert np.isnan(quantile([], 0.5))


# --------------------------------------------------------------------------
# cache: tag-scoped executor eviction (the graceful-refresh primitive)
# --------------------------------------------------------------------------
def test_evict_memory_tag_scoped(tmp_path):
    import jax.numpy as jnp

    from raft_tpu import cache
    from raft_tpu.cache import aot

    cache.enable(str(tmp_path / "c"))
    try:
        aot.clear_memory()
        args = (jnp.arange(4, dtype=jnp.float32),)
        f1 = aot.cached_compile("serve_evict_a", lambda x: x + 1, args)
        f2 = aot.cached_compile("serve_evict_b", lambda x: x * 2, args)
        assert aot.cached_compile("serve_evict_a", lambda x: x + 1,
                                  args) is f1
        # evicting tag b leaves tag a memoized
        assert cache.evict_memory("serve_evict_b") == 1
        assert aot.cached_compile("serve_evict_a", lambda x: x + 1,
                                  args) is f1
        # b re-resolves from DISK: a fresh object, but zero new compiles
        c0 = aot.compile_count("serve_evict_b")
        f2b = aot.cached_compile("serve_evict_b", lambda x: x * 2, args)
        assert f2b is not f2
        assert aot.compile_count("serve_evict_b") == c0
        # full eviction
        assert cache.evict_memory() == 2
    finally:
        aot.clear_memory()
        cache.disable()


# --------------------------------------------------------------------------
# docs drift: the serving knob table is generated from the registry
# --------------------------------------------------------------------------
def test_serving_docs_knob_table_in_sync():
    from raft_tpu.lint import knobs

    path = os.path.join(REPO, "docs", "serving.rst")
    block = knobs.rendered_docs_block(open(path, encoding="utf-8").read())
    assert block is not None, "serving.rst lost its AUTOGEN markers"
    assert block.strip() == knobs.rst_table(
        knobs.serve_knob_names()).strip(), (
        "docs/serving.rst knob table is stale — run "
        "`python -m raft_tpu.lint.knobs`")
    assert "RAFT_TPU_SERVE_BATCH_DEADLINE_MS" in block


def test_serve_smoke_stream_is_mixed_and_closed_form():
    from raft_tpu.serve import smoke

    assert len(smoke.STREAM) == 9
    assert {d for d, _h, _t in smoke.STREAM} == {"oc3", "oc4", "volturnus"}
    # closed form: a re-import cannot change the stream
    again = [(d, 6.0 + 0.5 * (i % 3), 10.0 + 0.5 * (i % 2))
             for i, d in enumerate(["oc3", "oc4", "volturnus"] * 3)]
    assert smoke.STREAM == again
