"""graftlint: fixture corpus (one trigger + one near-miss per rule),
suppression + baseline machinery, reachability edge cases, the trace
audit's budget pins for the north-star sweep entry, and the repo gate
(the merged tree must stay clean vs the committed baseline — running in
the fast tier makes any lint regression fail ``make fast``)."""
import json
import os
import subprocess
import sys
import textwrap

import pytest

from raft_tpu.lint import baseline as bl
from raft_tpu.lint.rules import RULES, lint_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint_src(tmp_path, src, name="mod.py", extra=None):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    if extra:
        for fname, fsrc in extra.items():
            (tmp_path / fname).write_text(textwrap.dedent(fsrc))
    return lint_paths([str(tmp_path)], str(tmp_path))


# --------------------------------------------------------------------------
# fixture corpus: (rule, trigger source, near-miss source)
# --------------------------------------------------------------------------
FIXTURES = {
    "GL101": (
        """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return np.sin(x)
        """,
        """
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def f(x):
            c = np.sin(0.5)          # host constant: no tracer involved
            return jnp.sin(x) * c
        """,
    ),
    "GL102": (
        """
        import jax

        @jax.jit
        def f(x):
            return float(x) + 1.0
        """,
        """
        import jax

        @jax.jit
        def f(x):
            n = float(x.shape[0])    # shape is static under trace
            return x * n
        """,
    ),
    "GL103": (
        """
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
        """,
        """
        import jax

        @jax.jit
        def f(x, flag=None):
            if flag is None:         # pytree-structure check: static
                return x
            if x.shape[0] == 3:      # shape: static
                return x + x
            return x
        """,
    ),
    "GL104": (
        """
        from functools import partial
        import jax
        import jax.numpy as jnp

        Array = jnp.ndarray

        @partial(jax.jit, static_argnames=("scale", "typo"))
        def f(x, scale: Array):
            return x * scale
        """,
        """
        from functools import partial
        import jax

        @partial(jax.jit, static_argnames=("n",))
        def f(x, n: int = 3):
            return x * n
        """,
    ),
    "GL105": (
        """
        import numpy as np

        BAD = np.zeros(3, dtype=np.float64)

        def g(arr):
            return arr.astype("float64")
        """,
        """
        import numpy as np

        OK = np.zeros(3, dtype=np.float32)
        # justified host-side use rides a suppression:
        HASHED = np.float64(1.5)  # graftlint: disable=GL105
        """,
    ),
    "GL106": (
        """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            print(x)
            return np.asarray(x)
        """,
        """
        import numpy as np

        def host_report(x):          # never jit-reachable: host is free
            print(x)
            return np.asarray(x)
        """,
    ),
    "GL107": (
        """
        def key_parts(names):
            out = []
            for k in {"b", "a"}:
                out.append(k)
            return tuple(set(out))
        """,
        """
        def key_parts(names):
            out = []
            for k in sorted({"b", "a"}):
                out.append(k)
            return tuple(sorted(set(out)))
        """,
    ),
    "GL201": (
        """
        import os

        def stage(x):
            return os.environ.get("RAFT_TPU_WIDGET", "1")
        """,
        """
        import os

        def stage(x):
            # registered host-only knob, read in host-side code: fine
            return os.environ.get("RAFT_TPU_STRICT", "1")
        """,
    ),
    "GL202": (
        """
        import json
        import os
        from raft_tpu.cache.config import subdir

        def publish(payload, key):
            path = os.path.join(subdir("aot"), key + ".json")
            with open(path, "w") as f:
                json.dump(payload, f)
        """,
        """
        import json
        import os
        import tempfile
        from raft_tpu.cache.config import subdir

        def publish(payload, key):
            path = os.path.join(subdir("aot"), key + ".json")
            fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
            with os.fdopen(fd, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
        """,
    ),
    "GL203": (
        """
        import subprocess

        def build(cmd):
            return subprocess.run(cmd, capture_output=True)
        """,
        """
        import subprocess

        def build(cmd):
            return subprocess.run(cmd, capture_output=True, timeout=300.0)
        """,
    ),
    "GL204": (
        """
        import jax

        def make_step(step):
            return jax.jit(step, donate_argnums=(0,))
        """,
        """
        from raft_tpu.cache.aot import cached_callable

        def make_step(step, x):
            return cached_callable("step", step, (x,),
                                   jit_kwargs={"donate_argnums": (0,)})
        """,
    ),
    "GL301": (
        """
        import threading

        _memo: dict = {}
        _lock = threading.Lock()

        def remember(k, v):
            _memo[k] = v
        """,
        """
        import threading

        _memo: dict = {}
        _lock = threading.Lock()

        def remember(k, v):
            with _lock:
                _memo[k] = v

        def shadowed(k, v):
            _memo = {}               # local: shadows the module global
            _memo[k] = v
            return _memo
        """,
    ),
    "GL302": (
        """
        _memo: dict = {}

        def get_or_compute(k):
            if k not in _memo:
                _memo[k] = k * 2
            return _memo[k]
        """,
        """
        import threading

        _memo: dict = {}
        _lock = threading.Lock()

        def get_or_compute(k):
            with _lock:              # one lock spans check AND act
                if k not in _memo:
                    _memo[k] = k * 2
                return _memo[k]
        """,
    ),
    "GL303": (
        """
        import os

        __graftlint_concurrent__ = ("serve",)

        def serve(req):
            return req * _depth()

        def _depth():
            return int(os.environ.get("RAFT_TPU_PIPELINE_DEPTH", "2"))
        """,
        """
        import os

        __graftlint_concurrent__ = ("serve",)

        def serve(req, depth: int):
            return req * depth

        def arm():
            # snapshot at arm time, outside the concurrent request path
            return int(os.environ.get("RAFT_TPU_PIPELINE_DEPTH", "2"))
        """,
    ),
    "GL401": (
        """
        import os

        import jax
        from raft_tpu.cache import cached_callable

        __graftlint_multihost__ = ("sweep",)

        def sweep(xs, mesh):
            if os.environ.get("SWEEP_DEBUG_HOST"):
                return _dispatch(xs, mesh)
            return xs

        def _dispatch(xs, mesh):
            fn = cached_callable("t", jax.vmap(lambda x: x * 2), (xs,),
                                 mesh=mesh)
            return fn(xs)
        """,
        """
        import os

        import jax
        from raft_tpu.cache import cached_callable

        __graftlint_multihost__ = ("sweep",)

        def sweep(xs, mesh):
            # key-salted knob: the compiled program moves WITH the value,
            # identically on every host (the GL303 triage precedent)
            if os.environ.get("RAFT_TPU_BEM"):
                return _dispatch(xs, mesh)
            return xs

        def _dispatch(xs, mesh):
            fn = cached_callable("t", jax.vmap(lambda x: x * 2), (xs,),
                                 mesh=mesh)
            return fn(xs)
        """,
    ),
    "GL402": (
        """
        import os

        __graftlint_multihost__ = ("export",)

        def resolve_dir():
            return os.environ.get("RAFT_TPU_OBS", "/tmp/obs")

        def _atomic_write(path, payload):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(payload)
            os.replace(tmp, path)

        def export(payload):
            d = resolve_dir()
            path = os.path.join(d, f"obs-{os.getpid()}.jsonl")
            _atomic_write(path, payload)
        """,
        """
        import os

        import jax

        __graftlint_multihost__ = ("export",)

        def resolve_dir():
            return os.environ.get("RAFT_TPU_OBS", "/tmp/obs")

        def _atomic_write(path, payload):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(payload)
            os.replace(tmp, path)

        def export(payload):
            d = resolve_dir()
            tag = f"p{jax.process_index()}-{os.getpid()}"
            path = os.path.join(d, f"obs-{tag}.jsonl")
            _atomic_write(path, payload)
        """,
    ),
    "GL403": (
        """
        import jax
        import jax.numpy as jnp
        from raft_tpu.cache import cached_callable

        __graftlint_multihost__ = ("sweep",)

        def sweep(xs):
            big = jnp.zeros((64, 64))

            def one(x):
                return (x * big).sum()

            fn = cached_callable("t", jax.vmap(one), (xs,))
            return fn(xs)
        """,
        """
        import jax
        import jax.numpy as jnp
        from raft_tpu.cache import cached_callable

        __graftlint_multihost__ = ("sweep",)

        def sweep(xs, mesh):
            big = jnp.zeros((64, 64))

            def one(x):
                return (x * big).sum()

            fn = cached_callable("t", jax.vmap(one), (xs,),
                                 consts=(big,), mesh=mesh)
            return fn(xs)
        """,
    ),
    "GL404": (
        """
        import jax
        import numpy as np
        from jax.sharding import Mesh

        __graftlint_multihost__ = ("reduce_stats",)

        def make_mesh():
            return Mesh(np.array(jax.devices()), axis_names=("designs",))

        def reduce_stats(x):
            if jax.process_index() == 0:
                x = jax.lax.psum(x, "dezigns")
            return x
        """,
        """
        import jax
        import numpy as np
        from jax.sharding import Mesh

        __graftlint_multihost__ = ("reduce_stats",)

        def make_mesh():
            return Mesh(np.array(jax.devices()), axis_names=("designs",))

        def reduce_stats(x):
            # unconditional: every host joins, on the declared axis
            return jax.lax.psum(x, "designs")
        """,
    ),
}


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_rule_triggers(tmp_path, rule):
    trigger, _ = FIXTURES[rule]
    vs = _lint_src(tmp_path, trigger)
    hits = [v for v in vs if v.rule == rule]
    assert hits, f"{rule} fixture produced no {rule} violation: {vs}"


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_rule_near_miss(tmp_path, rule):
    _, near_miss = FIXTURES[rule]
    vs = _lint_src(tmp_path, near_miss)
    hits = [v for v in vs if v.rule == rule]
    assert not hits, f"{rule} near-miss wrongly flagged: " + "\n".join(
        v.format() for v in hits)


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_cli_fails_on_each_seeded_fixture(tmp_path, rule):
    """`python -m raft_tpu.lint <fixture>` (in-process main) must exit
    non-zero on every seeded-violation fixture — the acceptance gate."""
    from raft_tpu.lint.cli import main

    p = tmp_path / "bad.py"
    p.write_text(textwrap.dedent(FIXTURES[rule][0]))
    rc = main([str(p), "--root", str(tmp_path), "--no-baseline"])
    assert rc == 1


# --------------------------------------------------------------------------
# reachability edges
# --------------------------------------------------------------------------
def test_nested_def_passed_to_vmap_is_reachable(tmp_path):
    vs = _lint_src(tmp_path, """
        import jax
        import numpy as np

        def orchestrator(members, thetas):
            def one(theta):
                return np.abs(theta)
            return jax.jit(jax.vmap(one))(thetas)
        """)
    assert any(v.rule == "GL101" and ".one" in v.msg for v in vs), vs


def test_returned_closure_is_reachable(tmp_path):
    vs = _lint_src(tmp_path, """
        import numpy as np

        def make_loss(members):
            def loss(theta):
                return np.abs(theta)
            return loss
        """)
    assert any(v.rule == "GL101" for v in vs), vs


def test_cross_module_call_edge(tmp_path):
    vs = _lint_src(tmp_path, """
        import jax
        from helper import warp

        @jax.jit
        def f(x):
            return warp(x)
        """, extra={"helper.py": """
        import numpy as np

        def warp(x):
            return np.tanh(x)
        """})
    assert any(v.rule == "GL101" and v.path == "helper.py" for v in vs), vs


def test_host_orchestrator_not_reachable(tmp_path):
    """A host function calling jitted code freely uses numpy/print."""
    vs = _lint_src(tmp_path, """
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def solve(x):
            return jnp.sin(x)

        def orchestrator(x):
            out = solve(jnp.asarray(x))
            print("done")
            return np.asarray(out)
        """)
    assert vs == [], [v.format() for v in vs]


def test_jax_tree_map_is_not_a_tracing_transform(tmp_path):
    vs = _lint_src(tmp_path, """
        import jax
        import numpy as np

        def stage(tree):
            def put(x):
                return np.asarray(x)
            return jax.tree.map(put, tree)
        """)
    assert vs == [], [v.format() for v in vs]


def test_static_argname_params_are_not_traced(tmp_path):
    vs = _lint_src(tmp_path, """
        from functools import partial
        import jax

        @partial(jax.jit, static_argnames=("method",))
        def f(x, method):
            if method == "scan":
                return x + 1
            return x
        """)
    assert vs == [], [v.format() for v in vs]


# --------------------------------------------------------------------------
# suppression + baseline machinery
# --------------------------------------------------------------------------
def test_gl105_catches_from_import_spelling(tmp_path):
    vs = _lint_src(tmp_path, """
        from numpy import float64 as f64

        BAD = f64(1.5)
        """)
    assert any(v.rule == "GL105" for v in vs), vs


def test_line_suppression(tmp_path):
    vs = _lint_src(tmp_path, """
        import numpy as np

        A = np.zeros(2, dtype=np.float64)  # graftlint: disable=GL105
        """)
    assert vs == []


def test_file_suppression(tmp_path):
    vs = _lint_src(tmp_path, """
        # graftlint: disable-file=GL105 — host ABI requires doubles
        import numpy as np

        A = np.zeros(2, dtype=np.float64)
        B = np.ones(2, dtype=np.float64)
        """)
    assert vs == []


def test_baseline_round_trip(tmp_path):
    src = """
        import numpy as np

        A = np.zeros(2, dtype=np.float64)
        """
    vs = _lint_src(tmp_path, src)
    assert len(vs) == 1
    path = str(tmp_path / "baseline.json")
    bl.save(vs, path)
    fresh, absorbed = bl.filter_new(vs, path)
    assert fresh == [] and absorbed == 1
    # a NEW violation in the same file is not absorbed
    vs2 = _lint_src(tmp_path, src + "B = np.ones(3, dtype=np.float64)\n")
    fresh2, absorbed2 = bl.filter_new(vs2, path)
    assert absorbed2 == 1 and len(fresh2) == 1
    # fingerprints are line-number-free: prepending a comment moves every
    # line yet the baseline still absorbs the violation
    vs3 = _lint_src(tmp_path,
                    "# a new leading comment\n" + textwrap.dedent(src))
    fresh3, _ = bl.filter_new(vs3, path)
    assert fresh3 == []


# --------------------------------------------------------------------------
# contract rules: reachability through the AOT registry + edge semantics
# --------------------------------------------------------------------------
def test_cached_callable_fn_is_jit_reachable(tmp_path):
    """A function handed to cached_compile/cached_callable is traced and
    compiled like a jax.jit target — GL1xx rules must see it."""
    vs = _lint_src(tmp_path, """
        import numpy as np
        from raft_tpu.cache.aot import cached_callable

        def orchestrate(x):
            def one(v):
                return np.sin(v)
            return cached_callable("t", one, (x,))(x)
        """)
    assert any(v.rule == "GL101" and ".one" in v.msg for v in vs), vs


def test_gl201_salted_knob_in_traced_code_ok(tmp_path):
    """A key-salted knob (RAFT_TPU_PALLAS rides _solver_salts) may be
    read at trace time: the AOT key distinguishes its settings."""
    vs = _lint_src(tmp_path, """
        import os
        import jax

        @jax.jit
        def f(x):
            on = os.environ.get("RAFT_TPU_PALLAS") == "1"
            return x * (2.0 if on else 1.0)
        """)
    assert not any(v.rule == "GL201" for v in vs), vs


def test_gl201_host_knob_in_traced_code_flagged(tmp_path):
    """A host-only knob read inside jit-reachable code bakes its value
    into compiled programs the AOT key cannot tell apart."""
    vs = _lint_src(tmp_path, """
        import os
        import jax

        @jax.jit
        def f(x):
            depth = int(os.environ.get("RAFT_TPU_PIPELINE_DEPTH", "2"))
            return x * depth
        """)
    hits = [v for v in vs if v.rule == "GL201"]
    assert hits and "jit-reachable" in hits[0].msg, vs


def test_gl202_taints_through_join_and_or(tmp_path):
    """The native_bem shape: root from cache_dir()/resolve_dir(), path
    through os.path.join chains, then a direct np.savez write."""
    vs = _lint_src(tmp_path, """
        import os
        import numpy as np
        from raft_tpu.cache import config

        def persist(A):
            root = config.cache_dir() or config.resolve_dir()
            base = os.path.join(root, "bem")
            key = os.path.join(base, "k.npz")
            np.savez_compressed(key, A=A)
        """)
    assert any(v.rule == "GL202" for v in vs), vs


def test_gl202_taint_survives_deep_join_chains(tmp_path):
    """The taint fixpoint runs until stable, not a fixed pass count —
    body nodes arrive in non-source order, so a long join chain needs
    as many passes as links."""
    vs = _lint_src(tmp_path, """
        import os
        import numpy as np
        from raft_tpu.cache import config

        def persist(A):
            root = config.cache_dir()
            a = os.path.join(root, 'x')
            b = os.path.join(a, 'y')
            c = os.path.join(b, 'z')
            d = os.path.join(c, 'w')
            np.savez_compressed(d, A=A)
        """)
    assert any(v.rule == "GL202" for v in vs), vs


def test_gl203_popen_always_flagged(tmp_path):
    vs = _lint_src(tmp_path, """
        import subprocess

        def spawn(cmd):
            return subprocess.Popen(cmd)
        """)
    assert any(v.rule == "GL203" and "Popen" in v.msg for v in vs), vs


def test_gl204_out_of_range_donation_at_registry_site(tmp_path):
    vs = _lint_src(tmp_path, """
        from raft_tpu.cache.aot import cached_callable

        def make(fn, x):
            return cached_callable("t", fn, (x,),
                                   jit_kwargs={"donate_argnums": (3,)})
        """)
    assert any(v.rule == "GL204" and "out of range" in v.msg
               for v in vs), vs


def test_gl204_keyword_args_after_jit_kwargs(tmp_path):
    """args= resolved regardless of keyword order relative to
    jit_kwargs= (a lexical-order dependence was a false negative)."""
    vs = _lint_src(tmp_path, """
        from raft_tpu.cache.aot import cached_compile

        def make(fn, x):
            return cached_compile("t", fn,
                                  jit_kwargs={"donate_argnums": (3,)},
                                  args=(x,))
        """)
    assert any(v.rule == "GL204" and "out of range" in v.msg
               for v in vs), vs


# --------------------------------------------------------------------------
# concurrency contracts: GL301/302/303 edges + entry-point registry drift
# --------------------------------------------------------------------------
def test_gl301_mutator_methods_and_augassign(tmp_path):
    vs = _lint_src(tmp_path, """
        from collections import deque

        _ring = deque(maxlen=8)
        _counts: dict = {}

        def record(tag):
            _ring.append(tag)
            _counts[tag] = _counts.get(tag, 0) + 1

        def bump(tag):
            _counts[tag] += 1
        """)
    hits = [v for v in vs if v.rule == "GL301"]
    assert {(v.func, v.line) for v in hits} == {
        ("record", 8), ("record", 9), ("bump", 12)}, [
        v.format() for v in vs]


def test_gl301_module_level_init_exempt(tmp_path):
    """Import-time population of a module global is serialized by the
    import lock — only function-body mutations are contract writes."""
    vs = _lint_src(tmp_path, """
        _table: dict = {}
        for _k in ("a", "b"):
            _table[_k] = len(_k)
        """)
    assert not any(v.rule == "GL301" for v in vs), [
        v.format() for v in vs]


def test_gl301_nested_def_does_not_inherit_lock(tmp_path):
    """A closure defined inside a `with lock:` block runs LATER, without
    the lock held — its mutations are bare."""
    vs = _lint_src(tmp_path, """
        import threading

        _memo: dict = {}
        _lock = threading.Lock()

        def make(k):
            with _lock:
                def later(v):
                    _memo[k] = v
                return later
        """)
    assert any(v.rule == "GL301" and "later" in v.func for v in vs), [
        v.format() for v in vs]


def test_gl302_get_then_assign_flagged(tmp_path):
    """The AOT-memo shape: unlocked d.get(k) in a function that also
    stores into d."""
    vs = _lint_src(tmp_path, """
        _mem: dict = {}

        def get_or_compile(key):
            hit = _mem.get(key)
            if hit is None:
                hit = key * 2
                _mem[key] = hit
            return hit
        """)
    assert any(v.rule == "GL302" and ".get(" in v.msg for v in vs), [
        v.format() for v in vs]


def test_gl302_readonly_get_not_flagged(tmp_path):
    """A dict the function never stores into is a read-only lookup —
    knobs-registry style .get() must stay clean."""
    vs = _lint_src(tmp_path, """
        _by_name = {k: k for k in ("a", "b")}

        def lookup(name):
            return _by_name.get(name)
        """)
    assert not any(v.rule == "GL302" for v in vs), [
        v.format() for v in vs]


def test_gl303_crosses_module_attribute_calls(tmp_path):
    """Concurrent reachability follows module_alias.func edges across
    files — the daemon request path is spelled that way."""
    vs = _lint_src(tmp_path, """
        import helper

        __graftlint_concurrent__ = ("serve",)

        def serve(req):
            return helper.depth() + req
        """, extra={"helper.py": """
        import os

        def depth():
            return int(os.environ.get("RAFT_TPU_PIPELINE_DEPTH", "2"))
        """})
    assert any(v.rule == "GL303" and v.path == "helper.py" for v in vs), [
        v.format() for v in vs]


def test_gl303_repo_seeds_reach_pipeline_knob():
    """Linting the real package, the registry's concurrent entries must
    reach the dispatch-ahead executor's env knob read (triaged in the
    baseline) — the reachability cannot silently go dark."""
    vs = lint_paths(["raft_tpu"], REPO)
    assert any(v.rule == "GL303"
               and v.path == "raft_tpu/parallel/pipeline.py"
               for v in vs), "GL303 lost the sweep->pipeline edge"


def test_concurrent_entry_registry_drift():
    """Every concurrent=True audit entry rides CONCURRENT_FUNCTIONS,
    every registered name resolves to a real callable (no zombie
    flags), and each is named in the docs' Concurrency contracts
    section — the knobs table==registry precedent."""
    import importlib

    from raft_tpu.lint import registry

    conc = {e.public_api for e in registry.ENTRY_POINTS if e.concurrent}
    assert conc, "no concurrent=True entries registered"
    assert conc <= set(registry.CONCURRENT_FUNCTIONS)
    for dotted in registry.CONCURRENT_FUNCTIONS:
        mod_name, fn_name = dotted.rsplit(".", 1)
        fn = getattr(importlib.import_module(mod_name), fn_name, None)
        assert callable(fn), f"zombie concurrent flag: {dotted}"
    docs = open(os.path.join(REPO, "docs", "architecture.rst"),
                encoding="utf-8").read()
    assert "Concurrency contracts" in docs
    for dotted in registry.CONCURRENT_FUNCTIONS:
        assert dotted in docs, (
            f"{dotted} missing from docs/architecture.rst "
            f"'Concurrency contracts'")


def test_multihost_entry_registry_drift():
    """Every multihost=True audit entry is also sharded=True (a pod
    entry whose lowering is never audited sharded is a blind spot),
    every MULTIHOST_FUNCTIONS name resolves to a real callable (no
    zombie flags), and each is named in the docs' SPMD contracts
    section — the concurrent-registry precedent, one family up."""
    import importlib

    from raft_tpu.lint import registry

    mh = {e.name for e in registry.ENTRY_POINTS if e.multihost}
    sharded = {e.name for e in registry.ENTRY_POINTS if e.sharded}
    assert mh, "no multihost=True entries registered"
    assert mh <= sharded, (
        f"multihost entries missing the sharded-lowering audit: "
        f"{sorted(mh - sharded)}")
    for dotted in registry.MULTIHOST_FUNCTIONS:
        mod_name, fn_name = dotted.rsplit(".", 1)
        fn = getattr(importlib.import_module(mod_name), fn_name, None)
        assert callable(fn), f"zombie multihost flag: {dotted}"
    docs = open(os.path.join(REPO, "docs", "architecture.rst"),
                encoding="utf-8").read()
    assert "SPMD contracts" in docs
    for dotted in registry.MULTIHOST_FUNCTIONS:
        assert dotted in docs, (
            f"{dotted} missing from docs/architecture.rst "
            f"'SPMD contracts'")


def test_sharded_lowering_bound_on_real_entry():
    """The sharded-lowering gate, end to end on one real registry entry:
    lowering sweep_designs with the batch axis sharded over the audit
    mesh must cost <= replicated / n_devices x (1 + tolerance) in
    per-device peak bytes — the claim budgets.json commits for every
    sharded entry.  Missing metrics must fail LOUD."""
    from raft_tpu.lint import audit, registry

    e = next(e for e in registry.ENTRY_POINTS
             if e.name == "sweep_designs" and e.sharded)
    mesh = audit._sharded_mesh()
    m = audit.sharded_metrics(e, mesh)
    n = audit.SHARDED_MESH_DEVICES
    assert m["sharded_mesh_devices"] == n
    assert m["sharded_batch_lanes"] % n == 0
    ok, notes = audit.check_sharded(e.name, m)
    assert ok, notes
    assert m["sharded_peak_bytes"] <= (
        m["replicated_peak_bytes"] / n * (1 + audit.SHARDED_TOLERANCE))
    bad_ok, bad_notes = audit.check_sharded("ghost", {})
    assert not bad_ok and bad_notes


def test_gl3xx_baseline_reasons_cover_triaged_findings():
    """Every triaged GL3xx fingerprint carries its justification in the
    baseline's _reasons map — the zero-unsuppressed-findings bar means
    triage, and triage means saying why."""
    data = json.load(open(os.path.join(
        REPO, "raft_tpu", "lint", "baseline.json")))
    gl3 = [fp for fp in data["violations"] if fp.startswith("GL3")]
    reasons = data.get("_reasons", {})
    missing = [fp for fp in gl3 if not reasons.get(fp, "").strip()]
    assert not missing, f"GL3xx baseline entries without a reason: {missing}"


def test_baseline_save_preserves_reasons(tmp_path):
    vs = _lint_src(tmp_path, """
        import numpy as np

        A = np.zeros(2, dtype=np.float64)
        """)
    path = str(tmp_path / "baseline.json")
    bl.save(vs, path)
    data = json.load(open(path))
    (fp,) = data["violations"]
    data["_reasons"] = {fp: "host ABI needs doubles", "stale": "gone"}
    json.dump(data, open(path, "w"))
    bl.save(vs, path)       # refresh: surviving reason kept, stale dropped
    data2 = json.load(open(path))
    assert data2["_reasons"] == {fp: "host ABI needs doubles"}


# --------------------------------------------------------------------------
# knob registry: env-read coverage + salt sites + docs table drift
# --------------------------------------------------------------------------
def test_every_env_read_is_registered():
    """Adding an env knob without a registry entry (or keeping a zombie
    entry no code reads) fails here — the docs table and GL201 both
    build on the registry being exact."""
    from raft_tpu.lint import knobs
    from raft_tpu.lint.rules import collect_env_reads

    reads = collect_env_reads(
        ["raft_tpu", "__graft_entry__.py", "bench.py", "examples"], REPO)
    unregistered = set(reads) - knobs.names()
    assert not unregistered, (
        f"env knobs read but not registered in lint/knobs.py: "
        f"{ {k: reads[k] for k in sorted(unregistered)} }")
    zombies = {k.name for k in knobs.KNOBS
               if k.name.startswith("RAFT_TPU_")} - set(reads)
    assert not zombies, (f"registered knobs no code reads any more "
                         f"(delete them): {sorted(zombies)}")


def test_aot_key_knobs_have_live_salt_sites():
    """Each key-salted knob declares the function folding it into the
    AOT keys; that function must exist and its source must carry the
    declared token — the classification cannot rot into a claim."""
    import importlib
    import inspect

    from raft_tpu.lint import knobs

    for k in knobs.KNOBS:
        if k.classification != knobs.AOT_KEY:
            assert k.salted_via is None, k
            continue
        assert k.salted_via and k.salt_token, k
        mod_name, fn_name = k.salted_via.rsplit(".", 1)
        fn = getattr(importlib.import_module(mod_name), fn_name)
        src = inspect.getsource(fn)
        assert k.salt_token in src, (
            f"{k.name}: salt site {k.salted_via} no longer mentions "
            f"{k.salt_token!r}")


def test_docs_knob_table_in_sync():
    """docs/usage.rst's generated block == the registry's rendering
    (regenerate with `python -m raft_tpu.lint.knobs`)."""
    from raft_tpu.lint import knobs

    text = open(os.path.join(REPO, "docs", "usage.rst"),
                encoding="utf-8").read()
    block = knobs.rendered_docs_block(text)
    assert block is not None, "AUTOGEN markers missing from docs/usage.rst"
    assert block.strip() == knobs.rst_table().strip(), (
        "docs/usage.rst knob table is stale — run "
        "`python -m raft_tpu.lint.knobs`")


# --------------------------------------------------------------------------
# repo gate: the merged tree stays clean (fails `make fast` on regression)
# --------------------------------------------------------------------------
def test_repo_is_lint_clean_vs_baseline():
    vs = lint_paths(["raft_tpu", "__graft_entry__.py", "bench.py",
                     "examples"], REPO)
    fresh, _ = bl.filter_new(vs)
    assert fresh == [], "NEW lint violations:\n" + "\n".join(
        v.format() for v in fresh)


def test_cli_fails_loud_on_typod_target(tmp_path):
    """A misspelled lint target must never report green over zero files."""
    from raft_tpu.lint.cli import main

    rc = main([str(tmp_path / "sovle"), "--root", str(tmp_path),
               "--no-baseline"])
    assert rc == 2


def test_cli_subprocess_green_on_repo():
    r = subprocess.run([sys.executable, "-m", "raft_tpu.lint", "--json"],
                       cwd=REPO, capture_output=True, text=True,
                       timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["ok"] and summary["static"]["new"] == 0


# --------------------------------------------------------------------------
# trace audit
# --------------------------------------------------------------------------
def test_audit_north_star_sweep_budgets():
    """Acceptance pin: repeated same-shape north-star sweep call does not
    retrace, and its jaxpr has zero f64 leaves under x32 and zero host
    callbacks."""
    from raft_tpu.lint.audit import audit_entry
    from raft_tpu.lint.registry import get_entries

    (entry,) = get_entries(["north_star_sweep"])
    r = audit_entry(entry)
    assert r.retraces == 0, r.to_dict()
    assert r.f64_leaves == 0, r.to_dict()
    assert r.host_callbacks == 0, r.to_dict()
    assert r.ok and r.n_eqns > 100


def test_audit_registry_covers_required_entries():
    from raft_tpu.lint.registry import ENTRY_POINTS

    names = {e.name for e in ENTRY_POINTS}
    assert {"north_star_sweep", "dlc_solve", "freq_sharded_forward",
            "val_grad", "eigen", "fused_rao_solve"} <= names


def test_audit_jaxpr_detects_f64_leaves():
    import jax
    import jax.numpy as jnp

    from raft_tpu.lint.audit import audit_jaxpr

    # the suite runs x64, so a float64 pipeline is easy to make; the
    # walker must count its wide avals
    jaxpr = jax.make_jaxpr(lambda x: jnp.sin(x) * 2.0)(
        jnp.ones(3, dtype=jnp.float64))
    n_eqns, wide, examples, callbacks = audit_jaxpr(jaxpr)
    assert wide > 0 and examples


def test_audit_jaxpr_detects_host_callbacks():
    import jax
    import jax.numpy as jnp

    from raft_tpu.lint.audit import audit_jaxpr

    def f(x):
        jax.debug.print("x={x}", x=x)
        return x * 2

    jaxpr = jax.make_jaxpr(f)(jnp.ones(3))
    _, _, _, callbacks = audit_jaxpr(jaxpr)
    assert callbacks >= 1


def test_retrace_counter_detects_signature_instability():
    import jax.numpy as jnp

    from raft_tpu.lint.audit import _count_retraces

    fn = lambda x: x + 1  # noqa: E731
    # same shape, different dtype: a second abstract signature must be
    # reported as a retrace
    n = _count_retraces(fn, (jnp.ones(3, dtype=jnp.float32),),
                        (jnp.ones(3, dtype=jnp.int32),))
    assert n == 1
    n0 = _count_retraces(fn, (jnp.ones(3, dtype=jnp.float32),),
                         (2.0 * jnp.ones(3, dtype=jnp.float32),))
    assert n0 == 0


def test_rules_catalog_documented():
    """Every rule ID has a docs section (docs/lint.rst ships the catalog)."""
    docs = open(os.path.join(REPO, "docs", "lint.rst")).read()
    for rule in RULES:
        assert rule in docs, f"{rule} missing from docs/lint.rst"


# --------------------------------------------------------------------------
# compiled-artifact budget audit
# --------------------------------------------------------------------------
def _committed_budgets():
    from raft_tpu.lint import audit

    return audit.load_budgets()


def test_repo_budgets_cover_every_registered_entry():
    """Acceptance gate: all registered audit entries carry committed CPU
    budgets (registering an entry without budgeting it is half a gate)."""
    from raft_tpu.lint.registry import ENTRY_POINTS

    plat = _committed_budgets()["platforms"].get("cpu", {})
    missing = {e.name for e in ENTRY_POINTS} - set(plat)
    assert not missing, (f"registered entries without committed budgets "
                         f"(run `make lint-budgets`): {sorted(missing)}")
    for name, b in plat.items():
        metrics = [k for k in b if not k.startswith("_")]
        assert {"n_eqns", "flops", "bytes_accessed"} <= set(metrics), (
            name, metrics)


def test_budget_check_passes_within_tolerance():
    from raft_tpu.lint.audit import check_budget

    budgets = {"tolerance": 0.25,
               "platforms": {"cpu": {"e": {"flops": 1000.0,
                                           "n_eqns": 100}}}}
    ok, notes = check_budget("e", {"flops": 1100.0, "n_eqns": 100},
                             budgets, "cpu")
    assert ok, notes


def test_budget_check_fails_on_perturbed_budget():
    """The acceptance fixture: perturb a stored budget downward (so the
    unchanged program now reads as a regression) and the audit must fail
    loud, naming the metric."""
    from raft_tpu.lint.audit import check_budget

    metrics = {"flops": 1000.0, "n_eqns": 100}
    perturbed = {"tolerance": 0.25,
                 "platforms": {"cpu": {"e": {"flops": 500.0,
                                             "n_eqns": 100}}}}
    ok, notes = check_budget("e", metrics, perturbed, "cpu")
    assert not ok
    assert any("flops" in n and "exceeds budget" in n for n in notes), notes


def test_budget_check_fails_on_missing_budget_and_metric():
    from raft_tpu.lint.audit import check_budget

    budgets = {"tolerance": 0.25, "platforms": {"cpu": {}}}
    ok, notes = check_budget("e", {"flops": 1.0}, budgets, "cpu")
    assert not ok and "no committed budget" in notes[0]
    budgets = {"tolerance": 0.25,
               "platforms": {"cpu": {"e": {"temp_bytes": 64}}}}
    ok, notes = check_budget("e", {"flops": 1.0}, budgets, "cpu")
    assert not ok and any("unavailable" in n for n in notes), notes


def test_budget_improvement_is_note_not_failure():
    from raft_tpu.lint.audit import check_budget

    budgets = {"tolerance": 0.25,
               "platforms": {"cpu": {"e": {"flops": 1000.0}}}}
    ok, notes = check_budget("e", {"flops": 100.0}, budgets, "cpu")
    assert ok and any("below budget" in n for n in notes), notes


def test_write_budgets_preserves_tolerance_overrides(tmp_path):
    """A --write-budgets refresh replaces measured values only: the
    per-entry '_tolerance' override is maintainer state and survives."""
    import json

    from raft_tpu.lint.audit import AuditReport, save_budgets

    path = str(tmp_path / "budgets.json")
    json.dump({"tolerance": 0.25,
               "platforms": {"cpu": {"e": {"flops": 10.0,
                                           "_tolerance": 0.5}}}},
              open(path, "w"))
    r = AuditReport(name="e", public_api="x", n_eqns=1, f64_leaves=0,
                    f64_examples=[], host_callbacks=0, retraces=0,
                    trace_s=0.0, ok=True, metrics={"flops": 20.0})
    save_budgets([r], path, platform="cpu")
    saved = json.load(open(path))["platforms"]["cpu"]["e"]
    assert saved == {"flops": 20.0, "_tolerance": 0.5}


def test_budget_audit_integration_vs_committed():
    """One real AOT lowering: the cheapest registered entry's measured
    metrics must satisfy its committed CPU budget (the same check `make
    lint` gates on), and a 2x-tightened copy must fail rc-style."""
    import copy

    import jax

    from raft_tpu.lint.audit import audit_entry, check_budget
    from raft_tpu.lint.registry import get_entries

    if jax.default_backend() != "cpu":  # pragma: no cover - HW CI
        pytest.skip("budgets committed for the CPU lowering")
    (entry,) = get_entries(["dlc_solve"])
    r = audit_entry(entry, retrace_check=False, collect_metrics=True)
    assert r.metrics and r.metrics["flops"] > 0
    # sharded entries commit sharded-lowering metrics too; the gate
    # fails LOUD on committed-but-unmeasured keys, so measure them the
    # way run_audit does before checking
    from raft_tpu.lint.audit import _sharded_mesh, sharded_metrics

    r.metrics.update(sharded_metrics(entry, _sharded_mesh()))
    budgets = _committed_budgets()
    ok, notes = check_budget("dlc_solve", r.metrics, budgets, "cpu")
    assert ok, notes
    tight = copy.deepcopy(budgets)
    for k, v in tight["platforms"]["cpu"]["dlc_solve"].items():
        if not k.startswith("_"):
            tight["platforms"]["cpu"]["dlc_solve"][k] = v * 0.4
    ok2, notes2 = check_budget("dlc_solve", r.metrics, tight, "cpu")
    assert not ok2 and notes2
