"""graftlint: fixture corpus (one trigger + one near-miss per rule),
suppression + baseline machinery, reachability edge cases, the trace
audit's budget pins for the north-star sweep entry, and the repo gate
(the merged tree must stay clean vs the committed baseline — running in
the fast tier makes any lint regression fail ``make fast``)."""
import json
import os
import subprocess
import sys
import textwrap

import pytest

from raft_tpu.lint import baseline as bl
from raft_tpu.lint.rules import RULES, lint_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint_src(tmp_path, src, name="mod.py", extra=None):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    if extra:
        for fname, fsrc in extra.items():
            (tmp_path / fname).write_text(textwrap.dedent(fsrc))
    return lint_paths([str(tmp_path)], str(tmp_path))


# --------------------------------------------------------------------------
# fixture corpus: (rule, trigger source, near-miss source)
# --------------------------------------------------------------------------
FIXTURES = {
    "GL101": (
        """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return np.sin(x)
        """,
        """
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def f(x):
            c = np.sin(0.5)          # host constant: no tracer involved
            return jnp.sin(x) * c
        """,
    ),
    "GL102": (
        """
        import jax

        @jax.jit
        def f(x):
            return float(x) + 1.0
        """,
        """
        import jax

        @jax.jit
        def f(x):
            n = float(x.shape[0])    # shape is static under trace
            return x * n
        """,
    ),
    "GL103": (
        """
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
        """,
        """
        import jax

        @jax.jit
        def f(x, flag=None):
            if flag is None:         # pytree-structure check: static
                return x
            if x.shape[0] == 3:      # shape: static
                return x + x
            return x
        """,
    ),
    "GL104": (
        """
        from functools import partial
        import jax
        import jax.numpy as jnp

        Array = jnp.ndarray

        @partial(jax.jit, static_argnames=("scale", "typo"))
        def f(x, scale: Array):
            return x * scale
        """,
        """
        from functools import partial
        import jax

        @partial(jax.jit, static_argnames=("n",))
        def f(x, n: int = 3):
            return x * n
        """,
    ),
    "GL105": (
        """
        import numpy as np

        BAD = np.zeros(3, dtype=np.float64)

        def g(arr):
            return arr.astype("float64")
        """,
        """
        import numpy as np

        OK = np.zeros(3, dtype=np.float32)
        # justified host-side use rides a suppression:
        HASHED = np.float64(1.5)  # graftlint: disable=GL105
        """,
    ),
    "GL106": (
        """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            print(x)
            return np.asarray(x)
        """,
        """
        import numpy as np

        def host_report(x):          # never jit-reachable: host is free
            print(x)
            return np.asarray(x)
        """,
    ),
    "GL107": (
        """
        def key_parts(names):
            out = []
            for k in {"b", "a"}:
                out.append(k)
            return tuple(set(out))
        """,
        """
        def key_parts(names):
            out = []
            for k in sorted({"b", "a"}):
                out.append(k)
            return tuple(sorted(set(out)))
        """,
    ),
}


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_rule_triggers(tmp_path, rule):
    trigger, _ = FIXTURES[rule]
    vs = _lint_src(tmp_path, trigger)
    hits = [v for v in vs if v.rule == rule]
    assert hits, f"{rule} fixture produced no {rule} violation: {vs}"


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_rule_near_miss(tmp_path, rule):
    _, near_miss = FIXTURES[rule]
    vs = _lint_src(tmp_path, near_miss)
    hits = [v for v in vs if v.rule == rule]
    assert not hits, f"{rule} near-miss wrongly flagged: " + "\n".join(
        v.format() for v in hits)


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_cli_fails_on_each_seeded_fixture(tmp_path, rule):
    """`python -m raft_tpu.lint <fixture>` (in-process main) must exit
    non-zero on every seeded-violation fixture — the acceptance gate."""
    from raft_tpu.lint.cli import main

    p = tmp_path / "bad.py"
    p.write_text(textwrap.dedent(FIXTURES[rule][0]))
    rc = main([str(p), "--root", str(tmp_path), "--no-baseline"])
    assert rc == 1


# --------------------------------------------------------------------------
# reachability edges
# --------------------------------------------------------------------------
def test_nested_def_passed_to_vmap_is_reachable(tmp_path):
    vs = _lint_src(tmp_path, """
        import jax
        import numpy as np

        def orchestrator(members, thetas):
            def one(theta):
                return np.abs(theta)
            return jax.jit(jax.vmap(one))(thetas)
        """)
    assert any(v.rule == "GL101" and ".one" in v.msg for v in vs), vs


def test_returned_closure_is_reachable(tmp_path):
    vs = _lint_src(tmp_path, """
        import numpy as np

        def make_loss(members):
            def loss(theta):
                return np.abs(theta)
            return loss
        """)
    assert any(v.rule == "GL101" for v in vs), vs


def test_cross_module_call_edge(tmp_path):
    vs = _lint_src(tmp_path, """
        import jax
        from helper import warp

        @jax.jit
        def f(x):
            return warp(x)
        """, extra={"helper.py": """
        import numpy as np

        def warp(x):
            return np.tanh(x)
        """})
    assert any(v.rule == "GL101" and v.path == "helper.py" for v in vs), vs


def test_host_orchestrator_not_reachable(tmp_path):
    """A host function calling jitted code freely uses numpy/print."""
    vs = _lint_src(tmp_path, """
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def solve(x):
            return jnp.sin(x)

        def orchestrator(x):
            out = solve(jnp.asarray(x))
            print("done")
            return np.asarray(out)
        """)
    assert vs == [], [v.format() for v in vs]


def test_jax_tree_map_is_not_a_tracing_transform(tmp_path):
    vs = _lint_src(tmp_path, """
        import jax
        import numpy as np

        def stage(tree):
            def put(x):
                return np.asarray(x)
            return jax.tree.map(put, tree)
        """)
    assert vs == [], [v.format() for v in vs]


def test_static_argname_params_are_not_traced(tmp_path):
    vs = _lint_src(tmp_path, """
        from functools import partial
        import jax

        @partial(jax.jit, static_argnames=("method",))
        def f(x, method):
            if method == "scan":
                return x + 1
            return x
        """)
    assert vs == [], [v.format() for v in vs]


# --------------------------------------------------------------------------
# suppression + baseline machinery
# --------------------------------------------------------------------------
def test_gl105_catches_from_import_spelling(tmp_path):
    vs = _lint_src(tmp_path, """
        from numpy import float64 as f64

        BAD = f64(1.5)
        """)
    assert any(v.rule == "GL105" for v in vs), vs


def test_line_suppression(tmp_path):
    vs = _lint_src(tmp_path, """
        import numpy as np

        A = np.zeros(2, dtype=np.float64)  # graftlint: disable=GL105
        """)
    assert vs == []


def test_file_suppression(tmp_path):
    vs = _lint_src(tmp_path, """
        # graftlint: disable-file=GL105 — host ABI requires doubles
        import numpy as np

        A = np.zeros(2, dtype=np.float64)
        B = np.ones(2, dtype=np.float64)
        """)
    assert vs == []


def test_baseline_round_trip(tmp_path):
    src = """
        import numpy as np

        A = np.zeros(2, dtype=np.float64)
        """
    vs = _lint_src(tmp_path, src)
    assert len(vs) == 1
    path = str(tmp_path / "baseline.json")
    bl.save(vs, path)
    fresh, absorbed = bl.filter_new(vs, path)
    assert fresh == [] and absorbed == 1
    # a NEW violation in the same file is not absorbed
    vs2 = _lint_src(tmp_path, src + "B = np.ones(3, dtype=np.float64)\n")
    fresh2, absorbed2 = bl.filter_new(vs2, path)
    assert absorbed2 == 1 and len(fresh2) == 1
    # fingerprints are line-number-free: prepending a comment moves every
    # line yet the baseline still absorbs the violation
    vs3 = _lint_src(tmp_path,
                    "# a new leading comment\n" + textwrap.dedent(src))
    fresh3, _ = bl.filter_new(vs3, path)
    assert fresh3 == []


# --------------------------------------------------------------------------
# repo gate: the merged tree stays clean (fails `make fast` on regression)
# --------------------------------------------------------------------------
def test_repo_is_lint_clean_vs_baseline():
    vs = lint_paths(["raft_tpu", "__graft_entry__.py", "bench.py"], REPO)
    fresh, _ = bl.filter_new(vs)
    assert fresh == [], "NEW lint violations:\n" + "\n".join(
        v.format() for v in fresh)


def test_cli_fails_loud_on_typod_target(tmp_path):
    """A misspelled lint target must never report green over zero files."""
    from raft_tpu.lint.cli import main

    rc = main([str(tmp_path / "sovle"), "--root", str(tmp_path),
               "--no-baseline"])
    assert rc == 2


def test_cli_subprocess_green_on_repo():
    r = subprocess.run([sys.executable, "-m", "raft_tpu.lint", "--json"],
                       cwd=REPO, capture_output=True, text=True,
                       timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["ok"] and summary["static"]["new"] == 0


# --------------------------------------------------------------------------
# trace audit
# --------------------------------------------------------------------------
def test_audit_north_star_sweep_budgets():
    """Acceptance pin: repeated same-shape north-star sweep call does not
    retrace, and its jaxpr has zero f64 leaves under x32 and zero host
    callbacks."""
    from raft_tpu.lint.audit import audit_entry
    from raft_tpu.lint.registry import get_entries

    (entry,) = get_entries(["north_star_sweep"])
    r = audit_entry(entry)
    assert r.retraces == 0, r.to_dict()
    assert r.f64_leaves == 0, r.to_dict()
    assert r.host_callbacks == 0, r.to_dict()
    assert r.ok and r.n_eqns > 100


def test_audit_registry_covers_required_entries():
    from raft_tpu.lint.registry import ENTRY_POINTS

    names = {e.name for e in ENTRY_POINTS}
    assert {"north_star_sweep", "dlc_solve", "freq_sharded_forward",
            "val_grad", "eigen", "fused_rao_solve"} <= names


def test_audit_jaxpr_detects_f64_leaves():
    import jax
    import jax.numpy as jnp

    from raft_tpu.lint.audit import audit_jaxpr

    # the suite runs x64, so a float64 pipeline is easy to make; the
    # walker must count its wide avals
    jaxpr = jax.make_jaxpr(lambda x: jnp.sin(x) * 2.0)(
        jnp.ones(3, dtype=jnp.float64))
    n_eqns, wide, examples, callbacks = audit_jaxpr(jaxpr)
    assert wide > 0 and examples


def test_audit_jaxpr_detects_host_callbacks():
    import jax
    import jax.numpy as jnp

    from raft_tpu.lint.audit import audit_jaxpr

    def f(x):
        jax.debug.print("x={x}", x=x)
        return x * 2

    jaxpr = jax.make_jaxpr(f)(jnp.ones(3))
    _, _, _, callbacks = audit_jaxpr(jaxpr)
    assert callbacks >= 1


def test_retrace_counter_detects_signature_instability():
    import jax.numpy as jnp

    from raft_tpu.lint.audit import _count_retraces

    fn = lambda x: x + 1  # noqa: E731
    # same shape, different dtype: a second abstract signature must be
    # reported as a retrace
    n = _count_retraces(fn, (jnp.ones(3, dtype=jnp.float32),),
                        (jnp.ones(3, dtype=jnp.int32),))
    assert n == 1
    n0 = _count_retraces(fn, (jnp.ones(3, dtype=jnp.float32),),
                         (2.0 * jnp.ones(3, dtype=jnp.float32),))
    assert n0 == 0


def test_rules_catalog_documented():
    """Every rule ID has a docs section (docs/lint.rst ships the catalog)."""
    docs = open(os.path.join(REPO, "docs", "lint.rst")).read()
    for rule in RULES:
        assert rule in docs, f"{rule} missing from docs/lint.rst"
