"""End-to-end Model tests on the OC3-Hywind spar design.

Oracle: published OC3-Hywind system properties (Jonkman, NREL/TP-500-47535):
platform mass 7,466,330 kg; displacement 8,029 m^3; platform CB z -62.07 m;
and system natural frequencies (OC3 Phase IV / verification literature):
surge ~0.008 Hz, heave ~0.032 Hz, pitch ~0.034 Hz, yaw ~0.12 Hz.
"""
import numpy as np
import pytest

from raft_tpu.model import Model, load_design, run_raft

DESIGN = "raft_tpu/designs/OC3spar.yaml"


@pytest.fixture(scope="module")
def model():
    m = Model(load_design(DESIGN))
    m.setEnv(Hs=8.0, Tp=12.0, V=10.0, Fthrust=800e3)
    m.calcSystemProps()
    return m


def test_oc3_mass_properties(model):
    p = model.results["properties"]
    # platform (substructure) mass incl. ballast: published 7.4663e6 kg
    assert p["substructure mass"] == pytest.approx(7.4663e6, rel=0.05)
    # displacement: published 8029 m^3
    assert p["displacement"] == pytest.approx(8029.0, rel=0.03)
    # center of buoyancy: published -62.07 m
    assert p["center of buoyancy"][2] == pytest.approx(-62.07, rel=0.05)
    # buoyancy roughly balances total weight + mooring pull
    W = p["total mass"] * 9.81
    B = p["buoyancy (pgV)"]
    assert B > W
    assert (B - W) / B < 0.12


def test_oc3_natural_frequencies(model):
    model.solveEigen()
    fns = model.results["eigen"]["frequencies"]
    assert 0.005 < fns[0] < 0.011       # surge ~0.008 Hz
    assert 0.005 < fns[1] < 0.011       # sway
    assert 0.028 < fns[2] < 0.037       # heave ~0.032 Hz
    assert 0.028 < fns[3] < 0.042       # roll ~0.034 Hz
    assert 0.028 < fns[4] < 0.042       # pitch ~0.034 Hz
    assert 0.08 < fns[5] < 0.16         # yaw ~0.12 Hz


@pytest.mark.slow
def test_oc3_mean_offsets(model):
    model.calcMooringAndOffsets()
    r6 = model.results["means"]["platform offset"]
    # 800 kN thrust against ~41 kN/m surge stiffness: tens of meters
    assert 10.0 < r6[0] < 40.0
    assert abs(r6[1]) < 1.0
    # pitch offset positive (thrust above CG), a few degrees
    assert 0.01 < r6[4] < 0.15


@pytest.mark.slow
def test_oc3_rao_solve(model):
    model.calcMooringAndOffsets()
    model.solveDynamics()
    resp = model.results["response"]
    assert resp["converged"]
    rao = resp["RAO magnitude"]
    w = resp["w"]
    # surge RAO near the spectral peak (Tp=12 s -> wp~0.52): order 1 m/m
    # for long waves on a deep spar, decaying at high frequency
    ip = int(np.argmax(np.asarray(model.wave.zeta)))
    assert 0.2 < rao[ip, 0] < 2.0
    assert rao[-1, 0] < 0.1
    # significant responses are finite and positive
    assert np.isfinite(rao).all()
    # response std devs are sane: surge meters-scale in Hs=8 seas
    sigma = resp["std dev"]
    assert 0.1 < sigma[0] < 10.0
    # pitch std in radians: < ~5 degrees
    assert sigma[4] < 0.1
    # heave: small for a deep spar (guards the axial-FK accounting,
    # DEVIATIONS.md #16 — the reference's double count gives ~80 m here)
    assert sigma[2] < 1.0


def test_plot_smoke(model):
    """Geometry wireframe and RAO-curve plots render without a display
    (Agg) and return usable axes; plot_raos before a solve raises."""
    import matplotlib

    matplotlib.use("Agg", force=True)
    import matplotlib.pyplot as plt

    ax = model.plot()
    assert len(ax.lines) > 0                     # member edges + moor lines
    if "response" not in model.results:
        model.calcMooringAndOffsets()
        model.solveDynamics()
    axes = model.plot_raos()
    flat = np.asarray(axes).ravel()
    assert flat.shape[0] == 6
    assert all(len(a.lines) == 1 for a in flat)
    # surge curve carries the solved RAO, not zeros
    y = flat[0].lines[0].get_ydata()
    assert np.isfinite(y).all() and y.max() > 0.1
    plt.close("all")

    m2 = Model(load_design(DESIGN))
    with pytest.raises(RuntimeError, match="solveDynamics"):
        m2.plot_raos()


@pytest.mark.slow
def test_fairlead_tension_outputs(model):
    model.calcMooringAndOffsets()
    model.solveDynamics()
    model.calcOutputs()
    T = model.results["means"]["fairlead tensions"]
    assert T.shape == (3,)
    # OC3 pretension ~900 kN at zero offset; at the thrust offset the
    # downwind line relaxes and the upwind pair loads up
    assert 0.2e6 < T.min() < 1.0e6 < T.max() < 2.5e6
    sd = model.results["response"]["fairlead tension std dev"]
    assert sd.shape == (3,)
    assert (sd > 100.0).all() and (sd < 0.3e6).all()
    rao = model.results["response"]["fairlead tension RAO"]
    assert np.isfinite(rao).all()


@pytest.mark.slow
def test_outputs_nacelle_accel(model):
    model.calcMooringAndOffsets()
    model.solveDynamics()
    results = model.calcOutputs()
    a = results["response"]["nacelle acceleration RAO"]
    assert np.isfinite(a).all()
    sd = results["response"]["nacelle acceleration std dev"]
    assert 0.01 < sd < 5.0              # m/s^2 in 8 m seas


@pytest.mark.slow
def test_outputs_constraint_margins(model):
    """Design-constraint margins (the reference sketches these only in
    commented-out legacy code, raft/raft.py:1655-1698): the OC3 in 8 m
    seas keeps all lines taut at 3 sigma and stays under the 10 deg
    dynamic-pitch limit used there."""
    model.calcMooringAndOffsets()
    model.solveDynamics()
    results = model.calcOutputs()
    cons = results["constraints"]
    # taut-moored spar: comfortable positive slack margin [N]
    assert cons["slack line margin"] > 1e5
    # |static| + 3 sigma pitch well under the legacy 10 deg limit
    assert 0.0 < cons["dynamic pitch"] < cons["dynamic pitch limit"]
    # and consistent with the reported response: margin below the mean min
    T_mean = results["means"]["fairlead tensions"]
    assert cons["slack line margin"] < T_mean.min()


@pytest.mark.slow
def test_airgap_outputs(model):
    """Relative wave elevation / air gap: at the spar centerline the
    vertical motion is small, so sigma_rel ~ the incident elevation std
    Hs/4; margins are monotone in deck height and pitch coupling makes
    off-center points differ."""
    model.calcMooringAndOffsets()
    model.solveDynamics()
    out = model.airgap([[0.0, 0.0], [30.0, 0.0]], deck_z=15.0)
    sig = out["sigma rel elevation"]
    # incident-elevation std = sqrt(int S dw) = Hs/4 = 2.0 m for Hs=8;
    # the deep spar's heave/pitch motion shifts it only moderately
    assert 1.5 < sig[0] < 3.0
    # pitch lever makes the off-center point's relative motion different
    assert abs(sig[1] - sig[0]) > 1e-3
    # a 15 m deck on OC3 in 8 m seas keeps positive 3-sigma clearance;
    # a 4 m deck does not
    assert out["margin 3 sigma"][0] > 0.0
    low = model.airgap([[0.0, 0.0]], deck_z=4.0)
    assert low["margin 3 sigma"][0] < out["margin 3 sigma"][0]
    # manual recompute of the relative-elevation spectrum at the center
    w = np.asarray(model.w)
    dw = float(w[1] - w[0])
    Xi = np.asarray(model.rao.Xi.to_complex())
    eta_rel = np.asarray(model.wave.zeta) - Xi[:, 2]
    np.testing.assert_allclose(
        sig[0], np.sqrt((np.abs(eta_rel) ** 2).sum() * dw), rtol=1e-9
    )
    assert "airgap" in model.results
    with pytest.raises(ValueError, match="plan coordinates"):
        model.airgap([[0.0, 0.0, 10.0]], deck_z=15.0)


def test_bem_excitation_basis_consistency():
    """BEM excitation (per unit wave amplitude) must be scaled by zeta
    before summing with the spectral-amplitude-basis Morison excitation."""
    design = load_design(DESIGN)
    nw = 30
    w = np.linspace(0.05, 2.0, nw)
    A0 = np.zeros((6, 6, nw))
    B0 = np.zeros((6, 6, nw))
    F1 = np.ones((6, nw), dtype=complex)            # unit per-amplitude force
    m = Model(design, w=w, BEM=(A0, B0, F1))
    m.setEnv(Hs=8.0, Tp=12.0)
    m.calcSystemProps()
    lin_bem = m._linear_coeffs()
    zeta = np.asarray(m.wave.zeta)
    # potMod members are gated out of the Morison path when a BEM tuple is
    # present; subtracting the gated Morison excitation isolates the BEM term
    F_mor_gated = np.asarray(m.F_morison.re)
    dF_bem = np.asarray(lin_bem.F.re) - F_mor_gated
    np.testing.assert_allclose(dF_bem, zeta[:, None] * np.ones(6), rtol=1e-10)


@pytest.mark.slow
def test_bem_response_scales_with_hs():
    """With a pure-BEM excitation and no Morison drag on potMod members,
    response amplitude at each frequency scales ~linearly with Hs (the
    drag-linearized damping makes it sublinear, never superlinear)."""
    design = load_design(DESIGN)
    nw = 24
    w = np.linspace(0.1, 2.0, nw)
    A0 = np.zeros((6, 6, nw))
    B0 = np.zeros((6, 6, nw))
    F1 = np.zeros((6, nw), dtype=complex)
    F1[0] = 1e6                                     # surge-only unit force
    amps = {}
    for Hs in (2.0, 4.0):
        m = Model(design, w=w, BEM=(A0, B0, F1))
        m.setEnv(Hs=Hs, Tp=10.0)
        m.calcSystemProps()
        m.calcMooringAndOffsets()
        m.solveDynamics()
        amps[Hs] = np.asarray(m.rao.Xi.abs())[:, 0]
    mask = amps[2.0] > 1e-2 * amps[2.0].max()       # skip near-zero-zeta bins
    ratio = amps[4.0][mask] / amps[2.0][mask]
    # doubling Hs doubles zeta; response doubles to within the drag
    # corrections (quadratic drag excitation pushes slightly above 2, drag
    # damping slightly below).  The unscaled-BEM-force bug gives ratio ~1.
    assert (ratio > 1.5).all() and (ratio < 2.5).all()


@pytest.mark.slow
def test_run_raft_end_to_end():
    results = run_raft(DESIGN)
    assert set(results) >= {"properties", "means", "eigen", "response"}
    assert results["response"]["converged"]


# ---------------------------------------------------------- OC4 semi


@pytest.fixture(scope="module")
def oc4():
    m = Model(load_design("raft_tpu/designs/OC4semi.yaml"))
    m.setEnv(Hs=6.0, Tp=10.0, V=10.0, Fthrust=800e3)
    m.calcSystemProps()
    return m


def test_oc4_mass_properties(oc4):
    """Published values: Robertson et al., NREL/TP-5000-60601."""
    p = oc4.results["properties"]
    assert p["substructure mass"] == pytest.approx(1.3473e7, rel=0.02)
    assert p["shell mass"] == pytest.approx(3.8523e6, rel=0.02)
    assert p["ballast mass"] == pytest.approx(9.6207e6, rel=0.02)
    # centerline-to-centerline pontoons: volume ~2% above published 13,917
    assert p["displacement"] == pytest.approx(13917.0, rel=0.03)
    assert p["substructure CG"][2] == pytest.approx(-13.46, abs=0.8)
    # platform pitch inertia about the substructure CM: published 6.827e9
    # (geometry-derived value runs ~5% low of the published lumped total —
    # the main residual in the pitch period comparison)
    assert p["pitch inertia at subCG"] == pytest.approx(6.827e9, rel=0.06)
    assert p["roll inertia at subCG"] == pytest.approx(6.827e9, rel=0.06)


def test_oc4_natural_frequencies(oc4):
    """Strip-theory-only OC4 periods, tightly pinned.

    Strip theory overestimates surge added mass for the multi-column semi
    (A11 ~1.01e7 kg vs ~8.5e6 potential flow — see DEVIATIONS.md), putting
    the strip-path surge period at ~117 s; the BEM path (next test) lands
    at ~115 s, matching the published simulation class.  The pins here are
    +/-3% around the audited strip-theory values so a regression in any
    statics/mooring/added-mass term trips them."""
    oc4.solveEigen()
    fns = oc4.results["eigen"]["frequencies"]
    # 120-degree symmetric mooring: surge and sway must be degenerate
    assert fns[0] == pytest.approx(fns[1], rel=1e-3)
    assert fns[0] == pytest.approx(0.00854, rel=0.03)   # surge: T ~117.1 s
    assert fns[2] == pytest.approx(0.05749, rel=0.03)   # heave: T ~17.4 s
    assert fns[3] == pytest.approx(0.03977, rel=0.04)   # roll
    assert fns[4] == pytest.approx(0.03978, rel=0.04)   # pitch: T ~25.1 s
    assert fns[5] == pytest.approx(0.01222, rel=0.04)   # yaw:   T ~81.8 s


@pytest.mark.slow
def test_oc4_bem_natural_periods():
    """OC4 periods with the native BEM on the potMod columns, pinned to the
    published values: surge/sway ~115 s (the OC4 Phase II simulation class;
    the MARIN experiment's 107 s folds in dynamic-mooring effects outside
    this quasi-static model class — audit in DEVIATIONS.md), heave 17.5 s,
    pitch ~26 s, yaw ~80 s (Robertson et al., NREL/TP-5000-60601)."""
    m = Model(load_design("raft_tpu/designs/OC4semi.yaml"), BEM="native",
              w=np.linspace(0.05, 1.2, 8))
    m.setEnv(Hs=6.0, Tp=10.0)
    m.calcSystemProps()
    m.solveEigen()
    T = m.results["eigen"]["periods"]
    assert T[0] == pytest.approx(115.9, rel=0.05)       # surge (pub. sim 115.9)
    assert T[1] == pytest.approx(T[0], rel=1e-3)        # sway degenerate
    assert T[2] == pytest.approx(17.5, rel=0.05)        # heave (pub. 17.5)
    assert T[4] == pytest.approx(26.0, rel=0.08)        # pitch (pub. ~26.8)
    assert T[5] == pytest.approx(80.2, rel=0.05)        # yaw   (pub. 80.2)


# ------------------------------------------------------ VolturnUS-S


@pytest.fixture(scope="module")
def volturn():
    m = Model(load_design("raft_tpu/designs/VolturnUS-S.yaml"))
    m.setEnv(Hs=6.0, Tp=10.0, V=10.0, Fthrust=2.4e6)
    m.calcSystemProps()
    return m


def test_volturn_mass_properties(volturn):
    """Published values: Allen et al., NREL/TP-5000-76773."""
    p = volturn.results["properties"]
    assert p["substructure mass"] == pytest.approx(1.7839e7, rel=0.02)
    assert p["shell mass"] == pytest.approx(3.9148e6, rel=0.02)
    assert p["tower mass"] == pytest.approx(1.263e6, rel=0.02)
    # face-to-face pontoons: ~3% below the published 20,206 m^3
    assert p["displacement"] == pytest.approx(20206.0, rel=0.05)
    assert p["substructure CG"][2] == pytest.approx(-14.94, abs=0.8)


def test_volturn_natural_periods(volturn):
    """Published periods: surge 142.9 s, heave 20.4 s, pitch 27.8 s,
    yaw 90.7 s (Allen et al., Table 10)."""
    volturn.solveEigen()
    T = volturn.results["eigen"]["periods"]
    assert 120.0 < T[0] < 160.0         # surge
    assert 18.0 < T[2] < 23.0           # heave
    assert 25.0 < T[3] < 32.0           # roll
    assert 25.0 < T[4] < 32.0           # pitch
    assert 75.0 < T[5] < 105.0          # yaw


@pytest.mark.slow
def test_volturn_dynamics(volturn):
    volturn.calcMooringAndOffsets()
    volturn.solveDynamics()
    resp = volturn.results["response"]
    assert resp["converged"]
    assert np.isfinite(resp["RAO magnitude"]).all()


@pytest.mark.slow
def test_oc4_dynamics(oc4):
    oc4.calcMooringAndOffsets()
    oc4.solveDynamics()
    resp = oc4.results["response"]
    assert resp["converged"]
    assert np.isfinite(resp["RAO magnitude"]).all()
    # surge mean offset under 800 kN thrust: OC4 mooring is stiffer than
    # OC3 (~70 kN/m): expect offset of order 10 m
    r6 = oc4.results["means"]["platform offset"]
    assert 3.0 < r6[0] < 25.0


@pytest.mark.slow
def test_volturn_bem_natural_periods():
    """VolturnUS-S with the native BEM on the circular columns (pontoons
    rect -> Morison): published periods surge 142.9 s, heave 20.4 s,
    pitch 27.8 s, yaw 90.7 s (Allen et al., Table 10).  Heave and pitch pin
    at 5%; surge/yaw at 10% (quasi-static mooring linearization about zero
    offset runs ~8% stiff of the published free-decay values)."""
    m = Model(load_design("raft_tpu/designs/VolturnUS-S.yaml"), BEM="native",
              w=np.linspace(0.05, 1.2, 8))
    m.setEnv(Hs=8.0, Tp=12.0)
    m.calcSystemProps()
    m.solveEigen()
    T = m.results["eigen"]["periods"]
    assert T[2] == pytest.approx(20.4, rel=0.05)        # heave
    assert T[4] == pytest.approx(27.8, rel=0.05)        # pitch
    assert T[0] == pytest.approx(142.9, rel=0.10)       # surge
    assert T[5] == pytest.approx(90.7, rel=0.10)        # yaw
