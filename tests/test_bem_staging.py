"""Measured error bar for the bench's coarse-grid BEM staging.

bench.py solves the VolturnUS-S panel model on a coarse frequency grid and
interpolates A(w)/B(w)/F(w) to the 200-bin response grid
(bench._volturn_setup) — a documented approximation of the north-star
workload.  This test turns it into a measured one: the drag-linearized
response staged from the bench's 48-frequency coarse solve must agree with
one staged from a 2x denser 96-frequency solve of the SAME (small) mesh to
<1% of the dominant response amplitude per unit group, across the whole
grid.  (48 is the convergence-chosen default: the same measurement on a
24-point grid leaves 3-5% error — that is why _volturn_setup stages 48.)
The refinement isolates the frequency-interpolation error — mesh
resolution and the nominal-hull-across-variants approximation are held
fixed.
"""
import numpy as np
import pytest
import jax.numpy as jnp

pytestmark = pytest.mark.slow


def _staged_response(members, rna, env, wave, C_moor, panels, nw_bem):
    from raft_tpu.hydro.bem_io import interp_to_grid
    from raft_tpu.hydro.native_bem import solve_bem
    from raft_tpu.parallel import forward_response, stage_bem

    w = np.asarray(wave.w)
    wb = np.linspace(w[0], w[-1], nw_bem)
    A_c, B_c, F_c = solve_bem(panels, wb, rho=float(env.rho),
                              g=float(env.g), beta=0.0, depth=float(env.depth))
    bem = (
        interp_to_grid(wb, np.asarray(A_c), w),
        interp_to_grid(wb, np.asarray(B_c), w),
        interp_to_grid(wb, np.asarray(F_c), w),
    )
    out = forward_response(members, rna, env, wave, C_moor,
                           bem=stage_bem(bem, wave), n_iter=40, method="while")
    assert bool(out.converged)
    return np.asarray(out.Xi.re) + 1j * np.asarray(out.Xi.im)


def test_coarse_bem_staging_response_error_under_1pct():
    from raft_tpu.build.members import build_member_set, build_rna
    from raft_tpu.core.types import Env, WaveState
    from raft_tpu.core.waves import jonswap, wave_number
    from raft_tpu.hydro.mesh import mesh_design
    from raft_tpu.model import load_design
    from raft_tpu.mooring import mooring_stiffness, parse_mooring

    design = load_design("raft_tpu/designs/VolturnUS-S.yaml")
    members = build_member_set(design)
    rna = build_rna(design)
    depth = float(design["mooring"]["water_depth"])
    env = Env(Hs=8.0, Tp=12.0, depth=depth)
    nw = 100                             # half the bench grid, same span
    w = jnp.asarray(np.linspace(0.05, 2.95, nw))
    wave = WaveState(w=w, k=wave_number(w, depth),
                     zeta=jnp.sqrt(jonswap(w, 8.0, 12.0)))
    moor = parse_mooring(
        design["mooring"],
        yaw_stiffness=design["turbine"].get("yaw_stiffness", 0.0),
    )
    C_moor = mooring_stiffness(moor, jnp.zeros(6))
    panels = mesh_design(design, dz_max=6.0, da_max=6.0)   # small test mesh

    Xi48 = _staged_response(members, rna, env, wave, C_moor, panels, nw_bem=48)
    Xi96 = _staged_response(members, rna, env, wave, C_moor, panels, nw_bem=96)
    for name, sl in (("translations", slice(0, 3)), ("rotations", slice(3, 6))):
        scale = np.abs(Xi96[:, sl]).max()
        err = np.abs(Xi48[:, sl] - Xi96[:, sl]).max()
        assert err / scale < 0.01, (
            f"coarse-grid staging error {err / scale:.2%} in {name} "
            f"(nw_bem 48 vs 96)"
        )
