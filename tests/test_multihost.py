"""Multi-process (multi-host) distributed solve reproduces single-process.

The dry run and the sharding suite validate multi-DEVICE meshes inside one
process; this test validates the multi-HOST layer: two OS processes join
one ``jax.distributed`` runtime (the coordination path a TPU pod uses over
DCN), form a single 8-device global mesh from 2 x 4 virtual CPU devices,
and run the frequency-sharded RAO solve whose psum/pmax collectives cross
the process boundary.  Rank 0 gathers and prints the response; the parent
compares it against the in-process unsharded solve.
"""
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_freq_sharded_matches_single_process():
    port = _free_port()
    env = {
        **os.environ,
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "JAX_PLATFORMS": "cpu",
        # the worker runs by path, so its script dir (tests/) is on
        # sys.path but the repo root is not
        "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    worker = os.path.join(REPO, "tests", "multihost_worker.py")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), str(port)],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )
        for pid in (0, 1)
    ]
    # collect BOTH workers before asserting: if one dies early, its peer
    # must still be reaped (it would otherwise block forever in the
    # collective), and its output usually holds the root cause
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, (
            "worker failed:\n" + "\n---\n".join(o[-2000:] for o in outs)
        )
    xi_line = next(ln for ln in outs[0].splitlines() if ln.startswith("XI "))
    flat = np.array([float(v) for v in xi_line.split()[1:]])
    Xi_mh = (flat[: len(flat) // 2] + 1j * flat[len(flat) // 2:]).reshape(8, 6)

    # in-process oracle: same model, unsharded
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    import __graft_entry__ as ge
    from raft_tpu.mooring import mooring_stiffness, parse_mooring
    from raft_tpu.parallel import forward_response

    design, members, rna, env_m, wave = ge._base(nw=8)
    moor = parse_mooring(design["mooring"],
                         yaw_stiffness=design["turbine"]["yaw_stiffness"])
    C_moor = mooring_stiffness(moor, jnp.zeros(6))
    ref = forward_response(members, rna, env_m, wave, C_moor,
                           n_iter=40, method="while")
    Xi_ref = np.asarray(ref.Xi.to_complex())
    scale = np.abs(Xi_ref).max()
    assert np.abs(Xi_mh - Xi_ref).max() < 1e-9 * scale, (
        f"multi-process mismatch {np.abs(Xi_mh - Xi_ref).max():.3e}"
    )
    niter = next(ln for ln in outs[0].splitlines() if ln.startswith("NITER"))
    assert int(niter.split()[1]) == int(ref.n_iter)
