"""Test configuration: run on a virtual 8-device CPU mesh with float64.

Multi-chip sharding is validated on virtual CPU devices
(xla_force_host_platform_device_count); the driver separately dry-runs the
multi-chip path, and bench.py runs on the real TPU chip.
"""
import os

# NOTE: the environment may pin JAX_PLATFORMS to a hardware plugin via
# sitecustomize; jax.config.update below takes precedence over the env var.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
