"""Test configuration: run on a virtual 8-device CPU mesh with float64.

Multi-chip sharding is validated on virtual CPU devices
(xla_force_host_platform_device_count); the driver separately dry-runs the
multi-chip path, and bench.py runs on the real TPU chip.
"""
import os

# NOTE: the environment may pin JAX_PLATFORMS to a hardware plugin via
# sitecustomize; jax.config.update below takes precedence over the env var.
os.environ["JAX_PLATFORMS"] = "cpu"
# the warm-start subsystem defaults ON in the CLI/bench entry points; pin
# it OFF for the suite so every test runs the plain uncached paths (seed
# semantics, no artifacts under ~/.cache).  tests/test_cache.py opts back
# in per-test with an explicit tmp dir (an explicit enable(dir) argument
# overrides this env pin).
os.environ["RAFT_TPU_CACHE_DIR"] = "off"
# observability export defaults OFF; a developer environment that armed
# RAFT_TPU_OBS must not make the suite write sink files (tests that
# exercise the exporters pass explicit tmp directories)
os.environ.pop("RAFT_TPU_OBS", None)
# the obs knobs snapshot once per process; a developer override must not
# skew the debounce/roofline expectations pinned by the suite
os.environ.pop("RAFT_TPU_OBS_FLUSH_MS", None)
os.environ.pop("RAFT_TPU_ROOFLINE", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import atexit  # noqa: E402
import shutil  # noqa: E402
import tempfile  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# XLA persistent compilation cache, scoped to THIS run (fresh tmp dir,
# removed at exit — no cross-run state): the module-boundary
# jax.clear_caches() below drops live executables to keep XLA-CPU's JIT
# stable, which otherwise forces full recompiles of the same programs
# module after module (test_solve / test_cache / test_serve / ... all
# compile the same entry points).  With the disk cache armed those
# recompiles become cheap deserializations.  Subprocess-spawning tests
# are unaffected (config does not propagate through the environment),
# and the repo's own compile counters count jit/lower calls, not XLA
# cache misses, so compile-count pins are unchanged.
_xla_cache_dir = tempfile.mkdtemp(prefix="raft-test-xla-cache-")
atexit.register(shutil.rmtree, _xla_cache_dir, ignore_errors=True)
jax.config.update("jax_compilation_cache_dir", _xla_cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)


_last_module = [None]


def pytest_runtest_setup(item):
    """Clear JAX's compiled-executable caches at each MODULE boundary.

    XLA-CPU's JIT can segfault after a few hundred live compiled
    executables accumulate in one long process (the reason ci.yml splits
    the nightly suite into two process chunks).  Bounding the live-
    executable count per module makes a raw single-process
    ``pytest tests/`` safe too; warm-cache reuse within a module is
    unaffected.  (A runtest_setup hook, not a collection-time marker:
    fixture closures are already fixed by collection time, so markers
    added in pytest_collection_modifyitems cannot schedule a fixture.)
    """
    name = getattr(getattr(item, "module", None), "__name__", None)
    if _last_module[0] is not None and name != _last_module[0]:
        jax.clear_caches()
    _last_module[0] = name
