"""Statics assembly tests: analytic cylinder cases + OC3 spar sanity checks.

Golden values are closed-form (uniform cylinder) or the public OC3-Hywind
specification (Jonkman, NREL/TP-500-47535) — not outputs of the reference
code, which cannot run here (MoorPy absent) and contains documented bugs.
"""
import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.build.members import build_member_set, build_rna
from raft_tpu.core.types import Env, RNA
from raft_tpu.statics import assemble_statics

RHO = 1025.0
G = 9.81


def cylinder_design(d=10.0, t=0.05, z0=-80.0, z1=20.0, rho_shell=8000.0,
                    l_fill=0.0, rho_fill=0.0):
    return {
        "platform": {
            "members": [
                {
                    "name": "cyl",
                    "type": 2,
                    "rA": [0, 0, z0],
                    "rB": [0, 0, z1],
                    "shape": "circ",
                    "stations": [z0, z1],
                    "d": d,
                    "t": t,
                    "rho_shell": rho_shell,
                    "l_fill": l_fill,
                    "rho_fill": rho_fill,
                }
            ]
        },
    }


def zero_rna():
    return RNA(mRNA=0.0, IxRNA=0.0, IrRNA=0.0, xCG_RNA=0.0, hHub=0.0)


class TestCylinderAnalytic:
    def setup_method(self):
        self.d, self.t, self.z0, self.z1 = 10.0, 0.05, -80.0, 20.0
        self.L = self.z1 - self.z0
        ms = build_member_set(cylinder_design(self.d, self.t, self.z0, self.z1))
        self.stat = jax.jit(assemble_statics)(ms, zero_rna(), Env())

    def test_shell_mass(self):
        di = self.d - 2 * self.t
        m_exp = 8000.0 * np.pi / 4 * (self.d**2 - di**2) * self.L
        np.testing.assert_allclose(self.stat.mass, m_exp, rtol=1e-9)

    def test_cg_at_midheight(self):
        np.testing.assert_allclose(self.stat.rCG[2], 0.5 * (self.z0 + self.z1), rtol=1e-9)
        np.testing.assert_allclose(self.stat.rCG[:2], 0.0, atol=1e-6)

    def test_displaced_volume_and_cb(self):
        V_exp = np.pi / 4 * self.d**2 * abs(self.z0)
        np.testing.assert_allclose(self.stat.V, V_exp, rtol=1e-9)
        np.testing.assert_allclose(self.stat.rCB[2], self.z0 / 2, rtol=1e-9)

    def test_waterplane(self):
        A_exp = np.pi / 4 * self.d**2
        I_exp = np.pi / 64 * self.d**4
        np.testing.assert_allclose(self.stat.AWP, A_exp, rtol=1e-9)
        np.testing.assert_allclose(self.stat.IWPx, I_exp, rtol=1e-9)

    def test_heave_stiffness(self):
        np.testing.assert_allclose(
            self.stat.C_hydro[2, 2], RHO * G * np.pi / 4 * self.d**2, rtol=1e-9
        )

    def test_pitch_stiffness(self):
        # C44_hydro = rho g (IWP + V zCB)
        V = np.pi / 4 * self.d**2 * abs(self.z0)
        I = np.pi / 64 * self.d**4
        C44_exp = RHO * G * (I + V * (self.z0 / 2))
        np.testing.assert_allclose(self.stat.C_hydro[3, 3], C44_exp, rtol=1e-9)
        np.testing.assert_allclose(self.stat.C_hydro[4, 4], C44_exp, rtol=1e-9)

    def test_buoyancy_force(self):
        V = np.pi / 4 * self.d**2 * abs(self.z0)
        np.testing.assert_allclose(self.stat.W_hydro[2], RHO * G * V, rtol=1e-9)
        np.testing.assert_allclose(self.stat.W_hydro[:2], 0.0, atol=1e-4)

    def test_weight_force(self):
        np.testing.assert_allclose(self.stat.W_struc[2], -G * self.stat.mass, rtol=1e-9)

    def test_pitch_inertia_thin_shell(self):
        # thin-walled tube about its CG: I = m (d^2/8 + L^2/12) (mean radius)
        m = float(self.stat.mass)
        rm = (self.d - self.t) / 2
        I_exp = m * (rm**2 / 2 + self.L**2 / 12)
        zCG = 0.5 * (self.z0 + self.z1)
        I_prp = float(self.stat.M_struc[4, 4])
        I_cg = I_prp - m * zCG**2
        np.testing.assert_allclose(I_cg, I_exp, rtol=1e-3)

    def test_c_struc_cg_terms(self):
        zCG = 0.5 * (self.z0 + self.z1)
        np.testing.assert_allclose(
            self.stat.C_struc[3, 3], -float(self.stat.mass) * G * zCG, rtol=1e-9
        )


class TestBallast:
    def test_ballast_mass_and_cg(self):
        d, t, z0, z1 = 10.0, 0.05, -100.0, 0.0
        lf, rf = 30.0, 1800.0
        ms = build_member_set(cylinder_design(d, t, z0, z1, l_fill=lf, rho_fill=rf))
        stat = assemble_statics(ms, zero_rna(), Env())
        di = d - 2 * t
        m_fill = rf * np.pi / 4 * di**2 * lf
        m_shell = 8000.0 * np.pi / 4 * (d**2 - di**2) * (z1 - z0)
        np.testing.assert_allclose(stat.mass, m_fill + m_shell, rtol=1e-9)
        np.testing.assert_allclose(stat.m_ballast, m_fill, rtol=1e-9)
        zCG_exp = (m_shell * (z0 + z1) / 2 + m_fill * (z0 + lf / 2)) / (m_shell + m_fill)
        np.testing.assert_allclose(stat.rCG[2], zCG_exp, rtol=1e-9)


class TestSubmergedInclined:
    def test_volume_invariant_under_incline(self):
        # fully submerged member: displaced volume independent of orientation
        base = {
            "name": "pontoon", "type": 2, "shape": "circ",
            "stations": [0, 40], "d": 4.0, "t": 0.03,
        }
        d_vert = {"platform": {"members": [dict(base, rA=[0, 0, -60], rB=[0, 0, -20])]}}
        h = 40.0 / np.sqrt(2.0)
        d_incl = {"platform": {"members": [dict(base, rA=[0, 0, -60], rB=[h, 0, -60 + h])]}}
        s_v = assemble_statics(build_member_set(d_vert), zero_rna(), Env())
        s_i = assemble_statics(build_member_set(d_incl), zero_rna(), Env())
        np.testing.assert_allclose(s_v.V, np.pi / 4 * 16 * 40, rtol=1e-9)
        np.testing.assert_allclose(s_i.V, s_v.V, rtol=1e-6)
        np.testing.assert_allclose(s_i.mass, s_v.mass, rtol=1e-9)


class TestOrientationCanonicalization:
    def test_deck_down_member_matches_deck_up(self):
        # a surface-piercing member listed top-first must give identical
        # hydrostatics (regression: LWP blow-up via cosPhi clipping)
        base = {"name": "c", "type": 2, "shape": "circ", "d": 6.5, "t": 0.03}
        up = {"platform": {"members": [dict(base, rA=[0, 0, -30], rB=[0, 0, 10], stations=[0, 40])]}}
        dn = {"platform": {"members": [dict(base, rA=[0, 0, 10], rB=[0, 0, -30], stations=[0, 40])]}}
        s_up = assemble_statics(build_member_set(up), zero_rna(), Env())
        s_dn = assemble_statics(build_member_set(dn), zero_rna(), Env())
        np.testing.assert_allclose(s_dn.V, s_up.V, rtol=1e-9)
        np.testing.assert_allclose(s_dn.C_hydro, s_up.C_hydro, rtol=1e-9, atol=1e-6)
        np.testing.assert_allclose(s_dn.W_hydro, s_up.W_hydro, rtol=1e-9, atol=1e-6)


class TestRectangular:
    def test_single_pair_two_stations(self):
        # a 1-D [len, wid] spec must mean one cross-section pair even with
        # exactly two stations (regression: was parsed as two square sections)
        des = {
            "platform": {
                "members": [
                    {
                        "name": "box", "type": 2, "shape": "rect",
                        "rA": [0, 0, -20], "rB": [0, 0, 0],
                        "stations": [0, 20], "d": [4.0, 2.0], "t": 0.05,
                        "rho_shell": 8000.0,
                    }
                ]
            },
        }
        stat = assemble_statics(build_member_set(des), zero_rna(), Env())
        np.testing.assert_allclose(stat.V, 4.0 * 2.0 * 20.0, rtol=1e-9)
        v_shell = 4 * 2 * 20 - (4 - 0.1) * (2 - 0.1) * 20
        np.testing.assert_allclose(stat.mass, 8000.0 * v_shell, rtol=1e-9)


class TestCaps:
    def test_solid_bottom_cap_mass(self):
        des = cylinder_design(10.0, 0.05, -80.0, 20.0)
        mem = des["platform"]["members"][0]
        mem["cap_stations"] = [-80.0]
        mem["cap_t"] = [0.2]
        mem["cap_d_in"] = [0.0]
        ms = build_member_set(des)
        stat = assemble_statics(ms, zero_rna(), Env())
        ms0 = build_member_set(cylinder_design(10.0, 0.05, -80.0, 20.0))
        stat0 = assemble_statics(ms0, zero_rna(), Env())
        di = 10.0 - 2 * 0.05
        m_cap = 8000.0 * np.pi / 4 * di**2 * 0.2
        np.testing.assert_allclose(float(stat.mass - stat0.mass), m_cap, rtol=1e-6)
        # caps must not alter hydrostatics
        np.testing.assert_allclose(stat.V, stat0.V, rtol=1e-12)


class TestOC3Spar:
    """Sanity checks against the public OC3-Hywind spec (loose tolerances:
    the YAML spar is a shell+ballast approximation of the spec's lumped
    properties)."""

    def setup_method(self):
        import os

        import yaml

        path = os.path.join(os.path.dirname(__file__), "..", "raft_tpu", "designs", "OC3spar.yaml")
        with open(path) as f:
            self.design = yaml.safe_load(f)
        self.ms = build_member_set(self.design)
        self.rna = build_rna(self.design)
        self.stat = assemble_statics(self.ms, self.rna, Env(depth=320.0))

    def test_displacement(self):
        # OC3 spec platform displacement 8029.2 m^3
        np.testing.assert_allclose(self.stat.V, 8029.2, rtol=0.02)

    def test_center_of_buoyancy(self):
        # OC3 spec CB at -62.07 m
        np.testing.assert_allclose(self.stat.rCB[2], -62.07, rtol=0.02)

    def test_waterplane_area(self):
        np.testing.assert_allclose(self.stat.AWP, np.pi / 4 * 6.5**2, rtol=1e-6)

    def test_total_mass_magnitude(self):
        # platform 7,466,330 + tower 249,718 + RNA 350,000 ~ 8.07e6 kg
        assert 6.5e6 < float(self.stat.mass) < 9.5e6

    def test_tower_mass(self):
        # NREL 5MW tower (OC3 variant) ~ 249,718 kg
        np.testing.assert_allclose(self.stat.m_tower, 249718.0, rtol=0.03)

    def test_heave_stiffness(self):
        np.testing.assert_allclose(
            self.stat.C_hydro[2, 2], RHO * G * np.pi / 4 * 6.5**2, rtol=1e-6
        )


class TestBatchingAndGrad:
    def test_vmap_matches_loop(self):
        designs = [cylinder_design(d=8.0), cylinder_design(d=12.0)]
        sets = [build_member_set(d) for d in designs]
        batched = jax.tree.map(lambda *xs: jnp.stack(xs), *sets)
        rna, env = zero_rna(), Env()
        out_b = jax.vmap(lambda m: assemble_statics(m, rna, env))(batched)
        for i, s in enumerate(sets):
            out_i = assemble_statics(s, rna, env)
            np.testing.assert_allclose(out_b.V[i], out_i.V, rtol=1e-12)
            np.testing.assert_allclose(out_b.M_struc[i], out_i.M_struc, rtol=1e-12)

    def test_grad_volume_wrt_diameter(self):
        ms = build_member_set(cylinder_design(d=10.0))

        def vol(scale):
            m2 = ms.replace(
                seg_dA=ms.seg_dA * scale, seg_dB=ms.seg_dB * scale,
                seg_diA=ms.seg_diA * scale, seg_diB=ms.seg_diB * scale,
            )
            return assemble_statics(m2, zero_rna(), Env()).V

        g = jax.grad(vol)(1.0)
        eps = 1e-5
        fd = (vol(1.0 + eps) - vol(1.0 - eps)) / (2 * eps)
        np.testing.assert_allclose(g, fd, rtol=1e-5)

    def test_padding_invariance(self):
        des = cylinder_design(d=10.0)
        s1 = assemble_statics(build_member_set(des), zero_rna(), Env())
        s2 = assemble_statics(
            build_member_set(des, pad_segments=8, pad_nodes=40), zero_rna(), Env()
        )
        np.testing.assert_allclose(s1.mass, s2.mass, rtol=1e-12)
        np.testing.assert_allclose(s1.M_struc, s2.M_struc, rtol=1e-12)
        np.testing.assert_allclose(s1.C_hydro, s2.C_hydro, rtol=1e-12)
        np.testing.assert_allclose(s1.V, s2.V, rtol=1e-12)
