"""Geometry parameterizations: identity, exact scaling laws, grad, vmap.

The draft/plan knobs are the north star's own sweep axes ("1,000
VolturnUS-S draft/column-radius variants", BASELINE.json); these tests pin
the exact geometric relations they must satisfy on the OC3 spar (fully
vertical — draft laws are exact) and the OC4 semi (offset columns — plan
laws are exact on positions and waterplane spacing inertia).
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from raft_tpu.build.members import build_member_set, build_rna
from raft_tpu.core.types import Env
from raft_tpu.model import load_design
from raft_tpu.parallel import make_scale_plan, make_stretch_draft
from raft_tpu.statics import assemble_statics


@pytest.fixture(scope="module")
def oc3():
    design = load_design("raft_tpu/designs/OC3spar.yaml")
    return build_member_set(design), build_rna(design)


@pytest.fixture(scope="module")
def oc4():
    design = load_design("raft_tpu/designs/OC4semi.yaml")
    return build_member_set(design), build_rna(design)


def _tree_allclose(a, b, rtol=1e-12, atol=1e-12):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=rtol, atol=atol)


def test_identity_at_unit_scale(oc3, oc4):
    for members, _ in (oc3, oc4):
        for make in (make_stretch_draft, make_scale_plan):
            _tree_allclose(make(members)(members, 1.0), members)


def test_draft_stretch_exact_laws_on_spar(oc3):
    """Anchored at the waterline, a vertical hull's displaced volume, shell
    mass and ballast mass scale exactly by s; the waterplane is untouched."""
    members, rna = oc3
    env = Env(depth=320.0)
    fn = make_stretch_draft(members)
    s = 1.17
    s0 = assemble_statics(members, rna, env)
    s1 = assemble_statics(fn(members, s), rna, env)
    assert float(s1.V) == pytest.approx(s * float(s0.V), rel=1e-9)
    assert float(s1.AWP) == pytest.approx(float(s0.AWP), rel=1e-12)
    assert float(s1.rCB[2]) == pytest.approx(s * float(s0.rCB[2]), rel=1e-9)
    assert float(s1.m_ballast) == pytest.approx(s * float(s0.m_ballast), rel=1e-9)
    # shell mass: every substructure segment is vertical, caps keep thickness
    # -> shell scales by s up to the (thin) cap plates
    assert float(s1.m_shell) == pytest.approx(s * float(s0.m_shell), rel=2e-2)
    # tower untouched
    assert float(s1.m_tower) == pytest.approx(float(s0.m_tower), rel=1e-12)


def test_plan_scale_exact_laws_on_semi(oc4):
    """Offset columns move out by exactly s; the spacing term of the
    waterplane inertia (sum A x^2) grows by s^2; drafts are untouched."""
    members, rna = oc4
    env = Env(depth=200.0)
    fn = make_scale_plan(members)
    s = 1.25
    m1 = fn(members, s)
    r0 = np.asarray(members.node_r)[np.asarray(members.node_mask)]
    r1 = np.asarray(m1.node_r)[np.asarray(m1.node_mask)]
    # plan radius of the outermost substructure node scales exactly
    rad0 = np.hypot(r0[:, 0], r0[:, 1])
    rad1 = np.hypot(r1[:, 0], r1[:, 1])
    tower = rad0 < 1e-9
    assert rad1[~tower] == pytest.approx(s * rad0[~tower], rel=1e-9)
    np.testing.assert_allclose(r1[:, 2], r0[:, 2], atol=1e-9)  # drafts fixed

    s0 = assemble_statics(members, rna, env)
    s1 = assemble_statics(m1, rna, env)
    assert float(s1.AWP) == pytest.approx(float(s0.AWP), rel=1e-9)
    # IWP(s) = I_own + s^2 * I_spacing: fit the two unknowns from the
    # measurements at s=1 and s=1.25, then the value at a THIRD scale is an
    # overdetermined check of the quadratic law (a two-point fit alone would
    # be tautological)
    grow = (float(s1.IWPy) - float(s0.IWPy)) / (s**2 - 1.0)
    I_own = float(s0.IWPy) - grow
    assert grow > 0
    s2 = assemble_statics(fn(members, 1.1), rna, env)
    assert float(s2.IWPy) == pytest.approx(I_own + 1.1**2 * grow, rel=1e-6)


def test_pontoons_stretch_with_plan_scale(oc4):
    """Horizontal members' lumped node lengths pick up the stretch factor;
    vertical members' do not."""
    members, _ = oc4
    m1 = make_scale_plan(members)(members, 1.25)
    q = np.asarray(members.node_q)
    horiz = (np.abs(q[:, 2]) < 0.1) & np.asarray(members.node_mask)
    vert = (np.abs(q[:, 2]) > 0.9) & np.asarray(members.node_mask)
    sub = np.hypot(*np.asarray(members.node_r)[:, :2].T) > 1e-9
    dls0 = np.asarray(members.node_dls)
    dls1 = np.asarray(m1.node_dls)
    assert dls1[horiz & sub] == pytest.approx(1.25 * dls0[horiz & sub], rel=1e-9)
    assert dls1[vert] == pytest.approx(dls0[vert], rel=1e-9)


def test_grad_and_vmap_through_draft(oc3):
    members, rna = oc3
    env = Env(depth=320.0)
    fn = make_stretch_draft(members)

    def vol(s):
        return assemble_statics(fn(members, s), rna, env).V

    g = float(jax.grad(vol)(1.0))
    h = 1e-5
    fd = (float(vol(1.0 + h)) - float(vol(1.0 - h))) / (2 * h)
    assert g == pytest.approx(fd, rel=1e-6)
    assert g == pytest.approx(float(vol(1.0)), rel=1e-9)  # V linear in s

    scales = jnp.asarray([0.9, 1.0, 1.2])
    Vb = jax.vmap(vol)(scales)
    for i, s in enumerate(np.asarray(scales)):
        assert float(Vb[i]) == pytest.approx(float(vol(float(s))), rel=1e-12)


def test_padded_set_grad_finite_and_masks_correct():
    """Padding regression: (a) grads stay finite through the warp's frame
    normalization on padded (all-zero) rows; (b) the -1 pad ids in
    seg_member don't scatter into the substructure mask of the last
    member."""
    design = load_design("raft_tpu/designs/OC3spar.yaml")
    base = build_member_set(design)
    S = int(base.seg_mask.shape[0]) + 3
    N = int(base.node_mask.shape[0]) + 8
    padded = build_member_set(design, pad_segments=S, pad_nodes=N)
    rna = build_rna(design)
    env = Env(depth=320.0)
    fn = make_stretch_draft(padded)

    def vol(s):
        return assemble_statics(fn(padded, s), rna, env).V

    g = float(jax.grad(vol)(1.1))
    assert np.isfinite(g)
    # padded result matches the unpadded one exactly
    v_pad = float(vol(1.1))
    fn0 = make_stretch_draft(base)
    v0 = float(assemble_statics(fn0(base, 1.1), rna, env).V)
    assert v_pad == pytest.approx(v0, rel=1e-12)
    # masks: every padded row deselected, tower nodes deselected
    from raft_tpu.parallel import substructure_masks

    seg_sel, node_sel = substructure_masks(padded)
    assert not bool(np.asarray(seg_sel)[~np.asarray(padded.seg_mask)].any())
    assert not bool(np.asarray(node_sel)[~np.asarray(padded.node_mask)].any())
    # the highest VALID member id keeps its true classification even with
    # -1 pad ids present (negative-index scatter regression)
    nm = np.asarray(padded.node_member)
    last = int(nm[np.asarray(padded.node_mask)].max())
    seg_t = np.asarray(padded.seg_type)[np.asarray(padded.seg_member) == last]
    expect = bool((seg_t > 1).any())
    got = bool(np.asarray(node_sel)[nm == last].any())
    assert got == expect


@pytest.mark.slow
def test_rao_solve_runs_on_warped_geometry(oc3):
    """End-to-end: the warped geometry goes through the full RAO solve and
    deeper draft shifts heave resonance down (longer natural period)."""
    import __graft_entry__ as ge
    from raft_tpu.mooring import mooring_stiffness, parse_mooring
    from raft_tpu.solve import solve_eigen

    design, members, rna, env, wave = ge._base(nw=16)
    moor = parse_mooring(
        design["mooring"], yaw_stiffness=design["turbine"]["yaw_stiffness"]
    )
    C_moor = mooring_stiffness(moor, jnp.zeros(6))
    fn = make_stretch_draft(members)

    def heave_fn(s):
        st = assemble_statics(fn(members, s), rna, Env(depth=320.0))
        from raft_tpu.hydro import strip_added_mass

        A = strip_added_mass(fn(members, s), Env(depth=320.0))
        eig = solve_eigen(st.M_struc + A, st.C_struc + st.C_hydro + C_moor)
        return float(eig.fns[2])

    f0, f1 = heave_fn(1.0), heave_fn(1.3)
    assert f1 < f0  # more mass+added mass, same waterplane -> lower heave fn

    from raft_tpu.parallel import forward_response

    out = forward_response(fn(members, 1.3), rna, env, wave, C_moor,
                           n_iter=30, method="while")
    assert bool(out.converged)
    assert np.isfinite(np.asarray(out.Xi.re)).all()
