"""On-device (JAX) BEM solver tests.

Oracle: the native C++ f64 panel solver (``hydro/native_bem.py``), the
spec the JAX port reproduces.  Parity is pinned at the DOCUMENTED
tolerance (:data:`raft_tpu.hydro.jax_bem.PARITY_RTOL`) across the
contract surface the tentpole claims: deep + finite depth, scalar heading
+ heading grid, with and without an irregular-frequency lid — plus a
finite-difference check that ``jax.grad`` really flows through panel
geometry, influence assembly and the refined LU solve.

The cross-process side of the story (novel geometry with g++ POISONED,
warm/novel zero-compile legs) lives in ``make bem-smoke``
(:mod:`raft_tpu.hydro.bem_smoke`); these tests cover the numerics.
"""
import os

import numpy as np
import pytest

from raft_tpu.hydro import jax_bem

W = np.array([0.6, 1.1, 1.6])


def column_mesh(r=1.2, draft=7.0, top=2.0, dz_max=1.5, da_max=1.2,
                x0=0.0):
    from raft_tpu.hydro.mesh import mesh_member

    return mesh_member(
        stations=[0.0, draft + top], diameters=[2 * r, 2 * r],
        rA=[x0, 0.0, -draft], rB=[x0, 0.0, top],
        dz_max=dz_max, da_max=da_max)


def assert_parity(jax_out, native_out):
    for g, n, name in zip(jax_out, native_out, ("A", "B", "F")):
        err = jax_bem.parity_err(g, n)   # THE shared PARITY_RTOL metric
        assert err <= jax_bem.PARITY_RTOL, (
            f"{name}: {err:.2e} > PARITY_RTOL {jax_bem.PARITY_RTOL:.0e}")


# ------------------------------------------------------------- mode knob

def test_bem_mode_parsing(monkeypatch):
    monkeypatch.delenv(jax_bem.ENV_VAR, raising=False)
    assert jax_bem.bem_mode() == "auto"
    for raw, want in [("native", "native"), (" JAX ", "jax"),
                      ("auto", "auto"), ("", "auto"), ("bogus", "auto")]:
        monkeypatch.setenv(jax_bem.ENV_VAR, raw)
        assert jax_bem.bem_mode() == want
    # auto resolves per backend: CPU suite -> the native host solver
    monkeypatch.setenv(jax_bem.ENV_VAR, "auto")
    assert jax_bem.resolved_mode() == "native"
    assert jax_bem.resolved_mode("jax") == "jax"
    assert jax_bem.resolved_mode("native") == "native"
    # an EXPLICIT 'auto' (Model(BEM="auto")) defers to the env knob: the
    # operator override must reach every Model, whatever mode string it
    # was built with
    monkeypatch.setenv(jax_bem.ENV_VAR, "jax")
    assert jax_bem.resolved_mode("auto") == "jax"
    monkeypatch.setenv(jax_bem.ENV_VAR, "native")
    assert jax_bem.resolved_mode("auto") == "native"
    monkeypatch.delenv(jax_bem.ENV_VAR)
    assert jax_bem.resolved_mode("auto") == "native"   # backend rule (CPU)


def test_mode_is_key_salted():
    """A RAFT_TPU_BEM flip must change every AOT key (the staged
    coefficients differ at parity tolerance, not bitwise)."""
    from raft_tpu.cache.aot import _solver_salts

    salts = _solver_salts()
    assert "bem_mode" in salts
    assert salts[salts.index("bem_mode") + 1] in ("native", "jax")


def test_model_bem_arg_validated():
    from raft_tpu.model import Model, load_design

    design = load_design("raft_tpu/designs/OC3spar.yaml")
    with pytest.raises(ValueError, match="expected 'native'"):
        Model(design, BEM="typo-mode")


def test_pad_panel_count_follows_ladder():
    from raft_tpu.build import buckets

    classes = buckets.ladder()["panels"]
    assert jax_bem.pad_panel_count(1) == classes[0]
    assert jax_bem.pad_panel_count(classes[0]) == classes[0]
    assert jax_bem.pad_panel_count(classes[0] + 1) == classes[1]


# --------------------------------------------------- shared result cache

def test_cache_corrupt_counter(tmp_path, monkeypatch):
    """A corrupt artifact is a COUNTED miss: ``bem.cache_corrupt``
    increments (ChunkStore's ckpt.corrupt precedent), the file is
    deleted, and the caller recomputes — corruption is observable, not a
    silent unlink."""
    from raft_tpu import obs
    from raft_tpu.cache import config
    from raft_tpu.hydro import native_bem

    monkeypatch.setenv("RAFT_TPU_CACHE_DIR", str(tmp_path))
    config.disable()                       # force env re-resolution
    key = native_bem.result_cache_key(
        "bem", np.zeros((2, 4, 3)), W, np.zeros(1), (1.0, 2.0))
    corrupt0 = obs.metrics.counter("bem.cache_corrupt").value
    miss0 = obs.metrics.counter("bem.cache_miss").value

    # absent artifact: a plain miss, NOT corruption
    assert native_bem.result_cache_load(key, ("A",)) is None
    assert obs.metrics.counter("bem.cache_corrupt").value == corrupt0
    assert obs.metrics.counter("bem.cache_miss").value == miss0 + 1

    # garbage bytes: corrupt + miss, artifact deleted
    os.makedirs(os.path.dirname(key), exist_ok=True)
    with open(key, "wb") as f:
        f.write(b"\x00not-an-npz")
    assert native_bem.result_cache_load(key, ("A",)) is None
    assert obs.metrics.counter("bem.cache_corrupt").value == corrupt0 + 1
    assert not os.path.exists(key)

    # whole npz MISSING a needed key: also corruption (torn contract)
    native_bem.result_cache_store(key, {"B": np.ones(3)})
    assert native_bem.result_cache_load(key, ("A", "B")) is None
    assert obs.metrics.counter("bem.cache_corrupt").value == corrupt0 + 2
    assert not os.path.exists(key)

    # intact artifact: a hit, no further corruption counted
    native_bem.result_cache_store(key, {"A": np.arange(3.0)})
    out = native_bem.result_cache_load(key, ("A",))
    np.testing.assert_array_equal(out["A"], np.arange(3.0))
    assert obs.metrics.counter("bem.cache_corrupt").value == corrupt0 + 2


def test_native_lib_keyed_by_source_content():
    """The built .so is keyed by a CONTENT hash of bem.cpp — a git
    checkout that regresses mtimes cannot serve a stale solver (the old
    ``getmtime(_LIB) >= src_mtime`` check could)."""
    from raft_tpu.hydro import native_bem

    path = native_bem._lib_path()
    digest = native_bem._src_digest()
    assert digest[:16] in os.path.basename(path)
    # the key is pure content: recomputing it is stable
    assert native_bem._lib_path() == path


# ------------------------------------------------------ parity vs oracle

@pytest.mark.slow
def test_parity_deep_scalar_heading():
    from raft_tpu.hydro.native_bem import solve_bem

    mesh = column_mesh()
    kw = dict(rho=1025.0, g=9.81, beta=0.3, depth=0.0, cache=False)
    native = solve_bem(mesh, W, **kw)
    got = jax_bem.solve_bem_jax(mesh, W, **kw)
    assert_parity(got, native)


@pytest.mark.slow
def test_parity_finite_depth_heading_grid():
    """Finite depth (the 4-image exp-fit kernel) x a heading grid
    (factor once, back-substitute per heading) — F comes back
    (nb, 6, nw) on both paths."""
    from raft_tpu.hydro.native_bem import solve_bem

    mesh = column_mesh(r=1.1, draft=6.0)
    betas = np.array([0.0, 0.7, 1.4])
    kw = dict(rho=1025.0, g=9.81, beta=betas, depth=25.0, cache=False)
    native = solve_bem(mesh, W, **kw)
    got = jax_bem.solve_bem_jax(mesh, W, **kw)
    assert got[2].shape == native[2].shape == (3, 6, len(W))
    assert_parity(got, native)


@pytest.mark.slow
def test_parity_lid_mesh():
    """Irregular-frequency lid (extended boundary integral): the lid
    rows swap to the potential equation on both paths."""
    from raft_tpu.hydro.mesh import disk_panels
    from raft_tpu.hydro.native_bem import solve_bem

    mesh = column_mesh(r=1.5, draft=7.0)
    lid = disk_panels(np.zeros(3), 1.5, da_max=1.2)
    assert len(lid) > 0
    kw = dict(rho=1025.0, g=9.81, beta=0.0, depth=0.0, lid=lid,
              cache=False)
    native = solve_bem(mesh, W, **kw)
    got = jax_bem.solve_bem_jax(mesh, W, **kw)
    assert_parity(got, native)


@pytest.mark.slow
def test_residual_at_refinement_tolerance():
    """The measured refinement residual (the f32-vs-oracle quality
    signal the diagnostics return) sits at f32 roundoff, far inside the
    parity tolerance."""
    mesh = column_mesh(r=1.0, draft=5.0)
    _, _, _, diag = jax_bem.solve_bem_jax(
        mesh, W, beta=0.2, depth=30.0, cache=False,
        return_diagnostics=True)
    assert diag["refine_iters"] == jax_bem.N_REFINE
    assert diag["max_residual"] < 1e-4
    assert diag["padded"] >= diag["panels"]


@pytest.mark.slow
def test_solve_bem_any_routes_by_mode():
    """Both routes honor the shared return contract and agree to the
    parity tolerance — the staging sites can swap solver per knob."""
    mesh = column_mesh(r=0.9, draft=4.5, dz_max=2.0, da_max=1.6)
    kw = dict(rho=1025.0, g=9.81, beta=0.1, depth=0.0, cache=False)
    a_nat = jax_bem.solve_bem_any(mesh, W, mode="native", **kw)
    a_jax = jax_bem.solve_bem_any(mesh, W, mode="jax", **kw)
    assert a_nat[0].shape == a_jax[0].shape == (6, 6, len(W))
    assert a_nat[2].shape == a_jax[2].shape == (6, len(W))
    assert_parity(a_jax, a_nat)


@pytest.mark.slow
def test_jax_result_cache_roundtrip(tmp_path, monkeypatch):
    """The on-device solver shares the corruption-tolerant atomic result
    cache: a second identical solve is served bit-identically from disk
    (diagnostics say so), under the jax-specific namespace."""
    from raft_tpu.cache import config

    monkeypatch.setenv("RAFT_TPU_CACHE_DIR", str(tmp_path))
    config.disable()
    mesh = column_mesh(r=0.8, draft=4.0, dz_max=2.2, da_max=1.9)
    w = np.array([0.9])
    kw = dict(rho=1025.0, g=9.81, beta=0.0, depth=0.0, cache=True)
    A1, B1, F1, d1 = jax_bem.solve_bem_jax(mesh, w, return_diagnostics=True,
                                           **kw)
    assert d1["cached"] is False
    A2, B2, F2, d2 = jax_bem.solve_bem_jax(mesh, w, return_diagnostics=True,
                                           **kw)
    assert d2["cached"] is True
    # ONE diagnostics contract on both paths: callers index the keys
    # unconditionally, so a hit must carry them all (residual measured at
    # store time rides in the artifact)
    assert set(d2) == set(d1)
    assert d2["padded"] == d1["padded"]
    assert d2["max_residual"] == pytest.approx(d1["max_residual"])
    np.testing.assert_array_equal(A1, A2)
    np.testing.assert_array_equal(B1, B2)
    np.testing.assert_array_equal(F1, F2)
    assert os.path.isdir(os.path.join(str(tmp_path), "bem-jax"))


# ------------------------------------------- differentiability (tentpole)

@pytest.mark.slow
def test_grad_matches_finite_difference():
    """jax.grad through panel geometry -> influence assembly -> refined
    LU solve -> A/B/F, against a central finite difference, in f64 (the
    suite runs x64) so the FD truncation error is the only slack."""
    import jax
    import jax.numpy as jnp

    mesh = column_mesh(r=1.2, draft=6.0, dz_max=2.2, da_max=1.9)
    w = np.array([0.7, 1.2])
    bem_fn = jax_bem.make_bem_fn(mesh, w, depth=30.0, beta=0.1,
                                 dtype=jnp.float64)

    def loss(theta):
        A, B, F = bem_fn(theta)
        return (jnp.sum(A) * 1e-6 + jnp.sum(B) * 1e-6
                + jnp.sum(F.re ** 2 + F.im ** 2) * 1e-10)

    loss_j = jax.jit(loss)
    g = float(jax.jit(jax.grad(loss))(jnp.float64(1.0)))
    eps = 1e-5
    fd = (float(loss_j(jnp.float64(1.0 + eps)))
          - float(loss_j(jnp.float64(1.0 - eps)))) / (2 * eps)
    assert g == pytest.approx(fd, rel=1e-6)
    assert np.isfinite(g) and abs(g) > 0.0


@pytest.mark.slow
def test_optimize_design_bem_fn_descends():
    """The closed co-design loop: optimize_design(bem_fn=...) re-solves
    the panel method differentiably inside each step, and the optimizer
    still descends — the gradient carries geometry -> A/B/F -> RAO
    (with a static ``bem`` the coefficients are frozen at the nominal
    hull)."""
    import jax.numpy as jnp

    import __graft_entry__ as ge
    from raft_tpu.mooring import mooring_stiffness, parse_mooring
    from raft_tpu.parallel import optimize_design

    design, members, rna, env, wave = ge._base(nw=24)
    moor = parse_mooring(design["mooring"],
                         yaw_stiffness=design["turbine"]["yaw_stiffness"])
    C_moor = mooring_stiffness(moor, jnp.zeros(6))
    # a coarse spar-like column (64-panel class keeps the re-solve cheap)
    mesh = column_mesh(r=3.25, draft=8.0, dz_max=3.5, da_max=3.4)
    assert jax_bem.pad_panel_count(len(mesh)) == 64
    bem_fn = jax_bem.make_bem_fn(mesh, np.asarray(wave.w), beta=0.0,
                                 dtype=jnp.float32)
    res = optimize_design(members, rna, env, wave, C_moor, theta0=1.0,
                          steps=2, learning_rate=0.02, bounds=(0.8, 1.25),
                          n_iter=8, bem_fn=bem_fn)
    assert np.isfinite(res.history).all()
    assert res.history[-1] < res.history[0]
    # exclusivity: frozen bem AND differentiable bem_fn cannot combine
    with pytest.raises(ValueError, match="not both"):
        optimize_design(members, rna, env, wave, C_moor, theta0=1.0,
                        steps=1, bem=(np.zeros((6, 6, 24)),
                                      np.zeros((6, 6, 24)),
                                      np.zeros((6, 24), complex)),
                        bem_fn=bem_fn)


@pytest.mark.slow
def test_grad_f32_stays_finite():
    """The f32 production dtype: gradients through the padded mesh (with
    degenerate zero-area panels) stay finite — the _safe_norm contract."""
    import jax
    import jax.numpy as jnp

    mesh = column_mesh(r=1.0, draft=5.0, dz_max=2.2, da_max=1.9)
    w = np.array([0.8])
    bem_fn = jax_bem.make_bem_fn(mesh, w, beta=0.0, dtype=jnp.float32)

    def loss(theta):
        A, B, F = bem_fn(theta)
        return jnp.sum(B) * 1e-6

    g = float(jax.jit(jax.grad(loss))(jnp.float32(1.0)))
    assert np.isfinite(g)
