"""Sweep/sharding tests on the virtual 8-device CPU mesh (conftest)."""
import pytest
import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.build.members import build_member_set, build_rna
from raft_tpu.core.types import Env, WaveState
from raft_tpu.core.waves import jonswap, wave_number
from raft_tpu.model import load_design
from raft_tpu.mooring import mooring_stiffness, parse_mooring
from raft_tpu.parallel import (
    forward_response,
    grad_response_std,
    make_mesh,
    response_std,
    sweep,
)

DESIGN = "raft_tpu/designs/OC3spar.yaml"


def setup(nw=10):
    design = load_design(DESIGN)
    members = build_member_set(design)
    rna = build_rna(design)
    depth = float(design["mooring"]["water_depth"])
    env = Env(Hs=8.0, Tp=12.0, depth=depth)
    w = jnp.linspace(0.05, 2.95, nw)
    wave = WaveState(w=w, k=wave_number(w, depth), zeta=jnp.sqrt(jonswap(w, 8.0, 12.0)))
    moor = parse_mooring(design["mooring"], yaw_stiffness=design["turbine"]["yaw_stiffness"])
    C_moor = mooring_stiffness(moor, jnp.zeros(6))
    return members, rna, env, wave, C_moor


@pytest.mark.slow
def test_sea_state_sweep_matches_loop():
    """DLC-table evaluation: vmapped sea-state batch == per-case loop, and
    response grows with Hs."""
    import __graft_entry__ as ge
    from raft_tpu.core.types import WaveState
    from raft_tpu.parallel import (
        forward_response, make_wave_states, response_std, sweep_sea_states,
    )

    design, members, rna, env, wave = ge._base(nw=16)
    moor = parse_mooring(
        design["mooring"], yaw_stiffness=design["turbine"]["yaw_stiffness"]
    )
    C_moor = mooring_stiffness(moor, jnp.zeros(6))
    cases = [[4.0, 9.0], [8.0, 12.0], [12.0, 15.0]]
    waves = make_wave_states(np.asarray(wave.w), cases, float(env.depth))
    out = sweep_sea_states(members, rna, env, waves, C_moor)
    assert out["std dev"].shape == (3, 6)
    # monotone in severity for surge
    assert out["std dev"][0, 0] < out["std dev"][1, 0] < out["std dev"][2, 0]
    # case 1 == the plain single-sea-state solve
    w1 = WaveState(w=waves.w[1], k=waves.k[1], zeta=waves.zeta[1])
    ref = forward_response(members, rna, env, w1, C_moor)
    sig1 = np.asarray(response_std(ref.Xi.abs2(), w1.w))
    np.testing.assert_allclose(out["std dev"][1], sig1, rtol=1e-12, atol=1e-14)


@pytest.mark.slow
def test_sea_state_sweep_with_bem_matches_staged_single():
    """The per-case zeta re-staging of BEM excitation inside the vmap must
    equal stage_bem + forward_response case by case."""
    import __graft_entry__ as ge
    from raft_tpu.core.types import WaveState
    from raft_tpu.parallel import (
        forward_response, make_wave_states, response_std, stage_bem,
        sweep_sea_states,
    )

    design, members, rna, env, wave = ge._base(nw=12)
    moor = parse_mooring(
        design["mooring"], yaw_stiffness=design["turbine"]["yaw_stiffness"]
    )
    C_moor = mooring_stiffness(moor, jnp.zeros(6))
    nw = 12
    rng = np.random.default_rng(0)
    A = np.tile(np.eye(6)[:, :, None] * 5e6, (1, 1, nw))
    B = np.tile(np.eye(6)[:, :, None] * 1e5, (1, 1, nw))
    F = (rng.normal(size=(6, nw)) + 1j * rng.normal(size=(6, nw))) * 1e5
    waves = make_wave_states(np.asarray(wave.w), [[6.0, 10.0], [10.0, 14.0]],
                             float(env.depth))
    out = sweep_sea_states(members, rna, env, waves, C_moor, bem=(A, B, F))
    for i in range(2):
        wi = WaveState(w=waves.w[i], k=waves.k[i], zeta=waves.zeta[i])
        ref = forward_response(members, rna, env, wi, C_moor,
                               bem=stage_bem((A, B, F), wi))
        sig = np.asarray(response_std(ref.Xi.abs2(), wi.w))
        np.testing.assert_allclose(out["std dev"][i], sig, rtol=1e-12)


@pytest.mark.slow
def test_sweep_sea_states_heading_axis():
    """(Hs, Tp, beta) DLC rows: each case lane carries its own wave heading
    through the node kinematics, pinned against per-case single solves."""
    import __graft_entry__ as ge
    from raft_tpu.core.types import WaveState
    from raft_tpu.parallel import (
        forward_response, make_wave_states, response_std, sweep_sea_states,
    )

    design, members, rna, env, wave = ge._base(nw=12)
    moor = parse_mooring(
        design["mooring"], yaw_stiffness=design["turbine"]["yaw_stiffness"]
    )
    C_moor = mooring_stiffness(moor, jnp.zeros(6))
    cases = [[6.0, 10.0, 0.0], [6.0, 10.0, 0.7], [8.0, 12.0, 1.3]]
    waves = make_wave_states(np.asarray(wave.w), cases, float(env.depth))
    assert waves.beta is not None and waves.beta.shape == (3,)
    out = sweep_sea_states(members, rna, env, waves, C_moor)
    # cases 0 and 1 share (Hs, Tp): only the heading separates them
    assert np.abs(out["std dev"][0] - out["std dev"][1]).max() > 1e-9
    a_nac = out["nacelle accel std dev"]
    assert a_nac.shape == (3,) and np.isfinite(a_nac).all() and (a_nac > 0).all()
    for i, (Hs, Tp, beta) in enumerate(cases):
        wi = WaveState(w=waves.w[i], k=waves.k[i], zeta=waves.zeta[i])
        ref = forward_response(members, rna, env.replace(beta=beta), wi, C_moor)
        sig = np.asarray(response_std(ref.Xi.abs2(), wi.w))
        np.testing.assert_allclose(out["std dev"][i], sig, rtol=1e-12, atol=1e-14)
    # a heading-carrying WaveState means the same thing OUTSIDE the sweep:
    # forward_response folds wave.beta into env rather than ignoring it
    w1 = WaveState(w=waves.w[1], k=waves.k[1], zeta=waves.zeta[1],
                   beta=waves.beta[1])
    direct = forward_response(members, rna, env, w1, C_moor)
    via_env = forward_response(
        members, rna, env.replace(beta=0.7),
        WaveState(w=waves.w[1], k=waves.k[1], zeta=waves.zeta[1]), C_moor,
    )
    np.testing.assert_allclose(np.asarray(direct.Xi.re),
                               np.asarray(via_env.Xi.re), rtol=1e-12)


def test_sweep_sea_states_heading_axis_with_bem_grid():
    """Heading-varying cases consume a staged BEM heading grid: each case's
    excitation is interpolated to its own heading; a single-heading bem
    tuple under varying headings is rejected."""
    import __graft_entry__ as ge
    from raft_tpu.core.types import WaveState
    from raft_tpu.model import interp_heading_excitation
    from raft_tpu.parallel import (
        forward_response, make_wave_states, response_std, stage_bem,
        sweep_sea_states,
    )

    design, members, rna, env, wave = ge._base(nw=12)
    moor = parse_mooring(
        design["mooring"], yaw_stiffness=design["turbine"]["yaw_stiffness"]
    )
    C_moor = mooring_stiffness(moor, jnp.zeros(6))
    nw = 12
    rng = np.random.default_rng(1)
    A = np.tile(np.eye(6)[:, :, None] * 5e6, (1, 1, nw))
    B = np.tile(np.eye(6)[:, :, None] * 1e5, (1, 1, nw))
    bgrid = np.array([0.0, 1.0])
    F_all = (rng.normal(size=(2, 6, nw))
             + 1j * rng.normal(size=(2, 6, nw))) * 1e5
    cases = [[6.0, 10.0, 0.25], [8.0, 12.0, 0.75]]
    waves = make_wave_states(np.asarray(wave.w), cases, float(env.depth))
    out = sweep_sea_states(members, rna, env, waves, C_moor,
                           bem=(bgrid, F_all, A, B))
    for i, (Hs, Tp, beta) in enumerate(cases):
        F_i = interp_heading_excitation(bgrid, F_all, beta)
        wi = WaveState(w=waves.w[i], k=waves.k[i], zeta=waves.zeta[i])
        ref = forward_response(members, rna, env.replace(beta=beta), wi,
                               C_moor, bem=stage_bem((A, B, F_i), wi))
        sig = np.asarray(response_std(ref.Xi.abs2(), wi.w))
        np.testing.assert_allclose(out["std dev"][i], sig, rtol=1e-12)
    with pytest.raises(ValueError, match="heading"):
        sweep_sea_states(members, rna, env, waves, C_moor,
                         bem=(A, B, F_all[0]))


def test_spreading_weights_properties():
    from raft_tpu.core.waves import spreading_weights

    off, w = spreading_weights(n_dir=9, s=2.0)
    assert w.sum() == pytest.approx(1.0)
    np.testing.assert_allclose(w, w[::-1])            # symmetric about 0
    np.testing.assert_allclose(off, -off[::-1])
    assert w[4] == w.max()                            # peaked at the mean
    # larger s concentrates energy toward the mean heading
    _, w8 = spreading_weights(n_dir=9, s=8.0)
    assert w8[4] > w[4]
    # degenerate single-lane forms
    for kw in ({"n_dir": 1}, {"s": np.inf}):
        off1, w1 = spreading_weights(**kw)
        assert off1.shape == (1,) and w1[0] == 1.0


def test_directional_response_matches_manual_sum():
    """Short-crested sea: the spread response equals the per-direction
    manual combination, and n_dir=1 degenerates to the long-crested solve."""
    import __graft_entry__ as ge
    from raft_tpu.core.types import WaveState
    from raft_tpu.core.waves import spreading_weights
    from raft_tpu.parallel import (
        directional_response, forward_response, response_std,
        spread_sea_state,
    )

    design, members, rna, env, wave = ge._base(nw=12)
    moor = parse_mooring(
        design["mooring"], yaw_stiffness=design["turbine"]["yaw_stiffness"]
    )
    C_moor = mooring_stiffness(moor, jnp.zeros(6))
    w = np.asarray(wave.w)

    waves_dir = spread_sea_state(w, 8.0, 12.0, float(env.depth), beta0=0.0,
                                 n_dir=3, s=2.0)
    out = directional_response(members, rna, env, waves_dir, C_moor)

    offsets, wts = spreading_weights(n_dir=3, s=2.0)
    var = np.zeros(6)
    for j in range(3):
        wj = WaveState(w=waves_dir.w[j], k=waves_dir.k[j],
                       zeta=waves_dir.zeta[j])
        ref = forward_response(members, rna, env.replace(beta=float(offsets[j])),
                               wj, C_moor)
        var += np.asarray(response_std(ref.Xi.abs2(), wj.w)) ** 2
    np.testing.assert_allclose(out["std dev"], np.sqrt(var), rtol=1e-9)

    # short-crestedness puts energy into sway on an axisymmetric hull at
    # beta0=0, and reduces the surge response vs the long-crested sea
    single = spread_sea_state(w, 8.0, 12.0, float(env.depth), n_dir=1)
    out1 = directional_response(members, rna, env, single, C_moor)
    assert out["std dev"][1] > 1e-6                   # sway excited
    assert out["std dev"][0] < out1["std dev"][0]     # surge energy spread
    # long-crested degenerate case == plain single-heading solve
    ref1 = forward_response(members, rna, env, wave, C_moor)
    sig1 = np.asarray(response_std(ref1.Xi.abs2(), wave.w))
    np.testing.assert_allclose(out1["std dev"], sig1, rtol=1e-9)


def test_mixed_sea_bimodal_response():
    """Wind sea + swell from different headings: the bimodal response is
    the RSS of the component responses (independent linear systems), not
    a per-case table reduction."""
    from raft_tpu.core.types import WaveState
    from raft_tpu.parallel import (
        directional_response, mixed_sea_state, response_std,
    )

    members, rna, env, wave, C_moor = setup(nw=12)
    w = np.asarray(wave.w)
    comps = [[6.0, 9.0, 0.0], [3.0, 16.0, 1.2]]      # wind sea + swell
    waves = mixed_sea_state(w, comps, float(env.depth))
    out = directional_response(members, rna, env, waves, C_moor)

    var = np.zeros(6)
    for j, (Hs, Tp, beta) in enumerate(comps):
        wj = WaveState(w=waves.w[j], k=waves.k[j], zeta=waves.zeta[j])
        ref = forward_response(members, rna, env.replace(beta=beta), wj, C_moor)
        var += np.asarray(response_std(ref.Xi.abs2(), wj.w)) ** 2
    np.testing.assert_allclose(out["std dev"], np.sqrt(var), rtol=1e-9)
    # the swell heading excites sway; the wind sea alone would not
    assert out["std dev"][1] > 1e-6
    with pytest.raises(ValueError, match="Hs, Tp, beta"):
        mixed_sea_state(w, [[6.0, 9.0]], float(env.depth))


@pytest.mark.slow
def test_2d_mesh_dp_sp_matches_unsharded():
    """Composed design x frequency parallelism: a (2, 4) mesh — design
    batch data-parallel over rows, frequency grid sequence-parallel over
    columns — reproduces the single-device vmapped solve."""
    import __graft_entry__ as ge
    from jax.sharding import Mesh
    from raft_tpu.parallel import (
        forward_response, forward_response_dp_sp, scale_diameters,
    )

    design, members, rna, env, wave = ge._base(nw=8)
    moor = parse_mooring(
        design["mooring"], yaw_stiffness=design["turbine"]["yaw_stiffness"]
    )
    C_moor = mooring_stiffness(moor, jnp.zeros(6))
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                axis_names=("designs", "freq"))
    thetas = jnp.asarray([0.92, 0.98, 1.04, 1.1])

    out = forward_response_dp_sp(members, rna, env, wave, C_moor, thetas,
                                 mesh=mesh)
    ref = jax.vmap(
        lambda s: forward_response(scale_diameters(members, s), rna, env,
                                   wave, C_moor, n_iter=40, method="while")
    )(thetas)
    np.testing.assert_allclose(np.asarray(out.Xi.re), np.asarray(ref.Xi.re),
                               rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(np.asarray(out.Xi.im), np.asarray(ref.Xi.im),
                               rtol=1e-9, atol=1e-12)
    assert out.Xi.re.shape == (4, 8, 6)
    with pytest.raises(ValueError, match="not divisible"):
        forward_response_dp_sp(members, rna, env, wave, C_moor,
                               jnp.ones(3), mesh=mesh)


def test_sea_state_sweep_sharded_matches_unsharded():
    import __graft_entry__ as ge
    from jax.sharding import Mesh
    from raft_tpu.parallel import make_wave_states, sweep_sea_states

    design, members, rna, env, wave = ge._base(nw=12)
    moor = parse_mooring(
        design["mooring"], yaw_stiffness=design["turbine"]["yaw_stiffness"]
    )
    C_moor = mooring_stiffness(moor, jnp.zeros(6))
    cases = [[h, 8.0 + h / 2] for h in (2.0, 4.0, 6.0, 8.0)]
    waves = make_wave_states(np.asarray(wave.w), cases, float(env.depth))
    ref = sweep_sea_states(members, rna, env, waves, C_moor)
    mesh = Mesh(np.array(jax.devices()[:4]), axis_names=("cases",))
    out = sweep_sea_states(members, rna, env, waves, C_moor, mesh=mesh)
    np.testing.assert_allclose(out["std dev"], ref["std dev"], rtol=1e-12)

    # shared-heading BEM: the excitation is staged ONCE ((nw,6), replicated
    # over the mesh) while the per-case zeta scaling stays sharded
    rng = np.random.default_rng(3)
    nw = len(np.asarray(wave.w))
    A = np.tile(np.eye(6)[:, :, None] * 4e6, (1, 1, nw))
    B = np.tile(np.eye(6)[:, :, None] * 2e5, (1, 1, nw))
    F = (rng.normal(size=(6, nw)) + 1j * rng.normal(size=(6, nw))) * 2e5
    ref_b = sweep_sea_states(members, rna, env, waves, C_moor, bem=(A, B, F))
    out_b = sweep_sea_states(members, rna, env, waves, C_moor, bem=(A, B, F),
                             mesh=mesh)
    np.testing.assert_allclose(out_b["std dev"], ref_b["std dev"], rtol=1e-12)
    with pytest.raises(ValueError, match="not divisible"):
        sweep_sea_states(members, rna, env,
                         make_wave_states(np.asarray(wave.w), cases[:3],
                                          float(env.depth)),
                         C_moor, mesh=mesh)


@pytest.mark.slow
def test_2d_mesh_dp_sp_with_bem_matches_unsharded():
    """dp_sp with staged BEM coefficients == the vmapped staged solve."""
    import __graft_entry__ as ge
    from jax.sharding import Mesh
    from raft_tpu.parallel import (
        forward_response, forward_response_dp_sp, scale_diameters, stage_bem,
    )

    design, members, rna, env, wave = ge._base(nw=8)
    moor = parse_mooring(
        design["mooring"], yaw_stiffness=design["turbine"]["yaw_stiffness"]
    )
    C_moor = mooring_stiffness(moor, jnp.zeros(6))
    rng = np.random.default_rng(1)
    A = np.tile(np.eye(6)[:, :, None] * 4e6, (1, 1, 8))
    B = np.tile(np.eye(6)[:, :, None] * 2e5, (1, 1, 8))
    F = (rng.normal(size=(6, 8)) + 1j * rng.normal(size=(6, 8))) * 2e5
    bem = stage_bem((A, B, F), wave)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                axis_names=("designs", "freq"))
    thetas = jnp.asarray([0.95, 1.05])
    out = forward_response_dp_sp(members, rna, env, wave, C_moor, thetas,
                                 mesh=mesh, bem=bem)
    ref = jax.vmap(
        lambda s: forward_response(scale_diameters(members, s), rna, env,
                                   wave, C_moor, bem=bem, n_iter=40,
                                   method="while")
    )(thetas)
    np.testing.assert_allclose(np.asarray(out.Xi.re), np.asarray(ref.Xi.re),
                               rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(np.asarray(out.Xi.im), np.asarray(ref.Xi.im),
                               rtol=1e-9, atol=1e-12)


def test_sweep_sharded_matches_single():
    members, rna, env, wave, C_moor = setup()
    assert len(jax.devices()) == 8
    mesh = make_mesh()
    thetas = jnp.linspace(0.92, 1.08, 16)
    out = sweep(members, rna, env, wave, C_moor, thetas, mesh=mesh)
    assert out["std dev"].shape == (16, 6)
    # spot-check lane 5 against an unsharded single evaluation
    from raft_tpu.parallel import scale_diameters

    m5 = scale_diameters(members, thetas[5])
    single = forward_response(m5, rna, env, wave, C_moor)
    sigma5 = response_std(single.Xi.abs2(), wave.w)
    np.testing.assert_allclose(out["std dev"][5], np.asarray(sigma5), rtol=2e-5)


@pytest.mark.slow
def test_sweep_monotone_in_scale():
    # bigger platform -> different response; just check variation is real
    members, rna, env, wave, C_moor = setup()
    thetas = jnp.array([0.9, 1.0, 1.1])
    out = sweep(members, rna, env, wave, C_moor, thetas)
    surge = out["std dev"][:, 0]
    assert len(set(np.round(surge, 6))) == 3


@pytest.mark.slow
def test_grad_response_matches_fd():
    members, rna, env, wave, C_moor = setup()
    g = grad_response_std(members, rna, env, wave, C_moor, jnp.asarray(1.0))
    h = 1e-4

    def f(th):
        from raft_tpu.parallel import scale_diameters

        m = scale_diameters(members, jnp.asarray(th))
        out = forward_response(m, rna, env, wave, C_moor)
        return float(response_std(out.Xi.abs2(), wave.w)[0])

    fd = (f(1.0 + h) - f(1.0 - h)) / (2 * h)
    np.testing.assert_allclose(float(g), fd, rtol=1e-3)


@pytest.mark.slow
def test_freq_sharded_matches_unsharded():
    """Sequence parallelism over the frequency axis: shard_map over an
    8-device mesh with the drag-linearization spectral moment completed by
    psum and convergence by pmax must reproduce the unsharded fixed point
    (same iterations, same Xi)."""
    from raft_tpu.parallel import forward_response_freq_sharded

    members, rna, env, wave, C_moor = setup(nw=40)
    mesh = make_mesh(axis="freq")
    out_s = forward_response_freq_sharded(
        members, rna, env, wave, C_moor, mesh=mesh, method="while"
    )
    out_u = forward_response(members, rna, env, wave, C_moor,
                             n_iter=40, method="while")
    assert bool(out_s.converged) and bool(out_u.converged)
    assert int(out_s.n_iter) == int(out_u.n_iter)
    np.testing.assert_allclose(np.asarray(out_s.Xi.re), np.asarray(out_u.Xi.re),
                               rtol=1e-10, atol=1e-14)
    np.testing.assert_allclose(np.asarray(out_s.Xi.im), np.asarray(out_u.Xi.im),
                               rtol=1e-10, atol=1e-14)
    np.testing.assert_allclose(np.asarray(out_s.B_drag), np.asarray(out_u.B_drag),
                               rtol=1e-10)
