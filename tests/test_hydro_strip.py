"""Strip-theory hydrodynamics tests.

Oracle: a straight NumPy per-node loop implementing the Morison recipe
(reference FOWT.calcHydroConstants raft/raft.py:2076-2157 and
calcLinearizedTerms raft/raft.py:2160-2264, with the documented Cd-vs-Ca
fix), compared against the vectorized jax implementation; plus closed-form
added-mass checks on a vertical cylinder.
"""
import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.build.members import build_member_set
from raft_tpu.core.cplx import Cx
from raft_tpu.core.types import Env, WaveState
from raft_tpu.core.waves import jonswap, wave_number
from raft_tpu.hydro import (
    linearized_drag,
    node_kinematics,
    strip_added_mass,
    strip_excitation,
)

RHO = 1025.0
G = 9.81


def cylinder_design(d=10.0, z0=-80.0, z1=20.0, Cd=0.8, Ca=1.0, CdEnd=0.6, CaEnd=0.6):
    return {
        "platform": {
            "members": [
                {
                    "name": "cyl",
                    "type": 2,
                    "rA": [0, 0, z0],
                    "rB": [0, 0, z1],
                    "shape": "circ",
                    "stations": [z0, z1],
                    "d": d,
                    "t": 0.05,
                    "Cd": Cd,
                    "Ca": Ca,
                    "CdEnd": CdEnd,
                    "CaEnd": CaEnd,
                }
            ]
        },
    }


def make_wave(nw=20, depth=200.0, Hs=6.0, Tp=10.0):
    w = jnp.linspace(0.1, 2.0, nw)
    k = wave_number(w, depth)
    S = jonswap(w, Hs, Tp)
    return WaveState(w=w, k=k, zeta=jnp.sqrt(S)), Env(Hs=Hs, Tp=Tp, depth=depth)


# ---------------------------------------------------------------- oracle


def wave_kin_np(zeta0, w, k, depth, r, beta=0.0):
    """Independent NumPy linear wave kinematics (deep/finite depth, no guard)."""
    nw = len(w)
    u = np.zeros((3, nw), complex)
    ud = np.zeros((3, nw), complex)
    pDyn = np.zeros(nw, complex)
    x, y, z = r
    if z >= 0:
        return u, ud, pDyn
    cb, sb = np.cos(beta), np.sin(beta)
    for i in range(nw):
        ph = np.exp(-1j * k[i] * (cb * x + sb * y))
        zi = zeta0[i] * ph
        s = np.sinh(k[i] * (z + depth)) / np.sinh(k[i] * depth)
        c = np.cosh(k[i] * (z + depth)) / np.sinh(k[i] * depth)
        cc = np.cosh(k[i] * (z + depth)) / np.cosh(k[i] * depth)
        u[0, i] = zi * w[i] * c * cb
        u[1, i] = zi * w[i] * c * sb
        u[2, i] = 1j * zi * w[i] * s
        ud[:, i] = 1j * w[i] * u[:, i]
        pDyn[i] = zi * RHO * G * cc
    return u, ud, pDyn


def _node_arrays(ms):
    g = lambda a: np.asarray(a)
    return {
        "r": g(ms.node_r), "q": g(ms.node_q), "p1": g(ms.node_p1), "p2": g(ms.node_p2),
        "ds": g(ms.node_ds), "drs": g(ms.node_drs), "dls": g(ms.node_dls),
        "Ca_q": g(ms.node_Ca_q), "Ca_p1": g(ms.node_Ca_p1), "Ca_p2": g(ms.node_Ca_p2),
        "Ca_end": g(ms.node_Ca_end),
        "Cd_q": g(ms.node_Cd_q), "Cd_p1": g(ms.node_Cd_p1), "Cd_p2": g(ms.node_Cd_p2),
        "Cd_end": g(ms.node_Cd_end),
        "circ": g(ms.node_circ), "mask": g(ms.node_mask),
    }


def translate_mat(r, M):
    H = np.array([[0, -r[2], r[1]], [r[2], 0, -r[0]], [-r[1], r[0], 0]], float).T
    out = np.zeros((6, 6))
    out[:3, :3] = M
    out[:3, 3:] = M @ H
    out[3:, :3] = H.T @ M
    out[3:, 3:] = H @ M @ H.T
    return out


def translate_force(r, f):
    return np.concatenate([f, np.cross(r, f)])


def oracle(ms, wave, env, Xi=None):
    nd = _node_arrays(ms)
    w = np.asarray(wave.w)
    k = np.asarray(wave.k)
    zeta = np.asarray(wave.zeta)
    nw = len(w)
    A = np.zeros((6, 6))
    F = np.zeros((nw, 6), complex)
    B = np.zeros((6, 6))
    Fd = np.zeros((nw, 6), complex)
    Xi_np = None if Xi is None else np.asarray(Xi.to_complex())
    for n in range(len(nd["dls"])):
        if not nd["mask"][n] or nd["r"][n, 2] >= 0:
            continue
        r = nd["r"][n]
        q, p1, p2 = nd["q"][n], nd["p1"][n], nd["p2"][n]
        qq, p11, p22 = np.outer(q, q), np.outer(p1, p1), np.outer(p2, p2)
        circ = nd["circ"][n]
        ds, drs, dls = nd["ds"][n], nd["drs"][n], nd["dls"][n]
        u, ud, pd = wave_kin_np(zeta, w, k, float(env.depth), r)
        v_i = 0.25 * np.pi * ds[0] ** 2 * dls if circ else ds[0] * ds[1] * dls
        Amat = RHO * v_i * (nd["Ca_q"][n] * qq + nd["Ca_p1"][n] * p11 + nd["Ca_p2"][n] * p22)
        A += translate_mat(r, Amat)
        # side axial term carries only the added-mass correction Ca_q: the
        # axial FK force comes from the end/taper pressure terms (the
        # reference's extra volume-form (1+Ca_q) double counts it, see
        # DEVIATIONS.md)
        Imat = RHO * v_i * (
            nd["Ca_q"][n] * qq + (1 + nd["Ca_p1"][n]) * p11 + (1 + nd["Ca_p2"][n]) * p22
        )
        for i in range(nw):
            F[i] += translate_force(r, Imat @ ud[:, i])
        # end effects
        if circ:
            v_e = np.pi / 6 * ((ds[0] + drs[0]) ** 3 - (ds[0] - drs[0]) ** 3)
            a_e = np.pi * ds[0] * drs[0]
        else:
            dm, drm = np.mean(ds), np.mean(drs)
            v_e = np.pi / 6 * ((dm + drm) ** 3 - (dm - drm) ** 3)
            a_e = (ds[0] + drs[0]) * (ds[1] + drs[1]) - (ds[0] - drs[0]) * (ds[1] - drs[1])
        A += translate_mat(r, RHO * v_e * nd["Ca_end"][n] * qq)
        Ie = RHO * v_e * (1 + nd["Ca_end"][n]) * qq
        for i in range(nw):
            fe = Ie @ ud[:, i] + pd[i] * a_e * q    # pd is a true pressure (incl. rho)
            F[i] += translate_force(r, fe)
        # drag linearization
        if Xi_np is not None:
            vnode = np.zeros((3, nw), complex)
            for i in range(nw):
                dr = Xi_np[i, :3] + np.cross(Xi_np[i, 3:], r)
                vnode[:, i] = 1j * w[i] * dr
            vrel = u - vnode
            vq = np.sqrt(np.sum(np.abs(vrel * q[:, None]) ** 2))
            vp1 = np.sqrt(np.sum(np.abs(vrel * p1[:, None]) ** 2))
            vp2 = np.sqrt(np.sum(np.abs(vrel * p2[:, None]) ** 2))
            a_q = np.pi * ds[0] * dls if circ else 2 * (ds[0] + ds[1]) * dls
            a_p1 = ds[0] * dls
            a_p2 = ds[0] * dls if circ else ds[1] * dls
            c = np.sqrt(8 / np.pi) * 0.5 * RHO
            Bq = c * vq * a_q * nd["Cd_q"][n]
            Bp1 = c * vp1 * a_p1 * nd["Cd_p1"][n]
            Bp2 = c * vp2 * a_p2 * nd["Cd_p2"][n]
            Bend = c * vq * abs(a_e) * nd["Cd_end"][n]
            Bmat = (Bq + Bend) * qq + Bp1 * p11 + Bp2 * p22
            B += translate_mat(r, Bmat)
            for i in range(nw):
                Fd[i] += translate_force(r, Bmat @ u[:, i])
    return A, F, B, Fd


# ---------------------------------------------------------------- tests


class TestVerticalCylinderClosedForm:
    def setup_method(self):
        self.d = 10.0
        self.ms = build_member_set(cylinder_design(self.d))
        self.wave, self.env = make_wave()
        self.A = np.asarray(jax.jit(strip_added_mass)(self.ms, self.env))

    def test_transverse_added_mass(self):
        # 8 fully-submerged 10 m strips (centers -75..-5)
        A_exp = RHO * 1.0 * np.pi / 4 * self.d**2 * 80.0
        np.testing.assert_allclose(self.A[0, 0], A_exp, rtol=1e-9)
        np.testing.assert_allclose(self.A[1, 1], A_exp, rtol=1e-9)

    def test_axial_added_mass_is_end_term(self):
        # only the bottom end disk contributes axially (Ca_q = 0 default)
        v_end = np.pi / 6 * self.d**3
        np.testing.assert_allclose(self.A[2, 2], RHO * 0.6 * v_end, rtol=1e-9)

    def test_symmetry(self):
        np.testing.assert_allclose(self.A, self.A.T, atol=1e-6)


class TestAgainstOracle:
    def setup_method(self):
        # inclined rectangular + circular members to exercise every branch
        design = {
            "platform": {
                "members": [
                    {
                        "name": "pontoon",
                        "type": 2,
                        "rA": [5, -20, -15],
                        "rB": [5, 20, -15],
                        "shape": "rect",
                        "stations": [0, 1],
                        "d": [[4.0, 6.0], [4.0, 6.0]],
                        "t": 0.05,
                        "Cd": [0.9, 1.1],
                        "Ca": [0.8, 1.0],
                        "CdEnd": 0.7,
                        "CaEnd": 0.5,
                        "gamma": 15.0,
                    },
                    {
                        "name": "column",
                        "type": 2,
                        "rA": [-10, 0, -25],
                        "rB": [-6, 2, 12],
                        "shape": "circ",
                        "stations": [0, 0.4, 1],
                        "d": [12.0, 8.0, 8.0],
                        "t": 0.06,
                        "Cd": 0.8,
                        "Ca": 1.0,
                        "CdEnd": 0.6,
                        "CaEnd": 0.6,
                    },
                ]
            },
        }
        self.ms = build_member_set(design)
        self.wave, self.env = make_wave(nw=12)
        self.kin = node_kinematics(self.ms, self.wave, self.env)
        rng = np.random.default_rng(0)
        xi = 0.5 * (rng.standard_normal((12, 6)) + 1j * rng.standard_normal((12, 6)))
        self.Xi = Cx(jnp.asarray(xi.real), jnp.asarray(xi.imag))
        self.A_o, self.F_o, self.B_o, self.Fd_o = oracle(self.ms, self.wave, self.env, self.Xi)

    def test_added_mass(self):
        A = np.asarray(strip_added_mass(self.ms, self.env))
        np.testing.assert_allclose(A, self.A_o, rtol=1e-9, atol=1e-6)

    def test_excitation(self):
        F = strip_excitation(self.ms, self.kin, self.env)
        np.testing.assert_allclose(np.asarray(F.to_complex()), self.F_o, rtol=1e-9, atol=1e-6)

    def test_drag_linearization(self):
        B, Fd = linearized_drag(self.ms, self.kin, self.Xi, self.wave, self.env)
        np.testing.assert_allclose(np.asarray(B), self.B_o, rtol=1e-9, atol=1e-6)
        np.testing.assert_allclose(np.asarray(Fd.to_complex()), self.Fd_o, rtol=1e-9, atol=1e-6)

    def test_drag_damping_psd(self):
        B, _ = linearized_drag(self.ms, self.kin, self.Xi, self.wave, self.env)
        lam = np.linalg.eigvalsh(np.asarray(B))
        assert (lam > -1e-6).all()

    def test_jit_vmap_consistency(self):
        # a batch of 3 identical member sets must equal 3x the single call
        ms3 = jax.tree.map(lambda a: jnp.stack([a, a, a]), self.ms)
        A3 = jax.vmap(lambda m: strip_added_mass(m, self.env))(ms3)
        A1 = strip_added_mass(self.ms, self.env)
        np.testing.assert_allclose(np.asarray(A3), np.asarray(A1)[None].repeat(3, 0), rtol=1e-12)

    def test_grad_wrt_diameter(self):
        # d A[0,0] / d(node_ds) via autodiff matches finite differences
        def f(ds):
            return strip_added_mass(self.ms.replace(node_ds=ds), self.env)[0, 0]

        g = jax.grad(f)(self.ms.node_ds)
        eps = 1e-4
        i = int(np.argmax(np.asarray(self.ms.node_dls)))
        ds0 = np.asarray(self.ms.node_ds).copy()
        dsp = ds0.copy()
        dsp[i, 0] += eps
        dsm = ds0.copy()
        dsm[i, 0] -= eps
        fd = (f(jnp.asarray(dsp)) - f(jnp.asarray(dsm))) / (2 * eps)
        np.testing.assert_allclose(np.asarray(g)[i, 0], fd, rtol=1e-5)


def test_rect_potmod_members_stay_on_morison_path():
    """The mesher routes rectangular members to the Morison path regardless
    of potMod (only circular members are paneled), so the strip gate must
    NOT exclude them — otherwise they vanish from both providers (the
    VolturnUS-S pontoon bug: ~25e6 kg of heave added mass lost)."""
    import numpy as np

    from raft_tpu.build.members import build_member_set
    from raft_tpu.core.types import Env
    from raft_tpu.hydro import strip_added_mass

    design = {
        "platform": {
            "members": [
                {   # circular potMod column: gated out when BEM is staged
                    "name": "col", "type": 2, "rA": [0, 0, -20], "rB": [0, 0, 10],
                    "shape": "circ", "gamma": 0.0, "potMod": True,
                    "stations": [0, 30], "d": 10.0, "t": 0.05,
                    "Cd": 0.8, "Ca": 1.0, "CdEnd": 0.6, "CaEnd": 0.6,
                    "rho_shell": 7850.0,
                },
                {   # rectangular potMod pontoon: must STAY on Morison
                    "name": "pont", "type": 2, "rA": [5, 0, -17], "rB": [40, 0, -17],
                    "shape": "rect", "gamma": 0.0, "potMod": True,
                    "stations": [0, 35], "d": [[12.0, 7.0], [12.0, 7.0]], "t": 0.05,
                    "Cd": [0.8, 0.8], "Ca": [1.0, 1.0], "CdEnd": 0.6, "CaEnd": 0.6,
                    "rho_shell": 7850.0,
                },
            ]
        }
    }
    m = build_member_set(design)
    env = Env(depth=200.0)
    A_all = np.asarray(strip_added_mass(m, env))
    A_gated = np.asarray(strip_added_mass(m, env, exclude_potmod=True))
    # the circular column's transverse added mass is gated off...
    assert A_gated[0, 0] < 0.7 * A_all[0, 0]
    # ...but the rect pontoon's heave added mass survives the gate
    assert A_gated[2, 2] > 0.5 * A_all[2, 2]
    assert A_gated[2, 2] > 1e6
