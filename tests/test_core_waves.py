import jax.numpy as jnp
import numpy as np

from raft_tpu.core import waves


def test_dispersion_relation_satisfied():
    w = jnp.linspace(0.05, 3.0, 60)
    for h in (20.0, 200.0, 320.0, 4000.0):
        k = np.asarray(waves.wave_number(w, h))
        resid = np.asarray(w) ** 2 - 9.81 * k * np.tanh(k * h)
        np.testing.assert_allclose(resid, 0.0, atol=1e-8)


def test_dispersion_limits():
    # deep water: k -> w^2/g ; shallow water: k -> w / sqrt(g h)
    w = 2.5
    k_deep = float(waves.wave_number(jnp.asarray(w), 5000.0))
    np.testing.assert_allclose(k_deep, w**2 / 9.81, rtol=1e-6)
    w = 0.05
    h = 10.0
    k_shal = float(waves.wave_number(jnp.asarray(w), h))
    np.testing.assert_allclose(k_shal, w / np.sqrt(9.81 * h), rtol=1e-3)


def test_jonswap_pierson_moskowitz_moment():
    # m0 = integral S dw must equal Hs^2/16 for PM (gamma=1)
    Hs, Tp = 8.0, 12.0
    w = jnp.linspace(0.01, 6.0, 20000)
    S = np.asarray(waves.jonswap(w, Hs, Tp, 1.0))
    m0 = np.trapezoid(S, np.asarray(w))
    np.testing.assert_allclose(m0, Hs**2 / 16, rtol=2e-3)


def test_jonswap_peak_location():
    Hs, Tp = 6.0, 10.0
    w = np.linspace(0.1, 3.0, 5000)
    S = np.asarray(waves.jonswap(jnp.asarray(w), Hs, Tp, 3.3))
    wp = w[np.argmax(S)]
    np.testing.assert_allclose(wp, 2 * np.pi / Tp, rtol=2e-2)


def test_wave_kinematics_deepwater_oracle():
    # Deep water: |u| = w * zeta * e^{kz}, ud = i w u, pDyn = rho g zeta e^{kz}
    h = 5000.0
    w = jnp.asarray([0.8])
    k = waves.wave_number(w, h)
    zeta0 = jnp.asarray([2.0])
    r = jnp.asarray([0.0, 0.0, -10.0])
    u, ud, p = waves.wave_kinematics(zeta0, w, k, h, r)
    u, ud, p = u.to_complex(), ud.to_complex(), p.to_complex()
    decay = np.exp(float(k[0]) * -10.0)
    np.testing.assert_allclose(abs(complex(u[0, 0])), 0.8 * 2.0 * decay, rtol=1e-6)
    np.testing.assert_allclose(abs(complex(u[2, 0])), 0.8 * 2.0 * decay, rtol=1e-6)
    np.testing.assert_allclose(complex(ud[0, 0]), 1j * 0.8 * complex(u[0, 0]), rtol=1e-12)
    np.testing.assert_allclose(abs(complex(p[0])), 1025.0 * 9.81 * 2.0 * decay, rtol=1e-6)


def test_wave_kinematics_surface_node_dry():
    h = 200.0
    w = jnp.asarray([0.5, 1.0])
    k = waves.wave_number(w, h)
    zeta0 = jnp.asarray([1.0, 1.0])
    r_dry = jnp.asarray([0.0, 0.0, 5.0])
    u, ud, p = waves.wave_kinematics(zeta0, w, k, h, r_dry)
    assert np.all(np.asarray(u.abs()) == 0) and np.all(np.asarray(p.abs()) == 0)


def test_wave_kinematics_phase_shift_with_x():
    h = 300.0
    w = jnp.asarray([1.2])
    k = waves.wave_number(w, h)
    zeta0 = jnp.asarray([1.0])
    u0 = waves.wave_kinematics(zeta0, w, k, h, jnp.asarray([0.0, 0.0, -5.0]))[0].to_complex()
    u1 = waves.wave_kinematics(zeta0, w, k, h, jnp.asarray([30.0, 0.0, -5.0]))[0].to_complex()
    expected_phase = np.exp(-1j * float(k[0]) * 30.0)
    np.testing.assert_allclose(
        complex(u1[0, 0]) / complex(u0[0, 0]), expected_phase, rtol=1e-9
    )


def test_wave_kinematics_batched_nodes():
    h = 100.0
    w = jnp.linspace(0.1, 2.0, 10)
    k = waves.wave_number(w, h)
    zeta0 = jnp.ones(10)
    r = jnp.asarray(np.random.default_rng(0).normal(size=(4, 7, 3)) * [5, 5, -10])
    u, ud, p = waves.wave_kinematics(zeta0, w, k, h, r)
    assert u.shape == (4, 7, 3, 10) and p.shape == (4, 7, 10)


def test_incompressibility_deep_water():
    # In deep water, du_x/dx + du_z/dz = 0 for the linear potential solution.
    h = 3000.0
    w = jnp.asarray([1.0])
    k = waves.wave_number(w, h)
    zeta0 = jnp.asarray([1.0])
    eps = 1e-3
    f = lambda x, z: waves.wave_kinematics(zeta0, w, k, h, jnp.asarray([x, 0.0, z]))[0].to_complex()
    dux_dx = (complex(f(eps, -5.0)[0, 0]) - complex(f(-eps, -5.0)[0, 0])) / (2 * eps)
    duz_dz = (complex(f(0.0, -5.0 + eps)[2, 0]) - complex(f(0.0, -5.0 - eps)[2, 0])) / (2 * eps)
    np.testing.assert_allclose(dux_dx + duz_dz, 0.0, atol=1e-6)
