"""Independent finite-depth oracles for the native BEM solver tests.

Two oracles, both fully independent of the C++ implementation:

* ``green_series`` — John's eigenfunction expansion of the finite-depth
  free-surface Green function (Wehausen & Laitone eq. 13.19 family;
  propagating mode + evanescent K0 sum).  The native solver uses a
  completely different evaluation (four-image decomposition + deep-water
  PV table + exponential-sum remainder fit), so agreement validates both.

* ``cylinder_heave`` — semi-analytic heave added mass/damping of a
  floating truncated cylinder in finite depth by matched eigenfunction
  expansions (the method of Yeung 1981, "Added mass and damping of a
  vertical cylinder in finite-depth waters").  Interior region under the
  cylinder uses a cosine/Bessel-I series about a heave particular
  solution; the exterior uses the propagating H0^(2) mode plus K0
  evanescent modes; matching pressure and radial velocity at r=a gives a
  small linear system.  This is the in-repo replacement for the external
  finite-depth references the repository cannot fetch.
"""
import numpy as np
import mpmath as mp


def dispersion_roots(nu, h, M):
    """k0 (k tanh kh = nu) and the first M-1 evanescent roots
    (km tan km h = -nu, km in ((m-1/2)pi/h, m pi/h))."""
    k = np.sqrt(nu / h) if nu * h < 1 else nu
    for _ in range(200):
        t = np.tanh(k * h)
        f = k * t - nu
        df = t + k * h / np.cosh(k * h) ** 2
        k -= f / df
        if abs(f) < 1e-16:
            break
    k0 = k
    km = []
    for m in range(1, M):
        lo = (m - 0.5) * np.pi / h * (1 + 1e-14)
        hi = m * np.pi / h * (1 - 1e-14)
        f = lambda x: x * np.sin(x * h) + nu * np.cos(x * h)
        for _ in range(200):
            mid = 0.5 * (lo + hi)
            if f(lo) * f(mid) <= 0:
                hi = mid
            else:
                lo = mid
        km.append(0.5 * (lo + hi))
    return k0, np.array(km)


def green_series(nu, h, R, z, zeta, nterms=400):
    """Full finite-depth Green function (1/r singularities included) by
    John's eigenfunction series; complex, e^{i w t} convention."""
    nu, h, R, z, zeta = map(mp.mpf, (nu, h, R, z, zeta))
    k0f, _ = dispersion_roots(float(nu), float(h), 1)
    k0 = mp.mpf(k0f)
    C0 = (k0**2 - nu**2) / (h * (k0**2 - nu**2) + nu)
    G = -2 * mp.pi * C0 * mp.cosh(k0 * (z + h)) * mp.cosh(k0 * (zeta + h)) * (
        mp.bessely(0, k0 * R) + mp.mpc(0, 1) * mp.besselj(0, k0 * R)
    )
    for m in range(1, nterms + 1):
        lo = (m - mp.mpf(1) / 2) * mp.pi / h * (1 + mp.mpf(10) ** -15)
        hi = m * mp.pi / h * (1 - mp.mpf(10) ** -15)
        f = lambda k: k * mp.sin(k * h) + nu * mp.cos(k * h)
        for _ in range(80):
            mid = (lo + hi) / 2
            if f(lo) * f(mid) <= 0:
                hi = mid
            else:
                lo = mid
        km = (lo + hi) / 2
        Cm = (km**2 + nu**2) / (h * (km**2 + nu**2) - nu)
        term = 4 * Cm * mp.cos(km * (z + h)) * mp.cos(km * (zeta + h)) * mp.besselk(0, km * R)
        G += term
        if abs(term) < mp.mpf(10) ** -18 and m > 5:
            break
    return complex(G)


def cylinder_heave(a, d, h, omega, g=9.81, rho=1000.0, N=50, M=50):
    """(A33, B33) for a floating truncated cylinder: radius a, draft d,
    water depth h, frequency omega.  Matched eigenfunction expansion with
    N interior / M exterior modes."""
    b = h - d
    nu = omega**2 / g
    k0, km = dispersion_roots(nu, h, M)

    N0 = (2 * k0 * h + np.sinh(2 * k0 * h)) / (4 * k0)
    Nm = (2 * km * h + np.sin(2 * km * h)) / (4 * km)
    lam = np.array([n * np.pi / b for n in range(N)])

    # C_mn = int_0^b cos(lam_n t) zeta_m(t) dt / sqrt(N_m), t = z + h
    C = np.zeros((M, N))
    for n in range(N):
        ln = lam[n]
        C[0, n] = ((-1) ** n) * k0 * np.sinh(k0 * b) / (ln**2 + k0**2) / np.sqrt(N0)
        C[1:, n] = ((-1) ** n) * (-km * np.sin(km * b)) / (ln**2 - km**2) / np.sqrt(Nm)

    Rp = np.zeros(M, dtype=complex)     # radial log-derivatives R'_m(a)
    Rp[0] = -k0 * complex(mp.hankel2(1, k0 * a)) / complex(mp.hankel2(0, k0 * a))
    for m in range(1, M):
        Rp[m] = -km[m - 1] * float(
            mp.besselk(1, km[m - 1] * a) / mp.besselk(0, km[m - 1] * a)
        )

    gl = np.zeros(N)                    # interior radial derivative factors
    for l in range(1, N):
        gl[l] = lam[l] * float(mp.besseli(1, lam[l] * a) / mp.besseli(0, lam[l] * a))

    P = np.zeros(N)                     # projections of the particular solution
    P[0] = b**2 / 6 - a**2 / 4
    for n in range(1, N):
        P[n] = (-1) ** n / lam[n] ** 2

    eps = np.full(N, b / 2)
    eps[0] = b

    K = np.einsum("mn,ml,m->nl", C, C, 1.0 / Rp)
    Asys = np.diag(eps.astype(complex)) - K * gl[None, :]
    rhs = -P + (-a / (2 * b)) * K[:, 0]
    An = np.linalg.solve(Asys, rhs.astype(complex))

    # bottom-disk potential integral (n3 = -1 applied at the end)
    I_p = 2 * np.pi * (b**2 * a**2 / 2 - a**4 / 8) / (2 * b)
    I_h = An[0] * np.pi * a**2
    for n in range(1, N):
        i1 = float(mp.besseli(1, lam[n] * a))
        i0 = float(mp.besseli(0, lam[n] * a))
        I_h += An[n] * ((-1) ** n) * 2 * np.pi * (a * i1 / lam[n]) / i0
    J = -(I_p + I_h)
    return -rho * np.real(J), omega * rho * np.imag(J)


def truncated_cylinder_mesh(a=5.0, d=4.0, naz=36, nz=8, nr=6):
    """Panel mesh (side + bottom disk) for the Yeung-oracle comparisons."""
    pans = []
    zs = np.linspace(0, -d, nz + 1)
    th = np.linspace(0, 2 * np.pi, naz + 1)
    for i in range(nz):
        for j in range(naz):
            p = lambda z, t: [a * np.cos(t), a * np.sin(t), z]
            pans.append([p(zs[i], th[j]), p(zs[i + 1], th[j]),
                         p(zs[i + 1], th[j + 1]), p(zs[i], th[j + 1])])
    rs = np.linspace(a, 0, nr + 1)
    for i in range(nr):
        for j in range(naz):
            p = lambda r, t: [r * np.cos(t), r * np.sin(t), -d]
            pans.append([p(rs[i], th[j]), p(rs[i + 1], th[j]),
                         p(rs[i + 1], th[j + 1]), p(rs[i], th[j + 1])])
    return np.asarray(pans)
