"""Resilience subsystem tests: lane quarantine + escalation ladder,
chunk checkpoint/resume, fault injection, and the shared retry
discipline.

Covers this PR's robustness claims:

* degenerate sea-state inputs (Hs=0, Tp=0) through ``sweep_sea_states``
  produce a QUARANTINE verdict, never silent NaNs (the pre-resilience
  behavior: a NaN spectrum integrated to an innocent-looking 0.0);
* a lane that merely ran out of iterations is salvaged by the
  escalation ladder and reported, with the batch result patched in
  place;
* a truncated or bit-flipped checkpoint npz is detected by content
  hash, logged, recomputed — never crashes, never serves bad data;
* a killed-and-rerun chunked sweep resumes from the manifest and
  recomputes only the missing chunks, with identical results;
* ``retry_call``/``checked_subprocess`` are bounded, backoff- and
  deadline-aware, and redact credentials from committed diagnostics.
"""
import json
import os
import sys

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from raft_tpu.resilience import checkpoint, faults, health, ladder, retry


# ------------------------------------------------------------------ health


def test_strict_env_parsing(monkeypatch):
    monkeypatch.delenv("RAFT_TPU_STRICT", raising=False)
    assert health.strict() is True          # unset means strict: the default
    for on in ("1", "on", "true", "STRICT"):
        monkeypatch.setenv("RAFT_TPU_STRICT", on)
        assert health.strict() is True
    for off in ("0", "false", "OFF", "no"):
        monkeypatch.setenv("RAFT_TPU_STRICT", off)
        assert health.strict() is False


def test_failed_lanes_catches_host_nans_past_device_flags():
    """A lane whose fetched arrays went non-finite is quarantined even
    when the device-side flags say healthy (fetch-path corruption,
    injected faults)."""
    conv = np.array([True, True, True, True])
    vals = np.ones((4, 3))
    vals[2, 1] = np.nan
    bad = health.failed_lanes(conv, None, host_values=(vals,))
    assert list(bad) == [2]
    # device flags alone
    assert list(health.failed_lanes([True, False, True])) == [1]
    # finite flag composes
    assert list(health.failed_lanes([True, True], [False, True])) == [0]


def test_summarize_counts_rungs_and_unsalvaged():
    recs = [
        health.LaneHealth(3, True, True, 12, quarantined=True,
                          salvaged=True, rung="n_iter_x4"),
        health.LaneHealth(7, False, False, 48, quarantined=True),
    ]
    s = health.summarize(recs, 10, extra={"strict": False})
    assert s["lanes"] == 10
    assert s["n_quarantined"] == 2
    assert s["quarantined"] == [3, 7]
    assert s["salvaged"] == 1
    assert s["unsalvaged"] == [7]
    assert s["rungs_used"] == {"n_iter_x4": 1}
    assert s["strict"] is False
    json.dumps(s)                     # bench embeds it: must be JSON-clean


# ------------------------------------------------------------------ ladder


def test_rung_knobs_resolve():
    n, r, t = ladder.rung_knobs(ladder.RUNGS[0], 8)
    assert (n, r, t) == (32, ladder.DEFAULT_RELAX, 0.0)
    n, r, t = ladder.rung_knobs(ladder.RUNGS[3], 8)
    assert n == 48 and r == 0.5 and t == 1e-6
    # tiny budgets still escalate by at least one iteration
    assert ladder.rung_knobs(ladder.RUNGS[0], 0)[0] >= 1


def test_escalate_lanes_salvages_at_correct_rung():
    """A fake lane solver that only converges at relax=0.25: the ladder
    must walk past the first two rungs and report the third."""
    calls = []

    def solve_lane(idx, n_iter, relax, tik):
        calls.append((idx, n_iter, relax, tik))
        ok = relax == 0.25
        val = np.full(3, 1.0 if ok else np.nan)
        return (val,), ok, ok, n_iter

    records, salvaged = ladder.escalate_lanes([5], solve_lane, 8)
    assert len(records) == 1 and records[0].salvaged
    assert records[0].rung == "relax_0.25"
    assert 5 in salvaged
    assert [c[2] for c in calls] == [ladder.DEFAULT_RELAX, 0.5, 0.25]


def test_escalate_lanes_rejects_nan_payload_despite_flags():
    """A rung whose flags claim success but whose payload is NaN must
    NOT count as salvage (NaN in -> 'converged' NaN out)."""

    def solve_lane(idx, n_iter, relax, tik):
        return (np.full(2, np.nan),), True, True, n_iter

    records, salvaged = ladder.escalate_lanes([0], solve_lane, 4)
    assert not records[0].salvaged and salvaged == {}
    assert records[0].rung is None


def test_quarantine_and_salvage_patches_arrays_in_place():
    vals = np.array([[1.0, 1.0], [np.nan, np.nan], [3.0, 3.0]])
    iters = np.array([4, 4, 4])
    conv = np.array([True, False, True])

    def solve_lane(idx, n_iter, relax, tik):
        return (np.array([9.0, 9.0]), np.array(n_iter)), True, True, n_iter

    records, conv2, fin2 = ladder.quarantine_and_salvage(
        [vals, iters], conv, None, solve_lane, 4)
    assert [r.index for r in records] == [1]
    assert records[0].salvaged
    np.testing.assert_array_equal(vals[1], [9.0, 9.0])
    assert iters[1] == 16                       # the rung's budget, patched
    assert conv2.all() and fin2.all()
    # healthy batch: zero records, nothing touched
    recs, _, _ = ladder.quarantine_and_salvage(
        [np.ones((2, 2))], np.array([True, True]), None, solve_lane, 4)
    assert recs == []


# ------------------------------------------------------------------ faults


def test_fault_spec_parsing(monkeypatch):
    monkeypatch.delenv("RAFT_TPU_FAULT_INJECT", raising=False)
    assert faults.specs() == {} and not faults.active()
    monkeypatch.setenv("RAFT_TPU_FAULT_INJECT",
                       "nan_chunk:3,kill_after_chunk:5,hang_subprocess")
    assert faults.active()
    assert faults.specs() == {"nan_chunk": [3], "kill_after_chunk": [5],
                              "hang_subprocess": [None]}
    assert faults.chunk_fault("nan_chunk", 3)
    assert not faults.chunk_fault("nan_chunk", 2)
    monkeypatch.setenv("RAFT_TPU_FAULT_INJECT", "nan_chunk")
    assert faults.chunk_fault("nan_chunk", 17)  # argless targets every chunk
    monkeypatch.setenv("RAFT_TPU_FAULT_INJECT", "nan_chunk:xyz")
    with pytest.warns(UserWarning, match="non-integer"):
        assert faults.specs() == {}             # malformed: ignored, loud


def test_fault_consume_counted(monkeypatch):
    monkeypatch.setenv("RAFT_TPU_FAULT_INJECT", "hang_subprocess:2")
    faults.reset_counts()
    try:
        assert faults.consume("hang_subprocess")
        assert faults.consume("hang_subprocess")
        assert not faults.consume("hang_subprocess")   # budget spent
    finally:
        faults.reset_counts()


def test_nan_results_spares_flags_and_counts():
    res = (np.ones((2, 3)), np.array([7, 9]), np.array([True, True]))
    out = faults.nan_results(res)
    assert np.isnan(out[0]).all()
    np.testing.assert_array_equal(out[1], [7, 9])      # int: untouched
    np.testing.assert_array_equal(out[2], [True, True])
    assert np.isnan(faults.nan_results(np.zeros(4))).all()  # bare array


def test_maybe_corrupt_file_flips_one_byte(tmp_path, monkeypatch):
    p = tmp_path / "x.bin"
    p.write_bytes(b"\x00" * 64)
    monkeypatch.setenv("RAFT_TPU_FAULT_INJECT", "corrupt_ckpt:2")
    assert not faults.maybe_corrupt_file("corrupt_ckpt", 1, str(p))
    assert p.read_bytes() == b"\x00" * 64
    assert faults.maybe_corrupt_file("corrupt_ckpt", 2, str(p))
    data = p.read_bytes()
    assert len(data) == 64 and sum(b != 0 for b in data) == 1


# ------------------------------------------------------------------- retry


def test_retry_call_bounded_with_exponential_backoff():
    sleeps = []
    attempts = []

    def fn(attempt):
        attempts.append(attempt)
        raise ValueError(f"boom {attempt}")

    with pytest.raises(retry.RetryExhausted) as ei:
        retry.retry_call(fn, retries=3, backoff_s=1.0, growth=2.0,
                         sleep=sleeps.append, describe="unit")
    assert attempts == [0, 1, 2]
    assert sleeps == [1.0, 2.0]                 # exponential, capped count
    assert ei.value.attempts == 3
    assert isinstance(ei.value.last, ValueError)


def test_retry_call_succeeds_midway_and_notifies():
    seen = []

    def fn(attempt):
        if attempt < 1:
            raise OSError("transient")
        return "ok"

    out = retry.retry_call(fn, retries=3, sleep=lambda s: None,
                           on_retry=lambda a, e: seen.append((a, str(e))))
    assert out == "ok"
    assert seen == [(0, "transient")]


def test_retry_call_non_matching_exception_propagates_immediately():
    calls = []

    def fn(attempt):
        calls.append(attempt)
        raise KeyError("deterministic bug")

    with pytest.raises(KeyError):
        retry.retry_call(fn, retries=5, retry_on=(OSError,),
                         sleep=lambda s: None)
    assert calls == [0]                          # no backoff budget burned


def test_retry_call_deadline_skips_pointless_sleep():
    """When the next backoff would cross the deadline, the ladder stops
    early instead of sleeping into it."""
    sleeps = []

    with pytest.raises(retry.RetryExhausted) as ei:
        retry.retry_call(
            lambda a: (_ for _ in ()).throw(ValueError("x")),
            retries=10, backoff_s=100.0, deadline_s=1.0,
            sleep=sleeps.append)
    assert sleeps == []                          # never slept 100 s
    assert ei.value.attempts == 1


def test_checked_subprocess_ok_nonzero_and_timeout():
    r = retry.checked_subprocess(
        [sys.executable, "-c", "print('hi')"], timeout_s=60)
    assert r.stdout.strip() == "hi"

    with pytest.raises(retry.SubprocessFailed) as ei:
        retry.checked_subprocess(
            [sys.executable, "-c",
             "import sys; print('tok api_key=SECRET123', file=sys.stderr);"
             "sys.exit(3)"],
            timeout_s=60, describe="unit")
    assert ei.value.kind == "nonzero" and ei.value.returncode == 3
    assert "SECRET123" not in ei.value.stderr_tail
    assert "[redacted]" in ei.value.stderr_tail

    with pytest.raises(retry.SubprocessFailed) as ei:
        retry.checked_subprocess(
            [sys.executable, "-c", "import time; time.sleep(60)"],
            timeout_s=0.5, describe="unit")
    assert ei.value.kind == "timeout"

    with pytest.raises(retry.SubprocessFailed) as ei:
        retry.checked_subprocess(
            [sys.executable, "-c", "pass"], timeout_s=60,
            require_stdout=True)
    assert "empty stdout" in str(ei.value)


def test_hang_subprocess_fault_forces_timeout(monkeypatch):
    monkeypatch.setenv("RAFT_TPU_FAULT_INJECT", "hang_subprocess:1")
    faults.reset_counts()
    try:
        with pytest.raises(retry.SubprocessFailed) as ei:
            retry.checked_subprocess(
                [sys.executable, "-c", "print('fast')"], timeout_s=0.5)
        assert ei.value.kind == "timeout"
        # budget spent: the next launch runs the real command
        r = retry.checked_subprocess(
            [sys.executable, "-c", "print('fast')"], timeout_s=60)
        assert r.stdout.strip() == "fast"
    finally:
        faults.reset_counts()


def test_redacted_tail_masks_credentials():
    text = ("error: Authorization: Bearer abc.def.ghi failed\n"
            "api_key=sk-livekeyabcdef12345 token: topsecret\n"
            "plain diagnostic stays")
    out = retry.redacted_tail(text, n=500)
    for leak in ("abc.def.ghi", "livekey", "topsecret"):
        assert leak not in out
    assert "plain diagnostic stays" in out
    assert retry.redacted_tail(b"bytes ok") == "bytes ok"
    assert retry.redacted_tail("") == ""


def test_build_timeout_env(monkeypatch):
    monkeypatch.delenv("RAFT_TPU_BUILD_TIMEOUT", raising=False)
    assert retry.build_timeout_s() == 300.0
    monkeypatch.setenv("RAFT_TPU_BUILD_TIMEOUT", "42.5")
    assert retry.build_timeout_s() == 42.5
    monkeypatch.setenv("RAFT_TPU_BUILD_TIMEOUT", "soon")
    with pytest.warns(UserWarning, match="RAFT_TPU_BUILD_TIMEOUT"):
        assert retry.build_timeout_s() == 300.0


# -------------------------------------------------------------- checkpoint


def test_ckpt_root_env(monkeypatch, tmp_path):
    monkeypatch.delenv("RAFT_TPU_CKPT", raising=False)
    assert checkpoint.root() is None and not checkpoint.enabled()
    for off in ("off", "0", "none", "false"):
        monkeypatch.setenv("RAFT_TPU_CKPT", off)
        assert checkpoint.root() is None
    monkeypatch.setenv("RAFT_TPU_CKPT", str(tmp_path / "ck"))
    assert checkpoint.root() == str(tmp_path / "ck")
    assert checkpoint.store_for("t", (np.ones(2),), n_chunks=2) is not None
    monkeypatch.setenv("RAFT_TPU_CKPT", "off")
    assert checkpoint.store_for("t", (np.ones(2),), n_chunks=2) is None


def test_chunk_store_roundtrip(tmp_path):
    st = checkpoint.ChunkStore("k1", 3, str(tmp_path))
    tup = (np.arange(6.0).reshape(2, 3), np.array([4, 5]))
    st.save(0, tup)
    st.save(1, np.float64(2.5))                  # scalar result shape
    out = st.load(0)
    assert isinstance(out, tuple)
    np.testing.assert_array_equal(out[0], tup[0])
    np.testing.assert_array_equal(out[1], tup[1])
    assert not isinstance(st.load(1), tuple)
    assert float(st.load(1)) == 2.5
    assert st.load(2) is None and not st.complete()
    st.save(2, tup)
    assert st.complete()
    # a fresh store object over the same directory resumes everything
    st2 = checkpoint.ChunkStore("k1", 3, str(tmp_path))
    assert st2.complete()
    np.testing.assert_array_equal(st2.load(0)[0], tup[0])


def test_chunk_store_detects_truncation_and_bitflips(tmp_path):
    """Satellite: corrupt checkpoint artifacts are detected (content
    hash), logged, recomputed — never crash, never serve bad data."""
    st = checkpoint.ChunkStore("k2", 2, str(tmp_path))
    a = np.linspace(0.0, 1.0, 32).reshape(4, 8)
    st.save(0, (a,))
    st.save(1, (a + 1.0,))
    p0 = st._chunk_path(0)
    # truncation (kill mid-rewrite, disk-full): unreadable npz
    with open(p0, "r+b") as f:
        f.truncate(os.path.getsize(p0) // 2)
    with pytest.warns(UserWarning, match="unusable"):
        assert st.load(0) is None
    assert st.corrupt == 1
    assert not os.path.exists(p0)                # dropped, will recompute
    assert st.load(0) is None                    # manifest entry gone too
    # bit-flip (silent media corruption): npz may still parse — the
    # content hash is what catches it
    p1 = st._chunk_path(1)
    with open(p1, "r+b") as f:
        f.seek(os.path.getsize(p1) - 20)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0x01]))
    with pytest.warns(UserWarning, match="unusable"):
        assert st.load(1) is None
    assert st.corrupt == 2 and not st.complete()


def test_chunk_store_concurrent_writers_drop_nothing(tmp_path):
    """Satellite regression (manifest read-modify-write race): two writer
    threads checkpointing disjoint chunk sets into ONE store must not
    drop each other's manifest entries — the per-store lock makes the
    entry-update + atomic-replace one critical section.  Pre-fix this
    deterministically lost entries (and crashed with 'dictionary changed
    size during iteration') under a tiny GIL switch interval."""
    import threading

    n_chunks, writers = 32, 2
    st = checkpoint.ChunkStore("krace", n_chunks, str(tmp_path))

    def writer(t):
        for k in range(t, n_chunks, writers):
            st.save(k, (np.full(8, float(k)), np.array([k, k + 1])))

    old = sys.getswitchinterval()
    sys.setswitchinterval(1e-6)
    try:
        ts = [threading.Thread(target=writer, args=(t,))
              for t in range(writers)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    finally:
        sys.setswitchinterval(old)
    assert st.saved == n_chunks and st.complete()
    # a fresh store (new-process resume) sees EVERY chunk, hash-clean
    st2 = checkpoint.ChunkStore("krace", n_chunks, str(tmp_path))
    assert st2.complete()
    for k in range(n_chunks):
        out = st2.load(k)
        assert out is not None, f"chunk {k} lost by the manifest race"
        np.testing.assert_array_equal(out[0], np.full(8, float(k)))
    assert st2.corrupt == 0 and st2.resumed == n_chunks


def test_chunk_store_ignores_stale_manifest(tmp_path):
    """A store directory left by a different chunking (or a corrupted
    manifest) starts fresh instead of serving mismatched results."""
    st = checkpoint.ChunkStore("k3", 2, str(tmp_path))
    st.save(0, np.ones(3))
    st2 = checkpoint.ChunkStore("k3", 4, str(tmp_path))   # different n_chunks
    assert st2.load(0) is None
    with open(os.path.join(str(tmp_path), "k3", "manifest.json"), "w") as f:
        f.write("{not json")
    st3 = checkpoint.ChunkStore("k3", 2, str(tmp_path))
    assert st3.load(0) is None                   # unreadable manifest: fresh


def test_corrupt_ckpt_fault_is_caught_by_hash(tmp_path, monkeypatch):
    """The injected bit-rot (corrupt_ckpt:K) must be caught exactly like
    real corruption: detected on load, dropped, recomputed."""
    monkeypatch.setenv("RAFT_TPU_FAULT_INJECT", "corrupt_ckpt:0")
    st = checkpoint.ChunkStore("k4", 1, str(tmp_path))
    st.save(0, np.ones(8))
    monkeypatch.delenv("RAFT_TPU_FAULT_INJECT")
    with pytest.warns(UserWarning, match="unusable"):
        assert st.load(0) is None
    assert st.corrupt == 1


# ------------------------------------------------- pipeline + checkpoint


def _run_counting(ckpt, items=4):
    from raft_tpu.parallel import pipeline

    computed = []

    def fn(x):
        computed.append(float(x))
        return jax.jit(lambda v: v * 2.0)(x)

    results, stats = pipeline.run_pipelined(
        fn, [jnp.asarray(float(k)) for k in range(items)],
        depth=2, ckpt=ckpt)
    return [float(np.asarray(r)) for r in results], stats, computed


def test_pipeline_checkpoint_resume_recomputes_only_missing(tmp_path):
    st = checkpoint.ChunkStore("pk", 4, str(tmp_path))
    res1, stats1, computed1 = _run_counting(st)
    assert res1 == [0.0, 2.0, 4.0, 6.0]
    assert stats1.chunks_computed == 4 and stats1.chunks_checkpointed == 4
    assert len(computed1) == 4

    # drop chunk 2, as a kill between chunk 2's dispatch and save would
    os.unlink(st._chunk_path(2))
    st2 = checkpoint.ChunkStore("pk", 4, str(tmp_path))
    st2._manifest["chunks"].pop("2")
    res2, stats2, computed2 = _run_counting(st2)
    assert res2 == res1                          # identical final results
    assert computed2 == [2.0]                    # ONLY the missing chunk ran
    assert stats2.chunks_resumed == 3 and stats2.chunks_computed == 1

    # full store: nothing dispatches at all
    st3 = checkpoint.ChunkStore("pk", 4, str(tmp_path))
    res3, stats3, computed3 = _run_counting(st3)
    assert res3 == res1 and computed3 == []
    assert stats3.chunks_resumed == 4


def test_pipeline_corrupt_chunk_recomputed_in_stream(tmp_path):
    st = checkpoint.ChunkStore("pc", 3, str(tmp_path))
    res1, _, _ = _run_counting(st, items=3)
    p = st._chunk_path(1)
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)
    st2 = checkpoint.ChunkStore("pc", 3, str(tmp_path))
    with pytest.warns(UserWarning, match="unusable"):
        res2, stats2, computed2 = _run_counting(st2, items=3)
    assert res2 == res1
    assert computed2 == [1.0]                    # corrupt chunk recomputed
    assert stats2.ckpt_corrupt == 1 and stats2.chunks_resumed == 2


def test_pipeline_nan_chunk_injection(monkeypatch):
    from raft_tpu.parallel import pipeline

    monkeypatch.setenv("RAFT_TPU_FAULT_INJECT", "nan_chunk:1")
    results, stats = pipeline.run_pipelined(
        jax.jit(lambda x: x + 1.0),
        [jnp.asarray(float(k)) for k in range(3)], depth=2)
    assert stats.faults_injected == 1
    assert float(np.asarray(results[0])) == 1.0
    assert np.isnan(np.asarray(results[1])).all()
    assert float(np.asarray(results[2])) == 3.0


# -------------------------------------------- sweeps: the real solve paths


def _dlc_setup(nw=8):
    import __graft_entry__ as ge
    from raft_tpu.mooring import mooring_stiffness, parse_mooring

    design, members, rna, env, wave = ge._base(nw=nw)
    moor = parse_mooring(
        design["mooring"], yaw_stiffness=design["turbine"]["yaw_stiffness"])
    C_moor = mooring_stiffness(moor, jnp.zeros(6))
    return members, rna, env, wave, C_moor


def test_degenerate_sea_states_get_quarantine_verdict_not_silent_nans():
    """Satellite: Hs=0 and Tp=0 rows through sweep_sea_states.  Tp=0
    makes the JONSWAP spectrum NaN — before this PR that NaN integrated
    to an innocent 0.0 response std with no flag anywhere.  Now the lane
    carries an explicit quarantine verdict; the Hs=0 lane (a legitimate
    flat-calm case: zero response) stays healthy."""
    from raft_tpu.parallel import make_wave_states, sweep_sea_states

    members, rna, env, wave, C_moor = _dlc_setup()
    cases = [[6.0, 10.0], [0.0, 10.0], [6.0, 0.0]]
    waves = make_wave_states(np.asarray(wave.w), cases, float(env.depth))
    out = sweep_sea_states(members, rna, env, waves, C_moor,
                           health=True, escalate=False)
    h = out["health"]
    assert h["quarantined"] == [2] and h["unsalvaged"] == [2]
    assert not out["converged"][2] and not out["finite"][2]
    # healthy lanes untouched and verdicted
    assert out["converged"][0] and out["converged"][1]
    assert out["finite"][:2].all()
    assert np.isfinite(out["std dev"][:2]).all()
    # Hs=0 is a zero-response lane, not a failure
    np.testing.assert_allclose(out["std dev"][1], 0.0, atol=1e-30)
    # the bad lane's spectra stay NaN — REPORTED, never papered over
    assert np.isnan(out["Xi_abs2"][2]).all()


def test_ladder_salvages_iteration_starved_lanes():
    """Lanes that fail only because the batch iteration budget is too
    small must be rescued by the ladder's first rung (4x budget) and
    land on the converged batch answer."""
    from raft_tpu.parallel import make_wave_states, sweep_sea_states

    members, rna, env, wave, C_moor = _dlc_setup()
    cases = [[6.0, 10.0], [9.0, 13.0]]
    waves = make_wave_states(np.asarray(wave.w), cases, float(env.depth))
    ref = sweep_sea_states(members, rna, env, waves, C_moor, n_iter=25)
    out = sweep_sea_states(members, rna, env, waves, C_moor, n_iter=2,
                           health=True)
    h = out["health"]
    assert h["n_quarantined"] == 2               # n_iter=2 converges nothing
    assert h["salvaged"] == 2 and not h["unsalvaged"]
    assert set(h["rungs_used"]) == {"n_iter_x4"}
    assert out["converged"].all() and out["finite"].all()
    # salvaged lanes sit on the fixed point the full-budget batch finds
    np.testing.assert_allclose(out["std dev"], ref["std dev"],
                               rtol=1e-6, atol=1e-12)


def test_health_off_is_the_exact_legacy_result():
    """Resilience off (the default): same keys, same values — the fast
    path must be behavior-identical to the pre-resilience sweep."""
    from raft_tpu.parallel import make_wave_states, sweep_sea_states

    members, rna, env, wave, C_moor = _dlc_setup()
    waves = make_wave_states(np.asarray(wave.w), [[6.0, 10.0], [8.0, 12.0]],
                             float(env.depth))
    out = sweep_sea_states(members, rna, env, waves, C_moor)
    assert set(out) == {"std dev", "nacelle accel std dev", "iterations",
                        "Xi_abs2"}
    chunked = sweep_sea_states(members, rna, env, waves, C_moor, chunk=1)
    assert "health" not in chunked and "checkpoint" not in chunked
    np.testing.assert_allclose(chunked["std dev"], out["std dev"],
                               rtol=1e-12, atol=1e-14)


def test_chunked_sweep_checkpoint_resume_parity(tmp_path, monkeypatch):
    """The chunked DLC sweep with RAFT_TPU_CKPT armed: a second run over
    the same program resumes every chunk from the store and returns
    identical results (the in-process half of the kill/resume proof; the
    cross-process half is `make resilience-smoke`)."""
    from raft_tpu.parallel import make_wave_states, sweep_sea_states

    members, rna, env, wave, C_moor = _dlc_setup()
    waves = make_wave_states(np.asarray(wave.w), [[6.0, 10.0], [8.0, 12.0]],
                             float(env.depth))
    ref = sweep_sea_states(members, rna, env, waves, C_moor, chunk=1)

    monkeypatch.setenv("RAFT_TPU_CKPT", str(tmp_path))
    out1 = sweep_sea_states(members, rna, env, waves, C_moor, chunk=1)
    assert out1["checkpoint"]["saved"] == 2
    assert out1["pipeline"]["chunks_computed"] == 2
    out2 = sweep_sea_states(members, rna, env, waves, C_moor, chunk=1)
    assert out2["pipeline"]["chunks_resumed"] == 2
    assert out2["pipeline"]["chunks_computed"] == 0
    np.testing.assert_array_equal(out2["std dev"], out1["std dev"])
    np.testing.assert_array_equal(out2["Xi_abs2"], out1["Xi_abs2"])
    # and the store never changes WHAT is computed, only whether
    np.testing.assert_allclose(out1["std dev"], ref["std dev"],
                               rtol=1e-12, atol=1e-14)
    # a different program (n_iter knob) lands in a different store dir:
    # no cross-program result reuse
    out3 = sweep_sea_states(members, rna, env, waves, C_moor, chunk=1,
                            n_iter=10)
    assert out3["pipeline"]["chunks_resumed"] == 0
    # and so does a DIFFERENT DLC TABLE with identical shapes: stored
    # results depend on input VALUES, which the abstract AOT signature
    # alone would not distinguish
    waves_b = make_wave_states(np.asarray(wave.w), [[5.0, 9.0], [7.0, 11.0]],
                               float(env.depth))
    out4 = sweep_sea_states(members, rna, env, waves_b, C_moor, chunk=1)
    assert out4["pipeline"]["chunks_resumed"] == 0
    assert not np.allclose(out4["std dev"], out1["std dev"])


@pytest.mark.slow
def test_unsalvageable_lane_walks_full_ladder():
    """A NaN-input lane cannot be salvaged by any rung: the ladder is
    exhausted (all four rungs attempted), the lane reported unsalvaged —
    and the process never raises."""
    from raft_tpu.parallel import make_wave_states, sweep_sea_states

    members, rna, env, wave, C_moor = _dlc_setup()
    waves = make_wave_states(np.asarray(wave.w), [[6.0, 10.0], [6.0, 0.0]],
                             float(env.depth))
    out = sweep_sea_states(members, rna, env, waves, C_moor, health=True)
    h = out["health"]
    assert h["quarantined"] == [1] and h["unsalvaged"] == [1]
    assert h["rungs_used"] == {}                 # nothing claimed credit
    assert not out["converged"][1] and out["converged"][0]


@pytest.mark.slow
def test_sweep_design_batch_health_and_salvage():
    """The design-batch sweep() carries the same contract: per-lane
    verdicts, ladder salvage of iteration-starved lanes, and identical
    fast-path results with health off."""
    from raft_tpu.parallel import sweep

    members, rna, env, wave, C_moor = _dlc_setup()
    thetas = jnp.asarray([1.0, 1.05])

    ref = sweep(members, rna, env, wave, C_moor, thetas, n_iter=25)
    out = sweep(members, rna, env, wave, C_moor, thetas, n_iter=2,
                health=True)
    h = out["health"]
    assert h["salvaged"] == h["n_quarantined"] == 2
    assert out["converged"].all()
    np.testing.assert_allclose(out["std dev"], ref["std dev"],
                               rtol=1e-6, atol=1e-12)
