"""Pins on the two driver-artifact paths (the round deliverables).

Rounds 3 and 4 shipped a green local tree with red driver artifacts —
these tests pin the exact properties that failed there:

* the multi-chip dry run must print a heartbeat BEFORE jax imports (so a
  timeout always leaves a diagnosis), must never touch a hardware
  backend regardless of environment pins, and must finish green in a
  fresh subprocess (the driver's regime, not the pytest process);
* the evidence runner must read bench's one-line JSON from STDOUT so
  stderr spam can never hide a red bench behind an ok=true.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_dryrun_multichip_green_in_fresh_subprocess():
    r = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8)"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    lines = r.stdout.strip().splitlines()
    # heartbeat is the FIRST stdout line and precedes any jax/XLA output
    assert lines[0].startswith("[dryrun +"), lines[:3]
    assert "heartbeat printed before jax import" in lines[0]
    assert "backend=cpu forced" in r.stdout     # never probed the pin
    assert "dryrun_multichip ok: 8 cpu devices" in r.stdout


def test_evidence_parses_bench_json_from_stdout_only():
    from raft_tpu import evidence

    # a "bench" that floods stderr and puts its JSON on stdout: the JSON
    # must still be found, and a null value must downgrade ok
    code = ("import sys\n"
            "print('\\n'.join('noise %d' % i for i in range(40)), "
            "file=sys.stderr)\n"
            "print('{\"value\": 5, \"platform\": \"cpu\"}')\n")
    art = evidence._run([sys.executable, "-c", code], timeout=60, label="t")
    assert art["ok"] and art["rc"] == 0
    assert json.loads(art["stdout_tail"][-1])["value"] == 5

    code_null = code.replace('"value": 5', '"value": null')
    art2 = evidence._run([sys.executable, "-c", code_null], timeout=60,
                         label="t2")
    found = None
    for line in reversed(art2["stdout_tail"]):
        try:
            found = json.loads(line)
            break
        except json.JSONDecodeError:
            continue
    assert found is not None and found["value"] is None


def test_evidence_flags_missing_bench_json():
    from raft_tpu import evidence

    art = evidence._run([sys.executable, "-c", "print('no json here')"],
                        timeout=60, label="t3")
    parsed = [ln for ln in art["stdout_tail"]
              if ln.strip().startswith("{")]
    assert parsed == []


def test_spawn_full_bench_guards(tmp_path, monkeypatch):
    """The bench parent's child-spawn helper promotes only a genuine device
    number: a child that silently fell back to CPU (plugin registration
    failure after a good probe) or emitted its value-null diagnostic is a
    FAILURE, and a hung child is killed at the parent's wall-clock.  The
    child interpreter is faked so each case is deterministic."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    def fake_child(script: str) -> str:
        p = tmp_path / f"fake_{abs(hash(script)) % 10**8}.sh"
        p.write_text(f"#!/bin/sh\n{script}\n")
        p.chmod(0o755)
        return str(p)

    # 1. genuine device number -> promoted
    good = json.dumps({"value": 1.0e6, "platform": "tpu"})
    monkeypatch.setattr(bench.sys, "executable",
                        fake_child(f"echo '{good}'"))
    out, err = bench._spawn_full_bench({}, 30.0)
    assert err is None and out["platform"] == "tpu"

    # 2. full-batch number but on CPU (silent fallback) -> rejected
    cpu = json.dumps({"value": 2.0e4, "platform": "cpu"})
    monkeypatch.setattr(bench.sys, "executable",
                        fake_child(f"echo '{cpu}'"))
    out, err = bench._spawn_full_bench({}, 30.0)
    assert out is None and err["class"] == "DeviceBenchFailed"

    # 3. the child's own value-null diagnostic -> rejected, error surfaced
    diag = json.dumps({"value": None, "platform": "tpu",
                       "error": {"class": "JaxRuntimeError",
                                 "detail": "UNAVAILABLE: tunnel dropped"}})
    monkeypatch.setattr(bench.sys, "executable",
                        fake_child(f"echo '{diag}'"))
    out, err = bench._spawn_full_bench({}, 30.0)
    assert out is None
    assert "UNAVAILABLE" in json.dumps(err)

    # 4. hung child -> killed at the parent's wall-clock, classified
    monkeypatch.setattr(bench.sys, "executable", fake_child("sleep 60"))
    out, err = bench._spawn_full_bench({}, 2.0)
    assert out is None and err["class"] == "DeviceBenchTimeout"

    # 5. stdout that parses as JSON but is not a result dict ('null', a
    # number, a stray list) -> an error dict with the stderr diagnostic,
    # never an exception out of the rescue path
    for payload in ("echo null", "echo 42", "echo '[1, 2]'"):
        monkeypatch.setattr(bench.sys, "executable", fake_child(payload))
        out, err = bench._spawn_full_bench({}, 30.0)
        assert out is None and err["class"] == "DeviceBenchFailed"

    # 6. a crashed child's stderr tail is surfaced (and redacted)
    monkeypatch.setattr(
        bench.sys, "executable",
        fake_child("echo 'Trace: api_key=SEKRET died' >&2; echo notjson"))
    out, err = bench._spawn_full_bench({}, 30.0)
    assert out is None and "stderr_tail" in err
    assert "SEKRET" not in err["stderr_tail"]
    assert "died" in err["stderr_tail"]


def test_device_child_timeout_clamped_to_remaining_budget():
    """The device child's wall-clock is the REMAINING budget after the
    CPU-rescue reserve — and when that leaves less than the 60 s floor
    the child is SKIPPED (None), never granted a floor that overshoots
    the driver budget (ADVICE round-5: max(60, remaining) used to)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_mod2", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    # plenty of budget: child gets exactly what remains
    assert bench._device_child_timeout(1200.0, 10.0) == pytest.approx(950.0)
    # exactly at the floor: still allowed
    assert bench._device_child_timeout(310.0, 10.0) == pytest.approx(60.0)
    # below the floor after the reserve: SKIP, not a 60 s grant
    assert bench._device_child_timeout(309.0, 10.0) is None
    assert bench._device_child_timeout(200.0, 0.0) is None
    # a tiny driver budget can never produce a positive child window
    assert bench._device_child_timeout(60.0, 0.0) is None


def test_dryrun_cpu_device_plan_selection():
    """Non-slow pin on the jax-0.4.37 dryrun fix: the mesh-mechanism
    fallback must select correctly in every regime (first-class
    jax_num_cpu_devices knob vs XLA_FLAGS vs subprocess re-exec)."""
    import __graft_entry__ as g

    # enough devices however they arrived: proceed
    assert g._cpu_device_plan(True, 8, 8, False) == "ok"
    assert g._cpu_device_plan(False, 8, 8, False) == "ok"
    assert g._cpu_device_plan(False, 16, 8, True) == "ok"
    # knob took effect yet devices are short: a real failure, re-exec
    # would change nothing
    assert g._cpu_device_plan(True, 1, 8, False) == "fail"
    # old jax, flags already parsed without ours: re-exec with env preset
    assert g._cpu_device_plan(False, 1, 8, False) == "reexec"
    # ... but never recurse: the guard makes a second shortfall terminal
    assert g._cpu_device_plan(False, 1, 8, True) == "fail"


def test_dryrun_host_device_flag_is_replaced_not_kept():
    """An inherited smaller device count must be REWRITTEN to the
    requested one — keeping it would make the re-exec child fail the very
    shortfall it exists to fix."""
    import __graft_entry__ as g

    f = g._with_host_device_flag
    assert f("", 8) == "--xla_force_host_platform_device_count=8"
    assert f("--xla_force_host_platform_device_count=8", 16) == \
        "--xla_force_host_platform_device_count=16"
    out = f("--foo=1 --xla_force_host_platform_device_count=8 --bar=2", 16)
    assert "--xla_force_host_platform_device_count=16" in out
    assert "count=8" not in out and "--foo=1" in out and "--bar=2" in out
    assert f("--foo=1", 4) == "--foo=1 --xla_force_host_platform_device_count=4"


def test_dryrun_num_cpu_devices_knob_probe():
    """_config_cpu_devices must never raise — on jax without the knob
    (0.4.37: AttributeError 'Unrecognized config option') it reports
    False and the XLA_FLAGS path carries the mesh."""
    import jax

    import __graft_entry__ as g

    class _RaisingConfig:
        def update(self, *a):
            raise AttributeError("Unrecognized config option: "
                                 "jax_num_cpu_devices")

    class _FakeJax:
        config = _RaisingConfig()

    assert g._config_cpu_devices(_FakeJax(), 8) is False

    # against the REAL jax: never raises, reports a bool (False on this
    # container's 0.4.37; True once the knob exists and takes)
    ok = g._config_cpu_devices(jax, len(jax.devices()))
    assert isinstance(ok, bool)
    if not hasattr(jax.config, "jax_num_cpu_devices"):
        assert ok is False
