"""Pins on the two driver-artifact paths (the round deliverables).

Rounds 3 and 4 shipped a green local tree with red driver artifacts —
these tests pin the exact properties that failed there:

* the multi-chip dry run must print a heartbeat BEFORE jax imports (so a
  timeout always leaves a diagnosis), must never touch a hardware
  backend regardless of environment pins, and must finish green in a
  fresh subprocess (the driver's regime, not the pytest process);
* the evidence runner must read bench's one-line JSON from STDOUT so
  stderr spam can never hide a red bench behind an ok=true.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_dryrun_multichip_green_in_fresh_subprocess():
    r = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8)"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    lines = r.stdout.strip().splitlines()
    # heartbeat is the FIRST stdout line and precedes any jax/XLA output
    assert lines[0].startswith("[dryrun +"), lines[:3]
    assert "heartbeat printed before jax import" in lines[0]
    assert "backend=cpu forced" in r.stdout     # never probed the pin
    assert "dryrun_multichip ok: 8 cpu devices" in r.stdout


def test_evidence_parses_bench_json_from_stdout_only():
    from raft_tpu import evidence

    # a "bench" that floods stderr and puts its JSON on stdout: the JSON
    # must still be found, and a null value must downgrade ok
    code = ("import sys\n"
            "print('\\n'.join('noise %d' % i for i in range(40)), "
            "file=sys.stderr)\n"
            "print('{\"value\": 5, \"platform\": \"cpu\"}')\n")
    art = evidence._run([sys.executable, "-c", code], timeout=60, label="t")
    assert art["ok"] and art["rc"] == 0
    assert json.loads(art["stdout_tail"][-1])["value"] == 5

    code_null = code.replace('"value": 5', '"value": null')
    art2 = evidence._run([sys.executable, "-c", code_null], timeout=60,
                         label="t2")
    found = None
    for line in reversed(art2["stdout_tail"]):
        try:
            found = json.loads(line)
            break
        except json.JSONDecodeError:
            continue
    assert found is not None and found["value"] is None


def test_evidence_flags_missing_bench_json():
    from raft_tpu import evidence

    art = evidence._run([sys.executable, "-c", "print('no json here')"],
                        timeout=60, label="t3")
    parsed = [ln for ln in art["stdout_tail"]
              if ln.strip().startswith("{")]
    assert parsed == []
