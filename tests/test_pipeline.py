"""Dispatch-ahead chunk executor + donation + chunked DLC sweep tests.

Covers this PR's execution-layer claims:

* :func:`raft_tpu.parallel.pipeline.run_pipelined` preserves order,
  bounds the in-flight window, and really overlaps (stage of chunk k+1
  happens before the fetch of chunk k blocks);
* buffer donation is real (the backend invalidates the donated input)
  and the AOT registry keys on the donation signature;
* ``sweep_sea_states(chunk=...)`` matches the unchunked call exactly,
  including the heading-grid path whose staged excitation is donated;
* the bench's chunk-divisor search no longer degenerates silently.
"""
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from raft_tpu.parallel import pipeline


# ----------------------------------------------------------- run_pipelined


def test_run_pipelined_order_depth_and_overlap():
    """Results come back in item order, at most ``depth`` chunks are in
    flight, and chunk k+1's staging happens BEFORE chunk k's fetch (the
    overlap the executor exists for)."""
    log = []

    def stage(k):
        log.append(("stage", k))
        return (jnp.asarray(float(k)),)

    fn = jax.jit(lambda x: x * 2.0)

    def fetch(out):
        v = float(out)
        log.append(("fetch", int(v // 2)))
        return v

    results, stats = pipeline.run_pipelined(
        fn, list(range(5)), depth=2, stage=stage, fetch=fetch)
    assert results == [2.0 * k for k in range(5)]
    assert stats.chunks == 5
    assert stats.max_in_flight == 2
    # stage of chunk 1 precedes fetch of chunk 0: dispatch-ahead is real
    assert log.index(("stage", 1)) < log.index(("fetch", 0))
    # every stage k (k >= 2) precedes fetch k-1 under depth=2
    for k in range(2, 5):
        assert log.index(("stage", k)) < log.index(("fetch", k - 1))
    assert stats.overlap_fraction > 0.0


def test_run_pipelined_depth_one_is_blocking_loop():
    results, stats = pipeline.run_pipelined(
        jax.jit(lambda x: x + 1.0), [jnp.asarray(1.0), jnp.asarray(2.0)],
        depth=1)
    assert [float(r) for r in results] == [2.0, 3.0]
    assert stats.max_in_flight == 1


def test_dispatch_depth_knob(monkeypatch):
    monkeypatch.delenv("RAFT_TPU_PIPELINE_DEPTH", raising=False)
    assert pipeline.dispatch_depth() == 2
    monkeypatch.setenv("RAFT_TPU_PIPELINE_DEPTH", "4")
    assert pipeline.dispatch_depth() == 4
    monkeypatch.setenv("RAFT_TPU_PIPELINE_DEPTH", "0")
    assert pipeline.dispatch_depth() == 1          # clamped to >= 1
    monkeypatch.setenv("RAFT_TPU_PIPELINE_DEPTH", "nope")
    with pytest.warns(UserWarning, match="RAFT_TPU_PIPELINE_DEPTH"):
        assert pipeline.dispatch_depth() == 2


def test_donation_enabled_knob(monkeypatch):
    monkeypatch.delenv("RAFT_TPU_DONATE", raising=False)
    assert pipeline.donation_enabled() is True
    for off in ("0", "false", "OFF", "no"):
        monkeypatch.setenv("RAFT_TPU_DONATE", off)
        assert pipeline.donation_enabled() is False
    monkeypatch.setenv("RAFT_TPU_DONATE", "1")
    assert pipeline.donation_enabled() is True


# ----------------------------------------------------------------- donation


def test_donated_input_buffer_is_invalidated():
    """The executor's invalidation accounting sees the backend really
    consume a donated buffer (shape/dtype-matching output)."""
    fn = jax.jit(lambda x: x * 3.0, donate_argnums=(0,))

    def stage(k):
        return (jnp.full((64,), float(k)),)

    results, stats = pipeline.run_pipelined(
        fn, [0, 1, 2], depth=2, stage=stage, donate_argnums=(0,))
    assert stats.donated_buffers == 3
    assert stats.invalidated_buffers == 3
    assert stats.donated_bytes == 3 * 64 * results[0].dtype.itemsize
    np.testing.assert_array_equal(results[1], np.full(64, 3.0))


# ------------------------------------------------- chunked sweep_sea_states


def _oc3_base(nw=16):
    import __graft_entry__ as ge
    from raft_tpu.mooring import mooring_stiffness, parse_mooring

    design, members, rna, env, wave = ge._base(nw=nw)
    moor = parse_mooring(
        design["mooring"], yaw_stiffness=design["turbine"]["yaw_stiffness"]
    )
    C_moor = mooring_stiffness(moor, jnp.zeros(6))
    return design, members, rna, env, wave, C_moor


def test_sweep_sea_states_chunked_matches_unchunked():
    from raft_tpu.parallel import make_wave_states, sweep_sea_states

    design, members, rna, env, wave, C_moor = _oc3_base()
    waves = make_wave_states(np.asarray(wave.w),
                             [[5, 9], [6, 10], [7, 11], [8, 12]],
                             float(env.depth))
    ref = sweep_sea_states(members, rna, env, waves, C_moor, n_iter=15)
    out = sweep_sea_states(members, rna, env, waves, C_moor, n_iter=15,
                           chunk=2)
    np.testing.assert_allclose(out["std dev"], ref["std dev"],
                               rtol=1e-12, atol=0)
    np.testing.assert_allclose(out["Xi_abs2"], ref["Xi_abs2"],
                               rtol=1e-12, atol=0)
    np.testing.assert_array_equal(out["iterations"], ref["iterations"])
    stats = out["pipeline"]
    assert stats["chunks"] == 2
    assert stats["donated_bytes"] == 0        # strip-only: nothing to alias


def _heading_grid_bem(nw, seed=3):
    rng = np.random.default_rng(seed)
    scale = 1e6
    bgrid = np.array([0.0, 0.4, 0.8])
    A = np.repeat((0.1 * rng.normal(size=(6, 6, 1))
                   + np.eye(6)[..., None]) * scale, nw, axis=2)
    B = np.repeat(0.02 * rng.normal(size=(6, 6, 1)) * scale, nw, axis=2)
    F = (rng.normal(size=(3, 6, nw))
         + 1j * rng.normal(size=(3, 6, nw))) * 0.01 * scale
    return (bgrid, F, A, B)


def test_sweep_sea_states_chunked_heading_grid_donates(monkeypatch):
    """Heading-grid path: chunked == unchunked, per-chunk staged
    excitation donated and actually invalidated by the backend."""
    monkeypatch.delenv("RAFT_TPU_DONATE", raising=False)
    from raft_tpu.parallel import make_wave_states, sweep_sea_states

    design, members, rna, env, wave, C_moor = _oc3_base(nw=12)
    bem = _heading_grid_bem(nw=12)
    waves = make_wave_states(
        np.asarray(wave.w),
        [[5, 9, 0.1], [6, 10, 0.3], [7, 11, 0.5], [8, 12, 0.7]],
        float(env.depth))
    ref = sweep_sea_states(members, rna, env, waves, C_moor, bem=bem,
                           n_iter=12)
    out = sweep_sea_states(members, rna, env, waves, C_moor, bem=bem,
                           n_iter=12, chunk=2)
    np.testing.assert_allclose(out["std dev"], ref["std dev"],
                               rtol=1e-12, atol=0)
    np.testing.assert_array_equal(out["iterations"], ref["iterations"])
    stats = out["pipeline"]
    assert stats["donated_buffers"] > 0
    assert stats["invalidated_buffers"] == stats["donated_buffers"]
    assert stats["donated_bytes"] > 0
    # the knob really opts out (and still agrees)
    monkeypatch.setenv("RAFT_TPU_DONATE", "0")
    out_off = sweep_sea_states(members, rna, env, waves, C_moor, bem=bem,
                               n_iter=12, chunk=2)
    np.testing.assert_allclose(out_off["std dev"], ref["std dev"],
                               rtol=1e-12, atol=0)
    assert out_off["pipeline"]["donated_buffers"] == 0


def test_sweep_sea_states_chunked_raw_bem_matches_unchunked():
    """The chunked RAW-tuple path (one shared heading, excitation
    replicated via in_axes=None, no donation) also matches the unchunked
    call."""
    from raft_tpu.parallel import make_wave_states, sweep_sea_states

    design, members, rna, env, wave, C_moor = _oc3_base(nw=12)
    bgrid, F_all, A, B = _heading_grid_bem(nw=12)
    bem = (A, B, F_all[0])                   # raw single-heading tuple
    waves = make_wave_states(np.asarray(wave.w),
                             [[5, 9], [6, 10], [7, 11], [8, 12]],
                             float(env.depth))
    ref = sweep_sea_states(members, rna, env, waves, C_moor, bem=bem,
                           n_iter=12)
    out = sweep_sea_states(members, rna, env, waves, C_moor, bem=bem,
                           n_iter=12, chunk=2)
    np.testing.assert_allclose(out["std dev"], ref["std dev"],
                               rtol=1e-12, atol=0)
    np.testing.assert_array_equal(out["iterations"], ref["iterations"])
    assert out["pipeline"]["donated_buffers"] == 0   # nothing to alias


def test_sweep_sea_states_chunk_validation():
    from raft_tpu.parallel import make_mesh, make_wave_states, sweep_sea_states

    design, members, rna, env, wave, C_moor = _oc3_base()
    waves = make_wave_states(np.asarray(wave.w), [[5, 9], [6, 10], [7, 11]],
                             float(env.depth))
    with pytest.raises(ValueError, match="divisible by chunk"):
        sweep_sea_states(members, rna, env, waves, C_moor, chunk=2)
    with pytest.raises(ValueError, match="does not compose"):
        sweep_sea_states(members, rna, env, waves, C_moor, chunk=3,
                         mesh=make_mesh(1))


# ------------------------------------------------------- sweep(return_xi)


def test_sweep_return_xi_false_matches_and_drops_tensor():
    from raft_tpu.parallel import sweep

    design, members, rna, env, wave, C_moor = _oc3_base()
    thetas = jnp.linspace(0.97, 1.03, 3)
    full = sweep(members, rna, env, wave, C_moor, thetas, n_iter=15)
    slim = sweep(members, rna, env, wave, C_moor, thetas, n_iter=15,
                 return_xi=False)
    assert "Xi_abs2" in full and "Xi_abs2" not in slim
    np.testing.assert_allclose(slim["std dev"], full["std dev"],
                               rtol=1e-12, atol=0)
    np.testing.assert_array_equal(slim["iterations"], full["iterations"])


# ------------------------------------------------------- bench chunk picker


def test_bench_pick_chunk_divisor_scan():
    import bench

    assert bench._pick_chunk(1000, 250) == 250
    assert bench._pick_chunk(1000, 300) == 250
    assert bench._pick_chunk(100, 50) == 50
    assert bench._pick_chunk(7, 10) == 7          # request above batch
    # prime batch: degenerates — but loudly
    with pytest.warns(UserWarning, match="no divisor"):
        assert bench._pick_chunk(1009, 250) == 1
    with pytest.warns(UserWarning, match="no divisor"):
        assert bench._pick_chunk(997, 100) == 1
    # divisor just under half the request still warns; just over doesn't
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert bench._pick_chunk(512, 300) == 256
