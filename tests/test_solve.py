"""Solve-engine tests.

Oracles:
  * analytic single-DOF/diagonal response for the no-drag case;
  * an independent NumPy fixed-point loop (impedance assembly, per-frequency
    6x6 complex solve, under-relaxation — the reference recipe at
    raft/raft.py:1497-1552) that treats the jax drag linearization as a
    black box, validating the iteration driver itself;
  * numpy.linalg.eig for the eigen solve.
"""
import pytest
import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.build.members import build_member_set
from raft_tpu.core.cplx import Cx
from raft_tpu.core.types import Env, WaveState
from raft_tpu.core.waves import jonswap, wave_number
from raft_tpu.hydro import (
    linearized_drag,
    node_kinematics,
    strip_added_mass,
    strip_excitation,
)
from raft_tpu.solve import LinearCoeffs, impedance, solve_dynamics, solve_eigen


def cylinder_design(d=10.0, z0=-80.0, z1=20.0, Cd=0.8, CdEnd=0.6):
    return {
        "platform": {
            "members": [
                {
                    "name": "cyl",
                    "type": 2,
                    "rA": [0, 0, z0],
                    "rB": [0, 0, z1],
                    "shape": "circ",
                    "stations": [z0, z1],
                    "d": d,
                    "t": 0.05,
                    "Cd": Cd,
                    "Ca": 1.0,
                    "CdEnd": CdEnd,
                    "CaEnd": 0.6,
                }
            ]
        },
    }


def setup(nw=24, Cd=0.8, CdEnd=0.6, Hs=6.0):
    m = build_member_set(cylinder_design(Cd=Cd, CdEnd=CdEnd))
    w = jnp.linspace(0.15, 2.0, nw)
    depth = 200.0
    k = wave_number(w, depth)
    S = jonswap(w, Hs, 10.0)
    wave = WaveState(w=w, k=k, zeta=jnp.sqrt(S))
    env = Env(Hs=Hs, Tp=10.0, depth=depth)
    kin = node_kinematics(m, wave, env)

    # plausible rigid-body terms: mass ~ displaced water, hydrostatic C
    A = strip_added_mass(m, env)
    F = strip_excitation(m, kin, env)
    mass = 1025.0 * np.pi * 25.0 * 80.0
    M = jnp.eye(6) * mass
    M = M.at[3, 3].set(mass * 40.0**2).at[4, 4].set(mass * 40.0**2).at[5, 5].set(mass * 5.0**2)
    C = jnp.diag(jnp.array([1e5, 1e5, 8e5, 5e9, 5e9, 1e8]))
    nwl = w.shape[0]
    lin = LinearCoeffs(
        M=jnp.broadcast_to(M + A, (nwl, 6, 6)),
        B=jnp.zeros((nwl, 6, 6)),
        C=C,
        F=F,
    )
    return m, kin, wave, env, lin


@pytest.mark.slow
def test_no_drag_matches_direct_solve():
    m, kin, wave, env, lin = setup(Cd=0.0, CdEnd=0.0)
    out = solve_dynamics(m, kin, wave, env, lin)
    assert bool(out.converged)
    # under-relaxation (0.2/0.8) makes even the linear case take a few
    # iterations to pass the relative-change check, as in the reference
    assert int(out.n_iter) < 10
    # analytic: Xi = Z^-1 F per frequency via numpy
    Z = np.asarray(impedance(wave.w, lin.M, lin.B, lin.C).to_complex())
    F = np.asarray(lin.F.to_complex())
    Xi_ref = np.stack([np.linalg.solve(Z[i], F[i]) for i in range(len(wave.w))])
    np.testing.assert_allclose(np.asarray(out.Xi.to_complex()), Xi_ref, rtol=1e-8, atol=1e-30)


def test_fixed_point_matches_numpy_loop():
    m, kin, wave, env, lin = setup()
    out = solve_dynamics(m, kin, wave, env, lin, method="scan")

    # independent loop: numpy impedance assembly + solve + relaxation,
    # drag terms from the (separately tested) jax kernel
    nw = len(wave.w)
    w = np.asarray(wave.w)
    Mw = np.asarray(lin.M)
    Cc = np.asarray(lin.C)
    F0 = np.asarray(lin.F.to_complex())
    Xi_last = np.full((nw, 6), 0.1 + 0j)
    tol, n_used = 0.01, 0
    for it in range(15):
        Bd, Fd = linearized_drag(
            m, kin, Cx(jnp.asarray(Xi_last.real), jnp.asarray(Xi_last.imag)), wave, env
        )
        Bd = np.asarray(Bd)
        Fd = np.asarray(Fd.to_complex())
        Xi = np.zeros((nw, 6), dtype=complex)
        for i in range(nw):
            Z = -w[i] ** 2 * Mw[i] + 1j * w[i] * Bd + Cc
            Xi[i] = np.linalg.solve(Z, F0[i] + Fd[i])
        n_used = it + 1
        if np.max(np.abs(Xi - Xi_last) / (np.abs(Xi) + tol)) < tol:
            break
        Xi_last = 0.2 * Xi_last + 0.8 * Xi
    assert int(out.n_iter) == n_used
    np.testing.assert_allclose(np.asarray(out.Xi.to_complex()), Xi, rtol=1e-6)


def test_while_matches_scan():
    m, kin, wave, env, lin = setup()
    a = solve_dynamics(m, kin, wave, env, lin, method="scan")
    b = solve_dynamics(m, kin, wave, env, lin, method="while")
    np.testing.assert_allclose(
        np.asarray(a.Xi.to_complex()), np.asarray(b.Xi.to_complex()), rtol=1e-9
    )
    assert int(a.n_iter) == int(b.n_iter)


def test_iteration_error_history():
    """history=True records each iteration's convergence error (NaN past the
    exit iteration) in both drivers without changing the solution; the
    default path carries no buffer at all."""
    m, kin, wave, env, lin = setup()
    base = solve_dynamics(m, kin, wave, env, lin, method="scan")
    assert base.err_hist is None
    for method in ("scan", "while"):
        out = solve_dynamics(m, kin, wave, env, lin, method=method,
                             history=True)
        h = np.asarray(out.err_hist)
        n = int(out.n_iter)
        assert h.shape == (15,) and 0 < n <= 15
        assert np.isfinite(h[:n]).all()
        assert np.isnan(h[n:]).all()
        assert h[n - 1] < 0.01          # exit iterate passed the tolerance
        np.testing.assert_allclose(
            np.asarray(out.Xi.to_complex()),
            np.asarray(base.Xi.to_complex()), rtol=1e-9,
        )


@pytest.mark.slow
def test_vmap_over_seastates_matches_loop():
    m, kin, wave, env, lin = setup()

    def run(hs):
        envb = env.replace(Hs=hs)
        S = jonswap(wave.w, hs, envb.Tp)
        waveb = wave.replace(zeta=jnp.sqrt(S))
        kinb = node_kinematics(m, waveb, envb)
        Fb = strip_excitation(m, kinb, envb)
        return solve_dynamics(m, kinb, waveb, envb, lin.replace(F=Fb)).Xi

    hss = jnp.array([2.0, 6.0, 10.0])
    batched = jax.vmap(run)(hss)
    for i, hs in enumerate(hss):
        single = run(hs)
        np.testing.assert_allclose(
            np.asarray(batched.re[i]), np.asarray(single.re), rtol=2e-5, atol=1e-10
        )
        np.testing.assert_allclose(
            np.asarray(batched.im[i]), np.asarray(single.im), rtol=2e-5, atol=1e-10
        )


@pytest.mark.slow
def test_grad_flows_through_scan():
    m, kin, wave, env, lin = setup()

    def rms_surge(hs):
        S = jonswap(wave.w, hs, env.Tp)
        waveb = wave.replace(zeta=jnp.sqrt(S))
        envb = env.replace(Hs=hs)
        kinb = node_kinematics(m, waveb, envb)
        Fb = strip_excitation(m, kinb, envb)
        out = solve_dynamics(m, kinb, waveb, envb, lin.replace(F=Fb))
        return jnp.sqrt(jnp.sum(out.Xi.abs2()[:, 0]))

    g = jax.grad(rms_surge)(6.0)
    h = 1e-4
    fd = (rms_surge(6.0 + h) - rms_surge(6.0 - h)) / (2 * h)
    np.testing.assert_allclose(float(g), float(fd), rtol=1e-4)


@pytest.mark.slow
def test_grad_finite_with_padded_nodes():
    # padded nodes have zero unit vectors -> vRMS hits sqrt(0); the
    # double-where in linearized_drag must keep the gradient finite
    m = build_member_set(cylinder_design(), pad_nodes=40, pad_segments=12)
    w = jnp.linspace(0.15, 2.0, 8)
    depth = 200.0
    wave = WaveState(w=w, k=wave_number(w, depth), zeta=jnp.sqrt(jonswap(w, 6.0, 10.0)))
    env = Env(Hs=6.0, Tp=10.0, depth=depth)

    def rms_surge(hs):
        waveb = wave.replace(zeta=jnp.sqrt(jonswap(w, hs, 10.0)))
        envb = env.replace(Hs=hs)
        kinb = node_kinematics(m, waveb, envb)
        A = strip_added_mass(m, envb)
        Fb = strip_excitation(m, kinb, envb)
        mass = 1025.0 * np.pi * 25.0 * 80.0
        M = jnp.eye(6) * mass
        M = M.at[3, 3].set(mass * 1600.0).at[4, 4].set(mass * 1600.0).at[5, 5].set(mass * 25.0)
        C = jnp.diag(jnp.array([1e5, 1e5, 8e5, 5e9, 5e9, 1e8]))
        lin = LinearCoeffs(
            M=jnp.broadcast_to(M + A, (8, 6, 6)), B=jnp.zeros((8, 6, 6)), C=C, F=Fb
        )
        out = solve_dynamics(m, kinb, waveb, envb, lin)
        return jnp.sqrt(jnp.sum(out.Xi.abs2()[:, 0]))

    g = jax.grad(rms_surge)(6.0)
    assert np.isfinite(float(g))


# ---------------------------------------------------------------- eigen


def test_eigen_matches_numpy():
    rng = np.random.default_rng(0)
    Q = rng.normal(size=(6, 6))
    M = Q @ Q.T + 6 * np.eye(6)
    C = np.diag([4.0, 9.0, 16.0, 25.0, 36.0, 49.0]).astype(float)
    out = solve_eigen(jnp.asarray(M), jnp.asarray(C))
    lam_ref = np.sort(np.linalg.eigvals(np.linalg.inv(M) @ C).real)
    np.testing.assert_allclose(np.sort(np.asarray(out.wns) ** 2), lam_ref, rtol=1e-8)


def test_eigen_dominance_order_diagonal():
    M = jnp.eye(6)
    C = jnp.diag(jnp.array([9.0, 4.0, 25.0, 1.0, 49.0, 16.0]))
    out = solve_eigen(M, C)
    np.testing.assert_allclose(
        np.asarray(out.wns), np.sqrt(np.array([9.0, 4.0, 25.0, 1.0, 49.0, 16.0])), rtol=1e-10
    )
    np.testing.assert_allclose(np.abs(np.asarray(out.modes)), np.eye(6), atol=1e-8)


@pytest.mark.slow
def test_eigen_batched():
    rng = np.random.default_rng(1)
    Ms, Cs = [], []
    for _ in range(3):
        Q = rng.normal(size=(6, 6))
        Ms.append(Q @ Q.T + 6 * np.eye(6))
        D = rng.uniform(1, 50, size=6)
        Cs.append(np.diag(D))
    Mb, Cb = jnp.asarray(np.stack(Ms)), jnp.asarray(np.stack(Cs))
    out = jax.vmap(solve_eigen)(Mb, Cb)
    for i in range(3):
        lam_ref = np.sort(np.linalg.eigvals(np.linalg.inv(Ms[i]) @ Cs[i]).real)
        np.testing.assert_allclose(np.sort(np.asarray(out.wns[i]) ** 2), lam_ref, rtol=1e-7)


def test_diagonal_estimates_decoupled():
    """No off-diagonal coupling: every DOF estimate is sqrt(C_ii/M_ii)/2pi."""
    from raft_tpu.solve import diagonal_estimates

    m = np.array([1e6, 1e6, 1e6, 1e9, 1e9, 2e9])
    c = np.array([4e4, 4e4, 3e5, 5e8, 5e8, 1e8])
    est = np.asarray(diagonal_estimates(jnp.diag(jnp.asarray(m)), jnp.diag(jnp.asarray(c))))
    np.testing.assert_allclose(est, np.sqrt(c / m) / (2 * np.pi), rtol=1e-10)


def test_diagonal_estimates_cg_lever_matches_eigen():
    """Surge-pitch coupled point mass: the z-lever-corrected pitch estimate
    must agree with the full 2-DOF eigen solve (which the plain diagonal
    entry C44/M44 does not)."""
    from raft_tpu.solve import diagonal_estimates

    m0, z0 = 5e6, -30.0          # mass at z0 below the PRP
    I_cg = 2e9
    C00, C44 = 1e5, 3e9          # mooring surge + hydrostatic pitch stiffness
    M = np.zeros((6, 6))
    M[0, 0] = M[1, 1] = M[2, 2] = m0
    M[0, 4] = M[4, 0] = m0 * z0
    M[1, 3] = M[3, 1] = m0 * z0
    M[3, 3] = M[4, 4] = I_cg + m0 * z0 * z0
    M[5, 5] = I_cg
    C = np.diag([C00, C00, 3e5, C44, C44, 1e8]).astype(float)
    est = np.asarray(diagonal_estimates(jnp.asarray(M), jnp.asarray(C)))
    # full coupled surge-pitch eigenvalues
    lam = np.linalg.eigvals(np.linalg.solve(M[np.ix_([0, 4], [0, 4])],
                                            C[np.ix_([0, 4], [0, 4])]))
    f_full = np.sqrt(np.sort(lam.real)) / (2 * np.pi)
    assert abs(est[4] - f_full[1]) / f_full[1] < 0.02
    # the naive diagonal entry is off by the z-lever correction
    f_naive = np.sqrt(C44 / M[4, 4]) / (2 * np.pi)
    assert abs(f_naive - f_full[1]) / f_full[1] > abs(est[4] - f_full[1]) / f_full[1]


@pytest.mark.slow
def test_eigen_bem_added_mass_fixed_point():
    """With a strongly frequency-dependent staged A_bem, solveEigen must
    evaluate A at each mode's own natural frequency (self-consistency),
    not at the lowest grid frequency."""
    from raft_tpu.model import Model, load_design
    from raft_tpu.solve import solve_eigen as _se

    design = load_design("raft_tpu/designs/OC3spar.yaml")
    nw = 40
    w = np.linspace(0.05, 2.0, nw)
    # added mass decaying strongly in frequency: A(w) = A0 / (1 + 4 w^2)
    A0 = 8e6
    A = np.zeros((6, 6, nw))
    for i in range(6):
        A[i, i] = A0 / (1.0 + 4.0 * w**2) * (1e3 if i >= 3 else 1.0)
    B0 = np.zeros((6, 6, nw))
    F0 = np.zeros((6, nw), dtype=complex)
    m = Model(design, w=w, BEM=(A, B0, F0))
    m.setEnv(Hs=8.0, Tp=12.0)
    m.calcSystemProps()
    m.solveEigen()
    fns = m.results["eigen"]["frequencies"]
    assert np.isfinite(fns).all() and (fns > 0).all()
    assert "estimates" in m.results["eigen"]
    # self-consistency: re-assemble with A(wn_i) and re-solve; mode i's
    # frequency must reproduce itself
    M_base = np.asarray(m.statics.M_struc + m.A_morison)
    C_tot = np.asarray(m.statics.C_struc + m.statics.C_hydro + m.C_moor0)
    for i in (0, 2, 4):
        wn = 2 * np.pi * fns[i]
        Ai = np.stack([[np.interp(wn, w, A[a, b]) for b in range(6)]
                       for a in range(6)])
        out = _se(jnp.asarray(M_base + Ai), jnp.asarray(C_tot))
        assert abs(np.asarray(out.wns)[i] - wn) / wn < 1e-3


@pytest.mark.slow
def test_remat_gradient_matches():
    """jax.checkpoint on the scan step must not change values or gradients
    (it only trades memory for recompute)."""
    m = build_member_set(cylinder_design())
    env = Env(Hs=6.0, Tp=10.0, depth=300.0)
    nw = 12
    w = jnp.linspace(0.1, 2.5, nw)
    wave = WaveState(w=w, k=wave_number(w, 300.0),
                     zeta=jnp.sqrt(jonswap(w, 6.0, 10.0)))
    kin = node_kinematics(m, wave, env)
    A = strip_added_mass(m, env)
    F = strip_excitation(m, kin, env)
    M0 = jnp.eye(6) * 8e6 + A
    C = jnp.diag(jnp.asarray([1e5, 1e5, 3e5, 5e9, 5e9, 1e8]))

    def sigma(scale, remat):
        lin = LinearCoeffs(
            M=jnp.broadcast_to(M0 * scale, (nw, 6, 6)),
            B=jnp.zeros((nw, 6, 6)),
            C=C,
            F=F,
        )
        out = solve_dynamics(m, kin, wave, env, lin, n_iter=12, remat=remat)
        return jnp.sum(out.Xi.abs2())

    v0, g0 = jax.value_and_grad(sigma)(1.0, False)
    v1, g1 = jax.value_and_grad(sigma)(1.0, True)
    np.testing.assert_allclose(float(v1), float(v0), rtol=1e-12)
    np.testing.assert_allclose(float(g1), float(g0), rtol=1e-10)


# ------------------------------------------- fused assemble+solve parity


def _staged_design(name, nw=12):
    import os

    import raft_tpu
    from raft_tpu.model import stage_design_base

    pkg = os.path.dirname(os.path.abspath(raft_tpu.__file__))
    design, members, rna, env, wave, C_moor = stage_design_base(
        os.path.join(pkg, "designs", name), nw=nw, Hs=6.0, Tp=10.0,
        w_min=0.3, w_max=2.1)
    from raft_tpu.hydro import node_kinematics, strip_added_mass, strip_excitation
    from raft_tpu.statics import assemble_statics

    stat = assemble_statics(members, rna, env)
    kin2 = node_kinematics(members, wave, env)
    A2 = strip_added_mass(members, env)
    F2 = strip_excitation(members, kin2, env)
    lin2 = LinearCoeffs(
        M=jnp.broadcast_to(stat.M_struc + A2, (nw, 6, 6)),
        B=jnp.zeros((nw, 6, 6)),
        C=stat.C_struc + stat.C_hydro + C_moor,
        F=F2,
    )
    return members, kin2, wave, env, lin2


def _run_unfused_reference(m, kin, wave, env, lin, method, n_iter=15):
    """The PRE-fusion driver: identical fixed point, but every iteration
    materializes the full complex impedance ``Z = Z0 + i w B_drag`` and
    hands it to the plain ``solve_cx`` — the expression this PR's fused
    path replaced.  Runs the real driver body (unjitted, with the fused
    solve monkey-swapped) so nothing else can drift."""
    from raft_tpu.core.linalg6 import solve_cx
    from raft_tpu.solve import dynamics

    def unfused(Z0, w, B_drag, F, n=6):
        Z = Z0 + Cx(jnp.zeros_like(Z0.re),
                    w[..., None, None] * B_drag[..., None, :, :])
        return solve_cx(Z, F, n=n)

    impl = dynamics._solve_dynamics_impl.__wrapped__
    orig = dynamics.solve_cx_fused
    dynamics.solve_cx_fused = unfused
    try:
        return impl(m, kin, wave, env, lin, n_iter=n_iter, tol=0.01,
                    relax=0.8, method=method, axis_name=None, remat=False,
                    history=False, use_pallas=False)
    finally:
        dynamics.solve_cx_fused = orig


@pytest.mark.parametrize("design", [
    "OC3spar.yaml",
    # the VolturnUS staging + eager reference driver is heavy: slow tier
    pytest.param("VolturnUS-S.yaml", marks=pytest.mark.slow),
])
@pytest.mark.parametrize("method", ["while", "scan"])
def test_fused_driver_matches_unfused_reference(design, method):
    """Acceptance gate for the fused assemble+solve: on the OC3 spar and
    the VolturnUS-S semi, both fixed-point drivers produce |dXi| <= 1e-5
    against the pre-fusion driver with IDENTICAL iteration counts."""
    m, kin, wave, env, lin = _staged_design(design)
    fused = solve_dynamics(m, kin, wave, env, lin, n_iter=15, method=method)
    ref = _run_unfused_reference(m, kin, wave, env, lin, method)
    assert int(fused.n_iter) == int(ref.n_iter)
    assert bool(fused.converged) == bool(ref.converged)
    scale = np.max(np.abs(np.asarray(ref.Xi.re))) + np.max(
        np.abs(np.asarray(ref.Xi.im)))
    dxi = max(float(jnp.max(jnp.abs(fused.Xi.re - ref.Xi.re))),
              float(jnp.max(jnp.abs(fused.Xi.im - ref.Xi.im))))
    assert dxi <= 1e-5 * max(1.0, scale), f"|dXi|={dxi} (scale {scale})"


@pytest.mark.parametrize("design", [
    "OC3spar.yaml",
    pytest.param("VolturnUS-S.yaml", marks=pytest.mark.slow),
])
def test_fused_scan_grad_matches_unfused_reference(design):
    """``jax.grad`` through the differentiable scan driver agrees between
    the fused path and the pre-fusion reference."""
    m, kin, wave, env, lin = _staged_design(design)

    def loss_fused(s):
        lin2 = lin.replace(F=Cx(lin.F.re * s, lin.F.im * s))
        out = solve_dynamics(m, kin, wave, env, lin2, n_iter=15,
                             method="scan")
        return jnp.sum(out.Xi.abs2())

    def loss_ref(s):
        lin2 = lin.replace(F=Cx(lin.F.re * s, lin.F.im * s))
        out = _run_unfused_reference(m, kin, wave, env, lin2, "scan")
        return jnp.sum(out.Xi.abs2())

    g_f = float(jax.grad(loss_fused)(jnp.asarray(1.0)))
    g_r = float(jax.grad(loss_ref)(jnp.asarray(1.0)))
    assert np.isfinite(g_f)
    np.testing.assert_allclose(g_f, g_r, rtol=1e-6)
