"""Tiled BEM assembly + blocked panel LU tests.

Two contracts from the perf tentpole:

* **Blocked LU** (:mod:`raft_tpu.core.linalg6`): the blocked
  right-looking factorization is pinned against its row-by-row reference
  twin — same pivot sequence, same LAPACK layout — on random,
  pivot-stressed (tiny leading diagonals) and near-singular
  (irregular-frequency lid-mesh conditioning) systems, at sizes that do
  and do not divide the block, plus under ``vmap``.  The ``custom_vjp``
  adjoint of the refined solve is re-pinned against finite differences
  THROUGH the new factorization.
* **Cross-route assembly parity**: the Pallas tiled kernels
  (:mod:`raft_tpu.core.pallas_bem`, interpreter mode on CPU) agree with
  the XLA assembly route within the documented
  :data:`~raft_tpu.core.pallas_bem.INTERP_PARITY_RTOL` (the PR 3
  dual-route precedent), deep and finite-depth, and the bf16 assembly
  mode stays finite with its refinement-residual guardrail intact.

The native-oracle parity pins (3e-5..9e-5 scale-relative) live in
``tests/test_jax_bem.py`` and are untouched by the route split — both
assembly routes feed the same factor/solve/combine tail.
"""
import numpy as np
import pytest

from raft_tpu.core.linalg6 import (
    LU_BLOCK,
    lu_factor_blocked,
    lu_factor_unblocked,
    lu_solve_blocked,
    lu_solve_unblocked,
)

W2 = np.array([0.7, 1.3])


def _mats(kind: str, m: int, seed: int = 0) -> np.ndarray:
    rng = np.random.default_rng(seed)
    A = rng.normal(size=(m, m))
    if kind == "random":
        return A + 2.0 * np.eye(m)
    if kind == "pivot":
        # tiny leading diagonals: row-by-row elimination without pivoting
        # would divide by ~1e-12 immediately — every panel must pivot
        A = A + 2.0 * np.eye(m)
        A[np.diag_indices(m)] = 1e-12 * np.arange(1, m + 1)
        return A
    if kind == "near_singular":
        # two nearly dependent rows (the lid-mesh irregular-frequency
        # conditioning shape): cond ~ 1/eps_row, still factorable
        A = A + 2.0 * np.eye(m)
        A[m // 2] = A[m // 3] + 1e-9 * rng.normal(size=m)
        return A
    raise ValueError(kind)


@pytest.mark.parametrize("kind", ["pivot", "near_singular"])
@pytest.mark.parametrize("m", [37])
def test_blocked_lu_matches_unblocked(kind, m):
    """Same pivot sequence and factors as the row-by-row reference on a
    ragged size (identity padding must never let a padded row win a
    pivot search).  Fast tier keeps the two adversarial kinds at the
    ragged m=37; the full kind x {24, 37, 64, 96} ladder rides in the
    slow tier below (single-core tier-1 is budgeted)."""
    import jax.numpy as jnp

    A = jnp.asarray(_mats(kind, m), jnp.float64)
    LUb, pb = lu_factor_blocked(A, block=16)
    LUu, pu = lu_factor_unblocked(A)
    np.testing.assert_array_equal(np.asarray(pb), np.asarray(pu))
    scale = float(jnp.max(jnp.abs(LUu)))
    assert float(jnp.max(jnp.abs(LUb - LUu))) <= 1e-10 * scale


@pytest.mark.parametrize("m", [37])
def test_blocked_solve_residual(m):
    """factor+solve residual at dtype roundoff for a multi-RHS system,
    blocked and reference paths agreeing on the solution."""
    import jax.numpy as jnp

    A = jnp.asarray(_mats("random", m, seed=3), jnp.float64)
    B = jnp.asarray(np.random.default_rng(4).normal(size=(m, 5)))
    LUb, pb = lu_factor_blocked(A, block=16)
    Xb = lu_solve_blocked(LUb, pb, B, block=16)
    LUu, pu = lu_factor_unblocked(A)
    Xu = lu_solve_unblocked(LUu, pu, B)
    assert float(jnp.max(jnp.abs(A @ Xb - B))) < 1e-10
    assert float(jnp.max(jnp.abs(Xb - Xu))) < 1e-9
    # vector RHS path
    xv = lu_solve_blocked(LUb, pb, B[:, 0], block=16)
    np.testing.assert_allclose(np.asarray(xv), np.asarray(Xb[:, 0]),
                               rtol=1e-12, atol=1e-12)


@pytest.mark.slow
def test_blocked_lu_vmaps():
    """The frequency-batched use: one vmapped factor+solve over a stack
    of systems (the ``lax.map(checkpoint(vmap))`` wrapper relies on
    this).  Slow tier — tracing dominates, and the fast tier already
    drives this path through every ``jax_bem`` solve."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    A = jnp.asarray(rng.normal(size=(2, 32, 32)) + 2 * np.eye(32))
    B = jnp.asarray(rng.normal(size=(2, 32, 3)))

    def solve(a, b):
        lu, p = lu_factor_blocked(a, block=16)
        return lu_solve_blocked(lu, p, b, block=16)

    X = jax.vmap(solve)(A, B)
    resid = jnp.max(jnp.abs(jnp.einsum("bij,bjk->bik", A, X) - B))
    assert float(resid) < 1e-8


def test_default_block_size_used_by_solver():
    """The refined solve really runs the blocked path at LU_BLOCK (a
    source pin: the hot path must not silently fall back to the
    reference)."""
    import inspect

    from raft_tpu.hydro import jax_bem

    src = inspect.getsource(jax_bem._solve_refined_impl)
    assert "lu_factor_blocked" in src and "lu_solve_blocked" in src
    assert LU_BLOCK >= 8


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["random", "pivot", "near_singular"])
@pytest.mark.parametrize("m", [24, 64, 96])
def test_blocked_lu_matches_unblocked_wide(kind, m):
    """The full size ladder for the pivot-sequence pin (single-panel
    ragged 24, aligned 64, triple-panel 96, every kind) — slow tier;
    the adversarial kinds at ragged 37 stay fast."""
    import jax.numpy as jnp

    A = jnp.asarray(_mats(kind, m), jnp.float64)
    LUb, pb = lu_factor_blocked(A, block=16)
    LUu, pu = lu_factor_unblocked(A)
    np.testing.assert_array_equal(np.asarray(pb), np.asarray(pu))
    scale = float(jnp.max(jnp.abs(LUu)))
    assert float(jnp.max(jnp.abs(LUb - LUu))) <= 1e-10 * scale


@pytest.mark.slow
def test_refined_solve_grad_matches_fd():
    """grad through the ``custom_vjp`` refined solve — now backed by the
    blocked factorization — against central finite differences (slow
    tier, like the geometry-to-coefficients FD pin
    tests/test_jax_bem.py::test_grad_matches_finite_difference:
    tracing the adjoint dominates)."""
    import jax
    import jax.numpy as jnp

    from raft_tpu.hydro.jax_bem import _solve_refined

    rng = np.random.default_rng(11)
    M0 = jnp.asarray(rng.normal(size=(40, 40)) + 3 * np.eye(40))
    B0 = jnp.asarray(rng.normal(size=(40, 2)))

    def loss(t):
        return jnp.sum(_solve_refined(M0 + t * jnp.eye(40), B0) ** 2)

    g = float(jax.grad(loss)(jnp.float64(0.0)))
    eps = 1e-6
    fd = (float(loss(jnp.float64(eps)))
          - float(loss(jnp.float64(-eps)))) / (2 * eps)
    assert g == pytest.approx(fd, rel=1e-6)


# ------------------------------------------------------- knobs + salting

def test_assembly_knob_parsing(monkeypatch):
    from raft_tpu.hydro import jax_bem

    monkeypatch.delenv(jax_bem.ASSEMBLY_ENV, raising=False)
    assert jax_bem.assembly_mode() == "auto"
    for raw, want in [("pallas", "pallas"), (" XLA ", "xla"),
                      ("auto", "auto"), ("", "auto"), ("bogus", "auto")]:
        monkeypatch.setenv(jax_bem.ASSEMBLY_ENV, raw)
        assert jax_bem.assembly_mode() == want
    # auto resolves per backend: the CPU suite takes the XLA route
    monkeypatch.setenv(jax_bem.ASSEMBLY_ENV, "auto")
    assert jax_bem.resolved_assembly() == "xla"
    assert jax_bem.resolved_assembly("pallas") == "pallas"
    # an EXPLICIT 'auto' defers to the env knob (the resolved_mode
    # override contract)
    monkeypatch.setenv(jax_bem.ASSEMBLY_ENV, "pallas")
    assert jax_bem.resolved_assembly("auto") == "pallas"
    monkeypatch.delenv(jax_bem.ASSEMBLY_ENV)
    assert jax_bem.resolved_assembly("auto") == "xla"


def test_precision_knob_parsing(monkeypatch):
    from raft_tpu.hydro import jax_bem

    monkeypatch.delenv(jax_bem.PRECISION_ENV, raising=False)
    assert jax_bem.bem_precision() == "f32"
    for raw, want in [("bf16", "bf16"), ("BFLOAT16", "bf16"),
                      ("f32", "f32"), ("float32", "f32"), ("", "f32"),
                      ("f16", "f32")]:   # unsupported degrades, warned once
        monkeypatch.setenv(jax_bem.PRECISION_ENV, raw)
        assert jax_bem.bem_precision() == want


def test_assembly_and_precision_are_key_salted():
    """An assembly-route or precision flip must change every AOT key:
    the routes agree only to INTERP_PARITY_RTOL, not bitwise, and bf16
    coefficients differ at bf16 scale."""
    from raft_tpu.cache.aot import _solver_salts

    salts = _solver_salts()
    assert "bem_assembly" in salts
    assert salts[salts.index("bem_assembly") + 1] in ("xla", "pallas")
    assert "bem_precision" in salts
    assert salts[salts.index("bem_precision") + 1] in ("f32", "bf16")


def test_tile_ok_matches_ladder():
    from raft_tpu.build import buckets
    from raft_tpu.core import pallas_bem

    for c in buckets.DEFAULT_LADDER["panels"]:
        assert pallas_bem.tile_ok(c)          # built-in ladder is aligned
    assert not pallas_bem.tile_ok(96)         # custom class -> XLA route
    assert not pallas_bem.tile_ok(32)
    assert pallas_bem.TILE == buckets.BEM_TILE


# ------------------------------------------- cross-route assembly parity

def _tile_mesh():
    """~60 hull panels -> the 64 ``panels`` class (tile-aligned)."""
    from raft_tpu.hydro.mesh import mesh_member

    return mesh_member(stations=[0.0, 8.0], diameters=[2.3, 2.3],
                       rA=[0.0, 0.0, -6.0], rB=[0.0, 0.0, 2.0],
                       dz_max=1.6, da_max=1.3)


def _solve_args(w, depth):
    import jax.numpy as jnp

    from raft_tpu.hydro import jax_bem, wavetable

    padded, pm, lm = jax_bem._pad_mesh(_tile_mesh(), None)
    fd = wavetable.fd_fit_grid(w, depth if depth > 0 else -1.0, 9.81)
    tab = jax_bem._stage_table(jnp.float32)
    return (jnp.asarray(padded, jnp.float32), jnp.asarray(pm, jnp.float32),
            jnp.asarray(lm, jnp.float32), jnp.asarray(w, jnp.float32),
            jnp.asarray([depth], jnp.float32),
            {k: jnp.asarray(v, jnp.float32) for k, v in fd.items()}, tab)


@pytest.mark.slow
@pytest.mark.parametrize("depth", [0.0, 35.0])
def test_xla_vs_pallas_interpret_parity(depth):
    """The dual-route pin (PR 3 precedent): identical math, different
    tiling — XLA vs pallas-interpret within INTERP_PARITY_RTOL on A, B
    and F, deep (region-split wave integrals + Bessel far field) and
    finite depth (the 4-image exp-fit branch)."""
    from raft_tpu.core.pallas_bem import INTERP_PARITY_RTOL
    from raft_tpu.hydro import jax_bem

    args = _solve_args(W2, depth)
    kw = dict(finite_depth=depth > 0, depth=depth, dtype=None)
    Ax, Bx, Fx, rx = jax_bem.solve_panels(*args, assembly="xla", **kw)
    Ap, Bp, Fp, rp = jax_bem.solve_panels(*args, assembly="pallas", **kw)
    for name, x, p in [("A", Ax, Ap), ("B", Bx, Bp),
                       ("F.re", Fx.re, Fp.re), ("F.im", Fx.im, Fp.im)]:
        err = jax_bem.parity_err(np.asarray(p), np.asarray(x))
        assert err <= INTERP_PARITY_RTOL, (
            f"{name} (depth={depth}): {err:.2e} > {INTERP_PARITY_RTOL:.0e}")
    assert float(np.max(rp)) < 1e-4 and float(np.max(rx)) < 1e-4


@pytest.mark.slow
def test_bf16_assembly_guarded_by_residual():
    """The mixed-precision mode: bf16 assembly + f32 factor/refinement
    stays finite, its refinement residual (THE guardrail metric) stays
    small, and the coefficients track the f32 route at bf16 resolution
    — loose by design; the knob is opt-in and key-salted."""
    from raft_tpu.hydro import jax_bem

    args = _solve_args(W2, 0.0)
    kw = dict(finite_depth=False, depth=0.0, dtype=None)
    A1, B1, F1, r1 = jax_bem.solve_panels(*args, assembly="xla", **kw)
    A2, B2, F2, r2 = jax_bem.solve_panels(*args, assembly="xla",
                                          precision="bf16", **kw)
    for x in (A2, B2, F2.re, F2.im):
        assert np.isfinite(np.asarray(x)).all()
    assert float(np.max(r2)) < 1e-4           # refinement holds the line
    assert jax_bem.parity_err(np.asarray(A2), np.asarray(A1)) < 0.1


def test_non_tile_aligned_falls_back(monkeypatch):
    """A non-TILE-multiple padded class must take the XLA route even
    when the knob says pallas — routing, not a crash (custom
    RAFT_TPU_BUCKETS ladders stay supported)."""
    from raft_tpu.core import pallas_bem

    with pytest.raises(ValueError, match="multiple"):
        pallas_bem.rankine_assembly(np.zeros((96, 4, 3)), *([None] * 7))
