"""Mesher + BEM file-IO tests.

Oracles: analytic cylinder volume/area for the mesher; synthetic round-trip
golden files for the WAMIT parsers (written then re-read at 1e-12, the
regression style of the reference's Capytaine test suite, SURVEY.md §4);
out-of-range interpolation must raise (tests/test_capytaine_integration.py:31).
"""
import numpy as np
import pytest

from raft_tpu.hydro.bem_io import (
    dimensionalize,
    interp_to_grid,
    load_wamit_coeffs,
    read_wamit1,
    read_wamit3,
)
from raft_tpu.hydro.mesh import (
    clip_waterline,
    mesh_design,
    mesh_member,
    mesh_volume,
    panel_normals_areas,
    read_pnl,
    write_pnl,
)
from raft_tpu.model import load_design


def test_cylinder_mesh_volume_and_normals():
    p = mesh_member([0, 40], [10, 10], rA=[0, 0, -30], rB=[0, 0, 10], dz_max=2, da_max=1.0)
    V = mesh_volume(p)
    assert V == pytest.approx(np.pi / 4 * 100 * 30, rel=0.02)
    n, a = panel_normals_areas(p)
    assert a.sum() == pytest.approx(np.pi * 10 * 30 + np.pi / 4 * 100, rel=0.02)
    # everything clipped at the waterline
    assert p[..., 2].max() <= 1e-9


def test_tapered_spar_mesh():
    # OC3-like taper: d 9.4 below, 6.5 above
    p = mesh_member(
        [0, 108, 116, 130], [9.4, 9.4, 6.5, 6.5], rA=[0, 0, -120], rB=[0, 0, 10],
        dz_max=3, da_max=2,
    )
    rA_, rB_ = 9.4 / 2, 6.5 / 2
    V_expect = (
        np.pi * rA_**2 * 108
        + np.pi / 3 * 8 * (rA_**2 + rA_ * rB_ + rB_**2)   # conical frustum
        + np.pi * rB_**2 * 4
    )
    # inscribed-polygon discretization at da_max=2 m underestimates ~2-3%
    assert mesh_volume(p) == pytest.approx(V_expect, rel=0.04)


def test_clip_drops_dry_panels():
    p = mesh_member([0, 10], [5, 5], rA=[0, 0, 5], rB=[0, 0, 15])
    assert len(clip_waterline(p)) == 0


def test_mesh_design_oc3():
    design = load_design("raft_tpu/designs/OC3spar.yaml")
    p = mesh_design(design)
    assert len(p) > 100
    assert p[..., 2].max() <= 1e-9
    assert mesh_volume(p) == pytest.approx(8029.0, rel=0.03)


def test_pnl_round_trip(tmp_path):
    p = mesh_member([0, 40], [10, 10], rA=[0, 0, -30], rB=[0, 0, 10], dz_max=4, da_max=2.5)
    path = str(tmp_path / "HullMesh.pnl")
    write_pnl(path, p)
    q = read_pnl(path)
    assert q.shape == p.shape
    assert mesh_volume(q) == pytest.approx(mesh_volume(p), rel=1e-6)


# ------------------------------------------------------------ WAMIT files


def synth_wamit(tmp_path, nw=5):
    rng = np.random.default_rng(3)
    w = np.linspace(0.2, 1.0, nw)
    A = rng.normal(size=(6, 6, nw))
    B = rng.normal(size=(6, 6, nw))
    Xre = rng.normal(size=(6, nw))
    Xim = rng.normal(size=(6, nw))
    p1 = tmp_path / "body.1"
    with open(p1, "w") as f:
        for iw in range(nw):
            for i in range(6):
                for j in range(6):
                    f.write(f"{w[iw]:.6E} {i+1} {j+1} {A[i,j,iw]:.6E} {B[i,j,iw]:.6E}\n")
    p3 = tmp_path / "body.3"
    with open(p3, "w") as f:
        for iw in range(nw):
            for i in range(6):
                mod = np.hypot(Xre[i, iw], Xim[i, iw])
                ph = np.rad2deg(np.arctan2(Xim[i, iw], Xre[i, iw]))
                f.write(
                    f"{w[iw]:.6E} 0.000000E+00 {i+1} {mod:.6E} {ph:.6E} "
                    f"{Xre[i,iw]:.6E} {Xim[i,iw]:.6E}\n"
                )
    return w, A, B, Xre, Xim, str(p1), str(p3)


def test_wamit1_round_trip(tmp_path):
    w, A, B, _, _, p1, _ = synth_wamit(tmp_path)
    w_r, A_r, B_r = read_wamit1(p1)
    np.testing.assert_allclose(w_r, w, rtol=1e-12)
    np.testing.assert_allclose(A_r, A, rtol=1e-6)
    np.testing.assert_allclose(B_r, B, rtol=1e-6)
    assert A_r.shape == (6, 6, len(w))


def test_wamit3_round_trip(tmp_path):
    w, _, _, Xre, Xim, _, p3 = synth_wamit(tmp_path)
    w_r, headings, mod, phase, re, im = read_wamit3(p3)
    np.testing.assert_allclose(re, Xre, rtol=1e-6)
    np.testing.assert_allclose(im, Xim, rtol=1e-6)
    assert im.dtype == np.float64
    assert len(headings) == 1


def test_dimensionalize_scaling():
    w = np.array([0.5, 1.0])
    A_bar = np.ones((6, 6, 2))
    B_bar = np.ones((6, 6, 2))
    X = np.ones((6, 2))
    A, B, F = dimensionalize(w, A_bar, B_bar, X, 0 * X, rho=1000.0, g=10.0)
    assert A[0, 0, 0] == pytest.approx(1000.0)       # rho * A'
    assert B[0, 0, 1] == pytest.approx(1000.0)       # rho * w * B'
    assert B[0, 0, 0] == pytest.approx(500.0)
    assert F[0, 0] == pytest.approx(10000.0)         # rho g X'
    # ulen exponents: trans-trans ulen^3, cross ulen^4, rot-rot ulen^5,
    # rotational excitation ulen^3
    A2, _, F2 = dimensionalize(w, A_bar, B_bar, X, 0 * X, rho=1000.0, g=10.0, ulen=2.0)
    assert A2[0, 0, 0] == pytest.approx(1000.0 * 8)
    assert A2[0, 3, 0] == pytest.approx(1000.0 * 16)
    assert A2[3, 3, 0] == pytest.approx(1000.0 * 32)
    assert F2[3, 0] == pytest.approx(10000.0 * 8)


def test_interp_out_of_range_raises():
    w = np.linspace(0.2, 1.0, 5)
    arr = np.ones((6, 5))
    with pytest.raises(ValueError):
        interp_to_grid(w, arr, np.linspace(0.1, 0.9, 4))
    with pytest.raises(ValueError):
        interp_to_grid(w, arr, np.linspace(0.3, 1.4, 4))
    out = interp_to_grid(w, arr, np.linspace(0.3, 0.9, 4))
    assert out.shape == (6, 4)


def test_load_wamit_coeffs_end_to_end(tmp_path):
    w, A, B, Xre, Xim, p1, p3 = synth_wamit(tmp_path)
    grid = np.linspace(0.25, 0.95, 8)
    A_d, B_d, F_d = load_wamit_coeffs(p1, p3, grid, rho=1025.0, g=9.81)
    assert A_d.shape == (6, 6, 8)
    assert F_d.dtype == complex
    # spot value: A at grid point inside source range interpolates rho*A'
    a_interp = np.interp(grid[0], w, A[0, 0])
    np.testing.assert_allclose(A_d[0, 0, 0], 1025.0 * a_interp, rtol=1e-6)
