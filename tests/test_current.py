"""Steady-current loading (beyond-reference: the reference Env is wind +
waves only, raft/raft.py:22-30).

Oracles:
  * Monte-Carlo pins on the closed-form Gaussian drag moments: for
    X ~ N(U, sigma^2), E[|X|X] and the MMSE slope Cov(|X|X, X)/sigma^2
    match the erf/exp expressions used by hydro/strip.py;
  * limits: slope(0, sigma) = sqrt(8/pi) sigma (the Borgman factor —
    zero current reproduces the reference linearization exactly) and
    slope(U, 0) = 2|U| (steady-flow drag derivative);
  * analytic mean force on a uniform-current vertical cylinder
    (0.5 rho Cd d L U^2 surge force, pitch moment from the z-lever);
  * facade: current pushes the OC3 mean surge offset down-stream and the
    response still converges.
"""
import numpy as np
import pytest
import jax.numpy as jnp

from raft_tpu.core.types import Env
from raft_tpu.build.members import build_member_set
from raft_tpu.hydro import current_mean_force, node_current
from raft_tpu.hydro.strip import _gauss_drag_slope

from tests.test_hydro_strip import cylinder_design

RHO = 1025.0


def _mc_moments(U, sigma, n=400_000, seed=7):
    rng = np.random.default_rng(seed)
    x = rng.normal(U, sigma, size=n)
    e_absxx = np.mean(np.abs(x) * x)
    slope = np.mean(np.abs(x) * x * (x - U)) / sigma**2
    return e_absxx, slope


@pytest.mark.parametrize("U,sigma", [(0.7, 1.3), (2.0, 0.5), (-1.1, 0.9)])
def test_gauss_moments_match_monte_carlo(U, sigma):
    from math import erf, exp, pi, sqrt

    mc_m, mc_b = _mc_moments(U, sigma)
    r = U / (sigma * sqrt(2.0))
    m = (U**2 + sigma**2) * erf(r) + U * sigma * sqrt(2.0 / pi) * exp(-(r**2))
    b = float(_gauss_drag_slope(jnp.asarray(U), jnp.asarray(sigma)))
    assert m == pytest.approx(mc_m, rel=2e-2, abs=2e-2)
    assert b == pytest.approx(mc_b, rel=2e-2)


def test_slope_limits():
    # zero current: exactly the Borgman sqrt(8/pi) sigma factor
    s = 1.7
    assert float(_gauss_drag_slope(jnp.asarray(0.0), jnp.asarray(s))) == (
        pytest.approx(np.sqrt(8.0 / np.pi) * s, rel=1e-12))
    # steady-flow limit: d(|U|U)/dU = 2|U|; sigma=0 lane stays finite
    assert float(_gauss_drag_slope(jnp.asarray(-3.0), jnp.asarray(0.0))) == (
        pytest.approx(6.0, rel=1e-12))
    # large-U/sigma ratio converges to the same limit smoothly
    assert float(_gauss_drag_slope(jnp.asarray(3.0), jnp.asarray(1e-3))) == (
        pytest.approx(6.0, rel=1e-4))


def test_profile_and_projection():
    m = build_member_set(cylinder_design(z0=-100.0, z1=10.0))
    depth = 200.0
    # uniform profile: every submerged node sees the surface speed
    env = Env(depth=depth, current=1.5, current_heading=0.0, current_exp=0.0)
    uc = np.asarray(node_current(m, env))
    wet = np.asarray(m.node_r[:, 2]) <= 0
    assert np.allclose(uc[wet, 0], 1.5)
    assert np.allclose(uc[:, 1:], 0.0)
    # sheared profile decays toward the seabed with the power law
    env7 = env.replace(current_exp=1.0 / 7.0)
    uc7 = np.asarray(node_current(m, env7))
    z = np.asarray(m.node_r[:, 2])
    expect = 1.5 * np.clip((depth + z) / depth, 0.0, 1.0) ** (1.0 / 7.0)
    assert np.allclose(uc7[:, 0], expect, rtol=1e-6)
    # heading rotates the vector in plan
    env_y = env.replace(current_heading=np.pi / 2.0)
    ucy = np.asarray(node_current(m, env_y))
    assert np.allclose(ucy[wet, 1], 1.5) and np.allclose(ucy[:, 0], 0.0, atol=1e-7)


def test_mean_force_vertical_cylinder_analytic():
    d, z0, Cd, U = 10.0, -80.0, 0.8, 1.5
    m = build_member_set(cylinder_design(d=d, z0=z0, z1=20.0, Cd=Cd))
    env = Env(depth=200.0, current=U, current_heading=0.0, current_exp=0.0)
    F6 = np.asarray(current_mean_force(m, env))
    # surge: 0.5 rho Cd d L U^2 over the submerged length (transverse
    # drag only -- the axial q direction is vertical, orthogonal to the
    # flow, and end-disk drag acts axially too)
    L = abs(z0)
    Fx = 0.5 * RHO * Cd * d * L * U**2
    assert F6[0] == pytest.approx(Fx, rel=2e-2)          # node discretization
    assert abs(F6[1]) < 1e-6 * Fx and abs(F6[2]) < 1e-6 * Fx
    # pitch about the PRP (z=0): -0.5 rho Cd d U^2 * integral z dz
    My = 0.5 * RHO * Cd * d * U**2 * (z0**2 / 2.0)
    assert F6[4] == pytest.approx(-My, rel=2e-2)
    # quadratic in U, odd in sign
    F6_2 = np.asarray(current_mean_force(m, env.replace(current=2 * U)))
    assert F6_2[0] == pytest.approx(4.0 * F6[0], rel=1e-6)
    F6_m = np.asarray(current_mean_force(m, env.replace(current=-U)))
    assert F6_m[0] == pytest.approx(-F6[0], rel=1e-6)


@pytest.mark.slow
def test_oc3_current_shifts_offset_and_converges():
    from raft_tpu.model import Model, load_design

    W = np.arange(0.05, 3.0, 0.25)
    base = Model(load_design("raft_tpu/designs/OC3spar.yaml"), w=W)
    base.setEnv(Hs=8.0, Tp=12.0, Fthrust=800e3)
    base.calcSystemProps()
    base.calcMooringAndOffsets()
    x0 = float(base.r6_eq[0])

    cur = Model(load_design("raft_tpu/designs/OC3spar.yaml"), w=W)
    cur.setEnv(Hs=8.0, Tp=12.0, Fthrust=800e3,
               current=1.5, current_heading=0.0, current_exp=1.0 / 7.0)
    cur.calcSystemProps()
    cur.calcMooringAndOffsets()
    x1 = float(cur.r6_eq[0])
    assert x1 > x0 + 0.5          # down-stream surge grows by metres-ish
    cur.solveDynamics()
    assert cur.results["response"]["converged"]
    assert np.isfinite(cur.results["response"]["std dev"]).all()

    # the mean-flow-aware linearization adds damping: surge response std
    # does not grow when a strong collinear current is switched on
    base.solveDynamics()
    s0 = base.results["response"]["std dev"][0]
    s1 = cur.results["response"]["std dev"][0]
    assert s1 <= s0 * 1.05


@pytest.mark.slow
def test_array_current_matches_single():
    from raft_tpu.model import Model, load_design

    W = np.arange(0.05, 3.0, 0.25)
    kw = dict(Hs=8.0, Tp=12.0, Fthrust=800e3,
              current=1.2, current_heading=0.3, current_exp=1.0 / 7.0)
    m1 = Model(load_design("raft_tpu/designs/OC3spar.yaml"), w=W)
    m1.setEnv(**kw)
    m1.calcSystemProps()
    m1.calcMooringAndOffsets()

    a = Model(load_design("raft_tpu/designs/OC3spar.yaml"), w=W, nTurbines=2)
    a.setEnv(**kw)
    a.calcSystemProps()
    a.calcMooringAndOffsets()
    ra = np.asarray(a.r6_eq)
    np.testing.assert_allclose(ra[0], np.asarray(m1.r6_eq),
                               rtol=1e-6, atol=1e-8)
    np.testing.assert_allclose(ra[1], ra[0], rtol=1e-8, atol=1e-10)
