"""Unrolled 6x6 kernels vs numpy.linalg oracle."""
import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import linalg6
from raft_tpu.core.cplx import Cx

rng = np.random.default_rng(7)


def test_solve_cx_single():
    A = rng.normal(size=(6, 6)) + 1j * rng.normal(size=(6, 6))
    b = rng.normal(size=6) + 1j * rng.normal(size=6)
    x = linalg6.solve_cx(Cx.of(A), Cx.of(b))
    np.testing.assert_allclose(np.asarray(x.to_complex()), np.linalg.solve(A, b), rtol=1e-10)


def test_solve_cx_batched():
    A = rng.normal(size=(50, 6, 6)) + 1j * rng.normal(size=(50, 6, 6))
    b = rng.normal(size=(50, 6)) + 1j * rng.normal(size=(50, 6))
    x = np.asarray(linalg6.solve_cx(Cx.of(A), Cx.of(b)).to_complex())
    expect = np.linalg.solve(A, b[..., None])[..., 0]
    np.testing.assert_allclose(x, expect, rtol=1e-8)


def test_solve_cx_needs_pivoting():
    # zero leading pivot forces a row swap
    A = np.array(
        [
            [0.0, 1.0],
            [1.0, 0.0],
        ]
    ) + 0j
    b = np.array([2.0, 3.0]) + 0j
    x = linalg6.solve_cx(Cx.of(A), Cx.of(b), n=2)
    np.testing.assert_allclose(np.asarray(x.to_complex()), [3.0, 2.0], atol=1e-12)


def test_solve_cx_impedance_like():
    # realistic RAO impedance: Z = -w^2 M + i w B + C with large magnitude spread
    M = np.diag([8e6, 8e6, 8e6, 5e9, 5e9, 1e9])
    C = np.diag([7e4, 7e4, 3e5, 1e9, 1e9, 1e8])
    B = 0.05 * np.sqrt(np.diag(M) * np.diag(C))
    ws = np.linspace(0.05, 3.0, 60)
    Z = -ws[:, None, None] ** 2 * M + 1j * ws[:, None, None] * np.diag(B) + C
    Z = Z + rng.normal(size=(6, 6)) * 1e3  # light coupling
    F = rng.normal(size=(60, 6)) * 1e5 + 1j * rng.normal(size=(60, 6)) * 1e5
    x = np.asarray(linalg6.solve_cx(Cx.of(Z), Cx.of(F)).to_complex())
    expect = np.linalg.solve(Z, F[..., None])[..., 0]
    np.testing.assert_allclose(x, expect, rtol=1e-8)


def test_solve_re():
    A = rng.normal(size=(6, 6)) + 6 * np.eye(6)
    b = rng.normal(size=(6, 3))
    x = np.asarray(linalg6.solve_re(jnp.asarray(A), jnp.asarray(b)))
    np.testing.assert_allclose(x, np.linalg.solve(A, b), rtol=1e-10)


def test_cholesky():
    A = rng.normal(size=(6, 6))
    M = A @ A.T + 6 * np.eye(6)
    L = np.asarray(linalg6.cholesky(jnp.asarray(M)))
    np.testing.assert_allclose(L, np.linalg.cholesky(M), rtol=1e-10)


def test_triangular_solves():
    A = rng.normal(size=(6, 6))
    M = A @ A.T + 6 * np.eye(6)
    L = np.linalg.cholesky(M)
    b = rng.normal(size=6)
    y = np.asarray(linalg6.solve_lower(jnp.asarray(L), jnp.asarray(b)))
    np.testing.assert_allclose(y, np.linalg.solve(L, b), rtol=1e-10)
    z = np.asarray(linalg6.solve_upper(jnp.asarray(L.T), jnp.asarray(b)))
    np.testing.assert_allclose(z, np.linalg.solve(L.T, b), rtol=1e-10)


def test_eigh_jacobi():
    A = rng.normal(size=(6, 6))
    S = A + A.T
    lam, V = linalg6.eigh_jacobi(jnp.asarray(S))
    lam, V = np.asarray(lam), np.asarray(V)
    expect = np.sort(np.linalg.eigvalsh(S))
    np.testing.assert_allclose(np.sort(lam), expect, rtol=1e-9, atol=1e-9)
    # eigenvector property
    for i in range(6):
        np.testing.assert_allclose(S @ V[:, i], lam[i] * V[:, i], atol=1e-7)


def test_eigh_jacobi_batched():
    A = rng.normal(size=(10, 6, 6))
    S = A + np.swapaxes(A, -1, -2)
    lam, V = linalg6.eigh_jacobi(jnp.asarray(S))
    for i in range(10):
        np.testing.assert_allclose(
            np.sort(np.asarray(lam[i])), np.sort(np.linalg.eigvalsh(S[i])), rtol=1e-8, atol=1e-8
        )


def test_generalized_eigh_natural_freqs():
    # K x = lam M x with physical-ish scales: natural freqs of a 6-dof system
    A = rng.normal(size=(6, 6))
    M = A @ A.T + np.diag([8e6, 8e6, 8e6, 5e9, 5e9, 1e9])
    B = rng.normal(size=(6, 6)) * 1e3
    K = B @ B.T + np.diag([7e4, 7e4, 3e5, 1e9, 1e9, 1e8])
    lam, X = linalg6.generalized_eigh(jnp.asarray(K), jnp.asarray(M))
    lam = np.asarray(lam)
    import scipy.linalg as sla

    expect = np.sort(sla.eigh(K, M, eigvals_only=True))
    np.testing.assert_allclose(np.sort(lam), expect, rtol=1e-7)
    # generalized eigenvector check
    X = np.asarray(X)
    for i in range(6):
        r = K @ X[:, i] - lam[i] * (M @ X[:, i])
        assert np.linalg.norm(r) / np.linalg.norm(K @ X[:, i]) < 1e-6


def test_solve_under_jit_grad():
    A = rng.normal(size=(6, 6)) + 10 * np.eye(6)

    def loss(scale):
        Az = Cx(jnp.asarray(A) * scale, jnp.asarray(A) * 0.1)
        b = Cx(jnp.ones(6), jnp.zeros(6))
        return linalg6.solve_cx(Az, b).abs2().sum()

    g = jax.grad(loss)(1.0)
    # finite-difference check
    eps = 1e-6
    fd = (loss(1.0 + eps) - loss(1.0 - eps)) / (2 * eps)
    np.testing.assert_allclose(float(g), float(fd), rtol=1e-4)


def test_solve_cx_fused_is_bitwise_unfused():
    """``solve_cx_fused`` must be the EXACT unfused expression (explicit
    ``Z = Z0 + i w B_drag`` assembly followed by ``solve_cx``) — the
    fusion is the compiler's, not a reformulation, so the fixed-point
    drivers cannot change numerics by routing through it."""
    nw = 21
    Z0 = Cx(jnp.asarray(rng.normal(size=(nw, 6, 6)) + 8 * np.eye(6)),
            jnp.asarray(0.3 * rng.normal(size=(nw, 6, 6))))
    w = jnp.asarray(rng.uniform(0.1, 3.0, nw))
    Bd = jnp.asarray(rng.normal(size=(6, 6)))
    F = Cx(jnp.asarray(rng.normal(size=(nw, 6))),
           jnp.asarray(rng.normal(size=(nw, 6))))
    Z = Cx(Z0.re, Z0.im + w[:, None, None] * Bd[None, :, :])
    x_ref = linalg6.solve_cx(Z, F)
    x_fus = linalg6.solve_cx_fused(Z0, w, Bd, F)
    np.testing.assert_array_equal(np.asarray(x_fus.re), np.asarray(x_ref.re))
    np.testing.assert_array_equal(np.asarray(x_fus.im), np.asarray(x_ref.im))
    # and under jit (the form the drivers compile; XLA may reassociate
    # the fused elementwise ops, so eps-level rather than bitwise)
    x_jit = jax.jit(linalg6.solve_cx_fused)(Z0, w, Bd, F)
    np.testing.assert_allclose(np.asarray(x_jit.re), np.asarray(x_ref.re),
                               rtol=1e-12)


def test_solve_cx_fused_grad_matches_unfused():
    """``jax.grad`` through the fused expression equals grad through the
    explicit assembly + solve — same graph, same adjoints."""
    nw = 8
    Z0 = Cx(jnp.asarray(rng.normal(size=(nw, 6, 6)) + 8 * np.eye(6)),
            jnp.asarray(0.3 * rng.normal(size=(nw, 6, 6))))
    w = jnp.asarray(rng.uniform(0.1, 3.0, nw))
    Bd = jnp.asarray(rng.normal(size=(6, 6)))
    F = Cx(jnp.asarray(rng.normal(size=(nw, 6))),
           jnp.asarray(rng.normal(size=(nw, 6))))

    def loss_fused(Bd):
        x = linalg6.solve_cx_fused(Z0, w, Bd, F)
        return jnp.sum(x.abs2())

    def loss_unfused(Bd):
        Z = Cx(Z0.re, Z0.im + w[:, None, None] * Bd[None, :, :])
        return jnp.sum(linalg6.solve_cx(Z, F).abs2())

    g_f = jax.grad(loss_fused)(Bd)
    g_u = jax.grad(loss_unfused)(Bd)
    np.testing.assert_allclose(np.asarray(g_f), np.asarray(g_u), rtol=1e-12)
