"""Unrolled 6x6 kernels vs numpy.linalg oracle."""
import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import linalg6
from raft_tpu.core.cplx import Cx

rng = np.random.default_rng(7)


def test_solve_cx_single():
    A = rng.normal(size=(6, 6)) + 1j * rng.normal(size=(6, 6))
    b = rng.normal(size=6) + 1j * rng.normal(size=6)
    x = linalg6.solve_cx(Cx.of(A), Cx.of(b))
    np.testing.assert_allclose(np.asarray(x.to_complex()), np.linalg.solve(A, b), rtol=1e-10)


def test_solve_cx_batched():
    A = rng.normal(size=(50, 6, 6)) + 1j * rng.normal(size=(50, 6, 6))
    b = rng.normal(size=(50, 6)) + 1j * rng.normal(size=(50, 6))
    x = np.asarray(linalg6.solve_cx(Cx.of(A), Cx.of(b)).to_complex())
    expect = np.linalg.solve(A, b[..., None])[..., 0]
    np.testing.assert_allclose(x, expect, rtol=1e-8)


def test_solve_cx_needs_pivoting():
    # zero leading pivot forces a row swap
    A = np.array(
        [
            [0.0, 1.0],
            [1.0, 0.0],
        ]
    ) + 0j
    b = np.array([2.0, 3.0]) + 0j
    x = linalg6.solve_cx(Cx.of(A), Cx.of(b), n=2)
    np.testing.assert_allclose(np.asarray(x.to_complex()), [3.0, 2.0], atol=1e-12)


def test_solve_cx_impedance_like():
    # realistic RAO impedance: Z = -w^2 M + i w B + C with large magnitude spread
    M = np.diag([8e6, 8e6, 8e6, 5e9, 5e9, 1e9])
    C = np.diag([7e4, 7e4, 3e5, 1e9, 1e9, 1e8])
    B = 0.05 * np.sqrt(np.diag(M) * np.diag(C))
    ws = np.linspace(0.05, 3.0, 60)
    Z = -ws[:, None, None] ** 2 * M + 1j * ws[:, None, None] * np.diag(B) + C
    Z = Z + rng.normal(size=(6, 6)) * 1e3  # light coupling
    F = rng.normal(size=(60, 6)) * 1e5 + 1j * rng.normal(size=(60, 6)) * 1e5
    x = np.asarray(linalg6.solve_cx(Cx.of(Z), Cx.of(F)).to_complex())
    expect = np.linalg.solve(Z, F[..., None])[..., 0]
    np.testing.assert_allclose(x, expect, rtol=1e-8)


def test_solve_re():
    A = rng.normal(size=(6, 6)) + 6 * np.eye(6)
    b = rng.normal(size=(6, 3))
    x = np.asarray(linalg6.solve_re(jnp.asarray(A), jnp.asarray(b)))
    np.testing.assert_allclose(x, np.linalg.solve(A, b), rtol=1e-10)


def test_cholesky():
    A = rng.normal(size=(6, 6))
    M = A @ A.T + 6 * np.eye(6)
    L = np.asarray(linalg6.cholesky(jnp.asarray(M)))
    np.testing.assert_allclose(L, np.linalg.cholesky(M), rtol=1e-10)


def test_triangular_solves():
    A = rng.normal(size=(6, 6))
    M = A @ A.T + 6 * np.eye(6)
    L = np.linalg.cholesky(M)
    b = rng.normal(size=6)
    y = np.asarray(linalg6.solve_lower(jnp.asarray(L), jnp.asarray(b)))
    np.testing.assert_allclose(y, np.linalg.solve(L, b), rtol=1e-10)
    z = np.asarray(linalg6.solve_upper(jnp.asarray(L.T), jnp.asarray(b)))
    np.testing.assert_allclose(z, np.linalg.solve(L.T, b), rtol=1e-10)


def test_eigh_jacobi():
    A = rng.normal(size=(6, 6))
    S = A + A.T
    lam, V = linalg6.eigh_jacobi(jnp.asarray(S))
    lam, V = np.asarray(lam), np.asarray(V)
    expect = np.sort(np.linalg.eigvalsh(S))
    np.testing.assert_allclose(np.sort(lam), expect, rtol=1e-9, atol=1e-9)
    # eigenvector property
    for i in range(6):
        np.testing.assert_allclose(S @ V[:, i], lam[i] * V[:, i], atol=1e-7)


def test_eigh_jacobi_batched():
    A = rng.normal(size=(10, 6, 6))
    S = A + np.swapaxes(A, -1, -2)
    lam, V = linalg6.eigh_jacobi(jnp.asarray(S))
    for i in range(10):
        np.testing.assert_allclose(
            np.sort(np.asarray(lam[i])), np.sort(np.linalg.eigvalsh(S[i])), rtol=1e-8, atol=1e-8
        )


def test_generalized_eigh_natural_freqs():
    # K x = lam M x with physical-ish scales: natural freqs of a 6-dof system
    A = rng.normal(size=(6, 6))
    M = A @ A.T + np.diag([8e6, 8e6, 8e6, 5e9, 5e9, 1e9])
    B = rng.normal(size=(6, 6)) * 1e3
    K = B @ B.T + np.diag([7e4, 7e4, 3e5, 1e9, 1e9, 1e8])
    lam, X = linalg6.generalized_eigh(jnp.asarray(K), jnp.asarray(M))
    lam = np.asarray(lam)
    import scipy.linalg as sla

    expect = np.sort(sla.eigh(K, M, eigvals_only=True))
    np.testing.assert_allclose(np.sort(lam), expect, rtol=1e-7)
    # generalized eigenvector check
    X = np.asarray(X)
    for i in range(6):
        r = K @ X[:, i] - lam[i] * (M @ X[:, i])
        assert np.linalg.norm(r) / np.linalg.norm(K @ X[:, i]) < 1e-6


def test_solve_under_jit_grad():
    A = rng.normal(size=(6, 6)) + 10 * np.eye(6)

    def loss(scale):
        Az = Cx(jnp.asarray(A) * scale, jnp.asarray(A) * 0.1)
        b = Cx(jnp.ones(6), jnp.zeros(6))
        return linalg6.solve_cx(Az, b).abs2().sum()

    g = jax.grad(loss)(1.0)
    # finite-difference check
    eps = 1e-6
    fd = (loss(1.0 + eps) - loss(1.0 - eps)) / (2 * eps)
    np.testing.assert_allclose(float(g), float(fd), rtol=1e-4)
