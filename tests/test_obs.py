"""Observability layer: span tracing, bounded metrics, exporters.

Covers the PR-11 acceptance surface: Chrome trace schema + nesting,
exact histogram quantile math on hand-built bucket counts, bounded
buffers past the ring wrap, corruption-tolerant JSONL reads (mid-write
kill survival), the profiling back-compat shim (thread safety, scoped
sync), and the pipeline/registry instrumentation hooks.
"""
import json
import math
import os
import threading

import numpy as np
import pytest

from raft_tpu import obs
from raft_tpu.obs import export, metrics, trace


@pytest.fixture(autouse=True)
def _fresh_obs():
    obs.reset()
    yield
    obs.reset()


# ------------------------------------------------------------- spans ----

def test_span_nesting_and_rollup():
    with trace.span("outer"):
        with trace.span("inner"):
            pass
        with trace.span("inner"):
            pass
    r = trace.rollup()
    assert r["outer"]["count"] == 1
    assert r["outer/inner"]["count"] == 2
    # parent wall-clock covers its children
    assert r["outer"]["total_s"] >= r["outer/inner"]["total_s"]


def test_span_records_on_exception():
    with pytest.raises(RuntimeError):
        with trace.span("boom"):
            raise RuntimeError("x")
    assert trace.rollup()["boom"]["count"] == 1
    # the stack unwound: a later span is NOT nested under "boom"
    with trace.span("after"):
        pass
    assert "after" in trace.rollup()


def test_span_thread_safety_separate_stacks():
    """Two threads nesting concurrently must never see each other's
    stack (the module-global-list bug the span API replaces)."""
    n, reps = 4, 200
    start = threading.Barrier(n)

    def worker(i):
        start.wait()
        for _ in range(reps):
            with trace.span(f"t{i}"):
                with trace.span("leaf"):
                    pass

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    r = trace.rollup()
    for i in range(n):
        assert r[f"t{i}"]["count"] == reps
        assert r[f"t{i}/leaf"]["count"] == reps
    # no cross-thread contamination: every name is one of the expected
    assert set(r) == {f"t{i}" for i in range(n)} | {
        f"t{i}/leaf" for i in range(n)}


def test_span_ring_bounded_rollup_exact():
    """The ordered span log is a bounded ring; the roll-up counts stay
    exact past the wrap (the compile_events / compile_count contract)."""
    n = trace._SPANS_MAX + 50
    for _ in range(n):
        trace.record("wrap", 0, 1000)
    assert len(trace.spans()) == trace._SPANS_MAX
    assert trace.rollup()["wrap"]["count"] == n


def test_rollup_name_cap_overflows_to_other():
    for i in range(trace._AGG_MAX + 7):
        trace.record(f"name{i}", 0, 1000)
    r = trace.rollup()
    assert len(r) == trace._AGG_MAX + 1          # cap + "<other>"
    assert r[trace._OVERFLOW]["count"] == 7


def test_chrome_trace_schema():
    with trace.span("a", attrs={"k": 3}):
        with trace.span("b"):
            pass
    doc = trace.chrome_trace()
    evs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert len(evs) == 2
    # one thread_name metadata event names the recording track
    assert len(meta) == 1 and meta[0]["name"] == "thread_name"
    for ev in evs:
        for field in ("ts", "dur", "pid", "tid"):
            assert isinstance(ev[field], int) and ev[field] >= 0
        assert ev["cat"] == "raft_tpu"
    names = {ev["name"] for ev in evs}
    assert names == {"a", "b"}
    paths = {ev["args"]["path"] for ev in evs}
    assert paths == {"a", "a/b"}
    assert [ev for ev in evs if ev["name"] == "a"][0]["args"]["k"] == 3
    json.dumps(doc)                               # JSON-serializable


def test_chrome_trace_nesting_consistent():
    """Children lie within their parent's [ts, ts+dur] on the same tid —
    the containment property Perfetto's slice nesting renders."""
    with trace.span("p"):
        with trace.span("c1"):
            pass
        with trace.span("c2"):
            pass
    evs = [e for e in trace.chrome_trace()["traceEvents"]
           if e["ph"] == "X"]
    by = {ev["args"]["path"]: ev for ev in evs}
    p = by["p"]
    for path in ("p/c1", "p/c2"):
        c = by[path]
        assert c["tid"] == p["tid"]
        assert p["ts"] <= c["ts"]
        assert c["ts"] + c["dur"] <= p["ts"] + p["dur"]


def test_chrome_trace_containment_survives_subus_rounding():
    """dur_us derives from FLOORED endpoints, not an independently-floored
    (t1-t0): a child whose ns interval lies inside its parent's must stay
    inside in integer µs (the pair below violated containment under the
    old arithmetic: parent [999, 2000]ns rounded to [0, 1]µs while its
    child [1000, 2000]ns rounded to [1, 2]µs)."""
    e = trace._EPOCH_NS
    trace.record("p", e + 999, e + 2000, depth=0)
    trace.record("p/c", e + 1000, e + 2000, depth=1)
    by = {s.name: s for s in trace.spans()}
    p, c = by["p"], by["p/c"]
    assert p.t0_us <= c.t0_us
    assert c.t0_us + c.dur_us <= p.t0_us + p.dur_us


# ---------------------------------------------- trace context / trees ----

def test_new_trace_id_unique_and_deterministic_shape():
    ids = [trace.new_trace_id() for _ in range(100)]
    assert len(set(ids)) == 100
    assert all(i.startswith(f"{os.getpid():x}-") for i in ids)


def test_trace_context_crosses_threads():
    """The cross-thread span-tree primitive: a context token captured
    on one thread, adopted on another — spans on BOTH threads share one
    trace id and nest under one path."""
    tid = trace.new_trace_id()
    tok = trace.TraceContext(trace=tid, path="request/server")

    def worker():
        with trace.context(tok):
            assert trace.current_trace() == tid
            assert trace.current_path() == "request/server"
            with trace.span("stage"):
                pass
        # context restored: this thread is traceless again
        assert trace.current_trace() == ""

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    with trace.context(tok):
        with trace.span("solve"):
            pass
    spans = [s for s in trace.spans() if s.trace == tid]
    assert {s.name for s in spans} == {"request/server/stage",
                                       "request/server/solve"}
    # two different recording threads, one trace id
    assert len({s.tid for s in spans}) == 2


def test_record_explicit_trace_tid_track_and_metadata():
    """Explicit-endpoint spans on synthetic tracks (the serve solver
    loop's queue_wait/solve emission): trace id carried, track named by
    a thread_name metadata event, args.trace exported."""
    tid = trace.new_trace_id()
    stid = trace.synthetic_tid(tid + "#0")
    assert stid == trace.synthetic_tid(tid + "#0")    # stable
    trace.record("request/server/queue_wait", 1000, 5000, depth=2,
                 trace=tid, tid=stid, track="req r7 lane 0")
    with trace.span("plain"):
        pass
    doc = trace.chrome_trace()
    meta = {e["tid"]: e["args"]["name"]
            for e in doc["traceEvents"] if e["ph"] == "M"}
    evs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    qw = [e for e in evs if e["name"] == "queue_wait"][0]
    assert qw["tid"] == stid and qw["args"]["trace"] == tid
    assert meta[stid] == "req r7 lane 0"
    # the real thread's track is named after the Python thread
    plain = [e for e in evs if e["name"] == "plain"][0]
    assert meta[plain["tid"]] == threading.current_thread().name
    json.dumps(doc)


def test_jsonl_carries_trace_and_track(tmp_path):
    tid = trace.new_trace_id()
    trace.record("request/server/solve", 0, 2000, trace=tid,
                 tid=trace.synthetic_tid(tid), track="req x")
    paths = export.publish("t", directory=str(tmp_path))
    events, corrupt = export.read_jsonl(paths["jsonl"])
    assert corrupt == 0
    sp = [e for e in events if e.get("type") == "span"
          and e.get("trace") == tid]
    assert sp and sp[0]["track"] == "req x"


# ----------------------------------------------------------- metrics ----

def test_histogram_quantiles_exact_on_hand_built_counts():
    """Deterministic quantile math: rank-walk to the bucket UPPER edge,
    verified against hand-placed observations in known buckets."""
    h = metrics.histogram("q_s")
    edges = metrics.Histogram.edges
    # 10 observations: 5 in the bucket ending at edges[10], 4 ending at
    # edges[20], 1 ending at edges[30] (observe just below each edge)
    for _ in range(5):
        h.observe(edges[10] * 0.999)
    for _ in range(4):
        h.observe(edges[20] * 0.999)
    h.observe(edges[30] * 0.999)
    # total 10: p50 -> rank 5 -> first bucket; p90 -> rank 9 -> second;
    # p99 -> rank 10 -> third
    assert h.quantile(0.50) == edges[10]
    assert h.quantile(0.90) == edges[20]
    assert h.quantile(0.99) == edges[30]
    assert h.total == 10
    assert h.quantile(0.0) == edges[10]           # rank clamps to 1


def test_histogram_under_and_overflow_saturate():
    h = metrics.histogram("sat_s")
    h.observe(0.0)                                # at/below lowest edge
    h.observe(1e12)                               # beyond top edge
    edges = metrics.Histogram.edges
    assert h.quantile(0.5) == edges[0]
    assert h.quantile(1.0) == edges[-1]           # saturates, never inf
    d = h.to_dict()
    assert d["buckets"][-1][0] == "+Inf"
    assert all(math.isfinite(d[q]) for q in ("p50", "p90", "p99"))
    json.dumps(d)


def test_histogram_ignores_nonfinite():
    h = metrics.histogram("nan_s")
    h.observe(float("nan"))
    h.observe(float("inf"))
    assert h.total == 0 and h.quantile(0.5) == 0.0


def test_counter_and_gauge():
    c = metrics.counter("events")
    c.inc()
    c.inc(4)
    metrics.gauge("level").set(0.75)
    snap = metrics.snapshot()
    assert snap["counters"]["events"] == 5
    assert snap["gauges"]["level"] == 0.75


def test_metric_kind_collision_raises():
    metrics.counter("dual")
    with pytest.raises(ValueError, match="already registered"):
        metrics.gauge("dual")


def test_metric_registry_bounded():
    """Past the name cap, registrations degrade to a shared overflow
    instance and are counted — memory stays bounded."""
    for i in range(metrics._MAX_METRICS):
        metrics.counter(f"c{i}")
    extra = metrics.counter("one_too_many")
    extra2 = metrics.counter("two_too_many")
    assert extra is extra2                        # shared overflow
    extra.inc()
    snap = metrics.snapshot()
    assert snap["dropped_names"] == 2
    assert len(snap["counters"]) <= metrics._MAX_METRICS + 3


def test_snapshot_json_safe():
    metrics.counter("a").inc()
    metrics.gauge("b").set(1e-9)
    metrics.histogram("c_s").observe(1e9)         # overflow bucket
    json.dumps(metrics.snapshot())                # strict JSON, no Infinity


# ------------------------------------------------- sliding SLO windows ----

def test_sliding_histogram_hand_computable_schedule():
    """The live-SLO determinism pin: a hand-built observation schedule
    on a virtual clock yields exactly hand-computable windowed
    quantiles (rank-walk to the bucket upper edge, same rule as the
    cumulative histogram)."""
    edges = metrics.Histogram.edges
    w = metrics.SlidingHistogram("slo", window_s=60.0, n_sub=12)
    # 10 observations in one sub-window: 5 under edges[10], 4 under
    # edges[20], 1 under edges[30] — the cumulative-histogram fixture
    for _ in range(5):
        w.observe(edges[10] * 0.999, now=1.0)
    for _ in range(4):
        w.observe(edges[20] * 0.999, now=2.0)
    w.observe(edges[30] * 0.999, now=3.0)
    snap = w.window(now=3.0)
    assert snap["count"] == 10
    assert snap["p50"] == pytest.approx(edges[10])
    assert snap["p90"] == pytest.approx(edges[20])
    assert snap["p99"] == pytest.approx(edges[30])
    assert snap["errors"] == 0 and snap["error_rate"] == 0.0


def test_sliding_histogram_ages_out_and_error_rate():
    w = metrics.SlidingHistogram("slo2", window_s=12.0, n_sub=4)
    w.observe(0.01, now=0.0)       # sub-window 0 (3 s each)
    w.error(now=4.0)               # sub-window 1
    w.observe(0.02, now=7.0)       # sub-window 2
    snap = w.window(now=7.0)
    assert snap["count"] == 2 and snap["errors"] == 1
    assert snap["error_rate"] == pytest.approx(1 / 3)
    # at t=12.5 sub-window 0 has aged out of the 4-slot ring; 1 and 2
    # are still live
    snap2 = w.window(now=12.5)
    assert snap2["count"] == 1 and snap2["errors"] == 1
    # far future: everything aged out, slots lazily recycled
    snap3 = w.window(now=1000.0)
    assert snap3 == metrics.SlidingHistogram("slo3",
                                             window_s=12.0,
                                             n_sub=4).window(now=1000.0)
    # and a fresh observation after the gap starts a clean window
    w.observe(0.5, now=1000.0)
    assert w.window(now=1000.0)["count"] == 1


def test_sliding_registry_and_snapshot():
    w = metrics.sliding("serve.lat", window_s=30.0, n_sub=6)
    assert metrics.sliding("serve.lat") is w
    with pytest.raises(ValueError, match="already registered"):
        metrics.counter("serve.lat")
    w.observe(0.05)
    snap = metrics.snapshot()
    assert snap["sliding"]["serve.lat"]["count"] == 1
    json.dumps(snap)


# ----------------------------------------------------- flight recorder ----

def test_flight_recorder_bounded_counts_and_dump(tmp_path):
    from raft_tpu.obs.flight import FlightRecorder

    fr = FlightRecorder(capacity=4)
    for i in range(9):
        fr.record({"id": f"r{i}", "op": "solve",
                   "outcome": "ok" if i % 3 else "error:RuntimeError"})
    c = fr.counts()
    assert c == {"capacity": 4, "size": 4, "recorded": 9, "errors": 3}
    assert [r["id"] for r in fr.snapshot()] == ["r5", "r6", "r7", "r8"]
    path = fr.dump(path=str(tmp_path / "fl.jsonl"), reason="test")
    events, corrupt = export.read_jsonl(path)
    assert corrupt == 0
    assert events[0]["type"] == "meta" and events[0]["reason"] == "test"
    assert events[0]["recorded"] == 9
    assert [e["id"] for e in events[1:]] == ["r5", "r6", "r7", "r8"]
    # no tmp droppings (atomic publish)
    assert not [p for p in os.listdir(tmp_path) if p.endswith(".tmp")]


def test_flight_recorder_dump_unarmed_returns_none(monkeypatch):
    from raft_tpu.obs.flight import FlightRecorder

    monkeypatch.delenv("RAFT_TPU_OBS", raising=False)
    fr = FlightRecorder()
    fr.record({"id": "x"})
    assert fr.dump() is None        # no sink, nowhere durable to land


# --------------------------------------------------- performance ledger ----

class _FakeCompiled:
    """Stands in for a resolved AOT executable: the two compiler
    accounting calls the ledger joins with measured time."""

    def __init__(self, flops=1.0e9, byts=2.0e8):
        self._flops, self._bytes = flops, byts

    def cost_analysis(self):
        return [{"flops": self._flops, "bytes accessed": self._bytes}]

    def memory_analysis(self):
        return None


@pytest.fixture()
def _ledger_cache(tmp_path):
    from raft_tpu import cache
    from raft_tpu.obs import ledger

    cache.enable(str(tmp_path / "c"))
    ledger.reset()
    ledger._reset_peak_cache()
    yield ledger
    ledger.reset()
    ledger._reset_peak_cache()
    cache.disable()


def test_ledger_record_flush_merge_and_roofline(_ledger_cache):
    ledger = _ledger_cache
    exe = _FakeCompiled()
    assert ledger.record("sweep_designs", "16x64x32", exe, 0.010)
    assert ledger.record("sweep_designs", "16x64x32", exe, 0.005)
    paths = ledger.flush()
    assert len(paths) == 1 and os.path.exists(paths[0])
    rec = json.load(open(paths[0]))
    assert rec["count"] == 2 and rec["best_s"] == 0.005
    # achieved FLOP/s from the BEST observation: 1e9 / 0.005
    assert rec["achieved_flops_per_s"] == pytest.approx(2.0e11, rel=1e-3)
    # roofline: intensity 5 flop/B -> attainable = min(1e11, 5 * 5e10)
    # = 1e11 -> fraction = 2e11 / 1e11 (synthetic: > 1 is fine, finite)
    assert math.isfinite(rec["roofline_fraction"])
    assert rec["peak"]["source"].startswith("builtin:")
    # a second flush MERGES (count sums, best min) instead of forking
    ledger.record("sweep_designs", "16x64x32", exe, 0.020)
    assert ledger.flush() == paths
    rec2 = json.load(open(paths[0]))
    assert rec2["count"] == 3 and rec2["best_s"] == 0.005
    # summary + entries read it back
    ents = ledger.entries()
    assert len(ents) == 1 and ents[0]["bucket"] == "16x64x32"
    assert ledger.summary()["n_entries"] == 1
    # the lightweight stats-op form parses nothing but agrees on counts
    assert ledger.stat() == {"dir": ledger.root(), "pending": 0,
                             "n_entries": 1}


def test_ledger_distinct_buckets_distinct_files(_ledger_cache):
    ledger = _ledger_cache
    exe = _FakeCompiled()
    ledger.record("sweep_designs", "16x64x32", exe, 0.01)
    ledger.record("sweep_designs", "48x128x32", exe, 0.02)
    assert len(ledger.flush()) == 2
    assert {e["bucket"] for e in ledger.entries()} == {"16x64x32",
                                                       "48x128x32"}


def test_ledger_noop_without_cache_or_cost():
    from raft_tpu import cache
    from raft_tpu.obs import ledger

    cache.disable()
    # a plain jitted function has no artifact identity: nothing recorded
    assert ledger.record("t", "b", lambda x: x, 0.01) is None
    ledger.record("t", "b", _FakeCompiled(), 0.01)
    # pending exists, but with the cache off there is nowhere durable
    assert ledger.root() is None and ledger.flush() == []
    ledger.reset()


def test_ledger_peak_env_override(_ledger_cache, monkeypatch):
    ledger = _ledger_cache
    monkeypatch.setenv("RAFT_TPU_ROOFLINE", "1e12:1e11")
    ledger._reset_peak_cache()
    ledger.record("sweep_designs", "16x64x32", _FakeCompiled(), 0.010)
    rec = json.load(open(ledger.flush()[0]))
    assert rec["peak"] == {"flops_per_s": 1e12, "bytes_per_s": 1e11,
                           "source": "env"}
    # snapshot-once: a mid-process env change does not reach the model
    monkeypatch.setenv("RAFT_TPU_ROOFLINE", "5e12:5e11")
    ledger.record("sweep_designs", "16x64x32", _FakeCompiled(), 0.001)
    rec2 = json.load(open(ledger.flush()[0]))
    assert rec2["peak"]["flops_per_s"] == 1e12


# --------------------------------------------------------- exporters ----

def test_prometheus_text_cumulative_buckets():
    metrics.counter("hits").inc(3)
    h = metrics.histogram("lat_s")
    for v in (1e-4, 1e-4, 0.2):
        h.observe(v)
    text = export.prometheus_text()
    assert "# TYPE raft_tpu_hits counter" in text
    assert "raft_tpu_hits 3" in text
    assert "# TYPE raft_tpu_lat_s histogram" in text
    assert 'raft_tpu_lat_s_bucket{le="+Inf"} 3' in text
    assert "raft_tpu_lat_s_count 3" in text
    # cumulative: every bucket line's value is non-decreasing
    vals = [int(line.rsplit(" ", 1)[1]) for line in text.splitlines()
            if line.startswith("raft_tpu_lat_s_bucket")]
    assert vals == sorted(vals)


def test_publish_atomic_and_loadable(tmp_path):
    with trace.span("phase"):
        metrics.counter("n").inc()
    paths = export.publish("t", directory=str(tmp_path))
    # atomic publish leaves no tmp droppings
    assert not [p for p in os.listdir(tmp_path) if p.endswith(".tmp")]
    events, corrupt = export.read_jsonl(paths["jsonl"])
    assert corrupt == 0
    kinds = [e["type"] for e in events]
    assert kinds[0] == "meta" and "span" in kinds and kinds[-1] == "metrics"
    with open(paths["chrome_trace"]) as f:
        assert json.load(f)["traceEvents"]
    assert os.path.getsize(paths["prom"]) > 0


def test_publish_disabled_is_noop(tmp_path, monkeypatch):
    monkeypatch.delenv("RAFT_TPU_OBS", raising=False)
    assert not export.enabled()
    assert export.maybe_publish("x") is None
    monkeypatch.setenv("RAFT_TPU_OBS", "off")
    assert not export.enabled()
    with pytest.raises(RuntimeError, match="not armed"):
        export.publish("x")


def test_env_arming_resolves_directory(tmp_path, monkeypatch):
    monkeypatch.setenv("RAFT_TPU_OBS", str(tmp_path / "sink"))
    assert export.enabled()
    with trace.span("s"):
        pass
    paths = export.maybe_publish("armed")
    assert paths and os.path.dirname(paths["jsonl"]) == str(tmp_path / "sink")


def test_maybe_publish_debounced(tmp_path, monkeypatch):
    """Per-sweep auto-publish amortizes: within the monotonic debounce
    interval a second maybe_publish is skipped (and counted); force and
    a fresh interval always write.  The knob snapshots once."""
    monkeypatch.setenv("RAFT_TPU_OBS", str(tmp_path))
    monkeypatch.setenv("RAFT_TPU_OBS_FLUSH_MS", "60000")
    export._reset_debounce()
    assert export.flush_interval_s() == 60.0
    # snapshot-once: a mid-process env change does not reach the knob
    monkeypatch.setenv("RAFT_TPU_OBS_FLUSH_MS", "1")
    assert export.flush_interval_s() == 60.0
    with trace.span("x"):
        pass
    assert export.maybe_publish("deb") is not None      # first: writes
    assert export.maybe_publish("deb") is None          # debounced
    assert export.maybe_publish("deb") is None
    assert metrics.snapshot()["counters"]["obs.publish_skipped"] == 2
    assert export.maybe_publish("deb", force=True) is not None
    # obs.reset() clears the stamp: the next auto-publish writes again
    obs.reset()
    monkeypatch.setenv("RAFT_TPU_OBS_FLUSH_MS", "60000")
    assert export.maybe_publish("deb") is not None


def test_read_jsonl_tolerates_midwrite_kill(tmp_path):
    """A log truncated mid-line (non-atomic foreign writer killed) keeps
    its valid prefix loadable — the ChunkStore corruption rule."""
    p = tmp_path / "log.jsonl"
    good = [json.dumps({"type": "span", "name": "a"}),
            json.dumps({"type": "span", "name": "b"})]
    # a torn tail: half a JSON object, then binary garbage
    p.write_text("\n".join(good) + "\n" + '{"type": "spa' + "\n\x00\x01\n")
    events, corrupt = export.read_jsonl(str(p))
    assert [e["name"] for e in events] == ["a", "b"]
    assert corrupt == 2


def test_obs_block_shape_and_json():
    with trace.span("roll"):
        pass
    metrics.counter("k").inc()
    metrics.histogram("h_s").observe(0.01)
    block = export.obs_block()
    assert block["spans"]["roll"]["count"] == 1
    assert block["counters"]["k"] == 1
    assert {"p50", "p90", "p99", "count"} <= set(block["histograms"]["h_s"])
    assert isinstance(block["compiles"], dict)
    json.dumps(block)


# ---------------------------------------------- profiling shim (compat) ----

def test_profiling_shim_totals_and_summary():
    from raft_tpu.utils import profiling as prof

    prof.reset()
    with prof.phase("alpha", sync=False):
        with prof.phase("beta", sync=False):
            pass
    t = prof.totals()
    assert set(t) == {"alpha", "alpha/beta"}
    assert "alpha/beta" in prof.summary()
    prof.reset()
    assert prof.totals() == {}


def test_profiling_shim_feeds_spans():
    """Every prof.phase call site now lands in the Chrome trace for
    free — the migration's point."""
    from raft_tpu.utils import profiling as prof

    with prof.phase("migrated", sync=False):
        pass
    assert any(s.name == "migrated" for s in trace.spans())


def test_profiling_phase_sync_is_scoped():
    """The exit sync waits only on arrays produced INSIDE the block —
    the all-live-arrays blast radius is gone (daemon-bound fix)."""
    import jax.numpy as jnp

    from raft_tpu.utils import profiling as prof

    pre = jnp.arange(8.0) * 2          # live before the phase
    with prof.phase("scoped"):
        inside = jnp.ones(4) + 1
    # functional check: results correct, phase recorded, both arrays fine
    assert float(inside.sum()) == 8.0
    assert float(pre[1]) == 2.0
    assert trace.rollup()["scoped"]["count"] == 1
    # the delta helper really excludes pre-existing arrays
    before = prof._live_ids()
    assert id(pre) in before


def test_profiling_threaded_phases_do_not_cross():
    from raft_tpu.utils import profiling as prof

    barrier = threading.Barrier(2)

    def run(tag):
        barrier.wait()
        for _ in range(50):
            with prof.phase(tag, sync=False):
                pass

    a = threading.Thread(target=run, args=("ta",))
    b = threading.Thread(target=run, args=("tb",))
    a.start(); b.start(); a.join(); b.join()
    t = prof.totals()
    assert set(t) == {"ta", "tb"}      # never "ta/tb" or "tb/ta"


# ------------------------------------------------- instrumentation ----

def test_pipeline_feeds_spans_and_metrics():
    from raft_tpu.parallel.pipeline import run_pipelined

    results, stats = run_pipelined(
        lambda x: x * 2, [1, 2, 3],
        stage=lambda k: np.asarray(float(k)),
        fetch=lambda o: float(o), depth=2)
    assert results == [2.0, 4.0, 6.0]
    snap = metrics.snapshot()
    assert snap["histograms"]["pipeline.stage_s"]["count"] == 3
    assert snap["histograms"]["pipeline.fetch_s"]["count"] == 3
    assert snap["histograms"]["pipeline.dispatch_s"]["count"] == 3
    assert snap["counters"]["pipeline.chunks_computed"] == 3
    assert "pipeline.overlap_fraction" in snap["gauges"]
    paths = {s.name for s in trace.spans()}
    assert {"pipeline/stage", "pipeline/dispatch", "pipeline/fetch"} <= paths


def test_cache_stats_mirror_into_registry():
    from raft_tpu.cache import stats as cstats

    cstats.record("aot", "mem_hit")
    cstats.record("aot", "mem_hit")
    cstats.record("staging", "miss")
    snap = metrics.snapshot()
    assert snap["counters"]["cache.aot.mem_hit"] == 2
    assert snap["counters"]["cache.staging.miss"] == 1


@pytest.mark.slow
def test_sweep_designs_emits_bucket_histograms(tmp_path, monkeypatch):
    """End-to-end (single design, tiny grid): a sweep_designs run with
    RAFT_TPU_OBS armed publishes a loadable trace + per-bucket dispatch
    histogram with quantiles.  The cross-process mixed-stream proof is
    ``make obs-smoke``."""
    from raft_tpu.parallel.sweep import sweep_designs

    monkeypatch.setenv("RAFT_TPU_OBS", str(tmp_path))
    pkg = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "raft_tpu")
    out = sweep_designs([os.path.join(pkg, "designs", "OC3spar.yaml")],
                        nw=12, n_iter=4, return_xi=False)
    snap = metrics.snapshot()
    names = [k for k in snap["histograms"]
             if k.startswith("sweep_designs.dispatch_s[")]
    assert len(names) == out["buckets"]["n_buckets"] == 1
    h = snap["histograms"][names[0]]
    assert h["count"] >= 1 and h["p50"] > 0 and h["p99"] >= h["p50"]
    assert snap["gauges"]["sweep_designs.solves_per_s"] > 0
    # the armed sweep published its sinks
    files = os.listdir(tmp_path)
    assert any(f.startswith("obs-sweep_designs") for f in files)
    assert any(f.startswith("trace-sweep_designs") for f in files)
    assert any(s.name.endswith("sweep_designs/bucket")
               for s in trace.spans())
