"""Capytaine NetCDF ingestion tests.

Implements the reference's documented test contract
(/root/reference/tests/test_capytaine_integration.py:10-78): shape checks,
dtype, out-of-range ValueError, and 1e-12 golden regression against the
committed reference datasets when the reference tree is mounted; plus a
mount-independent round trip through a synthetic dataset written with the
same classic-NetCDF layout.
"""
import os
import warnings

import numpy as np
import pytest

from raft_tpu.hydro.capy import load_capytaine_nc, read_capy_nc

REF = "/root/reference/tests"
NC = os.path.join(REF, "test_data", "mesh_converge_0.750_1.250.nc")
GOLD = os.path.join(REF, "ref_data", "capytaine_integration")

needs_ref = pytest.mark.skipif(not os.path.exists(NC),
                               reason="reference data not mounted")


def _write_synthetic_nc(path, w, A, B, D, FK):
    """Minimal Capytaine-layout classic-NetCDF writer (fixture helper)."""
    from scipy.io import netcdf_file

    nw = len(w)
    dofs = ["Surge", "Sway", "Heave", "Roll", "Pitch", "Yaw"]
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        f = netcdf_file(path, "w")
        f.createDimension("omega", nw)
        f.createDimension("radiating_dof", 6)
        f.createDimension("influenced_dof", 6)
        f.createDimension("wave_direction", 1)
        f.createDimension("complex", 2)
        f.createDimension("string5", 5)
        v = f.createVariable("omega", "d", ("omega",)); v[:] = w
        for name in ("radiating_dof", "influenced_dof"):
            v = f.createVariable(name, "c", (name, "string5"))
            for i, d in enumerate(dofs):
                v[i] = np.frombuffer(d.ljust(5)[:5].encode(), dtype="S1")
        v = f.createVariable("added_mass", "d",
                             ("omega", "radiating_dof", "influenced_dof"))
        v[:] = A.transpose(2, 0, 1)
        v = f.createVariable("radiation_damping", "d",
                             ("omega", "radiating_dof", "influenced_dof"))
        v[:] = B.transpose(2, 0, 1)
        for name, arr in (("diffraction_force", D), ("Froude_Krylov_force", FK)):
            v = f.createVariable(
                name, "d", ("complex", "omega", "wave_direction", "influenced_dof")
            )
            v[0] = arr.real.T[:, None, :]
            v[1] = arr.imag.T[:, None, :]
        f.close()


@pytest.fixture(scope="module")
def synth(tmp_path_factory):
    rng = np.random.default_rng(7)
    w = np.linspace(0.2, 2.5, 12)
    A = rng.normal(size=(6, 6, 12))
    B = rng.normal(size=(6, 6, 12))
    D = rng.normal(size=(6, 12)) + 1j * rng.normal(size=(6, 12))
    FK = rng.normal(size=(6, 12)) + 1j * rng.normal(size=(6, 12))
    path = str(tmp_path_factory.mktemp("capy") / "synth.nc")
    _write_synthetic_nc(path, w, A, B, D, FK)
    return path, w, A, B, D, FK


def test_synthetic_roundtrip(synth):
    path, w, A, B, D, FK = synth
    w2, A2, B2, F2 = read_capy_nc(path)
    np.testing.assert_allclose(w2, w, atol=1e-14)
    np.testing.assert_allclose(A2, A, atol=1e-14)
    np.testing.assert_allclose(B2, B, atol=1e-14)
    np.testing.assert_allclose(F2, D + FK, atol=1e-14)
    _, _, _, Fd = read_capy_nc(path, include_froude_krylov=False)
    np.testing.assert_allclose(Fd, D, atol=1e-14)


def test_synthetic_interp_and_range(synth):
    path, w, A, *_ = synth
    wD = np.linspace(0.3, 2.4, 40)
    wo, Ai, Bi, Fi = read_capy_nc(path, wDes=wD)
    assert Ai.shape == (6, 6, 40) and Fi.shape == (6, 40)
    assert Fi.dtype == np.complex128
    with pytest.raises(ValueError):
        read_capy_nc(path, wDes=np.arange(0.01, 3, 0.01))


@needs_ref
def test_reference_shapes_and_dtype():
    w, A, B, F = read_capy_nc(NC)
    assert len(w) == 28
    assert A.shape == (6, 6, 28)
    assert B.shape == (6, 6, 28)
    assert F.shape == (6, 28)
    assert F.dtype == "complex128"


@needs_ref
def test_reference_golden_1e12():
    w, A, B, F = read_capy_nc(NC, include_froude_krylov=False)
    gold = lambda n: np.loadtxt(os.path.join(GOLD, n))
    assert np.abs(gold("wCapy-addedMass-surge.txt")[:, 1] - A[0, 0]).max() < 1e-12
    assert np.abs(gold("wCapy-damping-surge.txt")[:, 1] - B[0, 0]).max() < 1e-12
    assert np.abs(gold("wCapy-fExcitationReal-surge.txt")[:, 1] - F[0].real).max() < 1e-12
    assert np.abs(gold("wCapy-fExcitationImag-surge.txt")[:, 1] - F[0].imag).max() < 1e-12


@needs_ref
def test_reference_golden_interp_1e12():
    wD = np.arange(0.1, 2.8, 0.01)
    _, A, B, F = read_capy_nc(NC, wDes=wD, include_froude_krylov=False)
    gold = lambda n: np.loadtxt(os.path.join(GOLD, n))
    assert np.abs(gold("wDes-addedMassInterp-surge.txt")[:, 1] - A[0, 0]).max() < 1e-12
    assert np.abs(gold("wDes-dampingInterp-surge.txt")[:, 1] - B[0, 0]).max() < 1e-12
    assert np.abs(gold("wDes-fExcitationInterpReal-surge.txt")[:, 1] - F[0].real).max() < 1e-12
    assert np.abs(gold("wDes-fExcitationInterpImag-surge.txt")[:, 1] - F[0].imag).max() < 1e-12


@needs_ref
@pytest.mark.slow
def test_capy_coeffs_feed_model():
    """End-to-end: capytaine dataset -> Model(BEM=...) solve."""
    from raft_tpu.model import Model, load_design

    w = np.linspace(0.3, 2.5, 20)
    A, B, F = load_capytaine_nc(NC, w_grid=w)
    m = Model(load_design("raft_tpu/designs/OC3spar.yaml"), w=w, BEM=(A, B, F))
    m.setEnv(Hs=6.0, Tp=10.0)
    m.calcSystemProps()
    m.calcMooringAndOffsets()
    m.solveDynamics()
    assert m.results["response"]["converged"]
    assert np.isfinite(m.results["response"]["RAO magnitude"]).all()
