"""One-command local mirror of the driver's round artifacts.

``python -m raft_tpu.evidence`` runs, in order:

1. the fast test tier (``pytest -m "not slow"``),
2. graftlint (``python -m raft_tpu.lint --audit``: static rules vs the
   committed baseline + the trace-audit budgets over every registered
   entry point + the compiled-artifact budget gate vs
   ``lint/budgets.json``, surfaced as the ``lint.budgets`` block),
3. the serve smoke (``python -m raft_tpu.serve smoke``: the resident
   daemon's cross-process compile-collapse + kill/warm-restart proof),
4. the fleet smoke (``python -m raft_tpu.serve fleet-smoke``: supervised
   replicas behind the failover router — kill mid-stream with zero
   lost/duplicated answers and bit-identical rows, warm zero-compile
   restart, deterministic typed load shedding),
5. the multi-chip dry run (``__graft_entry__.dryrun_multichip(8)``) in a
   fresh subprocess under the same kind of wall-clock budget the driver
   applies,
6. ``bench.py`` (device if reachable, labeled CPU fallback otherwise),

and writes ``EVIDENCE.json`` at the repo root with one entry per artifact
(ok flag, rc, wall-clock, output tail).  Purpose: "passes locally but red
in the driver" cannot go unnoticed — if this script's JSON is green, the
driver's ``MULTICHIP_r*.json`` / ``BENCH_r*.json`` should be green too,
because each step runs in the same fresh-subprocess regime the driver
uses (no shared jax state with the invoking process).

Knobs (env): ``RAFT_EVIDENCE_SKIP_TESTS=1`` to skip the test tier,
``RAFT_EVIDENCE_LINT_TIMEOUT`` (s, default 600),
``RAFT_EVIDENCE_DRYRUN_TIMEOUT`` (s, default 300),
``RAFT_EVIDENCE_FLEET_TIMEOUT`` (s, default 600),
``RAFT_EVIDENCE_BENCH_TIMEOUT`` (s, default 1800).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(cmd, timeout, label):
    """Run cmd fresh-subprocess; return the artifact-shaped dict."""
    t0 = time.perf_counter()
    try:
        r = subprocess.run(
            cmd, cwd=REPO, capture_output=True, text=True, timeout=timeout,
        )
        rc, out, stdout = r.returncode, (r.stdout + r.stderr), r.stdout
    except subprocess.TimeoutExpired as e:
        rc = 124
        stdout = (e.stdout or b"").decode(errors="replace")
        out = stdout + (e.stderr or b"").decode(errors="replace")
    dt = time.perf_counter() - t0
    tail = out.strip().splitlines()[-12:]
    print(f"[evidence] {label}: rc={rc} in {dt:.1f}s", flush=True)
    return {"ok": rc == 0, "rc": rc, "elapsed_s": round(dt, 1), "tail": tail,
            # stderr spam must never bury the one-line JSON artifact, so
            # stdout's own tail rides along for the parse step
            "stdout_tail": stdout.strip().splitlines()[-3:]}


def main():
    evidence = {"host": os.uname().nodename, "python": sys.version.split()[0]}

    if not os.environ.get("RAFT_EVIDENCE_SKIP_TESTS"):
        print("[evidence] fast test tier (-m 'not slow') ...", flush=True)
        evidence["tests_fast"] = _run(
            [sys.executable, "-m", "pytest", "tests/", "-q", "-m", "not slow",
             "-p", "no:cacheprovider"],
            timeout=1800, label="tests_fast",
        )

    print("[evidence] graftlint (static + trace audit) ...", flush=True)
    lint = _run(
        [sys.executable, "-m", "raft_tpu.lint", "--audit", "--json"],
        timeout=float(os.environ.get("RAFT_EVIDENCE_LINT_TIMEOUT", "600")),
        label="lint",
    )
    # the CLI's --json line is the last stdout line; embed it when present
    for line in reversed(lint.pop("stdout_tail", [])):
        try:
            lint["json"] = json.loads(line)
            break
        except json.JSONDecodeError:
            continue
    # compiled-artifact budget gate (per-entry cost/memory metrics +
    # pass/fail vs lint/budgets.json): one key deep in the round
    # artifact, so an ahead-of-time perf regression is never buried
    bj = (lint.get("json") or {}).get("budgets")
    if bj is not None:
        lint["budgets"] = bj
    # concurrency-contract summary (GL301-GL303 new/triaged counts): the
    # daemon-readiness gate rides one key deep in the round artifact too
    gj = (lint.get("json") or {}).get("gl3xx")
    if gj is not None:
        lint["gl3xx"] = gj
    # SPMD-contract summary (GL401-GL404 new/triaged counts): the
    # pod-readiness gate, same one-key-deep treatment
    g4 = (lint.get("json") or {}).get("gl4xx")
    if g4 is not None:
        lint["gl4xx"] = g4
    evidence["lint"] = lint

    print("[evidence] serve-smoke (resident daemon cross-process) ...",
          flush=True)
    serve = _run(
        [sys.executable, "-m", "raft_tpu.serve", "smoke"],
        timeout=float(os.environ.get("RAFT_EVIDENCE_SERVE_TIMEOUT", "600")),
        label="serve_smoke",
    )
    # the smoke's one JSON line carries the kill-the-daemon warm-restart
    # proof (compiles == buckets cold, ZERO warm, bitwise-identical
    # responses): embed it so the claim is one key deep
    for line in reversed(serve.pop("stdout_tail", [])):
        try:
            serve["json"] = json.loads(line)
            break
        except json.JSONDecodeError:
            continue
    evidence["serve_smoke"] = serve

    print("[evidence] fleet-smoke (replicas + failover router, "
          "cross-process) ...", flush=True)
    fleet = _run(
        [sys.executable, "-m", "raft_tpu.serve", "fleet-smoke"],
        timeout=float(os.environ.get("RAFT_EVIDENCE_FLEET_TIMEOUT", "600")),
        label="fleet_smoke",
    )
    # the fleet smoke's one JSON line carries the robustness proof
    # (kill_replica:1 mid-stream -> every request answered exactly once
    # with bit-identical rows, warm zero-compile restart + re-admission,
    # deterministic typed shed + recover): one key deep, same as serve
    for line in reversed(fleet.pop("stdout_tail", [])):
        try:
            fleet["json"] = json.loads(line)
            break
        except json.JSONDecodeError:
            continue
    evidence["fleet_smoke"] = fleet

    print("[evidence] dryrun_multichip(8) ...", flush=True)
    evidence["multichip"] = _run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8)"],
        timeout=float(os.environ.get("RAFT_EVIDENCE_DRYRUN_TIMEOUT", "300")),
        label="multichip",
    )

    print("[evidence] bench.py ...", flush=True)
    bench = _run(
        [sys.executable, "bench.py"],
        timeout=float(os.environ.get("RAFT_EVIDENCE_BENCH_TIMEOUT", "1800")),
        label="bench",
    )
    # bench prints exactly ONE JSON line on stdout; a bench that emitted
    # value=null (its own diagnostic form) must downgrade ok, and a bench
    # whose stdout has no JSON at all is red regardless of rc
    bench_json = None
    for line in reversed(bench.pop("stdout_tail", [])):
        try:
            bench_json = json.loads(line)
            break
        except json.JSONDecodeError:
            continue
    if bench_json is not None:
        bench["json"] = bench_json
        bench["ok"] = bench["ok"] and bench_json.get("value") is not None
        # surface the dispatch-ahead execution stats (chunks in flight,
        # overlap fraction, donated bytes) as a first-class block so the
        # pipeline regression story is one key deep, not four
        pb = (bench_json.get("workloads", {})
              .get("north_star_volturn_bem", {}).get("pipeline"))
        if pb is not None:
            bench["pipeline"] = pb
        # lane-health / checkpoint accounting (quarantined + salvaged
        # lanes, ladder rungs, chunks resumed): degradation must be one
        # key deep in the round artifact, never buried
        rb = (bench_json.get("workloads", {})
              .get("north_star_volturn_bem", {}).get("resilience"))
        if rb is not None:
            bench["resilience"] = rb
        # shape-bucket megabatch proof (compile count <= bucket count for
        # a mixed design stream, padded-lane parity vs solo solves): the
        # O(designs)->O(buckets) claim must be one key deep too
        bb = bench_json.get("workloads", {}).get("hetero_buckets")
        if bb is not None:
            bench["buckets"] = bb
        # on-device BEM staging (novel-geometry native-host vs device
        # solve, parity vs the f64 oracle, refinement residual): the
        # staging-cliff claim one key deep
        bem = bench_json.get("workloads", {}).get("bem")
        if bem is not None:
            bench["bem"] = bem
        # unified observability block (raft_tpu.obs): span roll-up +
        # metric snapshot with latency histogram quantiles + per-tag
        # compile counts — the measured-telemetry story one key deep
        # (supersedes the bespoke phases_s dict)
        ob = bench_json.get("obs")
        if ob is not None:
            bench["obs"] = ob
        # resident-service block (open-loop p50/p99 + solves/s vs the
        # sequential baseline, per-bucket occupancy, compile collapse,
        # warm-restart, windowed server-side SLO cross-checked against
        # the client quantiles, measured-performance ledger rooflines):
        # the serving story one key deep as well
        sv = bench_json.get("workloads", {}).get("serving")
        if sv is not None:
            bench["serving"] = sv
            # the two new measured claims ride one key deep themselves:
            # windowed SLO consistency and per-bucket roofline fractions
            if sv.get("slo") is not None:
                bench["serving_slo"] = sv["slo"]
            if sv.get("ledger") is not None:
                bench["serving_ledger"] = sv["ledger"]
        # replica-scaling block (solves/s at 1 vs 2 vs 4 replicas behind
        # the failover router, load-step p99, kill-leg p99): the fleet
        # throughput/robustness story one key deep as well
        sf = bench_json.get("workloads", {}).get("serving_fleet")
        if sf is not None:
            bench["serving_fleet"] = sf
    else:
        bench["ok"] = False
        bench["error"] = "no JSON line found on bench stdout"
    evidence["bench"] = bench

    evidence["all_green"] = all(
        v.get("ok") for k, v in evidence.items() if isinstance(v, dict)
    )
    path = os.path.join(REPO, "EVIDENCE.json")
    with open(path, "w") as f:
        json.dump(evidence, f, indent=1)
    print(f"[evidence] all_green={evidence['all_green']} -> {path}",
          flush=True)
    return 0 if evidence["all_green"] else 1


if __name__ == "__main__":
    sys.exit(main())
