"""Measured-performance ledger: achieved FLOP/s, bytes/s, and roofline
fraction per (entry, bucket, topology), persisted next to the AOT cache.

PR 10's budget gate extracts per-executable flops/bytes from AOT
lowering; the obs layer measures per-bucket dispatch wall time.  This
module JOINS them: every measured dispatch of a registry-resolved
executable feeds one ledger entry

    achieved_flops_per_s = flops / best_dispatch_s
    intensity            = flops / bytes_accessed         (flop/byte)
    attainable           = min(peak_flops, intensity * peak_bw)
    roofline_fraction    = achieved_flops_per_s / attainable

and the aggregates are persisted CONTENT-KEYED under the warm-start
cache root (``<root>/ledger/<entry>-<bucket>-<digest>.json``): the
digest covers the entry tag, bucket label, device topology, the
in-repo code fingerprint, and the artifact's own flops/bytes — a source
edit or a re-lowered program re-keys its measurements instead of
polluting them, exactly like the AOT executables one directory over.
This is the measurement substrate ROADMAP item 5's autotuner starts
from: a tuned knob point must beat THESE numbers, on this topology.

Peak numbers are a small table of per-device-kind assumptions
(overridable via the ``RAFT_TPU_ROOFLINE`` knob, ``"<flops>:<bytes/s>"``,
snapshotted once) — each persisted entry records which peak model it
used (``peak.source``), so a fraction is never mistaken for a
hardware-verified measurement.  Everything is host-side, bounded, and
write-atomic (tmp + ``os.replace``, GL202); with the warm-start cache
disabled the ledger has nowhere durable to live and degrades to a
no-op at flush time.
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import os
import threading

#: peak (FLOP/s, bytes/s) ASSUMPTIONS by device-kind substring, checked
#: in order (first match wins).  Sources: published TPU spec sheets
#: (bf16 peak, HBM bandwidth); the CPU row is a deliberate
#: order-of-magnitude host default — roofline fractions on CPU compare
#: runs against each other, not against vendor silicon claims.
_PEAKS: tuple = (
    ("v5 lite", (197e12, 819e9)),        # TPU v5e
    ("v5e", (197e12, 819e9)),
    ("v5p", (459e12, 2765e9)),
    ("v4", (275e12, 1228e9)),
    ("v3", (123e12, 900e9)),
    ("v2", (45e12, 700e9)),
    ("cpu", (1e11, 5e10)),
)
_DEFAULT_PEAK = (1e11, 5e10)

ROOFLINE_ENV = "RAFT_TPU_ROOFLINE"

_lock = threading.Lock()
_pending: dict = {}              # digest -> mutable aggregate dict
_peak_cache: list = [None]       # snapshot-once (arm-time) peak override


def _peak_model(device_kind: str) -> dict:
    """The (peak_flops, peak_bytes_per_s, source) triple for this
    process: the ``RAFT_TPU_ROOFLINE`` override when set (read ONCE, at
    first use — the arm-time-snapshot contract), else the built-in
    assumption table matched on the device kind."""
    with _lock:
        if _peak_cache[0] is None:
            raw = os.environ.get(ROOFLINE_ENV, "").strip()
            if raw:
                try:
                    fs, bs = raw.split(":", 1)
                    _peak_cache[0] = (float(fs), float(bs), "env")
                except ValueError:
                    raise ValueError(
                        f"{ROOFLINE_ENV}={raw!r} is not "
                        f"'<peak_flops>:<peak_bytes_per_s>'") from None
            else:
                _peak_cache[0] = ()      # sentinel: use the table
        override = _peak_cache[0]
    if override:
        return {"flops_per_s": override[0], "bytes_per_s": override[1],
                "source": override[2]}
    kind = (device_kind or "").lower()
    for sub, (pf, pb) in _PEAKS:
        if sub in kind:
            return {"flops_per_s": pf, "bytes_per_s": pb,
                    "source": f"builtin:{sub}"}
    return {"flops_per_s": _DEFAULT_PEAK[0],
            "bytes_per_s": _DEFAULT_PEAK[1], "source": "builtin:default"}


def _reset_peak_cache() -> None:
    """Tests only: forget the snapshot so the next use re-reads env."""
    with _lock:
        _peak_cache[0] = None


def record(entry: str, bucket: str, compiled, dt_s: float) -> dict | None:
    """Feed one measured dispatch: ``compiled`` is the resolved
    executable the dispatch ran (a plain jitted function — cache off —
    contributes nothing: without the registry there is no artifact
    identity to key by), ``dt_s`` its wall time through
    materialization.  Aggregates in memory; :func:`flush` persists.
    Returns the in-memory aggregate, or None when unmeasurable."""
    from raft_tpu.cache import aot

    if not (dt_s > 0.0):
        return None
    cost = aot.artifact_cost(compiled)
    if not cost or not cost.get("flops") or not cost.get("bytes_accessed"):
        return None
    from raft_tpu.cache import config

    topo = aot._topology()
    digest = hashlib.sha256(repr(
        ("ledger", entry, bucket, topo, config.code_fingerprint(),
         cost.get("flops"), cost.get("bytes_accessed"))
    ).encode()).hexdigest()[:16]
    with _lock:
        agg = _pending.get(digest)
        if agg is None:
            agg = _pending[digest] = {
                "entry": entry, "bucket": bucket,
                "topology": [str(t) for t in topo],
                "device_kind": str(topo[1]) if len(topo) > 1 else "?",
                "flops": float(cost["flops"]),
                "bytes_accessed": float(cost["bytes_accessed"]),
                **({"peak_bytes": int(cost["peak_bytes"])}
                   if "peak_bytes" in cost else {}),
                "count": 0, "total_s": 0.0, "best_s": float("inf"),
            }
        agg["count"] += 1
        agg["total_s"] += float(dt_s)
        agg["best_s"] = min(agg["best_s"], float(dt_s))
        return dict(agg)


def _derived(agg: dict) -> dict:
    """The persisted form of one aggregate: raw accounting plus the
    achieved/roofline numbers (computed from ``best_s`` — the cleanest
    observation of the hardware; the mean is reported beside it)."""
    out = dict(agg)
    best = out["best_s"]
    out["mean_s"] = round(out["total_s"] / max(1, out["count"]), 9)
    out["best_s"] = round(best, 9)
    out["total_s"] = round(out["total_s"], 9)
    peak = _peak_model(out.get("device_kind", ""))
    achieved_f = out["flops"] / best
    achieved_b = out["bytes_accessed"] / best
    intensity = (out["flops"] / out["bytes_accessed"]
                 if out["bytes_accessed"] else 0.0)
    attainable = min(peak["flops_per_s"], intensity * peak["bytes_per_s"])
    out.update({
        "achieved_flops_per_s": float(f"{achieved_f:.6g}"),
        "achieved_bytes_per_s": float(f"{achieved_b:.6g}"),
        "intensity_flops_per_byte": float(f"{intensity:.6g}"),
        "peak": {k: (float(f"{v:.6g}") if isinstance(v, float) else v)
                 for k, v in peak.items()},
        "attainable_flops_per_s": float(f"{attainable:.6g}"),
        "roofline_fraction": (float(f"{achieved_f / attainable:.6g}")
                              if attainable > 0 else 0.0),
        "schema": 1,
    })
    return out


def root() -> str | None:
    """The ledger directory (``<cache root>/ledger``), or None when the
    warm-start cache is disabled (no durable home next to the AOT
    artifacts means nothing to persist)."""
    from raft_tpu.cache import config

    try:
        return config.subdir("ledger")
    except config.CacheDisabledError:
        return None


@contextlib.contextmanager
def _merge_lock(d: str):
    """Advisory cross-process lock around the read-merge-write cycle:
    two armed processes sharing one cache root (a daemon and a bench,
    two daemons) must not lose each other's counts to the classic
    read-modify-write race — ``os.replace`` makes each WRITE atomic,
    but only the flock makes the MERGE atomic.  Best-effort: where
    flock is unavailable the flush still runs, merely unserialized."""
    path = os.path.join(d, ".merge.lock")
    try:
        fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)
    except OSError:                  # pragma: no cover - perms
        yield
        return
    try:
        try:
            import fcntl

            fcntl.flock(fd, fcntl.LOCK_EX)
        except (ImportError, OSError):  # pragma: no cover - non-posix
            pass
        yield
    finally:
        os.close(fd)                 # closing releases the flock


def flush() -> list:
    """Persist every pending aggregate, merging with what is already on
    disk for the same digest (count/total sum, best min — a restarted
    daemon keeps improving the same entry instead of forking it).
    Atomic per file, and the read-merge-write cycle is serialized
    across processes by an advisory flock; returns the paths written
    ([] when the cache is off or nothing is pending).  Pending
    aggregates are consumed."""
    d = root()
    if d is None:
        return []
    with _lock:
        batch = dict(_pending)
        _pending.clear()
    if not batch:
        return []
    from raft_tpu.obs import export

    with _merge_lock(d):
        return _flush_batch(d, batch, export)


def _flush_batch(d: str, batch: dict, export) -> list:
    paths = []
    for digest, agg in sorted(batch.items()):
        path = os.path.join(
            d, f"{agg['entry']}-{agg['bucket']}-{digest}.json")
        prev = None
        try:
            with open(path, "r", encoding="utf-8") as f:
                prev = json.load(f)
        except (OSError, json.JSONDecodeError):
            prev = None              # absent or corrupt: start fresh
        if isinstance(prev, dict) and prev.get("count"):
            agg = dict(agg)
            agg["count"] += int(prev.get("count", 0))
            agg["total_s"] += float(prev.get("total_s", 0.0))
            agg["best_s"] = min(agg["best_s"],
                                float(prev.get("best_s", float("inf"))))
        try:
            export._atomic_write(path, json.dumps(_derived(agg), indent=1,
                                                  sort_keys=True) + "\n")
        except OSError:              # pragma: no cover - disk full/perms
            continue
        paths.append(path)
    return paths


def entries() -> list:
    """Every persisted ledger entry (corruption-tolerant: undecodable
    files are skipped — the ChunkStore rule), sorted by (entry,
    bucket)."""
    d = root()
    if d is None or not os.path.isdir(d):
        return []
    out = []
    for fname in sorted(os.listdir(d)):
        if not fname.endswith(".json"):
            continue
        try:
            with open(os.path.join(d, fname), "r", encoding="utf-8") as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if isinstance(rec, dict):
            rec["file"] = fname
            out.append(rec)
    return sorted(out, key=lambda r: (r.get("entry", ""),
                                      r.get("bucket", "")))


def stat() -> dict:
    """Lightweight ledger status — directory, unflushed aggregate
    count, persisted file count — WITHOUT reading any file contents.
    This is what a polled control op (the daemon's ``stats``) embeds:
    a monitoring client hitting it every few seconds must not make the
    server re-parse every ledger entry per poll (use :func:`entries`
    for the full records)."""
    d = root()
    n = 0
    if d is not None and os.path.isdir(d):
        n = sum(1 for f in sorted(os.listdir(d)) if f.endswith(".json"))
    with _lock:
        pending = len(_pending)
    return {"dir": d, "pending": pending, "n_entries": n}


def summary() -> dict:
    """The ``stats``-op / bench-block form: where the ledger lives, how
    many aggregates are unflushed, and the persisted entries' headline
    numbers."""
    d = root()
    with _lock:
        pending = len(_pending)
    ents = entries()
    return {
        "dir": d,
        "pending": pending,
        "n_entries": len(ents),
        "entries": [{
            "entry": e.get("entry"), "bucket": e.get("bucket"),
            "count": e.get("count"),
            "best_s": e.get("best_s"),
            "achieved_flops_per_s": e.get("achieved_flops_per_s"),
            "roofline_fraction": e.get("roofline_fraction"),
        } for e in ents],
    }


def reset() -> None:
    """Drop unflushed aggregates (tests, phase boundaries)."""
    with _lock:
        _pending.clear()
