"""``python -m raft_tpu.obs`` — the observability smoke (see smoke.py)."""
from raft_tpu.obs.smoke import main

if __name__ == "__main__":
    raise SystemExit(main())
