"""Unified observability layer: spans, bounded metrics, exporters.

One subsystem replaces the scattered ad-hoc telemetry (module-global
phase timers, pipeline stats dicts, compile-event rings, health
summaries) with a shared schema and export path:

* :mod:`raft_tpu.obs.trace` — thread-safe nested span tracing with a
  Chrome trace-event exporter (Perfetto-loadable);
* :mod:`raft_tpu.obs.metrics` — process-wide counters, gauges, and
  log-bucket latency histograms with deterministic quantiles;
* :mod:`raft_tpu.obs.export` — sinks armed by ``RAFT_TPU_OBS`` (JSONL
  event log, Chrome trace file, Prometheus text) plus the ``obs`` block
  bench JSON / EVIDENCE.json embed.

Everything here is host-side and bounded in memory; arming or reading
it can never change a traced program, an AOT key, or a compiled
artifact.  ``make obs-smoke`` proves the end-to-end story cross-process
(valid exports, quantiles present, bounded overhead).
"""
from raft_tpu.obs import export, metrics, trace                   # noqa: F401
from raft_tpu.obs.export import (                                 # noqa: F401
    enabled, maybe_publish, obs_block, prometheus_text, publish, read_jsonl,
)
from raft_tpu.obs.metrics import counter, gauge, histogram, snapshot  # noqa: F401
from raft_tpu.obs.trace import chrome_trace, span                 # noqa: F401


def reset() -> None:
    """Clear spans AND metrics (tests, phase boundaries of a daemon)."""
    trace.reset()
    metrics.reset()
