"""Unified observability layer: spans, bounded metrics, exporters.

One subsystem replaces the scattered ad-hoc telemetry (module-global
phase timers, pipeline stats dicts, compile-event rings, health
summaries) with a shared schema and export path:

* :mod:`raft_tpu.obs.trace` — thread-safe nested span tracing with
  request-scoped trace ids that cross threads (context tokens +
  synthetic request tracks) and a Chrome trace-event exporter
  (Perfetto-loadable, thread-name metadata included);
* :mod:`raft_tpu.obs.metrics` — process-wide counters, gauges,
  log-bucket latency histograms with deterministic quantiles, and
  sliding-window SLO histograms (windowed p50/p99 + error rate on an
  injectable clock);
* :mod:`raft_tpu.obs.export` — sinks armed by ``RAFT_TPU_OBS`` (JSONL
  event log, Chrome trace file, Prometheus text; auto-publish debounced
  via ``RAFT_TPU_OBS_FLUSH_MS``) plus the ``obs`` block bench JSON /
  EVIDENCE.json embed;
* :mod:`raft_tpu.obs.flight` — bounded flight recorder of the last-N
  completed request records, dumped atomically on error/SIGTERM/refresh;
* :mod:`raft_tpu.obs.ledger` — measured-performance ledger joining the
  budget gate's per-executable flops/bytes with measured dispatch
  times into achieved FLOP/s + roofline fractions per (entry, bucket,
  topology), persisted content-keyed next to the AOT cache.

Everything here is host-side and bounded in memory; arming or reading
it can never change a traced program, an AOT key, or a compiled
artifact.  ``make obs-smoke`` proves the end-to-end story cross-process
(valid exports, quantiles present, bounded overhead).
"""
from raft_tpu.obs import export, flight, ledger, metrics, trace  # noqa: F401
from raft_tpu.obs.export import (                                 # noqa: F401
    enabled, maybe_publish, obs_block, prometheus_text, publish, read_jsonl,
)
from raft_tpu.obs.flight import FlightRecorder                    # noqa: F401
from raft_tpu.obs.metrics import (                                # noqa: F401
    counter, gauge, histogram, sliding, snapshot,
)
from raft_tpu.obs.trace import (                                  # noqa: F401
    TraceContext, chrome_trace, current_context, new_trace_id, span,
)


def reset() -> None:
    """Clear spans, metrics, the publish debounce, and unflushed ledger
    aggregates (tests, phase boundaries of a daemon)."""
    trace.reset()
    metrics.reset()
    export._reset_debounce()
    ledger.reset()
