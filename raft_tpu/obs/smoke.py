"""Obs-smoke: prove the observability layer end-to-end, cross-process.

``python -m raft_tpu.obs`` runs a small mixed-design
:func:`~raft_tpu.parallel.sweep.sweep_designs` stream (OC3 spar +
VolturnUS-S + OC4 semi — two shape buckets) in TWO fresh child
processes sharing one warm-start cache dir — first with ``RAFT_TPU_OBS``
off, then with it armed at a scratch sink — and asserts:

* the armed child published a **valid JSONL event log** (every line
  parses; meta + span + metrics records present, zero corrupt lines);
* the **Chrome trace loads** and is schema-valid (``ph``/``ts``/``dur``/
  ``pid``/``tid`` on every event, per-thread time-containment nesting
  consistent) — i.e. Perfetto-loadable;
* the metrics snapshot carries a **per-bucket dispatch latency
  histogram** for every bucket signature with deterministic **p50/p99**
  present, plus the Prometheus exposition file;
* **overhead guard**: the armed child's timed solve leg (warm
  executable, best of 3) stays within a small factor of the unarmed
  child's — instrumentation must never cost the hot path real
  throughput.

Exit code 0/1; prints one JSON line.  ``make obs-smoke`` wraps it
(< 60 s CPU); runs in the CI fast job.

``python -m raft_tpu.obs child`` is the per-process payload (internal).
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

DESIGNS = ("OC3spar", "VolturnUS-S", "OC4semi")

#: the armed child's solves/s may lag the unarmed child's by at most
#: this factor.  Generous on purpose: the timed leg is only ~10 ms on
#: CPU, so the CONSTANT per-call publish cost (three sink files per
#: armed sweep_designs call, ~2 ms) dominates the ratio — on a real
#: workload (seconds per sweep) it amortizes to noise, and the marginal
#: span/metric cost is a few µs per bucket.  The guard exists to catch
#: an accidental O(lanes) instrumentation cost, not to pin the publish
#: constant; CI boxes also share cores with neighbors.
OVERHEAD_FACTOR = 2.0


def _child(argv) -> None:
    p = argparse.ArgumentParser(prog="raft_tpu.obs child")
    p.add_argument("--nw", type=int, default=32)
    args = p.parse_args(argv)

    # the smoke must never dial a hardware backend: pin CPU before jax init
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    from raft_tpu import cache, obs
    from raft_tpu.model import stage_designs
    from raft_tpu.parallel.sweep import sweep_designs

    cache.enable()                      # RAFT_TPU_CACHE_DIR from the parent

    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    fnames = [os.path.join(pkg, "designs", n + ".yaml") for n in DESIGNS]
    staged = stage_designs(fnames, nw=args.nw, Hs=8.0, Tp=12.0,
                           w_min=0.05, w_max=2.95)

    # warm-up pass absorbs compile (AOT registry: a later child gets
    # disk hits); the timed leg below measures pure execution
    sweep_designs(staged=staged, n_iter=8, return_xi=False)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = sweep_designs(staged=staged, n_iter=8, return_xi=False)
        best = min(best, time.perf_counter() - t0)

    nw_phys = next(iter(staged.values())).nw
    solves = len(fnames) * nw_phys
    # forced: the per-sweep auto-publishes above are debounced
    # (RAFT_TPU_OBS_FLUSH_MS), and the child's final snapshot must
    # always be complete
    published = obs.maybe_publish("smoke", force=True)
    print(json.dumps({
        "armed": obs.enabled(),
        "n_designs": len(fnames),
        "n_buckets": out["buckets"]["n_buckets"],
        "signatures": out["buckets"]["signatures"],
        "solves_per_s": round(solves / best, 1),
        "timed_leg_s": round(best, 4),
        "published": published,
    }))


def _run_child(cache_dir: str, nw: int, obs_dir: str | None) -> dict:
    env = dict(os.environ)
    env["RAFT_TPU_CACHE_DIR"] = cache_dir
    env["JAX_PLATFORMS"] = "cpu"
    # deterministic whatever environment launches it (cache-smoke
    # precedent): a caller's virtual-device mesh changes topology, AOT
    # keys, and XLA-CPU compile times
    env.pop("XLA_FLAGS", None)
    env.pop("RAFT_TPU_BUCKETS", None)
    if obs_dir is None:
        env.pop("RAFT_TPU_OBS", None)
    else:
        env["RAFT_TPU_OBS"] = obs_dir
    r = subprocess.run(
        [sys.executable, "-m", "raft_tpu.obs", "child", "--nw", str(nw)],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
    )
    if r.returncode != 0:
        raise SystemExit(
            f"obs-smoke child failed (rc={r.returncode}):\n"
            + (r.stderr or r.stdout)[-2000:]
        )
    return json.loads(r.stdout.strip().splitlines()[-1])


def _validate_chrome_trace(path: str) -> dict:
    """Load a Chrome trace file and check trace-event schema + nesting.

    Every event must be a complete event (``ph == "X"``) carrying
    integer ``ts``/``dur``/``pid``/``tid`` and a name; within one
    ``tid`` track, events must nest by time containment (a child's
    ``[ts, ts+dur]`` inside its parent's) — the property Perfetto's
    slice renderer relies on.
    """
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    all_events = doc.get("traceEvents")
    assert isinstance(all_events, list) and all_events, \
        "traceEvents missing/empty"
    # metadata events ("ph": "M" — thread names) carry no ts/dur and are
    # exempt from the complete-event schema and the nesting walk
    meta = [ev for ev in all_events if ev.get("ph") == "M"]
    events = [ev for ev in all_events if ev.get("ph") != "M"]
    for ev in meta:
        assert ev.get("name") == "thread_name" and "name" in ev.get(
            "args", {}), f"malformed metadata event: {ev}"
    for ev in events:
        for field in ("name", "ph", "ts", "dur", "pid", "tid"):
            assert field in ev, f"event missing {field!r}: {ev}"
        assert ev["ph"] == "X", f"unexpected phase {ev['ph']!r}"
        for field in ("ts", "dur", "pid", "tid"):
            assert isinstance(ev[field], int), f"non-integer {field}"
    # every track with complete events is named by a metadata event
    named = {ev["tid"] for ev in meta}
    assert {ev["tid"] for ev in events} <= named, \
        "track missing its thread_name metadata event"
    bad_nesting = 0
    by_tid: dict = {}
    for ev in events:
        by_tid.setdefault(ev["tid"], []).append(ev)
    for evs in by_tid.values():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack: list = []          # open-interval end times
        for ev in evs:
            while stack and stack[-1] <= ev["ts"]:
                stack.pop()
            if stack and ev["ts"] + ev["dur"] > stack[-1]:
                bad_nesting += 1
            stack.append(ev["ts"] + ev["dur"])
    assert bad_nesting == 0, f"{bad_nesting} events violate nesting"
    return {"events": len(events), "tracks": len(by_tid)}


def smoke(argv) -> int:
    p = argparse.ArgumentParser(prog="raft_tpu.obs smoke")
    p.add_argument("--nw", type=int, default=32, help="frequency bins")
    p.add_argument("--dir", default=None,
                   help="work dir (default: fresh temp dir, removed after)")
    args = p.parse_args(argv)

    from raft_tpu.obs.export import read_jsonl

    work = args.dir or tempfile.mkdtemp(prefix="raft_tpu_obs_smoke_")
    cache_dir = os.path.join(work, "cache")
    obs_dir = os.path.join(work, "obs")
    try:
        # child 1: obs OFF (pays the cold compile into the shared cache);
        # child 2: obs ON (warm AOT hits — the timed legs compare fairly:
        # both time a warm in-process executable, best of 3)
        off = _run_child(cache_dir, args.nw, None)
        on = _run_child(cache_dir, args.nw, obs_dir)

        assert on["published"], "armed child published nothing"
        jsonl = on["published"]["jsonl"]
        events, corrupt = read_jsonl(jsonl)
        kinds = {e.get("type") for e in events}
        spans = [e for e in events if e.get("type") == "span"]
        metrics_evs = [e for e in events if e.get("type") == "metrics"]
        snap = metrics_evs[-1] if metrics_evs else {}
        hists = snap.get("histograms", {})
        per_bucket = {k: v for k, v in hists.items()
                      if k.startswith("sweep_designs.dispatch_s[")}
        quantiles_ok = all(
            isinstance(h.get("p50"), float) and isinstance(h.get("p99"), float)
            and h.get("count", 0) >= 1 for h in per_bucket.values())
        trace_info = _validate_chrome_trace(on["published"]["chrome_trace"])

        checks = {
            "jsonl_valid": corrupt == 0 and {"meta", "span", "metrics"}
                           <= kinds and len(spans) >= 3,
            "chrome_trace_valid": trace_info["events"] >= 3,
            "per_bucket_histograms":
                len(per_bucket) == on["n_buckets"] and quantiles_ok,
            "prom_written": os.path.exists(on["published"]["prom"]),
            "overhead_bounded":
                on["solves_per_s"] * OVERHEAD_FACTOR >= off["solves_per_s"],
            "unarmed_published_nothing": off["published"] is None,
        }
        ok = all(checks.values())
        print(json.dumps({
            "ok": ok,
            **checks,
            "n_buckets": on["n_buckets"],
            "jsonl_events": len(events),
            "chrome_trace": trace_info,
            "dispatch_histograms": {
                k: {q: v[q] for q in ("count", "p50", "p99")}
                for k, v in sorted(per_bucket.items())},
            "solves_per_s_obs_off": off["solves_per_s"],
            "solves_per_s_obs_on": on["solves_per_s"],
            "work_dir": work,
        }))
        return 0 if ok else 1
    finally:
        if args.dir is None:
            shutil.rmtree(work, ignore_errors=True)


def main() -> int:
    argv = sys.argv[1:]
    if argv and argv[0] == "child":
        _child(argv[1:])
        return 0
    return smoke(argv)
