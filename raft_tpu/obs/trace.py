"""Thread-safe nested span tracing — the measurement half of the
observability layer (:mod:`raft_tpu.obs`).

A *span* is one timed region of host-side work with a nested name
("north_star/run/pipeline/fetch"): spans opened while another span is
open on the SAME thread nest under it, exactly like the historical
``utils.profiling.phase`` names — but the nesting stack lives in
``threading.local`` storage, so two threads (a request-serving daemon,
the ROADMAP item this layer unblocks) can trace concurrently without
corrupting each other's paths.  Timestamps are monotonic
(``time.perf_counter_ns`` against a process epoch), never wall-clock.

Memory is BOUNDED (the ``cache.aot.compile_events`` ring precedent): the
ordered span log is a ring of the most recent :data:`_SPANS_MAX`
completed spans, while exact per-name ``(count, total seconds)``
aggregates live in a side table capped at :data:`_AGG_MAX` distinct
names (excess names aggregate under ``"<other>"``) — roll-up totals stay
exact long after the ring has wrapped, and a long-lived process can
never grow either without limit.

Exporters: :func:`chrome_trace` renders the ring as Chrome trace-event
JSON (complete ``"ph": "X"`` events plus one ``thread_name`` metadata
event per track; load the file in Perfetto or ``chrome://tracing`` —
children nest by time containment per thread track), and
:func:`rollup` is the machine-readable per-name summary the bench JSON
embeds.  All host-side: a span can never change a traced program, an
AOT key, or a compiled artifact.

**Trace context (request-scoped tracing).**  A span tree that follows
one *request* crosses threads: the client submits on one, a connection
reader stages on another, the solver loop dispatches on a third.  Three
primitives stitch those fragments into ONE tree:

* :func:`new_trace_id` mints a process-unique request id (pid +
  counter — deterministic, no wall-clock or randomness);
* :func:`current_context` captures this thread's ``(trace id, open
  path)`` as a :class:`TraceContext` token, and ``with context(tok):``
  adopts it on ANY thread — spans opened inside carry the token's trace
  id and nest under its path;
* :func:`record` accepts explicit ``trace``/``tid``/``track`` overrides
  so a coordinator thread (the serve solver loop) can emit spans for
  stages it timed on behalf of a request — e.g. queue wait — onto a
  stable synthetic track (:func:`synthetic_tid`), keeping per-track
  time containment intact even when many requests overlap in time.
"""
from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import itertools
import os
import threading
import time
from collections import deque

#: completed-span ring bound (compile_events precedent: bounded, recent)
_SPANS_MAX = 65536
#: distinct full-path names the exact roll-up tracks before aggregating
#: the rest under _OVERFLOW
_AGG_MAX = 4096
_OVERFLOW = "<other>"

#: process trace epoch — every span timestamp is µs after this instant
_EPOCH_NS = time.perf_counter_ns()

#: tid -> thread name, captured at record time for the Chrome metadata
#: events; bounded like every other buffer (FIFO eviction past the cap)
_TID_NAMES_MAX = 4096

_lock = threading.Lock()
_spans: deque = deque(maxlen=_SPANS_MAX)
_agg: dict = {}                  # full name -> [count, total_seconds]
_tid_names: dict = {}            # tid -> thread name (bounded)
_tls = threading.local()
_trace_ids = itertools.count(1)  # lock-free unique suffix per process


@dataclasses.dataclass(frozen=True)
class Span:
    """One completed span: full nested ``name``, start/duration in µs
    relative to the process trace epoch, and the recording thread.
    ``trace`` groups the spans of one request across threads (empty
    outside any trace context); ``track`` optionally names a synthetic
    Chrome track the span renders on (empty = the recording thread)."""

    name: str
    t0_us: int
    dur_us: int
    tid: int
    depth: int
    attrs: tuple = ()            # ((key, value), ...) — small, hashable
    trace: str = ""
    track: str = ""


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """A portable handle to an open span tree: the trace id plus the
    path spans should nest under.  Capture with :func:`current_context`
    on the owning thread, adopt with :func:`context` on any other."""

    trace: str = ""
    path: str = ""


def new_trace_id() -> str:
    """Mint a process-unique trace id (pid + counter): deterministic —
    no randomness, no wall clock — and unique across the processes of
    one machine, which is all a local request tree needs."""
    return f"{os.getpid():x}-{next(_trace_ids)}"


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_path() -> str:
    """The open span path on THIS thread ("" outside any span)."""
    return "/".join(_stack())


def current_trace() -> str:
    """The trace id adopted on THIS thread ("" outside any context)."""
    return getattr(_tls, "trace", "") or ""


def current_context() -> TraceContext:
    """This thread's trace id + open span path as a portable token."""
    return TraceContext(trace=current_trace(), path=current_path())


@contextlib.contextmanager
def context(ctx: TraceContext):
    """Adopt another thread's trace context: spans opened inside nest
    under ``ctx.path`` and carry ``ctx.trace``.  The thread's previous
    context (stack and trace id) is restored on exit — contexts nest."""
    old_trace = getattr(_tls, "trace", "")
    old_stack = getattr(_tls, "stack", None)
    _tls.trace = ctx.trace
    _tls.stack = [p for p in ctx.path.split("/") if p] if ctx.path else []
    try:
        yield
    finally:
        _tls.trace = old_trace
        _tls.stack = old_stack if old_stack is not None else []


def synthetic_tid(key: str) -> int:
    """A stable 31-bit Chrome track id for ``key`` (a trace id, or
    ``trace#lane``): the serve loop renders request-scoped stages on
    per-request tracks so overlapping requests never break per-track
    time containment.  Deterministic across processes."""
    return int.from_bytes(hashlib.blake2s(key.encode(),
                                          digest_size=4).digest(),
                          "big") & 0x7FFFFFFF


def record(full: str, t0_ns: int, t1_ns: int, depth: int = 0,
           attrs: dict | None = None, trace: str | None = None,
           tid: int | None = None, track: str | None = None) -> None:
    """Record one completed span from explicit monotonic-ns endpoints
    (the :func:`span` context manager's backend; callers that already
    timed a region feed it here rather than timing twice).

    ``trace`` defaults to the recording thread's adopted trace id;
    ``tid`` defaults to the recording thread (pass
    :func:`synthetic_tid` output to place the span on a synthetic
    track, naming it via ``track``) — the serve loop uses both to emit
    request-scoped stages it timed on other threads' behalf."""
    # µs endpoints are BOTH floored against the epoch and the duration is
    # their difference — never an independently-floored (t1-t0).  Floor is
    # monotonic, so a child interval inside its parent's ns interval stays
    # inside in integer µs too: the time-containment invariant Perfetto's
    # slice nesting (and the smoke's validator) relies on cannot be broken
    # by sub-µs rounding.
    t0_us = max(0, (t0_ns - _EPOCH_NS) // 1000)
    end_us = max(t0_us, (t1_ns - _EPOCH_NS) // 1000)
    real_tid = tid is None
    if real_tid:
        tid = threading.get_ident() & 0x7FFFFFFF
    s = Span(
        name=full,
        t0_us=t0_us,
        dur_us=end_us - t0_us,
        tid=tid,
        depth=depth,
        attrs=tuple(sorted(attrs.items())) if attrs else (),
        trace=current_trace() if trace is None else trace,
        track=track or "",
    )
    dt_s = max(0, t1_ns - t0_ns) / 1e9
    with _lock:
        _spans.append(s)
        if real_tid and tid not in _tid_names:
            if len(_tid_names) >= _TID_NAMES_MAX:  # pragma: no cover
                _tid_names.pop(next(iter(_tid_names)))
            _tid_names[tid] = threading.current_thread().name
        key = full if (full in _agg or len(_agg) < _AGG_MAX) else _OVERFLOW
        c = _agg.get(key)
        if c is None:
            c = _agg[key] = [0, 0.0]
        c[0] += 1
        c[1] += dt_s


@contextlib.contextmanager
def span(name: str, jax_trace: bool = False, attrs: dict | None = None):
    """Time a named region (nested names join with '/', per thread).

    ``jax_trace=True`` additionally annotates the region in the JAX/XLA
    profiler timeline (``jax.profiler.TraceAnnotation``; requires an
    active ``start_trace`` to show up — see ``utils.profiling.xla_trace``).
    ``attrs`` is a small dict of static labels carried into the Chrome
    trace event's ``args`` (chunk index, bucket signature, ...).

    The span records on EVERY exit path (exceptions included), and its
    cost is a few µs of host time: safe on hot host paths, meaningless
    inside traced code (it would measure tracing, not execution — keep
    spans outside ``jit``).
    """
    st = _stack()
    full = "/".join([*st, name])
    st.append(name)
    ctx = contextlib.nullcontext()
    if jax_trace:
        import jax.profiler

        ctx = jax.profiler.TraceAnnotation(full)
    t0 = time.perf_counter_ns()
    try:
        with ctx:
            yield
    finally:
        t1 = time.perf_counter_ns()
        st.pop()
        record(full, t0, t1, depth=len(st), attrs=attrs)


def spans() -> list:
    """The bounded ring of completed spans, oldest first."""
    with _lock:
        return list(_spans)


def rollup() -> dict:
    """Exact per-name ``{"count", "total_s"}`` aggregates since process
    start (or the last :func:`reset`) — unlike the ring, never lossy
    (the ``compile_count`` analog).  Names past the :data:`_AGG_MAX` cap
    fold into ``"<other>"``."""
    with _lock:
        return {k: {"count": v[0], "total_s": round(v[1], 6)}
                for k, v in sorted(_agg.items())}


def chrome_trace() -> dict:
    """The span ring as a Chrome trace-event JSON object (Perfetto /
    ``chrome://tracing`` loadable).  Complete events (``"ph": "X"``)
    with µs timestamps; one track per recording thread (or synthetic
    request track); the full nested path rides in ``args.path``, the
    request trace id in ``args.trace``, and the event name is the leaf.
    One ``thread_name`` metadata event (``"ph": "M"``) labels every
    track — real threads by their Python thread name, synthetic tracks
    by the recording span's ``track`` string."""
    pid = os.getpid()
    with _lock:
        ring = list(_spans)
        names = dict(_tid_names)
    track_names: dict = {}
    events = []
    for s in ring:
        if s.track:
            track_names[s.tid] = s.track
        elif s.tid not in track_names:
            track_names[s.tid] = names.get(s.tid, f"thread-{s.tid}")
        events.append({
            "name": s.name.rsplit("/", 1)[-1],
            "cat": "raft_tpu",
            "ph": "X",
            "ts": s.t0_us,
            "dur": s.dur_us,
            "pid": pid,
            "tid": s.tid,
            "args": {"path": s.name,
                     **({"trace": s.trace} if s.trace else {}),
                     **dict(s.attrs)},
        })
    meta = [{
        "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
        "args": {"name": name},
    } for tid, name in sorted(track_names.items())]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def reset() -> None:
    """Clear the span ring, the roll-up aggregates, and the track-name
    table (tests, phase boundaries of long-lived processes).  Open
    spans on any thread keep their stacks — only completed-span history
    is dropped."""
    with _lock:
        _spans.clear()
        _agg.clear()
        _tid_names.clear()
