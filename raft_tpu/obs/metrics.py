"""Process-wide metric registry: counters, gauges, and log-bucket
latency histograms — bounded, deterministic, host-side only.

Every hot path feeds the same registry (``counter("aot.disk_hit")``,
``histogram("pipeline.fetch_s")``, ...), so a daemon, the bench, or the
future autotuner read ONE coherent snapshot instead of scraping
scattered stats dicts.  Three deliberate properties:

* **Bounded memory** (the ``compile_events`` ring precedent): a
  histogram is a FIXED array of log-spaced bucket counts (no reservoir,
  no per-observation storage), and the registry caps distinct metric
  names at :data:`_MAX_METRICS` — excess registrations share one
  overflow instance per kind and are counted in ``dropped_names``, so a
  name-cardinality bug degrades a metric, never the process.
* **Deterministic quantiles**: p50/p90/p99 are computed from bucket
  counts alone (rank-walk to a bucket's UPPER edge), so a test can
  hand-build counts and assert the exact quantile — no wall-clock
  randomness.  Values past the top edge saturate to it (quantiles stay
  finite and JSON-safe); the saturation is visible in the overflow
  bucket count.
* **Thread safety**: one module lock guards registration and updates —
  the increments are far off any per-sample hot loop (per chunk / per
  bucket / per cache event, not per lane).

The cumulative :class:`Histogram` answers "since process start"; a
*live* SLO needs "over the last minute".  :class:`SlidingHistogram`
adds that: a ring of per-sub-window bucket counts (plus an error
count), rotated by an explicit ``now`` argument — the clock is the
CALLER'S (the serve loop passes its injectable clock), so windowed
p50/p99 and error rate are exactly reproducible on a virtual clock,
the same determinism contract as the micro-batcher.
"""
from __future__ import annotations

import math
import threading
import time
from bisect import bisect_left

#: registry cap on distinct metric names (bounded-memory contract)
_MAX_METRICS = 1024

#: histogram bucket edges: log-spaced, 5 per decade, 1 µs .. 1000 s —
#: wide enough for a span of anything from a device dispatch to a cold
#: BEM stage, coarse enough (±26%) to stay 46 numbers total
_PER_DECADE = 5
_EDGES: tuple = tuple(
    10.0 ** (-6 + i / _PER_DECADE) for i in range(9 * _PER_DECADE + 1)
)

_lock = threading.Lock()
_metrics: dict = {}              # name -> Counter | Gauge | Histogram | ...
_dropped: list = [0]             # registrations refused past the cap


def _quantile_from_counts(counts, total: int, q: float) -> float:
    """Deterministic rank-walk quantile shared by the cumulative and
    sliding histograms: the smallest bucket upper edge covering rank
    ``ceil(q * total)`` (0.0 when empty; saturates at the top edge)."""
    if total == 0:
        return 0.0
    rank = max(1, math.ceil(min(max(q, 0.0), 1.0) * total))
    c = 0
    for i, n in enumerate(counts):
        c += n
        if c >= rank:
            return _EDGES[min(i, len(_EDGES) - 1)]
    return _EDGES[-1]                # pragma: no cover - unreachable


class Counter:
    """Monotonic event count."""

    __slots__ = ("name", "_n")
    kind = "counter"

    def __init__(self, name: str):
        self.name = name
        self._n = 0

    def inc(self, n: int = 1) -> None:
        with _lock:
            self._n += int(n)

    @property
    def value(self) -> int:
        return self._n


class Gauge:
    """Last-written value (overlap fraction, solves/s, queue depth)."""

    __slots__ = ("name", "_v")
    kind = "gauge"

    def __init__(self, name: str):
        self.name = name
        self._v = 0.0

    def set(self, v: float) -> None:
        with _lock:
            self._v = float(v)

    @property
    def value(self) -> float:
        return self._v


class Histogram:
    """Fixed log-spaced-bucket latency histogram (seconds).

    ``counts[0]`` holds observations ≤ the lowest edge, ``counts[i]``
    (1 ≤ i ≤ len(edges)-1) the half-open bucket (edges[i-1], edges[i]],
    and ``counts[-1]`` everything above the top edge.  Quantiles walk
    the cumulative counts to rank ``max(1, ceil(q·total))`` and return
    that bucket's upper edge — exact, deterministic, saturating at the
    top edge (never infinity).
    """

    __slots__ = ("name", "counts", "total", "sum_s")
    kind = "histogram"
    edges = _EDGES

    def __init__(self, name: str):
        self.name = name
        self.counts = [0] * (len(_EDGES) + 1)
        self.total = 0
        self.sum_s = 0.0

    def observe(self, seconds: float) -> None:
        v = float(seconds)
        if not math.isfinite(v):
            return                       # a NaN latency is a bug upstream
        i = bisect_left(_EDGES, v) if v > _EDGES[0] else 0
        with _lock:
            self.counts[i] += 1
            self.total += 1
            self.sum_s += v

    def quantile(self, q: float) -> float:
        """The smallest bucket upper edge covering rank ``ceil(q·total)``
        (0.0 on an empty histogram)."""
        return _quantile_from_counts(self.counts, self.total, q)

    def to_dict(self) -> dict:
        """Snapshot: count/sum, the standard quantiles, and the NONZERO
        buckets as ``[upper_edge, count]`` pairs (the overflow bucket's
        edge is the string ``"+Inf"`` — JSON has no infinity)."""
        buckets = []
        for i, n in enumerate(self.counts):
            if n:
                edge = ("+Inf" if i >= len(_EDGES)
                        else float(f"{_EDGES[i]:.6g}"))
                buckets.append([edge, n])
        return {
            "count": self.total,
            "sum_s": round(self.sum_s, 6),
            "p50": float(f"{self.quantile(0.50):.6g}"),
            "p90": float(f"{self.quantile(0.90):.6g}"),
            "p99": float(f"{self.quantile(0.99):.6g}"),
            "buckets": buckets,
        }


class SlidingHistogram:
    """Windowed latency histogram + error counter: the live-SLO metric.

    The window of ``window_s`` seconds is a ring of ``n_sub``
    sub-windows, each a fixed bucket-count array (same log-spaced edges
    as :class:`Histogram`) plus an error count.  Every operation takes
    an explicit ``now`` (defaults to ``time.monotonic()``): sub-window
    ``floor(now / sub_s)`` is current, older slots age out of the
    merged view, and a slot is zeroed lazily when its ring position is
    reused — so memory is a FIXED ``n_sub × 47`` ints regardless of
    traffic, and the whole object is exactly reproducible under a
    virtual clock (windowed p50/p99 "match a hand-computable schedule"
    is a testable claim, not a hope).

    ``observe(seconds, now)`` records a success latency; ``error(now)``
    records a failure (errors are counted, not timed); ``window(now)``
    returns the merged snapshot: count, sum, p50/p90/p99, errors, and
    ``error_rate = errors / (count + errors)``.
    """

    __slots__ = ("name", "window_s", "n_sub", "sub_s", "_slots")
    kind = "sliding"
    edges = _EDGES

    def __init__(self, name: str, window_s: float = 60.0, n_sub: int = 12):
        if window_s <= 0 or n_sub < 1:
            raise ValueError(f"window_s must be > 0 and n_sub >= 1, got "
                             f"{window_s}/{n_sub}")
        self.name = name
        self.window_s = float(window_s)
        self.n_sub = int(n_sub)
        self.sub_s = self.window_s / self.n_sub
        # slot: [abs_index, counts list, total, sum_s, errors]
        self._slots = [[-1, [0] * (len(_EDGES) + 1), 0, 0.0, 0]
                       for _ in range(self.n_sub)]

    def _slot(self, now: float):
        """The current sub-window's slot, zeroed if its ring position
        still holds an older sub-window.  Caller holds the lock."""
        idx = int(now // self.sub_s)
        slot = self._slots[idx % self.n_sub]
        if slot[0] != idx:
            slot[0] = idx
            slot[1] = [0] * (len(_EDGES) + 1)
            slot[2] = 0
            slot[3] = 0.0
            slot[4] = 0
        return slot

    def observe(self, seconds: float, now: float | None = None) -> None:
        v = float(seconds)
        if not math.isfinite(v):
            return                       # a NaN latency is a bug upstream
        i = bisect_left(_EDGES, v) if v > _EDGES[0] else 0
        now = time.monotonic() if now is None else now
        with _lock:
            slot = self._slot(now)
            slot[1][i] += 1
            slot[2] += 1
            slot[3] += v

    def error(self, now: float | None = None) -> None:
        """Count one failed request in the current sub-window (errors
        feed the window's error rate, never its latency quantiles)."""
        now = time.monotonic() if now is None else now
        with _lock:
            self._slot(now)[4] += 1

    def window(self, now: float | None = None) -> dict:
        """Merged snapshot over the live sub-windows at ``now``: the
        last ``n_sub`` sub-window indices, current included — a
        deterministic function of the observation schedule."""
        with _lock:
            return self._window_locked(now)

    def _window_locked(self, now: float | None = None) -> dict:
        """:meth:`window` body; caller holds the module lock (the
        registry snapshot merges sliding windows under its own lock)."""
        now = time.monotonic() if now is None else now
        cur = int(now // self.sub_s)
        counts = [0] * (len(_EDGES) + 1)
        total, sum_s, errors = 0, 0.0, 0
        for slot in self._slots:
            if cur - self.n_sub < slot[0] <= cur:
                for i, n in enumerate(slot[1]):
                    counts[i] += n
                total += slot[2]
                sum_s += slot[3]
                errors += slot[4]
        return {
            "window_s": self.window_s,
            "count": total,
            "sum_s": round(sum_s, 6),
            "p50": float(f"{_quantile_from_counts(counts, total, 0.50):.6g}"),
            "p90": float(f"{_quantile_from_counts(counts, total, 0.90):.6g}"),
            "p99": float(f"{_quantile_from_counts(counts, total, 0.99):.6g}"),
            "errors": errors,
            "error_rate": (round(errors / (total + errors), 6)
                           if total + errors else 0.0),
        }

    def to_dict(self) -> dict:
        return self.window()


_OVERFLOW_NAME = "<overflow>"


def sliding(name: str, window_s: float = 60.0,
            n_sub: int = 12) -> SlidingHistogram:
    """Registry-backed :class:`SlidingHistogram` (the window parameters
    apply on first registration; later callers share the instance)."""
    with _lock:
        m = _metrics.get(name)
        if m is not None:
            if not isinstance(m, SlidingHistogram):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested as sliding")
            return m
        if len(_metrics) >= _MAX_METRICS:
            _dropped[0] += 1
            key = f"{_OVERFLOW_NAME}.sliding"
            m = _metrics.get(key)
            if m is None and len(_metrics) < _MAX_METRICS + 4:
                m = _metrics[key] = SlidingHistogram(key)
            return m if m is not None else SlidingHistogram(key)
        m = _metrics[name] = SlidingHistogram(name, window_s, n_sub)
        return m


def _get(name: str, cls):
    with _lock:
        m = _metrics.get(name)
        if m is not None:
            if not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested as {cls.kind}")
            return m
        if len(_metrics) >= _MAX_METRICS:
            # bounded-registry contract: degrade to a shared overflow
            # instance per kind, count the refusal, never grow
            _dropped[0] += 1
            key = f"{_OVERFLOW_NAME}.{cls.kind}"
            m = _metrics.get(key)
            if m is None and len(_metrics) < _MAX_METRICS + 3:
                m = _metrics[key] = cls(key)
            return m if m is not None else cls(key)   # pragma: no cover
        m = _metrics[name] = cls(name)
        return m


def counter(name: str) -> Counter:
    return _get(name, Counter)


def gauge(name: str) -> Gauge:
    return _get(name, Gauge)


def histogram(name: str) -> Histogram:
    return _get(name, Histogram)


def snapshot() -> dict:
    """One coherent, JSON-safe view of every registered metric:
    ``{"counters": {...}, "gauges": {...}, "histograms": {...},
    "sliding": {...}}`` (the ``sliding`` key only when any window is
    registered) plus ``dropped_names`` when the registry cap ever
    refused a name."""
    out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
    sliding_out: dict = {}
    # the whole read happens UNDER the lock (to_dict/quantile only read),
    # excluding concurrent observe()/inc(): the snapshot is coherent —
    # a histogram's bucket sum always equals its count
    with _lock:
        for name, m in sorted(_metrics.items()):
            if isinstance(m, Counter):
                out["counters"][name] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][name] = float(f"{m.value:.6g}")
            elif isinstance(m, SlidingHistogram):
                sliding_out[name] = m._window_locked()
            else:
                out["histograms"][name] = m.to_dict()
        if sliding_out:
            out["sliding"] = sliding_out
        if _dropped[0]:
            out["dropped_names"] = _dropped[0]
    return out


def reset() -> None:
    """Drop every registered metric (tests, phase boundaries)."""
    with _lock:
        _metrics.clear()
        _dropped[0] = 0
