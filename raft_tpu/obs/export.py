"""Observability sinks: JSONL event log, Chrome trace file, Prometheus
text exposition, and the ``obs`` block for bench JSON / EVIDENCE.json.

Armed by the ``RAFT_TPU_OBS`` env knob (registered host-only in
``lint/knobs.py``): unset/``off`` disables everything — the default, and
the fast path writes NOTHING; ``1``/``on`` roots the sink directory
under the warm-start cache root's ``obs/``; any other value is the sink
directory itself.  Host-side by contract: arming the knob can never
change a traced program, an AOT key, or a compiled artifact.

Publishing is ATOMIC (tmp + ``os.replace``, the GL202 contract shared
with the staging cache and the chunk store): a kill mid-publish leaves
either the previous complete file or nothing — never a torn artifact.
Reading is corruption-tolerant anyway (:func:`read_jsonl` skips
undecodable lines and reports how many, the ``ChunkStore`` precedent),
so even a log produced by a foreign writer that appends non-atomically
stays loadable after a mid-write kill.

File layout under the sink directory (pid-suffixed so concurrent
processes never clobber each other)::

    obs-<label>-<pid>.jsonl      one JSON object per line: a meta header,
                                 every completed span, one metric snapshot
    trace-<label>-<pid>.json     Chrome trace-event JSON (open in Perfetto)
    metrics-<label>-<pid>.prom   Prometheus text exposition
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

from raft_tpu.obs import metrics as _metrics
from raft_tpu.obs import trace as _trace

_OFF = ("", "off", "0", "none", "disabled", "false", "no")

#: knob naming the auto-publish debounce interval (milliseconds); its
#: value is snapshotted ONCE, at the first armed publish decision (the
#: arm-time contract) — the request path never re-reads the environment
FLUSH_ENV = "RAFT_TPU_OBS_FLUSH_MS"
DEFAULT_FLUSH_MS = 1000.0

_flush_lock = threading.Lock()
_flush_interval_s: list = [None]     # snapshot-once seconds
_last_publish: list = [None]         # monotonic stamp of the last publish


def root() -> str | None:
    """The sink directory this process would publish under, or None when
    ``RAFT_TPU_OBS`` is off (the default)."""
    v = os.environ.get("RAFT_TPU_OBS", "").strip()
    if v.lower() in _OFF:
        return None
    if v.lower() in ("1", "on", "true", "yes"):
        from raft_tpu.cache import config

        base = (config.cache_dir() or config.resolve_dir()
                or config.default_dir())
        return os.path.join(base, "obs")
    return os.path.abspath(os.path.expanduser(v))


def enabled() -> bool:
    return root() is not None


def flush_interval_s() -> float:
    """The auto-publish debounce interval (seconds), snapshotted from
    ``RAFT_TPU_OBS_FLUSH_MS`` at first use (default 1000 ms).  PR 11's
    smoke measured a constant ~2 ms per publish (three sink files);
    per-sweep auto-publish on a short timed leg pays it EVERY call —
    the debounce amortizes it to at most once per interval, while
    forced publishes (phase ends, shutdown) always write."""
    with _flush_lock:
        if _flush_interval_s[0] is None:
            raw = os.environ.get(FLUSH_ENV, "").strip()
            try:
                ms = float(raw) if raw else DEFAULT_FLUSH_MS
            except ValueError:
                ms = DEFAULT_FLUSH_MS
            _flush_interval_s[0] = max(0.0, ms) / 1e3
        return _flush_interval_s[0]


def _reset_debounce() -> None:
    """Tests (and ``obs.reset``): forget the interval snapshot and the
    last-publish stamp so each test arms fresh."""
    with _flush_lock:
        _flush_interval_s[0] = None
        _last_publish[0] = None


def _atomic_write(path: str, text: str) -> None:
    """tmp + ``os.replace`` publish (GL202: no torn artifact under a
    durable root, ever)."""
    d = os.path.dirname(path)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(text)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def process_tag(label: str) -> str:
    """Per-process export-file tag: ``<label>-p<process_index>-<pid>``.

    The pid alone is NOT collision-safe on a pod — two hosts sharing one
    export root (a common cache mount) can draw the same pid and clobber
    each other's files (GL402).  ``jax.process_index()`` is unique per
    host in a ``jax.distributed`` job and 0 when undistributed; it is
    read only when jax is already imported — telemetry must never be the
    reason jax initializes."""
    idx = 0
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            idx = int(jax.process_index())
        except Exception:
            idx = 0  # backend not initialized yet: single-process so far
    return f"{label}-p{idx}-{os.getpid()}"


def _jsonl_lines(label: str) -> list:
    lines = [json.dumps({
        "type": "meta", "label": label, "pid": os.getpid(),
        "schema": 1, "unix_time": time.time(),
    })]
    for s in _trace.spans():
        lines.append(json.dumps({
            "type": "span", "name": s.name, "ts_us": s.t0_us,
            "dur_us": s.dur_us, "tid": s.tid, "depth": s.depth,
            **({"trace": s.trace} if s.trace else {}),
            **({"track": s.track} if s.track else {}),
            **({"attrs": dict(s.attrs)} if s.attrs else {}),
        }))
    lines.append(json.dumps({"type": "metrics", **_metrics.snapshot()}))
    return lines


def publish(label: str = "run", directory: str | None = None) -> dict:
    """Write the three sink files for this process's current span ring +
    metric snapshot.  ``directory`` overrides the env-resolved root
    (tests); raises when neither resolves.  Returns the paths written."""
    d = directory or root()
    if d is None:
        raise RuntimeError(
            "obs export is not armed: set RAFT_TPU_OBS (1 = cache root, "
            "or a directory) or pass directory=")
    os.makedirs(d, exist_ok=True)
    tag = process_tag(label)
    paths = {
        "jsonl": os.path.join(d, f"obs-{tag}.jsonl"),
        "chrome_trace": os.path.join(d, f"trace-{tag}.json"),
        "prom": os.path.join(d, f"metrics-{tag}.prom"),
    }
    _atomic_write(paths["jsonl"], "\n".join(_jsonl_lines(label)) + "\n")
    _atomic_write(paths["chrome_trace"], json.dumps(_trace.chrome_trace()))
    _atomic_write(paths["prom"], prometheus_text())
    with _flush_lock:
        _last_publish[0] = time.monotonic()
    return paths


def maybe_publish(label: str = "run", force: bool = False) -> dict | None:
    """:func:`publish` when armed, no-op (None) otherwise — the call the
    instrumented entry points (bench, sweeps, smokes) make
    unconditionally.  Auto-publishes are DEBOUNCED on a monotonic clock
    (:func:`flush_interval_s`): within the interval of the last publish
    the call is skipped (counted in ``obs.publish_skipped``) so the
    constant per-publish file cost amortizes across a hot sweep loop
    instead of taxing every call.  ``force=True`` bypasses the debounce
    — phase ends (bench exit, daemon drain, smoke children) always
    flush a complete final snapshot.  Never raises: a full disk must
    degrade the telemetry, not the solve.  Also flushes the measured
    performance ledger (:mod:`raft_tpu.obs.ledger`) on every real
    publish, so its on-disk entries stay as fresh as the sinks."""
    if not enabled():
        return None
    if not force:
        interval = flush_interval_s()
        with _flush_lock:
            last = _last_publish[0]
        if last is not None and time.monotonic() - last < interval:
            _metrics.counter("obs.publish_skipped").inc()
            return None
    try:
        out = publish(label)
    except OSError:  # pragma: no cover - disk full / permissions
        return None
    try:
        from raft_tpu.obs import ledger as _ledger

        _ledger.flush()
    except Exception:  # pragma: no cover - ledger must not fail publish
        pass
    return out


def read_jsonl(path: str) -> tuple:
    """Parse a JSONL event log, skipping corrupt lines (a mid-write kill
    by a non-atomic foreign writer truncates the tail; the valid prefix
    must stay loadable — the ``ChunkStore`` corruption-tolerance rule).
    Returns ``(events, n_corrupt)``."""
    events, corrupt = [], 0
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                corrupt += 1
                continue
            if isinstance(ev, dict):
                events.append(ev)
            else:
                corrupt += 1
    return events, corrupt


# ------------------------------------------------------- Prometheus ----

def _prom_name(name: str) -> str:
    out = []
    for ch in "raft_tpu_" + name:
        out.append(ch if (ch.isalnum() or ch in "_:") else "_")
    return "".join(out)


def prometheus_text() -> str:
    """The metric snapshot as a Prometheus text exposition (counters,
    gauges, and histograms with cumulative ``_bucket{le=...}`` series —
    the standard scrape format, also consumable by a file exporter)."""
    snap = _metrics.snapshot()
    lines = []
    for name, v in snap["counters"].items():
        pn = _prom_name(name)
        lines += [f"# TYPE {pn} counter", f"{pn} {v}"]
    for name, v in snap["gauges"].items():
        pn = _prom_name(name)
        lines += [f"# TYPE {pn} gauge", f"{pn} {v}"]
    for name, h in snap["histograms"].items():
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} histogram")
        cum = 0
        for edge, n in h["buckets"]:
            cum += n
            le = "+Inf" if edge == "+Inf" else repr(float(edge))
            lines.append(f'{pn}_bucket{{le="{le}"}} {cum}')
        if not h["buckets"] or h["buckets"][-1][0] != "+Inf":
            lines.append(f'{pn}_bucket{{le="+Inf"}} {h["count"]}')
        lines += [f"{pn}_sum {h['sum_s']}", f"{pn}_count {h['count']}"]
    return "\n".join(lines) + "\n"


# ------------------------------------------------ bench / EVIDENCE ----

def obs_block() -> dict:
    """The ``obs`` block for bench JSON / EVIDENCE.json: the span
    roll-up (the successor of the bespoke ``phases_s`` dict — same
    nested names, now with call counts), the full metric snapshot
    (histogram quantiles included), and the exact per-tag compile
    counts from the AOT registry.  JSON-safe by construction."""
    from raft_tpu.cache import aot

    snap = _metrics.snapshot()
    return {
        "spans": _trace.rollup(),
        "counters": snap["counters"],
        "gauges": snap["gauges"],
        "histograms": snap["histograms"],
        **({"sliding": snap["sliding"]} if "sliding" in snap else {}),
        **({"dropped_names": snap["dropped_names"]}
           if "dropped_names" in snap else {}),
        "compiles": aot.compile_counts(),
    }
