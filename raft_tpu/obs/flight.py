"""Flight recorder: a bounded ring of the last-N completed request
records, dumped atomically when something goes wrong.

The live SLO window (:class:`raft_tpu.obs.metrics.SlidingHistogram`)
answers "how is the service doing"; the flight recorder answers "what
exactly were the last requests it served when it died".  Each record is
one small JSON-safe dict — id, op, trace id, bucket signatures, the
per-stage timing breakdown (staging, per-lane queue wait, solve,
total), and the outcome — appended by the serve delivery path and kept
in a fixed-size ring (the ``compile_events`` bounded-buffer precedent:
a month-long daemon holds exactly ``capacity`` records, never more).

:meth:`FlightRecorder.dump` publishes the ring as one JSONL file via
the atomic tmp + ``os.replace`` write every durable artifact uses
(GL202): triggered on batch failure, on graceful shutdown (SIGTERM
included), and on the ``refresh`` op — so a post-mortem always finds
either the previous complete dump or the new one, never a torn file.
Dumping is best-effort by contract: a full disk degrades the
post-mortem, never the serving loop.
"""
from __future__ import annotations

import json
import os
import threading
from collections import deque

#: default ring capacity — enough tail to reconstruct the last seconds
#: of a busy daemon, small enough that a dump is always instant
DEFAULT_CAPACITY = 256

#: the dump path writes under the shared obs sink and so falls under the
#: GL402 shared-root contract even though the serve loop reaches it only
#: through an instance attribute (invisible to the call-graph edges)
__graftlint_multihost__ = ("dump",)


class FlightRecorder:
    """See module docstring.  Thread contract: ``record`` is called by
    the solver loop and (on failures) whatever thread noticed; one lock
    guards the ring and the exact counters."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._recorded = 0           # exact, survives the ring wrap
        self._errors = 0

    def record(self, rec: dict) -> None:
        """Append one completed-request record (JSON-safe dict; the
        caller owns the schema — the serve loop records id/op/trace/
        buckets/stage timings/outcome)."""
        with self._lock:
            self._ring.append(dict(rec))
            self._recorded += 1
            if str(rec.get("outcome", "ok")) != "ok":
                self._errors += 1

    def snapshot(self) -> list:
        """The ring's records, oldest first (copies)."""
        with self._lock:
            return [dict(r) for r in self._ring]

    def counts(self) -> dict:
        """Exact totals since construction plus the current ring size —
        the ``stats`` op's ``flight`` block."""
        with self._lock:
            return {"capacity": self.capacity, "size": len(self._ring),
                    "recorded": self._recorded, "errors": self._errors}

    def dump(self, path: str | None = None, label: str = "flight",
             reason: str = "") -> str | None:
        """Write the ring as one JSONL file: a meta header line (label,
        pid, reason, exact counters), then one line per record, oldest
        first.  ``path`` overrides the destination; otherwise the file
        lands in the armed ``RAFT_TPU_OBS`` sink directory as
        ``flight-<label>-p<process_index>-<pid>.jsonl`` (None when obs
        is off — a recorder without a sink has nowhere to durably dump;
        the process-index salt keeps two pod hosts sharing one sink from
        clobbering each other, GL402).  Atomic, best-effort: returns the
        path written or None."""
        from raft_tpu.obs import export

        if path is None:
            d = export.root()
            if d is None:
                return None
            path = os.path.join(
                d, f"flight-{export.process_tag(label)}.jsonl")
        with self._lock:
            records = [dict(r) for r in self._ring]
            head = {"type": "meta", "label": label, "pid": os.getpid(),
                    "reason": reason, "capacity": self.capacity,
                    "recorded": self._recorded, "errors": self._errors}
        lines = [json.dumps(head)]
        lines += [json.dumps({"type": "request", **r}) for r in records]
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            export._atomic_write(path, "\n".join(lines) + "\n")
        except OSError:              # pragma: no cover - disk full/perms
            return None
        return path
