"""Deadline-or-capacity micro-batching: the deterministic serve-loop core.

Each shape bucket (a :class:`raft_tpu.build.buckets.BucketSig`) owns a
FIFO of pending lanes.  A bucket's open batch closes when EITHER

* it holds ``batch_max`` lanes (**capacity close** — exactly
  ``batch_max`` oldest lanes pop; any younger lanes stay queued with
  their original arrival times), or
* its OLDEST lane has waited ``batch_deadline_s`` (**deadline close** —
  everything pending pops, up to ``batch_max``).

Determinism contract (pinned by tests/test_serve.py on a virtual clock):
batch compositions are a pure function of the arrival schedule — the
sequence of ``submit(sig, lane)`` calls with their clock readings — and
the two knobs.  No wall-clock reads hide in the decision logic: the
clock is INJECTED (``time.monotonic`` in the daemon, a manual counter in
tests and the race harness), ties between simultaneously-closeable
buckets break on (oldest arrival, sorted signature), and the queues are
plain FIFOs.  Because the solver pads every batch to the fixed capacity
anyway (see :mod:`raft_tpu.serve.solver`), composition affects LATENCY
only — results are composition-independent by construction — but a
deterministic composition is what makes the serving bench reproducible
and the batching testable at all.

Thread contract: ``submit`` is called by N connection readers,
``next_batch`` by the single solver loop, ``close`` by the signal
handler — all state behind one lock + condition.  The race harness
(``make race-smoke``) hammers submit/close/drain from 8 threads and
asserts zero lanes lost or duplicated.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque


@dataclasses.dataclass
class Lane:
    """One unit of solve work: a single (design, sea state) pair owned by
    a request.  ``staged`` carries the memoized bucket-padded lane arrays
    (see :meth:`raft_tpu.serve.solver.SolverCore.stage_lane`); the
    batcher never looks inside it."""

    request_id: object
    seq: int                  # lane index within the owning request
    label: str                # short design label (metrics/logs)
    staged: object            # (design, members, rna, env, wave, C_moor)
    t_submit: float = 0.0     # batcher clock reading at submit
    trace: str = ""           # request-scoped trace id (obs.trace)
    t_submit_ns: int = 0      # perf_counter_ns at submit (span endpoints)


class MicroBatcher:
    """Deterministic deadline/capacity lane coalescer (see module doc)."""

    def __init__(self, batch_deadline_s: float, batch_max: int,
                 clock=time.monotonic):
        if batch_max < 1:
            raise ValueError(f"batch_max must be >= 1, got {batch_max}")
        self.deadline_s = float(batch_deadline_s)
        self.batch_max = int(batch_max)
        self.clock = clock
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._pending: dict = {}          # sig -> deque[Lane]
        self._closed = False
        self._submitted = 0
        self._popped = 0

    # ------------------------------------------------------------ intake
    def submit(self, sig, lane: Lane) -> None:
        """Enqueue one lane under its bucket signature (FIFO).  Raises
        once the batcher is closed — a request that raced shutdown gets
        an error response instead of vanishing into a dead queue."""
        with self._lock:
            if self._closed:
                raise RuntimeError("batcher is closed")
            lane.t_submit = self.clock()
            self._pending.setdefault(sig, deque()).append(lane)
            self._submitted += 1
            self._nonempty.notify_all()

    def close(self) -> None:
        """Stop intake and wake the solver loop; already-queued lanes
        stay drainable via :meth:`next_batch` (flush-on-close) until the
        queues empty."""
        with self._lock:
            self._closed = True
            self._nonempty.notify_all()

    def set_deadline(self, deadline_s: float) -> None:
        """Mid-life deadline change (the server's ``refresh`` op), under
        the lock so a concurrent ``next_batch`` decision never reads a
        torn value."""
        with self._lock:
            self.deadline_s = float(deadline_s)
            self._nonempty.notify_all()

    def set_batch_max(self, batch_max: int) -> None:
        """Mid-life capacity change (``refresh``): locked, and the
        waiting solver loop is woken so a now-capacity-closeable bucket
        pops immediately."""
        if batch_max < 1:
            raise ValueError(f"batch_max must be >= 1, got {batch_max}")
        with self._lock:
            self.batch_max = int(batch_max)
            self._nonempty.notify_all()

    # --------------------------------------------------------- decisions
    def _ready_sig(self, now: float):
        """The bucket to close at ``now``, or None.  Capacity wins over
        deadline; among closeable buckets the one whose OLDEST lane
        arrived first pops (ties on the sorted signature) — a total
        order, so two runs of one schedule close identical batches.
        After :meth:`close`, any non-empty bucket is closeable (drain)."""
        best = None
        for sig, q in self._pending.items():
            if not q:
                continue
            closeable = (len(q) >= self.batch_max
                         or self._closed
                         or now - q[0].t_submit >= self.deadline_s)
            if not closeable:
                continue
            key = (q[0].t_submit, tuple(sig))
            if best is None or key < best[0]:
                best = (key, sig)
        return None if best is None else best[1]

    def _next_deadline(self):
        """Earliest instant any bucket becomes deadline-closeable, or
        None when everything is empty."""
        t = None
        for q in self._pending.values():
            if q:
                d = q[0].t_submit + self.deadline_s
                t = d if t is None else min(t, d)
        return t

    # ------------------------------------------------------------- drain
    def next_batch(self, timeout: float | None = None):
        """Block until a batch closes; returns ``(sig, [lanes])`` (FIFO
        order, ``len <= batch_max``), or ``None`` when the batcher is
        closed AND drained (the solver loop's exit signal) or the
        optional ``timeout`` expires with nothing closeable."""
        t_wait0 = time.monotonic()
        with self._lock:
            while True:
                now = self.clock()
                sig = self._ready_sig(now)
                if sig is not None:
                    q = self._pending[sig]
                    lanes = [q.popleft()
                             for _ in range(min(len(q), self.batch_max))]
                    if not q:
                        del self._pending[sig]
                    self._popped += len(lanes)
                    return sig, lanes
                if self._closed:          # closed and fully drained
                    return None
                # sleep until the earliest pending deadline (or a submit
                # wakes us); an empty queue set waits for intake only
                nd = self._next_deadline()
                wait = None if nd is None else max(0.0, nd - now)
                if timeout is not None:
                    budget = timeout - (time.monotonic() - t_wait0)
                    if budget <= 0.0:
                        return None
                    wait = budget if wait is None else min(wait, budget)
                if wait is None:
                    # nothing pending: block until a submit/close notifies
                    self._nonempty.wait()
                else:
                    # a deadline is pending.  The sleep is capped at 50 ms
                    # because ``wait`` mixes clock domains when the clock
                    # is virtual (test/race harness units vs the real
                    # seconds Condition.wait consumes) — bounded-staleness
                    # re-polling keeps the loop live under any clock.
                    self._nonempty.wait(min(max(wait, 1e-4), 0.05))

    # ------------------------------------------------------------- stats
    def depth(self) -> dict:
        """Pending lane count per bucket (stats op)."""
        with self._lock:
            return {str(tuple(sig)): len(q)
                    for sig, q in self._pending.items() if q}

    def counters(self) -> dict:
        with self._lock:
            return {"submitted": self._submitted, "popped": self._popped,
                    "pending": sum(len(q) for q in self._pending.values())}
