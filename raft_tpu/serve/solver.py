"""Warm solving core of the resident service: staging memo + padded batch.

Two jobs, both built to keep the request path free of cold work:

* :meth:`SolverCore.stage_lane` — turn one ``(design, Hs, Tp)`` request
  lane into its bucket-padded staged arrays via the ONE shared recipe
  (:func:`raft_tpu.model._stage_design_one`, the same body every other
  entry point stages through), memoized: a stream that re-asks for the
  same design x sea state pays the YAML parse, member build, and mooring
  linearization exactly once per daemon life.  Staging happens in the
  CONNECTION READER thread at submit time (it also determines the lane's
  bucket signature for routing), so the solver loop only ever stacks
  warm arrays.
* :func:`solve_batch` — pad a closed batch to the FIXED lane capacity
  (``ServeConfig.batch_max``; unused lanes tile the real ones), stack
  the staged lanes into a :class:`raft_tpu.model.DesignBatch`, and solve
  it through :func:`raft_tpu.parallel.sweep.sweep_designs` with the
  resilience contract on — a client whose lane goes NaN is quarantined
  and ladder-salvaged without perturbing batch-mates — then slice the
  per-lane rows back out in request order.

Why the fixed capacity matters twice: (1) every occupancy of a bucket
shares ONE abstract signature, so the whole serving run compiles (or
AOT-loads) exactly ``n_buckets`` executables — the acceptance gate; and
(2) a lane's result is bit-identical no matter which batch it rode in
(vmapped lanes are value-independent; padding removes the remaining
shape dependence), which is what makes deadline-vs-capacity closes a
pure latency tradeoff.
"""
from __future__ import annotations

import hashlib
import json
import threading
from collections import OrderedDict

#: serve-loop functions under the GL3xx concurrency contracts (the
#: in-module analog of ``lint/registry.py``'s CONCURRENT_FUNCTIONS;
#: ``solve_batch`` additionally rides the registry's concurrent=True
#: ``serve_solve`` entry)
__graftlint_concurrent__ = ("solve_batch", "stage_lane", "design_key",
                            "solve_solo")

#: staged-lane memo bound: ~hundreds of distinct (design, sea-state)
#: pairs resident before LRU eviction; a lane is a few MB at stock sizes
_MEMO_MAX = 256


def design_key(spec) -> str:
    """Stable identity of a design argument: the path string for YAML
    files, a content hash for inline dicts (two requests carrying equal
    dicts share one staging)."""
    if isinstance(spec, str):
        return spec
    return "sha:" + hashlib.sha256(
        json.dumps(spec, sort_keys=True, default=repr).encode()
    ).hexdigest()[:24]


class SolverCore:
    """Resident staging memo + batch solver (see module docstring).

    Thread contract: ``stage_lane`` runs in N connection readers
    concurrently (single-flight per memo key under ``_lock`` — two
    clients asking for the same cold design stage it once);
    ``solve_batch`` runs in the single solver loop.  ``refresh`` may run
    from a control request between batches.
    """

    def __init__(self, config):
        self.config = config
        self._lock = threading.Lock()
        self._memo: OrderedDict = OrderedDict()   # key -> (sig, staged)
        self._inflight: dict = {}                 # key -> threading.Event
        self._stats_lock = threading.Lock()
        self._bucket_stats: dict = {}   # sig -> [batches, real_lanes]

    # ---------------------------------------------------------- staging
    def stage_lane(self, design, Hs: float, Tp: float):
        """Memoized lane staging; returns ``(sig, staged)`` where
        ``staged = (members, rna, env, wave, C_moor)`` is bucket-padded
        and ``sig`` is the lane's routing signature (any self-healing
        promotion already applied)."""
        key = (design_key(design), float(Hs), float(Tp))
        while True:
            with self._lock:
                hit = self._memo.get(key)
                if hit is not None:
                    self._memo.move_to_end(key)
                    return hit
                ev = self._inflight.get(key)
                if ev is None:
                    ev = self._inflight[key] = threading.Event()
                    break
            ev.wait()
        try:
            from raft_tpu.model import _stage_design_one, load_design

            cfg = self.config
            d = load_design(design)
            members, sig, rna, env, wave, C_moor = _stage_design_one(
                d, cfg.nw, float(Hs), float(Tp), cfg.w_min, cfg.w_max,
                with_mooring=True, bucket=True)
            out = (sig, (members, rna, env, wave, C_moor))
            with self._lock:
                self._memo[key] = out
                while len(self._memo) > _MEMO_MAX:
                    self._memo.popitem(last=False)
            return out
        finally:
            with self._lock:
                self._inflight.pop(key, None)
            ev.set()

    # ------------------------------------------------------------ admin
    def refresh(self) -> dict:
        """Graceful executor/staging refresh (the ``refresh`` op): drop
        the staged-lane memo and evict this loop's executables from the
        in-process AOT memo so the NEXT batch re-resolves them — from the
        AOT disk cache when the program is unchanged (cheap), or via a
        fresh compile when a ladder/knob change re-keyed it.  Runs
        between batches (the solver loop owns the call); in-flight
        results are never invalidated.  Returns eviction counts."""
        from raft_tpu import cache as _cache

        with self._lock:
            n_lanes = len(self._memo)
            self._memo.clear()
        n_exec = _cache.evict_memory("sweep_designs")
        return {"staged_lanes_dropped": n_lanes,
                "executables_evicted": n_exec}

    def record_batch(self, sig, n_real: int) -> None:
        with self._stats_lock:
            st = self._bucket_stats.setdefault(sig, [0, 0])
            st[0] += 1
            st[1] += n_real

    def reset_stats(self) -> None:
        """Zero the per-bucket batch/occupancy accounting (measurement
        window boundaries: the bench's warm pass vs measured pass)."""
        with self._stats_lock:
            self._bucket_stats.clear()

    def stats(self) -> dict:
        from raft_tpu import cache as _cache

        cfg = self.config
        with self._stats_lock:
            per = {
                str(tuple(sig)): {
                    "batches": b,
                    "lanes": r,
                    "mean_occupancy": round(r / (b * cfg.batch_max), 4),
                }
                for sig, (b, r) in self._bucket_stats.items()
            }
        return {
            "batch_max": cfg.batch_max,
            "batch_deadline_ms": round(cfg.batch_deadline_s * 1e3, 3),
            "nw": cfg.nw,
            "n_iter": cfg.n_iter,
            "buckets": per,
            "compiles": _cache.compile_count("sweep_designs"),
            "cache_enabled": _cache.is_enabled(),
        }


def _stack_batch(sig, staged_lanes, labels, nw: int):
    """Stack per-lane staged tuples into a :class:`DesignBatch` (the
    exact layout ``stage_designs`` builds, minus the per-batch parse —
    the lanes were staged and memoized individually)."""
    from raft_tpu.model import DesignBatch, _stack_trees
    import jax.numpy as jnp

    ms, rnas, envs, waves, cms = zip(*staged_lanes)
    return DesignBatch(
        sig=sig,
        fnames=list(labels),
        indices=list(range(len(labels))),
        members=_stack_trees(ms),
        rna=_stack_trees(rnas),
        env=_stack_trees(envs),
        wave=_stack_trees(waves),
        C_moor=None if cms[0] is None else jnp.stack(cms),
        nw=int(nw),
    )


def solve_batch(core: SolverCore, sig, lanes):
    """Solve one closed micro-batch; returns ``(rows, info)``.

    ``lanes``: the :class:`~raft_tpu.serve.batcher.Lane` list the batcher
    popped (``1 <= len <= batch_max``), each carrying its memoized
    ``staged`` tuple.  The batch is padded to EXACTLY
    ``core.config.batch_max`` lanes by tiling the real ones (pad results
    are discarded), solved via ``sweep_designs(health=True)``, and sliced
    back: ``rows[i]`` is lane ``i``'s client-facing result dict.  ``info``
    carries the batch-level health/occupancy block for metrics & stats.
    """
    import numpy as np

    from raft_tpu.parallel.sweep import sweep_designs

    cfg = core.config
    B = len(lanes)
    # a refresh may shrink the capacity while an old-capacity batch is
    # already popped: pad to whichever is larger, so every interleaving
    # of the (config, batcher) updates solves — a transient batch just
    # keys its own signature
    cap = max(cfg.batch_max, B)
    staged = [ln.staged for ln in lanes]
    labels = [ln.label for ln in lanes]
    # fixed-capacity padding: tile the real lanes cyclically.  Pad lanes
    # recompute a real lane's physics and are discarded — the price of
    # one executable per bucket across every occupancy.
    for j in range(cap - B):
        staged.append(staged[j % B])
        labels.append(f"<pad:{labels[j % B]}>")
    batch = _stack_batch(sig, staged, labels, cfg.nw)
    out = sweep_designs(staged={sig: batch}, n_iter=cfg.n_iter,
                        return_xi=False, health=True,
                        escalate=cfg.escalate, chunk=cfg.chunk)
    conv = np.asarray(out["converged"]).astype(bool)
    finite = np.asarray(out["finite"]).astype(bool)
    h = out["health"]
    quarantined = set(h["quarantined"])
    unsalvaged = set(h["unsalvaged"])
    rows = []
    for i in range(B):
        rows.append({
            "design": labels[i],
            "std_dev": np.asarray(out["std dev"][i]).tolist(),
            "iterations": int(np.asarray(out["iterations"][i])),
            "converged": bool(conv[i]),
            "finite": bool(finite[i]),
            "quarantined": i in quarantined,
            "salvaged": i in quarantined and i not in unsalvaged,
        })
    core.record_batch(sig, B)
    info = {
        "sig": tuple(sig),
        "lanes": B,
        "capacity": cap,
        "occupancy": B / cap,
        "quarantined_real": sorted(i for i in quarantined if i < B),
        "rungs_used": h.get("rungs_used", {}),
    }
    return rows, info


def solve_solo(core: SolverCore, design, Hs: float, Tp: float):
    """One request solved through the EXACT batch path, alone: a
    single-lane batch padded to capacity.  The reference the determinism
    tests hold mixed batches to — a lane's row from any batch must be
    bit-identical to its solo row — and the sequential baseline of the
    serving bench."""
    from raft_tpu.serve.batcher import Lane

    sig, staged = core.stage_lane(design, Hs, Tp)
    lane = Lane(request_id="solo", seq=0, label=design_key(design)[-24:],
                staged=staged)
    rows, _info = solve_batch(core, sig, [lane])
    return rows[0]
