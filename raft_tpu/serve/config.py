"""Serve-loop configuration, snapshotted at arm time.

The GL303 contract (docs/lint.rst): a resident process must read its env
knobs ONCE, when the daemon arms, and carry the snapshot — a mid-process
``os.environ`` change would silently diverge the loop's behavior from
whatever was folded into the AOT keys and logged at startup.  So the
concurrent request path (batcher, solver loop, connection readers) only
ever sees this frozen dataclass; :func:`ServeConfig.from_env` is called
from ``python -m raft_tpu.serve`` / the smoke harness / the bench — all
arm-time, none reachable from a registered concurrent entry point.

Knobs (registered in :mod:`raft_tpu.lint.knobs`):

* ``RAFT_TPU_SERVE_BATCH_DEADLINE_MS`` — how long an open micro-batch
  may wait for company before it closes anyway (default 25 ms).  Pure
  scheduling: because every dispatch is padded to the fixed lane
  capacity, the deadline changes LATENCY, never results.
* ``RAFT_TPU_SERVE_BATCH_MAX`` — the fixed per-bucket lane capacity
  (default 8).  Every dispatch is padded to exactly this many lanes, so
  each bucket compiles ONE executable regardless of occupancy; the
  capacity is also folded into the serve executable keys explicitly
  (:func:`raft_tpu.serve.solver.batch_salt`).
* ``RAFT_TPU_SERVE_SOCKET`` — default daemon socket path.
"""
from __future__ import annotations

import dataclasses
import os
import tempfile

DEADLINE_ENV = "RAFT_TPU_SERVE_BATCH_DEADLINE_MS"
BATCH_MAX_ENV = "RAFT_TPU_SERVE_BATCH_MAX"
SOCKET_ENV = "RAFT_TPU_SERVE_SOCKET"

DEFAULT_DEADLINE_MS = 25.0
DEFAULT_BATCH_MAX = 8


def default_socket_path() -> str:
    """Default AF_UNIX socket path (per-uid tmp namespace)."""
    return os.path.join(tempfile.gettempdir(),
                        f"raft_tpu_serve_{os.getuid()}.sock")


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Frozen arm-time snapshot of everything the serve loop consults."""

    batch_deadline_s: float = DEFAULT_DEADLINE_MS / 1e3
    batch_max: int = DEFAULT_BATCH_MAX
    socket_path: str = ""
    # solve parameters shared by every lane (the frequency grid is a
    # server-level contract: lanes of one bucket must stack one padded
    # grid, so per-request grids would fragment the buckets)
    nw: int = 100
    w_min: float = 0.05
    w_max: float = 2.95
    n_iter: int = 25
    escalate: bool = True
    # optional dispatch-ahead chunking of each padded batch through
    # parallel/pipeline.py (None = one dispatch per batch — right for
    # interactive capacities; set for very large batch_max on small HBM)
    chunk: int | None = None

    @classmethod
    def from_env(cls, **overrides) -> "ServeConfig":
        """Snapshot the ``RAFT_TPU_SERVE_*`` knobs (called at ARM time
        only — never from the request path).  ``overrides`` win over the
        environment (CLI flags, test fixtures)."""
        vals: dict = {}
        raw = os.environ.get(DEADLINE_ENV, "").strip()
        if raw:
            try:
                vals["batch_deadline_s"] = max(0.0, float(raw)) / 1e3
            except ValueError:
                raise ValueError(
                    f"{DEADLINE_ENV}={raw!r} is not a number (milliseconds)")
        raw = os.environ.get(BATCH_MAX_ENV, "").strip()
        if raw:
            try:
                vals["batch_max"] = int(raw)
            except ValueError:
                raise ValueError(f"{BATCH_MAX_ENV}={raw!r} is not an integer")
        vals["socket_path"] = (os.environ.get(SOCKET_ENV, "").strip()
                               or default_socket_path())
        vals.update(overrides)
        cfg = cls(**vals)
        if cfg.batch_max < 1:
            raise ValueError(f"{BATCH_MAX_ENV} must be >= 1, got "
                             f"{cfg.batch_max}")
        return cfg
