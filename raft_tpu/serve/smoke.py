"""Serve-smoke: cross-process proof of the resident solver service.

``python -m raft_tpu.serve smoke`` (``make serve-smoke``, CI fast job,
< 60 s CPU) spawns the REAL daemon in a child process on a fresh
warm-start cache root and proves, over the real socket:

* a mixed 3-design request stream (OC3 spar + OC4 semi + VolturnUS-S,
  varied sea states) is answered with exactly ``n_buckets`` compiles —
  the serving loop inherits the O(buckets) collapse;
* every response parity-matches a solo solve of the same request through
  the same padded path in THIS process (bit-identical: lanes are
  value-independent and the executables come off the shared AOT disk
  cache);
* SIGTERM is graceful (rc 0, socket unlinked), and a WARM RESTART on the
  same cache root reaches ready-to-serve with ZERO compiles (every
  bucket an AOT disk hit), in strictly less time than the cold start,
  and serves the same stream bit-identically;
* the warm restart runs with ``RAFT_TPU_OBS`` ARMED (the cold daemon
  runs unarmed) and proves the request-scoped observability layer
  cross-process: the exported JSONL is zero-corrupt and carries ONE
  complete span tree per served request (``request/server`` +
  ``stage``/``queue_wait``/``solve`` under one trace id), the daemon's
  ``stats`` op returns windowed p50/p99 consistent with the
  client-observed latencies, SIGTERM leaves a populated flight-recorder
  dump, a content-keyed ledger entry with finite achieved-FLOP/s and
  roofline fraction exists for EVERY warm bucket, and the armed
  stream's wall time stays within the 2x overhead guard of the unarmed
  one.

Prints one JSON line; rc 0 iff all checks hold.
"""
from __future__ import annotations

import glob
import json
import math
import os
import signal
import subprocess
import sys
import tempfile
import time

#: the mixed stream: (design alias, Hs, Tp) — 3 designs x 3 sea states,
#: landing in 2 buckets under the stock ladder
STREAM = [(d, 6.0 + 0.5 * (i % 3), 10.0 + 0.5 * (i % 2))
          for i, d in enumerate(["oc3", "oc4", "volturnus"] * 3)]

NW = 16
N_ITER = 12
BATCH_MAX = 4
DEADLINE_MS = 40.0


def _child_env(cache_dir: str, obs_dir: str | None = None) -> dict:
    env = dict(os.environ)
    env["RAFT_TPU_CACHE_DIR"] = cache_dir
    env["JAX_PLATFORMS"] = "cpu"
    # deterministic whatever environment launches it (hetero-smoke
    # precedent): a virtual-device mesh or ladder override would change
    # the AOT keys between parent and child
    env.pop("XLA_FLAGS", None)
    env.pop("RAFT_TPU_BUCKETS", None)
    env.pop("RAFT_TPU_SERVE_BATCH_DEADLINE_MS", None)
    env.pop("RAFT_TPU_SERVE_BATCH_MAX", None)
    env.pop("RAFT_TPU_OBS_FLUSH_MS", None)
    if obs_dir is None:
        env.pop("RAFT_TPU_OBS", None)
    else:
        env["RAFT_TPU_OBS"] = obs_dir
    return env


def _read_ready_line(proc, timeout_s: float) -> str:
    """First non-blank stdout line of the daemon child, read in a helper
    thread so the deadline is REAL (a bare ``readline()`` blocks forever
    on a hung child and the deadline check never re-runs)."""
    import threading

    box: list = []

    def reader():
        while True:
            line = proc.stdout.readline()
            if not line:            # EOF: child died without a line
                return
            if line.strip():
                box.append(line)
                return

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    t.join(timeout_s)
    if box:
        return box[0]
    if t.is_alive():                # hung child: kill, then fail loud
        proc.kill()
        proc.wait(10.0)
        raise RuntimeError(f"daemon printed no ready line in {timeout_s}s")
    raise RuntimeError(
        f"daemon died before ready (rc={proc.wait(10.0)})")


def _spawn_daemon(cache_dir: str, sock: str, stderr_path: str,
                  obs_dir: str | None = None):
    # a DAEMON child is unbounded by design: its lifetime is managed
    # explicitly (threaded ready-line deadline in _read_ready_line,
    # SIGTERM + bounded wait in _stop_daemon, kill on timeout) rather
    # than by a subprocess timeout.  stderr goes to a FILE, not a pipe —
    # a chatty child (XLA compile logging) must never block on a pipe
    # buffer nobody drains mid-run; the tail is read back on failure.
    stderr_f = open(stderr_path, "w")
    proc = subprocess.Popen(  # graftlint: disable=GL203
        [sys.executable, "-m", "raft_tpu.serve", "daemon",
         "--socket", sock, "--nw", str(NW), "--n-iter", str(N_ITER),
         "--deadline-ms", str(DEADLINE_MS), "--batch-max", str(BATCH_MAX),
         "--warm", "oc3,oc4,volturnus"],
        stdout=subprocess.PIPE, stderr=stderr_f, text=True,
        env=_child_env(cache_dir, obs_dir),
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
    )
    stderr_f.close()                 # the child holds its own handle
    t0 = time.perf_counter()
    try:
        line = _read_ready_line(proc, 300.0)
    except RuntimeError as e:
        try:
            with open(stderr_path) as f:
                tail = f.read()[-2000:]
        except OSError:
            tail = "<stderr unavailable>"
        raise RuntimeError(f"{e}\n--- daemon stderr tail ---\n{tail}")
    ready = json.loads(line)
    if not ready.get("ready"):
        raise RuntimeError(f"unexpected daemon line: {line!r}")
    ready["spawn_to_ready_s"] = round(time.perf_counter() - t0, 3)
    return proc, ready


def _drive_stream(sock: str):
    """Submit the whole mixed stream open-loop, collect responses + final
    server stats; returns ``(per-request std_dev rows, full stats
    response, drive info)`` where the info dict carries the stream wall
    time, the per-request client-side latencies, and every response's
    trace id (the server-side span trees are checked against them)."""
    from raft_tpu.serve.client import SolveClient

    with SolveClient(sock, connect_timeout=30.0) as cl:
        t0 = time.perf_counter()
        submit_t = []
        futs = []
        for d, Hs, Tp in STREAM:
            submit_t.append(time.perf_counter())
            futs.append(cl.submit({"op": "solve", "design": d,
                                   "Hs": Hs, "Tp": Tp}))
        rows, traces, lat = [], [], []
        for i, f in enumerate(futs):
            r = f.result(120.0)
            lat.append(time.perf_counter() - submit_t[i])
            if not r.get("ok"):
                raise RuntimeError(f"request failed: {r.get('error')}")
            rows.append(r["results"][0]["std_dev"])
            traces.append(r.get("trace"))
        wall_s = time.perf_counter() - t0
        stats = cl.stats()
    info = {"wall_s": wall_s, "latencies_s": lat, "traces": traces}
    return rows, stats, info


def _stop_daemon(proc) -> int:
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(60.0)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(10.0)
        return -9
    return proc.returncode


def _solo_reference(cache_dir: str):
    """Solo rows computed IN THIS PROCESS through the same padded batch
    path, executables off the shared AOT disk cache — the parity (and
    cross-process determinism) reference."""
    os.environ["RAFT_TPU_CACHE_DIR"] = cache_dir
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    from raft_tpu import cache
    from raft_tpu.serve import protocol
    from raft_tpu.serve.config import ServeConfig
    from raft_tpu.serve.solver import SolverCore, solve_solo

    cache.enable(cache_dir)
    cfg = ServeConfig(batch_deadline_s=DEADLINE_MS / 1e3,
                      batch_max=BATCH_MAX, nw=NW, n_iter=N_ITER)
    core = SolverCore(cfg)
    rows = []
    for d, Hs, Tp in STREAM:
        design, _label = protocol.resolve_design(d)
        rows.append(solve_solo(core, design, Hs, Tp)["std_dev"])
    return rows, cache.compile_count("sweep_designs")


def _check_obs_leg(obs_dir: str, cache_dir: str, traces, info, stats):
    """The armed warm daemon's observability proof: zero-corrupt JSONL
    with one complete per-request span tree per served request, a
    populated flight-recorder dump from the SIGTERM path, finite
    ledger rooflines for every warm bucket, and windowed stats p50/p99
    consistent with the client-observed latencies."""
    from raft_tpu.obs.export import read_jsonl

    out: dict = {}
    # -- JSONL event log (published by the daemon's post-drain flush) --
    logs = sorted(glob.glob(os.path.join(obs_dir, "obs-serve-*.jsonl")))
    out["armed_jsonl_published"] = bool(logs)
    spans_by_trace: dict = {}
    corrupt = 0
    for path in logs:
        events, bad = read_jsonl(path)
        corrupt += bad
        for ev in events:
            if ev.get("type") == "span" and ev.get("trace"):
                spans_by_trace.setdefault(ev["trace"], set()).add(
                    ev["name"])
    out["armed_jsonl_zero_corrupt"] = bool(logs) and corrupt == 0
    # -- one COMPLETE span tree per served request --
    need = {"request/server", "request/server/stage",
            "request/server/queue_wait", "request/server/solve"}
    trees = sum(1 for t in traces
                if t and need <= spans_by_trace.get(t, set()))
    out["per_request_span_trees"] = trees == len(traces) != 0
    out["span_trees_complete"] = trees
    # -- flight recorder dumped on SIGTERM --
    dumps = sorted(glob.glob(os.path.join(obs_dir, "flight-serve-*.jsonl")))
    flight_reqs = 0
    if dumps:
        events, bad = read_jsonl(dumps[-1])
        corrupt += bad
        flight_reqs = sum(1 for ev in events
                          if ev.get("type") == "request"
                          and ev.get("outcome") == "ok")
    out["flight_dump_on_sigterm"] = flight_reqs >= len(traces)
    out["flight_requests"] = flight_reqs
    # -- ledger: finite roofline per warm bucket --
    led_dir = os.path.join(cache_dir, "ledger")
    buckets_seen = set(stats["solver"]["buckets"])
    led_buckets: dict = {}
    for path in glob.glob(os.path.join(led_dir, "*.json")):
        try:
            with open(path, "r", encoding="utf-8") as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        frac = rec.get("roofline_fraction")
        if (rec.get("entry") == "sweep_designs"
                and isinstance(frac, float) and math.isfinite(frac)
                and frac > 0 and math.isfinite(
                    rec.get("achieved_flops_per_s", float("nan")))):
            led_buckets[rec.get("bucket")] = frac
    out["ledger_rooflines_all_buckets"] = (
        len(buckets_seen) >= 1
        and {f"{s}" for s in led_buckets} >= {
            b.strip("()").replace(", ", "x") for b in buckets_seen})
    out["ledger_rooflines"] = led_buckets
    # -- windowed SLO vs the client-observed latencies --
    tel = stats.get("telemetry", {})
    lat = tel.get("latency", {})
    client_max = max(info["latencies_s"])
    out["telemetry_window_counts_stream"] = lat.get("count") == len(traces)
    out["telemetry_quantiles_consistent"] = (
        0.0 < lat.get("p50", 0.0) <= lat.get("p99", 0.0)
        # windowed quantiles report a log-bucket UPPER edge (5 buckets
        # per decade: at most 10^(1/5) ~ 1.585x above the true value),
        # and the true server-side latency is <= the client-observed
        # one — so the server p99 can never legitimately exceed the
        # worst client latency by more than one bucket of quantization
        and lat.get("p99", 1e9) <= client_max * 1.585 + 0.05
        and lat.get("error_rate") == 0.0)
    out["server_window_p50_s"] = lat.get("p50")
    out["server_window_p99_s"] = lat.get("p99")
    out["client_max_latency_s"] = round(client_max, 4)
    out["queue_wait_windows"] = len(tel.get("queue_wait", {}))
    return out


def main(argv=None) -> int:
    t_all = time.perf_counter()
    keep = argv and "--keep" in argv
    tmp = tempfile.mkdtemp(prefix="raft_tpu_serve_smoke_")
    cache_dir = os.path.join(tmp, "cache")
    obs_dir = os.path.join(tmp, "obs")
    sock1 = os.path.join(tmp, "serve1.sock")
    sock2 = os.path.join(tmp, "serve2.sock")
    try:
        # ---- cold daemon: compile, serve, graceful SIGTERM (obs OFF:
        # the unarmed side of the overhead guard) ----
        proc1, ready1 = _spawn_daemon(cache_dir, sock1,
                                      os.path.join(tmp, "daemon1.err"))
        rows1, full1, info1 = _drive_stream(sock1)
        stats1 = full1["solver"]
        rc1 = _stop_daemon(proc1)
        sock1_gone = not os.path.exists(sock1)

        # ---- warm restart: zero compiles off the AOT disk cache, with
        # the observability layer ARMED ----
        proc2, ready2 = _spawn_daemon(cache_dir, sock2,
                                      os.path.join(tmp, "daemon2.err"),
                                      obs_dir=obs_dir)
        rows2, full2, info2 = _drive_stream(sock2)
        stats2 = full2["solver"]
        rc2 = _stop_daemon(proc2)

        # ---- in-process solo reference off the same cache root ----
        solo_rows, solo_compiles = _solo_reference(cache_dir)

        n_buckets = len(stats1["buckets"])
        checks = {
            "cold_compiles_eq_buckets": stats1["compiles"] == n_buckets,
            "fewer_compiles_than_designs": stats1["compiles"] < 3,
            "responses_match_solo_bitwise": rows1 == solo_rows,
            "sigterm_graceful_rc0": rc1 == 0,
            "socket_unlinked": sock1_gone,
            "warm_zero_compiles": stats2["compiles"] == 0,
            "warm_restart_bitwise_identical": rows2 == rows1,
            "warm_ready_faster_than_cold":
                ready2["ready_s"] < ready1["ready_s"],
            "warm_rc0": rc2 == 0,
            "solo_zero_compiles": solo_compiles == 0,
            # armed-vs-unarmed throughput guard (the obs-smoke factor):
            # instrumentation + tracing must never cost the serving
            # loop real wall time — both streams run on warm executables
            "armed_within_overhead_guard":
                info2["wall_s"] <= 2.0 * info1["wall_s"] + 0.5,
        }
        obs_checks = _check_obs_leg(obs_dir, cache_dir, info2["traces"],
                                    info2, full2)
        checks.update({k: v for k, v in obs_checks.items()
                       if isinstance(v, bool)})
        ok = all(checks.values())
        print(json.dumps({
            "ok": ok,
            **checks,
            **{k: v for k, v in obs_checks.items()
               if not isinstance(v, bool)},
            "n_requests": len(STREAM),
            "n_buckets": n_buckets,
            "cold_compiles": stats1["compiles"],
            "warm_compiles": stats2["compiles"],
            "cold_ready_s": ready1["ready_s"],
            "warm_ready_s": ready2["ready_s"],
            "warm_restart_speedup": (
                round(ready1["ready_s"] / ready2["ready_s"], 2)
                if ready2["ready_s"] > 0 else None),
            "stream_wall_unarmed_s": round(info1["wall_s"], 3),
            "stream_wall_armed_s": round(info2["wall_s"], 3),
            "bucket_stats_cold": stats1["buckets"],
            "wall_s": round(time.perf_counter() - t_all, 2),
            **({"dir": tmp} if keep else {}),
        }))
        return 0 if ok else 1
    finally:
        if not keep:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":                                # pragma: no cover
    sys.exit(main())
