"""CLI of the resident solver service.

``python -m raft_tpu.serve [daemon] [flags]``
    Run the daemon in the foreground: arm the warm-start layers, snapshot
    the ``RAFT_TPU_SERVE_*`` knobs, optionally pre-warm executables for a
    design list, print ONE ``{"ready": true, ...}`` JSON line, then serve
    until SIGTERM/SIGINT (graceful drain: queued requests are answered).

``python -m raft_tpu.serve smoke``
    The cross-process proof (``make serve-smoke``); see
    :mod:`raft_tpu.serve.smoke`.

``python -m raft_tpu.serve fleet [flags]``
    Run the supervised replica fleet in the foreground: N warm daemon
    children on one shared cache root behind the failover router, one
    ``{"ready": true, ...}`` JSON line, serve until SIGTERM/SIGINT.
    The ``RAFT_TPU_FLEET_*`` knobs govern; flags override.

``python -m raft_tpu.serve fleet-smoke``
    The fleet robustness proof (``make fleet-smoke``); see
    :mod:`raft_tpu.serve.fleet_smoke`.
"""
from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
import time


def _daemon(argv) -> int:
    t0 = time.perf_counter()
    p = argparse.ArgumentParser(prog="raft_tpu.serve")
    p.add_argument("--socket", default=None,
                   help="AF_UNIX socket path (default: RAFT_TPU_SERVE_SOCKET"
                        " or the per-uid tmp path)")
    p.add_argument("--nw", type=int, default=100, help="frequency bins")
    p.add_argument("--n-iter", type=int, default=25,
                   help="fixed-point iterations per solve")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="override RAFT_TPU_SERVE_BATCH_DEADLINE_MS")
    p.add_argument("--batch-max", type=int, default=None,
                   help="override RAFT_TPU_SERVE_BATCH_MAX")
    p.add_argument("--warm", default=None,
                   help="comma-separated designs to pre-arm (e.g. "
                        "'oc3,oc4,volturnus'): their buckets' executables "
                        "are resolved before the ready line prints")
    p.add_argument("--no-escalate", action="store_true",
                   help="quarantine bad lanes without ladder salvage")
    args = p.parse_args(argv)

    from raft_tpu import cache
    from raft_tpu.serve.config import ServeConfig
    from raft_tpu.serve.server import SolverServer

    cache.enable()           # warm-start layers; RAFT_TPU_CACHE_DIR governs

    overrides: dict = {"nw": args.nw, "n_iter": args.n_iter,
                       "escalate": not args.no_escalate}
    if args.deadline_ms is not None:
        overrides["batch_deadline_s"] = max(0.0, args.deadline_ms) / 1e3
    if args.batch_max is not None:
        overrides["batch_max"] = args.batch_max
    cfg = ServeConfig.from_env(**overrides)
    server = SolverServer(cfg, socket_path=args.socket)

    def _term(_sig, _frm):
        # stop() blocks on the solver drain — never inside a signal frame
        threading.Thread(target=server.stop, name="serve-sigterm",
                         daemon=True).start()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)

    server.start()
    warm = {}
    if args.warm:
        warm = server.warmup([s for s in args.warm.split(",") if s.strip()])
    print(json.dumps({
        "ready": True,
        "socket": server.socket_path,
        "ready_s": round(time.perf_counter() - t0, 3),
        "warm": warm,
        "batch_max": cfg.batch_max,
        "batch_deadline_ms": round(cfg.batch_deadline_s * 1e3, 3),
        "compiles_at_ready": cache.compile_count("sweep_designs"),
        "cache_enabled": cache.is_enabled(),
    }), flush=True)
    server.wait()
    print(json.dumps({"exit": True, "stats": server.core.stats(),
                      "queue": server.batcher.counters()}), flush=True)
    return 0


def _fleet(argv) -> int:
    t0 = time.perf_counter()
    p = argparse.ArgumentParser(prog="raft_tpu.serve fleet")
    p.add_argument("--replicas", type=int, default=None,
                   help="replica count (default: RAFT_TPU_FLEET_REPLICAS)")
    p.add_argument("--socket", default=None,
                   help="front-end AF_UNIX socket path (default: "
                        "RAFT_TPU_FLEET_SOCKET or the per-uid tmp path)")
    p.add_argument("--nw", type=int, default=100, help="frequency bins")
    p.add_argument("--n-iter", type=int, default=25,
                   help="fixed-point iterations per solve")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="per-replica RAFT_TPU_SERVE_BATCH_DEADLINE_MS")
    p.add_argument("--batch-max", type=int, default=None,
                   help="per-replica RAFT_TPU_SERVE_BATCH_MAX")
    p.add_argument("--warm", default=None,
                   help="comma-separated designs every replica pre-arms")
    args = p.parse_args(argv)

    from raft_tpu.serve.fleet import Fleet, FleetConfig

    overrides: dict = {}
    if args.replicas is not None:
        overrides["replicas"] = args.replicas
    if args.socket is not None:
        overrides["socket_path"] = args.socket
    cfg = FleetConfig.from_env(**overrides)
    serve_args = ["--nw", str(args.nw), "--n-iter", str(args.n_iter)]
    if args.deadline_ms is not None:
        serve_args += ["--deadline-ms", str(args.deadline_ms)]
    if args.batch_max is not None:
        serve_args += ["--batch-max", str(args.batch_max)]
    if args.warm:
        serve_args += ["--warm", args.warm]
    fleet = Fleet(cfg, serve_args=serve_args)

    stopped = threading.Event()

    def _term(_sig, _frm):
        # stop() blocks on child SIGTERM drains — never in a signal frame
        def _run():
            fleet.stop()
            stopped.set()

        threading.Thread(target=_run, name="fleet-sigterm",
                         daemon=True).start()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)

    ready = fleet.start()
    print(json.dumps({
        "ready": True,
        "socket": ready["socket"],
        "replicas": ready["replicas"],
        "ready_s": round(time.perf_counter() - t0, 3),
    }), flush=True)
    stopped.wait()
    print(json.dumps({"exit": True,
                      "telemetry": fleet.telemetry()}), flush=True)
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "smoke":
        from raft_tpu.serve import smoke

        return smoke.main(argv[1:])
    if argv and argv[0] == "fleet-smoke":
        from raft_tpu.serve import fleet_smoke

        return fleet_smoke.main(argv[1:])
    if argv and argv[0] == "fleet":
        return _fleet(argv[1:])
    if argv and argv[0] == "daemon":
        argv = argv[1:]
    return _daemon(argv)


if __name__ == "__main__":
    sys.exit(main())
