"""Synthetic open-loop load generator for the resident solver service.

OPEN loop: request ``i`` is submitted at its scheduled instant whether or
not earlier requests completed — the arrival process never adapts to the
server (closed-loop generators hide overload by self-throttling; see any
coordinated-omission discussion).  The schedule is CLOSED-FORM — design,
sea state, and arrival offset are pure functions of the request index,
zero wall-clock randomness — so two runs issue byte-identical request
streams and the bench's ``serving`` block is reproducible:

* ``design(i)``: cycles the mixed stream (default OC3 spar -> OC4 semi ->
  VolturnUS-S — two shape buckets under the stock ladder);
* ``Hs(i) = 6 + 0.5 * (i mod 5)``, ``Tp(i) = 10 + 0.25 * (i mod 7)``
  (35 distinct sea states, exercising the staging memo without
  unbounded growth);
* ``arrival_s(i) = i / rate``.

Latency accounting: per request, ``t_done - t_sched`` (completion wall
instant minus the SCHEDULED arrival) — the number a client shows a user,
queueing delay included.  Quantiles are deterministic rank statistics
(sorted, ``ceil(q*n)-1``), the same rule as
:meth:`raft_tpu.obs.metrics.Histogram.quantile`.

The sequential baseline (`run_sequential`) issues the SAME request
stream one-at-a-time (submit, wait, next) — the one-shot-process usage
pattern the daemon exists to beat; ``batched solves/s >= 3x sequential``
is the acceptance gate of the bench block.
"""
from __future__ import annotations

import math
import time

DEFAULT_DESIGNS = ("oc3", "oc4", "volturnus")


def schedule(i: int, rate: float, designs=DEFAULT_DESIGNS,
             n_hs: int = 5, n_tp: int = 7):
    """Request ``i`` of the closed-form stream ->
    ``(design, Hs, Tp, arrival_s)``.  ``n_hs``/``n_tp`` bound the
    sea-state variety (``n_hs * n_tp`` distinct states): the default 35
    exercises the staging memo hard; the bench uses a smaller product so
    a measured pass runs against a WARM memo (one staging per distinct
    state, amortized in the warm pass)."""
    return (designs[i % len(designs)],
            6.0 + 0.5 * (i % n_hs),
            10.0 + 0.25 * (i % n_tp),
            i / float(rate))


def quantile(xs, q: float) -> float:
    """Deterministic rank quantile (sorted, ``ceil(q*n)-1``)."""
    if not xs:
        return float("nan")
    s = sorted(xs)
    return s[min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))]


def _summary(lat, n: int, wall_s: float) -> dict:
    return {
        "n_requests": n,
        "wall_s": round(wall_s, 4),
        "solves_per_s": round(n / wall_s, 2) if wall_s > 0 else None,
        "latency_p50_s": round(quantile(lat, 0.50), 4),
        "latency_p99_s": round(quantile(lat, 0.99), 4),
        "latency_mean_s": round(sum(lat) / len(lat), 4) if lat else None,
    }


def run_open_loop(client, n: int, rate: float, designs=DEFAULT_DESIGNS,
                  timeout_s: float = 600.0, **sched_kw):
    """Drive ``n`` scheduled requests through an open
    :class:`~raft_tpu.serve.client.SolveClient`; block for every
    response; returns ``(summary, responses)``.  Raises on any failed
    response (a load test that drops errors measures nothing)."""
    done_t = [None] * n
    futs = []
    t0 = time.perf_counter()
    for i in range(n):
        design, Hs, Tp, arr = schedule(i, rate, designs, **sched_kw)
        delay = t0 + arr - time.perf_counter()
        if delay > 0:
            time.sleep(delay)             # open loop: schedule, not ack
        fut = client.submit({"op": "solve", "design": design,
                             "Hs": Hs, "Tp": Tp})

        def _stamp(f, i=i):
            done_t[i] = time.perf_counter()

        fut.add_done_callback(_stamp)
        futs.append(fut)
    results = [f.result(timeout_s) for f in futs]
    t_end = max(done_t)
    bad = [r for r in results if not r.get("ok")]
    if bad:
        raise RuntimeError(f"{len(bad)}/{n} requests failed; first: "
                           f"{bad[0].get('error')}")
    lat = [done_t[i] - (t0 + schedule(i, rate, designs, **sched_kw)[3])
           for i in range(n)]
    out = _summary(lat, n, t_end - t0)
    out["rate_req_per_s"] = rate
    out["mode"] = "open_loop"
    return out, results


def run_sequential(client, n: int, rate: float, designs=DEFAULT_DESIGNS,
                   timeout_s: float = 600.0, **sched_kw) -> dict:
    """The SAME request stream, one at a time (submit -> wait -> next):
    the one-shot usage pattern.  ``rate`` only selects the identical
    request parameters; arrivals are completion-driven by construction."""
    lat = []
    t0 = time.perf_counter()
    for i in range(n):
        design, Hs, Tp, _arr = schedule(i, rate, designs, **sched_kw)
        t_s = time.perf_counter()
        r = client.call({"op": "solve", "design": design,
                         "Hs": Hs, "Tp": Tp}, timeout=timeout_s)
        if not r.get("ok"):
            raise RuntimeError(f"sequential request {i} failed: "
                               f"{r.get('error')}")
        lat.append(time.perf_counter() - t_s)
    out = _summary(lat, n, time.perf_counter() - t0)
    out["mode"] = "sequential"
    return out
