"""Async client of the resident solver service.

A :class:`SolveClient` owns one socket connection, a background reader
thread, and a futures table keyed by request id: ``submit`` returns a
:class:`concurrent.futures.Future` immediately (the open-loop load
generator submits at its schedule regardless of completions), ``call``
is the synchronous convenience wrapper.  Responses arrive in whatever
order the server's batches close — the reader resolves each future by
the ``id`` echoed in the response frame.

Request tracing: every solve-kind submit carries a ``trace`` id (minted
here via :func:`raft_tpu.obs.trace.new_trace_id` unless the caller set
one), and the client records a ``request`` span — submit to response —
under that id on the request's synthetic track when the response lands.
In-process (the bench's embedded daemon, the tests) that client span is
the ROOT of the same tree the server's ``request/server`` spans nest
under; cross-process each side exports its own half, joined by the
shared trace id.
"""
from __future__ import annotations

import itertools
import socket
import threading
import time
from concurrent.futures import Future

from raft_tpu.serve import protocol

_TRACED_OPS = ("solve", "dlc", "sweep")


class ServerGone(ConnectionError):
    """The server closed the connection with requests still pending."""


class SolveClient:
    def __init__(self, socket_path: str, connect_timeout: float = 10.0,
                 retry_interval: float = 0.05):
        """Connect, retrying until ``connect_timeout`` — the standard way
        to wait for a freshly-spawned daemon to bind its socket."""
        self.socket_path = socket_path
        deadline = time.monotonic() + connect_timeout
        last: Exception | None = None
        while True:
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                self._sock.connect(socket_path)
                break
            except OSError as e:
                self._sock.close()
                last = e
                if time.monotonic() >= deadline:
                    raise ConnectionError(
                        f"could not reach solver daemon at {socket_path!r} "
                        f"within {connect_timeout}s: {e}") from last
                time.sleep(retry_interval)
        self._wlock = threading.Lock()
        self._flock = threading.Lock()
        self._futures: dict = {}
        self._ids = itertools.count()
        self._closed = False
        self._reader = threading.Thread(target=self._read_loop,
                                        name="serve-client-reader",
                                        daemon=True)
        self._reader.start()

    # ------------------------------------------------------------ plumbing
    def _read_loop(self) -> None:
        err: Exception = ServerGone("connection closed by server")
        try:
            while True:
                obj = protocol.recv_msg(self._sock)
                rid = obj.get("id") if isinstance(obj, dict) else None
                with self._flock:
                    entry = self._futures.pop(rid, None)
                if entry is not None:
                    fut, t_submit_ns, trace_id = entry
                    if trace_id:
                        # the client half of the request tree: submit ->
                        # response, on the request's synthetic track (the
                        # reader thread serves MANY overlapping requests —
                        # recording there would break track containment)
                        from raft_tpu.obs import trace as _trace

                        _trace.record(
                            "request", t_submit_ns, time.perf_counter_ns(),
                            trace=trace_id,
                            tid=_trace.synthetic_tid(trace_id),
                            track=f"req {rid}")
                    fut.set_result(obj)
                # responses for unknown ids (e.g. a server-side error
                # frame with id=None) are dropped — nothing waits on them
        except (protocol.PeerClosed, protocol.ProtocolError, OSError) as e:
            if not self._closed:
                err = e if isinstance(e, Exception) else err
        with self._flock:
            pending = [entry[0] for entry in self._futures.values()]
            self._futures.clear()
        for fut in pending:
            fut.set_exception(ServerGone(str(err)))

    def submit(self, obj: dict) -> Future:
        """Send one request frame; returns the Future of its response.
        Assigns a fresh ``id`` (and, for solve-kind ops, a fresh
        ``trace`` id) unless the caller set them."""
        if "id" not in obj or obj["id"] is None:
            obj = {**obj, "id": f"c{next(self._ids)}"}
        trace_id = obj.get("trace")
        if trace_id is None and obj.get("op") in _TRACED_OPS:
            from raft_tpu.obs import trace as _trace

            trace_id = _trace.new_trace_id()
            obj = {**obj, "trace": trace_id}
        fut: Future = Future()
        with self._flock:
            if self._closed:
                raise ConnectionError("client is closed")
            self._futures[obj["id"]] = (fut, time.perf_counter_ns(),
                                        trace_id or "")
        try:
            with self._wlock:
                protocol.send_msg(self._sock, obj)
        except OSError as e:
            with self._flock:
                self._futures.pop(obj["id"], None)
            raise ConnectionError(f"send failed: {e}") from e
        return fut

    def call(self, obj: dict, timeout: float = 120.0) -> dict:
        """Submit and wait; raises on transport failure, returns the
        response dict (check ``ok`` for application-level errors)."""
        return self.submit(obj).result(timeout)

    # ------------------------------------------------------- conveniences
    def ping(self, timeout: float = 10.0) -> dict:
        return self.call({"op": "ping"}, timeout)

    def stats(self, timeout: float = 30.0) -> dict:
        return self.call({"op": "stats"}, timeout)

    def solve(self, design, Hs: float, Tp: float,
              timeout: float = 120.0) -> dict:
        return self.call({"op": "solve", "design": design,
                          "Hs": Hs, "Tp": Tp}, timeout)

    def shutdown(self, timeout: float = 30.0) -> dict:
        return self.call({"op": "shutdown"}, timeout)

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
