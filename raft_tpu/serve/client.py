"""Async client of the resident solver service.

A :class:`SolveClient` owns one socket connection, a background reader
thread, and a futures table keyed by request id: ``submit`` returns a
:class:`concurrent.futures.Future` immediately (the open-loop load
generator submits at its schedule regardless of completions), ``call``
is the synchronous convenience wrapper.  Responses arrive in whatever
order the server's batches close — the reader resolves each future by
the ``id`` echoed in the response frame.

Failure typing (the fleet router's failover machinery keys on these):

* connect attempts run through :func:`raft_tpu.resilience.retry.
  retry_call` — bounded, backoff-aware, deadline-capped by
  ``connect_timeout`` — and exhaustion raises
  :class:`ServeConnectionLost`;
* with a ``read_timeout``, a request whose response has not arrived
  within the deadline fails its future with :class:`ServeTimeout` (the
  connection stays up: the daemon may just be slow, and other requests'
  frames are still good).  Without one, a dead-but-connected daemon can
  no longer block forever either — reader death fails every pending
  future with :class:`ServeConnectionLost`.

Request tracing: every solve-kind submit carries a ``trace`` id (minted
here via :func:`raft_tpu.obs.trace.new_trace_id` unless the caller set
one), and the client records a ``request`` span — submit to response —
under that id on the request's synthetic track when the response lands.
In-process (the bench's embedded daemon, the tests) that client span is
the ROOT of the same tree the server's ``request/server`` spans nest
under; cross-process each side exports its own half, joined by the
shared trace id.
"""
from __future__ import annotations

import itertools
import select
import socket
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as _FutTimeout

from raft_tpu.resilience.retry import RetryExhausted, retry_call
from raft_tpu.serve import protocol

_TRACED_OPS = ("solve", "dlc", "sweep")

#: reader poll granularity while a read deadline is armed (a pure
#: wake-up-and-scan cadence: frames are never truncated by it — the poll
#: is a ``select`` BEFORE the frame read, so no bytes are consumed)
_POLL_S = 0.05


class ServeConnectionLost(ConnectionError):
    """The server connection died (connect ladder exhausted, or the
    stream closed/broke with requests still pending)."""


class ServeTimeout(ConnectionError):
    """A request's response did not arrive within the client's read
    deadline.  The connection itself is still up — solves are pure, so
    the caller may re-submit (the fleet router does, to a survivor)."""


#: backwards-compatible alias (pre-fleet name of the connection-loss
#: failure; external callers may still catch it)
ServerGone = ServeConnectionLost


def _connect(socket_path: str, connect_timeout: float,
             retry_interval: float):
    """One bounded connect ladder through the shared retry discipline;
    returns the connected socket or raises :class:`ServeConnectionLost`."""
    def attempt(_i):
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            s.connect(socket_path)
            return s
        except OSError:
            s.close()
            raise

    tries = max(1, int(connect_timeout / max(retry_interval, 1e-3)) + 1)
    try:
        return retry_call(
            attempt, retries=tries, backoff_s=retry_interval, growth=1.0,
            max_backoff_s=retry_interval, deadline_s=connect_timeout,
            retry_on=(OSError,),
            describe=f"connect solver daemon at {socket_path!r}")
    except RetryExhausted as e:
        raise ServeConnectionLost(
            f"could not reach solver daemon at {socket_path!r} within "
            f"{connect_timeout}s: {e.last}") from e


class SolveClient:
    def __init__(self, socket_path: str, connect_timeout: float = 10.0,
                 retry_interval: float = 0.05,
                 read_timeout: float | None = None):
        """Connect, retrying until ``connect_timeout`` — the standard way
        to wait for a freshly-spawned daemon to bind its socket.
        ``read_timeout`` (seconds, per request) arms the read deadline:
        a response overdue past it fails that request's future with
        :class:`ServeTimeout` while the connection keeps serving the
        rest."""
        self.socket_path = socket_path
        self.read_timeout = read_timeout
        self._sock = _connect(socket_path, connect_timeout, retry_interval)
        self._wlock = threading.Lock()
        self._flock = threading.Lock()
        self._futures: dict = {}
        self._ids = itertools.count()
        self._closed = False
        self._reader = threading.Thread(target=self._read_loop,
                                        name="serve-client-reader",
                                        daemon=True)
        self._reader.start()

    # ------------------------------------------------------------ plumbing
    def _expire_overdue(self) -> None:
        """Fail every pending future whose read deadline has passed (the
        response, if it ever arrives, is dropped by the unknown-id
        path).  Called from the reader between polls."""
        if self.read_timeout is None:
            return
        now_ns = time.perf_counter_ns()
        limit_ns = int(self.read_timeout * 1e9)
        overdue = []
        with self._flock:
            for rid, (fut, t_submit_ns, _tr) in list(self._futures.items()):
                if now_ns - t_submit_ns > limit_ns:
                    overdue.append((rid, self._futures.pop(rid)[0]))
        for rid, fut in overdue:
            fut.set_exception(ServeTimeout(
                f"request {rid!r} got no response within "
                f"{self.read_timeout}s"))

    def _read_loop(self) -> None:
        err: Exception = ServeConnectionLost("connection closed by server")
        try:
            while True:
                if self.read_timeout is not None:
                    # deadline poll BEFORE the frame read: a timeout here
                    # consumes no bytes, so framing can never tear
                    r, _, _ = select.select([self._sock], [], [], _POLL_S)
                    if not r:
                        self._expire_overdue()
                        continue
                obj = protocol.recv_msg(self._sock)
                rid = obj.get("id") if isinstance(obj, dict) else None
                with self._flock:
                    entry = self._futures.pop(rid, None)
                if entry is not None:
                    fut, t_submit_ns, trace_id = entry
                    if trace_id:
                        # the client half of the request tree: submit ->
                        # response, on the request's synthetic track (the
                        # reader thread serves MANY overlapping requests —
                        # recording there would break track containment)
                        from raft_tpu.obs import trace as _trace

                        _trace.record(
                            "request", t_submit_ns, time.perf_counter_ns(),
                            trace=trace_id,
                            tid=_trace.synthetic_tid(trace_id),
                            track=f"req {rid}")
                    fut.set_result(obj)
                # responses for unknown ids (e.g. a server-side error
                # frame with id=None, or one that already timed out) are
                # dropped — nothing waits on them
        except (protocol.PeerClosed, protocol.ProtocolError, OSError) as e:
            if not self._closed:
                err = e if isinstance(e, Exception) else err
        with self._flock:
            pending = [entry[0] for entry in self._futures.values()]
            self._futures.clear()
        for fut in pending:
            fut.set_exception(ServeConnectionLost(str(err)))

    def submit(self, obj: dict) -> Future:
        """Send one request frame; returns the Future of its response.
        Assigns a fresh ``id`` (and, for solve-kind ops, a fresh
        ``trace`` id) unless the caller set them."""
        if "id" not in obj or obj["id"] is None:
            obj = {**obj, "id": f"c{next(self._ids)}"}
        trace_id = obj.get("trace")
        if trace_id is None and obj.get("op") in _TRACED_OPS:
            from raft_tpu.obs import trace as _trace

            trace_id = _trace.new_trace_id()
            obj = {**obj, "trace": trace_id}
        fut: Future = Future()
        with self._flock:
            if self._closed:
                raise ServeConnectionLost("client is closed")
            self._futures[obj["id"]] = (fut, time.perf_counter_ns(),
                                        trace_id or "")
        try:
            with self._wlock:
                protocol.send_msg(self._sock, obj)
        except OSError as e:
            with self._flock:
                self._futures.pop(obj["id"], None)
            raise ServeConnectionLost(f"send failed: {e}") from e
        return fut

    def call(self, obj: dict, timeout: float = 120.0) -> dict:
        """Submit and wait; raises on transport failure (typed:
        :class:`ServeTimeout` on deadline, :class:`ServeConnectionLost`
        on a dead connection), returns the response dict (check ``ok``
        for application-level errors)."""
        fut = self.submit(obj)
        try:
            return fut.result(timeout)
        except _FutTimeout:
            raise ServeTimeout(
                f"request {obj.get('id')!r} got no response within "
                f"{timeout}s") from None

    # ------------------------------------------------------- conveniences
    def ping(self, timeout: float = 10.0) -> dict:
        return self.call({"op": "ping"}, timeout)

    def stats(self, timeout: float = 30.0) -> dict:
        return self.call({"op": "stats"}, timeout)

    def solve(self, design, Hs: float, Tp: float,
              timeout: float = 120.0) -> dict:
        return self.call({"op": "solve", "design": design,
                          "Hs": Hs, "Tp": Tp}, timeout)

    def shutdown(self, timeout: float = 30.0) -> dict:
        return self.call({"op": "shutdown"}, timeout)

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
