"""Supervised replica fleet: N warm daemons behind one failover router.

``Fleet`` is the supervisor of the serving tier (ROADMAP item 2: from
one warm daemon to a horizontally scaled tier).  It launches N replica
daemons — each one a ``python -m raft_tpu.serve daemon`` child on its
own AF_UNIX socket — ALL sharing one ``RAFT_TPU_CACHE_DIR`` root, so
every replica past the first arms entirely off the AOT disk cache
(zero compiles at ready) and a restarted replica comes back warm for
the same reason.  In front of them it runs a
:class:`~raft_tpu.serve.router.FleetRouter` in-process: clients speak
the unchanged length-prefixed JSON protocol to ONE socket and never
learn the tier's width.

Supervision contract:

* the babysit loop ``wait``-polls every child; a dead one is restarted
  on its original socket path, warm off the shared cache root, and
  RE-ADMITTED only after the router's health probe passes — a replica
  that restarts but cannot serve never takes traffic;
* restarts are storm-bounded: at most ``restart_max`` restarts per
  ``restart_window_s`` sliding window per replica (a crash-looping
  child must not melt the host), with the suppression visible as the
  ``fleet.restart_suppressed`` counter and in telemetry;
* the supervisor is the router's fault *injector*: the counted
  ``kill_replica:K`` fault (:mod:`raft_tpu.resilience.faults`) reaches
  a real ``SIGKILL`` through :meth:`Fleet.kill`, which is also what the
  fleet smoke uses to prove the failover path against real processes.

Everything is injectable for the deterministic tests: ``spawn_fn``
replaces the Popen child with anything that returns ``(handle,
ready_dict)`` (the restart-storm test hands back instantly-dead
handles), ``clock`` drives the restart window, and
:meth:`Fleet._babysit_once` is the loop body tests call directly.

``FleetConfig`` is the arm-time snapshot of the ``RAFT_TPU_FLEET_*``
knobs (registered in :mod:`raft_tpu.lint.knobs`) — the GL303 contract:
the router's concurrent request path only ever sees this frozen
dataclass, never ``os.environ``.
"""
from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque

from raft_tpu.obs import metrics as _metrics
from raft_tpu.serve.router import FleetRouter

REPLICAS_ENV = "RAFT_TPU_FLEET_REPLICAS"
PROBE_MS_ENV = "RAFT_TPU_FLEET_PROBE_MS"
PROBE_TIMEOUT_MS_ENV = "RAFT_TPU_FLEET_PROBE_TIMEOUT_MS"
QUEUE_MAX_ENV = "RAFT_TPU_FLEET_QUEUE_MAX"
SHED_ERROR_RATE_ENV = "RAFT_TPU_FLEET_SHED_ERROR_RATE"
RESTART_MAX_ENV = "RAFT_TPU_FLEET_RESTART_MAX"
RESTART_WINDOW_S_ENV = "RAFT_TPU_FLEET_RESTART_WINDOW_S"
SOCKET_ENV = "RAFT_TPU_FLEET_SOCKET"

DEFAULT_REPLICAS = 2
DEFAULT_PROBE_MS = 500.0
DEFAULT_PROBE_TIMEOUT_MS = 2000.0
DEFAULT_QUEUE_MAX = 32
DEFAULT_SHED_ERROR_RATE = 0.5
DEFAULT_RESTART_MAX = 3
DEFAULT_RESTART_WINDOW_S = 30.0


def default_fleet_socket() -> str:
    """Default front-end AF_UNIX socket path (per-uid tmp namespace,
    distinct from the single daemon's default so both can coexist)."""
    return os.path.join(tempfile.gettempdir(),
                        f"raft_tpu_fleet_{os.getuid()}.sock")


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Frozen arm-time snapshot of everything the fleet tier consults
    (supervisor AND router — one snapshot, handed to both)."""

    replicas: int = DEFAULT_REPLICAS
    #: heartbeat cadence; <= 0 disables the probe/babysit threads (the
    #: deterministic tests drive probe_once()/_babysit_once() directly)
    probe_interval_s: float = DEFAULT_PROBE_MS / 1e3
    #: deadline on each ping probe AND each admission/refresh connection
    probe_timeout_s: float = DEFAULT_PROBE_TIMEOUT_MS / 1e3
    #: forward deadline: an in-flight request older than this is expired
    #: into the resubmission ladder (the stalled-replica recovery path)
    request_timeout_s: float = 120.0
    #: per-replica in-flight cap; total admission is queue_max x healthy
    queue_max: int = DEFAULT_QUEUE_MAX
    #: windowed SLO error rate above which admission sheds
    shed_error_rate: float = DEFAULT_SHED_ERROR_RATE
    #: minimum windowed events before the error budget can shed (a single
    #: early error must not latch an idle fleet shut)
    shed_min_events: int = 8
    #: retry-after hint carried on every shed response
    retry_after_ms: float = 50.0
    #: restart-storm bound: restarts per replica per sliding window
    restart_max: int = DEFAULT_RESTART_MAX
    restart_window_s: float = DEFAULT_RESTART_WINDOW_S
    #: failover resubmission ladder (retry_call bounds)
    resubmit_retries: int = 4
    resubmit_backoff_s: float = 0.05
    #: front-end socket path ("" = default_fleet_socket())
    socket_path: str = ""

    @classmethod
    def from_env(cls, **overrides) -> "FleetConfig":
        """Snapshot the ``RAFT_TPU_FLEET_*`` knobs (arm time only — never
        from the request path).  ``overrides`` win over the environment
        (CLI flags, test fixtures).  Malformed values fail LOUDLY."""
        vals: dict = {}

        def _num(raw, env: str, key: str, cast, scale=None, unit=""):
            # the caller fetches the value with the knob-name constant
            # inline so the registry-drift audit sees each read
            raw = (raw or "").strip()
            if not raw:
                return
            try:
                v = cast(raw)
            except ValueError:
                kind = "an integer" if cast is int else "a number"
                raise ValueError(f"{env}={raw!r} is not {kind}{unit}")
            vals[key] = v if scale is None else v * scale

        _num(os.environ.get(REPLICAS_ENV), REPLICAS_ENV,
             "replicas", int)
        _num(os.environ.get(PROBE_MS_ENV), PROBE_MS_ENV,
             "probe_interval_s", float, scale=1e-3,
             unit=" (milliseconds)")
        _num(os.environ.get(PROBE_TIMEOUT_MS_ENV), PROBE_TIMEOUT_MS_ENV,
             "probe_timeout_s", float, scale=1e-3,
             unit=" (milliseconds)")
        _num(os.environ.get(QUEUE_MAX_ENV), QUEUE_MAX_ENV,
             "queue_max", int)
        _num(os.environ.get(SHED_ERROR_RATE_ENV), SHED_ERROR_RATE_ENV,
             "shed_error_rate", float)
        _num(os.environ.get(RESTART_MAX_ENV), RESTART_MAX_ENV,
             "restart_max", int)
        _num(os.environ.get(RESTART_WINDOW_S_ENV), RESTART_WINDOW_S_ENV,
             "restart_window_s", float, unit=" (seconds)")
        vals["socket_path"] = (os.environ.get(SOCKET_ENV, "").strip()
                               or default_fleet_socket())
        vals.update(overrides)
        cfg = cls(**vals)
        if cfg.replicas < 1:
            raise ValueError(f"{REPLICAS_ENV} must be >= 1, got "
                             f"{cfg.replicas}")
        if cfg.queue_max < 1:
            raise ValueError(f"{QUEUE_MAX_ENV} must be >= 1, got "
                             f"{cfg.queue_max}")
        if cfg.probe_timeout_s <= 0:
            raise ValueError(f"{PROBE_TIMEOUT_MS_ENV} must be > 0, got "
                             f"{cfg.probe_timeout_s * 1e3}")
        if not (0.0 <= cfg.shed_error_rate <= 1.0):
            raise ValueError(f"{SHED_ERROR_RATE_ENV} must be in [0, 1], "
                             f"got {cfg.shed_error_rate}")
        if cfg.restart_max < 0 or cfg.restart_window_s <= 0:
            raise ValueError(
                f"{RESTART_MAX_ENV}/{RESTART_WINDOW_S_ENV} must be "
                f">= 0 / > 0, got {cfg.restart_max}/{cfg.restart_window_s}")
        return cfg


class _Replica:
    """Supervisor-side record of one replica child (babysit-loop state;
    the router keeps its own routing view keyed by the same index)."""

    def __init__(self, idx: int, socket_path: str):
        self.idx = idx
        self.socket_path = socket_path
        self.handle = None           # Popen-like: poll/kill/terminate/wait
        self.ready: dict = {}        # last ready line (compiles_at_ready..)
        self.restarts = 0
        self.suppressed = False
        self.restart_times: deque = deque()


class Fleet:
    """See module docstring.  ``serve_args`` is appended to every child's
    ``python -m raft_tpu.serve daemon --socket <path>`` command line
    (``--nw``, ``--warm``, ...); ``child_env`` replaces the inherited
    environment (the smoke pins the shared cache root there)."""

    def __init__(self, config: FleetConfig | None = None, serve_args=(),
                 child_env: dict | None = None, run_dir: str | None = None,
                 spawn_fn=None, clock=time.monotonic,
                 ready_timeout_s: float = 300.0):
        self.config = config if config is not None else FleetConfig.from_env()
        self.serve_args = list(serve_args)
        self.child_env = dict(child_env) if child_env is not None else None
        self.run_dir = run_dir or tempfile.mkdtemp(prefix="raft_tpu_fleet_")
        self.spawn_fn = spawn_fn or self._spawn_daemon_child
        self.clock = clock
        self.ready_timeout_s = float(ready_timeout_s)
        self._replicas = [
            _Replica(i, os.path.join(self.run_dir, f"replica{i}.sock"))
            for i in range(self.config.replicas)]
        self.router = FleetRouter(
            self.config, [r.socket_path for r in self._replicas],
            socket_path=(self.config.socket_path or default_fleet_socket()),
            injector=self, on_shutdown=self.stop)
        self._lock = threading.Lock()
        self._stopping = threading.Event()
        self._babysit_thread: threading.Thread | None = None

    # ---------------------------------------------------------- lifecycle
    def start(self) -> dict:
        """Spawn every replica to its ready line, arm the router (which
        admits them), start the babysit loop; returns the fleet's ready
        summary (front socket + per-replica ready lines)."""
        for r in self._replicas:
            self._spawn(r)
        self.router.start()
        if self.config.probe_interval_s > 0:
            self._babysit_thread = threading.Thread(
                target=self._babysit_loop, name="fleet-babysit", daemon=True)
            self._babysit_thread.start()
        return {"socket": self.router.socket_path,
                "replicas": {str(r.idx): r.ready for r in self._replicas}}

    def stop(self, timeout: float = 30.0) -> None:
        """Router first (stops intake, fails in-flight loudly), then
        SIGTERM every child with a bounded wait (kill on overrun)."""
        if self._stopping.is_set():
            return
        self._stopping.set()
        if self._babysit_thread is not None:
            self._babysit_thread.join(timeout=timeout)
        self.router.stop()
        procs = []
        for r in self._replicas:
            h = r.handle
            if h is None or h.poll() is not None:
                continue
            try:
                h.terminate()
                procs.append(h)
            except OSError:                     # pragma: no cover
                pass
        for h in procs:
            try:
                h.wait(timeout)
            except subprocess.TimeoutExpired:   # pragma: no cover
                h.kill()
                h.wait(10.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    # ----------------------------------------------------------- spawning
    def _spawn(self, r: _Replica) -> None:
        handle, ready = self.spawn_fn(r.idx, r.socket_path)
        with self._lock:
            r.handle = handle
            r.ready = ready

    def _spawn_daemon_child(self, idx: int, socket_path: str):
        """Default ``spawn_fn``: one real daemon child, stderr to a file
        (a chatty child must never block on an undrained pipe), blocking
        until its ready line (threaded deadline) — the serve-smoke spawn
        discipline."""
        from raft_tpu.serve.smoke import _read_ready_line

        stderr_path = os.path.join(self.run_dir, f"replica{idx}.err")
        stderr_f = open(stderr_path, "a")
        env = (dict(self.child_env) if self.child_env is not None
               else dict(os.environ))
        # a replica child is unbounded by design: its lifetime is owned
        # by this supervisor (ready-line deadline below, SIGTERM + bounded
        # wait in stop(), SIGKILL through the kill_replica injector)
        proc = subprocess.Popen(  # graftlint: disable=GL203
            [sys.executable, "-m", "raft_tpu.serve", "daemon",
             "--socket", socket_path, *self.serve_args],
            stdout=subprocess.PIPE, stderr=stderr_f, text=True, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
        )
        stderr_f.close()                 # the child holds its own handle
        try:
            line = _read_ready_line(proc, self.ready_timeout_s)
        except RuntimeError as e:
            try:
                with open(stderr_path) as f:
                    tail = f.read()[-2000:]
            except OSError:
                tail = "<stderr unavailable>"
            raise RuntimeError(
                f"replica {idx} failed to become ready: {e}\n"
                f"--- replica stderr tail ---\n{tail}")
        ready = json.loads(line)
        if not ready.get("ready"):
            raise RuntimeError(f"unexpected replica {idx} ready line: "
                               f"{line!r}")
        return proc, ready

    # ---------------------------------------------------- fault injector
    def kill(self, idx: int) -> None:
        """SIGKILL replica ``idx`` — the router's ``kill_replica``
        injection hook (and the smoke's chaos hand).  The babysit loop
        restarts it warm; the router re-admits it after a passing probe."""
        h = self._replicas[idx].handle
        if h is None:
            return
        try:
            h.kill()
        except OSError:                          # pragma: no cover
            pass

    # ------------------------------------------------------- babysitting
    def _babysit_loop(self) -> None:
        while not self._stopping.wait(self.config.probe_interval_s):
            try:
                self._babysit_once()
            except Exception:      # pragma: no cover - supervision must
                pass               # survive anything a respawn can raise

    def _babysit_once(self, now: float | None = None) -> list:
        """One supervision sweep (the loop body; the restart-storm test
        calls it directly on a virtual clock): restart dead children
        within the per-replica storm bound.  Returns the indices
        restarted this sweep."""
        now = self.clock() if now is None else now
        cfg = self.config
        restarted = []
        for r in self._replicas:
            h = r.handle
            if h is not None and h.poll() is None:
                continue                      # alive
            if self._stopping.is_set():
                break
            while (r.restart_times
                   and now - r.restart_times[0] > cfg.restart_window_s):
                r.restart_times.popleft()
            if len(r.restart_times) >= cfg.restart_max:
                if not r.suppressed:
                    r.suppressed = True
                    _metrics.counter("fleet.restart_suppressed").inc()
                continue                      # window full: wait it out
            r.restart_times.append(now)
            r.restarts += 1
            r.suppressed = False
            _metrics.counter("fleet.restart").inc()
            try:
                self._spawn(r)
                restarted.append(r.idx)
            except Exception:
                # the failed spawn consumed a restart-budget slot; the
                # next sweep retries, bounded by the same window
                with self._lock:
                    r.handle = None
        return restarted

    # -------------------------------------------------------- telemetry
    def telemetry(self) -> dict:
        """Supervisor view (restarts, suppression, ready lines) merged
        with the router's live routing/SLO snapshot."""
        with self._lock:
            sup = [{"idx": r.idx,
                    "alive": (r.handle is not None
                              and r.handle.poll() is None),
                    "restarts": r.restarts,
                    "suppressed": r.suppressed,
                    "compiles_at_ready": r.ready.get("compiles_at_ready"),
                    "socket": r.socket_path}
                   for r in self._replicas]
        return {"supervisor": {"replicas": sup, "run_dir": self.run_dir},
                "router": self.router.telemetry()}
