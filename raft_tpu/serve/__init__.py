"""Resident solver service: continuous deadline-bounded request batching.

Every other entry point in this package is a one-shot process: stage,
compile (or AOT-load), solve, exit.  This subsystem keeps all of that
machinery RESIDENT — staged design bases, one warm executable per shape
bucket, the dispatch pipeline — inside a long-lived daemon on a local
socket, and amortizes it across an arbitrary stream of independent
clients (the ROADMAP "millions of users" direction).

The moving parts, one module each:

``config``
    :class:`ServeConfig` — every knob the serve loop consults, snapshotted
    ONCE at arm time (``RAFT_TPU_SERVE_BATCH_DEADLINE_MS`` /
    ``RAFT_TPU_SERVE_BATCH_MAX`` / ``RAFT_TPU_SERVE_SOCKET``); the
    concurrent request path never reads the environment (GL303).
``protocol``
    Length-prefixed JSON framing over a local stream socket, plus request
    validation: ``solve`` (one design x one sea state = one lane),
    ``dlc`` (one design x N sea states = N lanes), ``sweep`` (N designs
    x one sea state = N lanes, possibly spanning buckets), ``ping`` /
    ``stats`` / ``refresh`` / ``shutdown``.
``batcher``
    :class:`~raft_tpu.serve.batcher.MicroBatcher` — the deterministic
    deadline-or-capacity micro-batching core.  Pure queue logic with an
    injectable clock: the same arrival schedule always closes the same
    batch compositions (pinned by tests on a virtual clock).
``solver``
    :class:`~raft_tpu.serve.solver.SolverCore` — warm staging memo
    (design x sea state -> bucket-padded lane arrays) and
    :func:`~raft_tpu.serve.solver.solve_batch`: pad a closed batch to the
    FIXED lane capacity, solve it through
    :func:`~raft_tpu.parallel.sweep.sweep_designs` (health + quarantine
    per lane), and slice per-lane results back to their owning requests.
``server``
    The daemon: accept loop, per-connection reader threads, one solver
    loop draining the batcher, graceful SIGTERM drain.
``client``
    :class:`~raft_tpu.serve.client.SolveClient` — async submit/collect
    over the socket (futures keyed by request id), typed transport
    failures (``ServeTimeout`` / ``ServeConnectionLost``), bounded
    reconnects through the shared retry ladder.
``fleet`` / ``router``
    The horizontally scaled tier: :class:`~raft_tpu.serve.fleet.Fleet`
    supervises N daemon replicas on one shared AOT cache root (warm,
    zero-compile restarts; storm-bounded) behind a
    :class:`~raft_tpu.serve.router.FleetRouter` — same wire protocol,
    one socket, bucket-affinity routing, heartbeat health probes,
    failover resubmission of in-flight requests, and error-budget load
    shedding with typed ``Overloaded`` responses (``make fleet-smoke``).
``loadgen``
    Synthetic OPEN-LOOP load generator with a closed-form arrival
    schedule (zero wall-clock randomness) and deterministic p50/p99
    accounting — the bench's ``serving`` block.
``smoke``
    ``make serve-smoke``: cross-process proof — mixed 3-design stream,
    compiles == n_buckets, parity vs solo solves, SIGTERM -> warm
    restart with ZERO compiles off the AOT disk cache.

Why per-request results cannot depend on batch-mates: every dispatch is
padded to ``batch_max`` lanes (unused lanes tile the real ones), so ONE
executable per bucket serves every occupancy, and a lane's values ride a
vmapped axis whose per-lane program is independent — the same request
returns bit-identical results whether it shared its batch with zero,
three, or seven strangers (pinned by tests/test_serve.py).
"""
# lazy exports (PEP 562): the fleet tier (router/supervisor/smoke
# parents) imports serve submodules without paying — or even having —
# the solver stack's JAX import; attribute access resolves on demand
_EXPORTS = {
    "ServeConfig": "raft_tpu.serve.config",
    "Lane": "raft_tpu.serve.batcher",
    "MicroBatcher": "raft_tpu.serve.batcher",
    "SolverCore": "raft_tpu.serve.solver",
    "solve_batch": "raft_tpu.serve.solver",
    "SolveClient": "raft_tpu.serve.client",
    "SolverServer": "raft_tpu.serve.server",
    "Fleet": "raft_tpu.serve.fleet",
    "FleetConfig": "raft_tpu.serve.fleet",
    "FleetRouter": "raft_tpu.serve.router",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    target = _EXPORTS.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}")
    import importlib

    return getattr(importlib.import_module(target), name)
