"""Front-end failover router of the serving fleet.

One router process speaks the existing length-prefixed JSON protocol
(:mod:`raft_tpu.serve.protocol`) on its own AF_UNIX socket and fans
solve-kind requests out to N replica daemons (each a ``python -m
raft_tpu.serve daemon`` child, babysat by :class:`raft_tpu.serve.fleet.
Fleet`).  The router imports no JAX: it is pure socket plumbing plus the
obs layer, so it stays responsive while every replica is busy solving.

Routing — bucket affinity, deterministically: the affinity key is the
request's first design label (a design pins its shape bucket, so the
label is a stable proxy for "which bucket executable this request
heats").  The first request for a label pins it to the least-loaded
healthy replica (ties break to the lowest index); subsequent requests
for the same label follow the pin while the pinned replica is healthy
and below its ``queue_max`` in-flight cap, and re-pin by the same
least-loaded rule otherwise.  Two routers fed the same request sequence
route identically.

Degradation contract:

* **replica death** (heartbeat deadline, connection EOF, send failure):
  the replica is marked down, its in-flight forwards are re-submitted to
  survivors through :func:`raft_tpu.resilience.retry.retry_call`'s
  bounded-backoff ladder — idempotent by construction, solves are pure —
  and each recovered response carries a ``resubmits`` count while
  keeping its original ``trace`` id.  Re-admission happens only after a
  passing ``ping`` probe (the supervisor restarts the process; this
  router decides when it is servable again).
* **overload**: deterministic admission control — total in-flight at or
  above ``queue_max`` x healthy replicas, a windowed
  :class:`~raft_tpu.obs.metrics.SlidingHistogram` error rate above the
  shed threshold, or no healthy replica at all — answers immediately
  with the typed ``Overloaded`` error and a ``retry_after_ms`` hint
  (:func:`raft_tpu.serve.protocol.overloaded_response`); nothing queues
  unboundedly.

Fault hooks (:mod:`raft_tpu.resilience.faults`): ``kill_replica:K``
SIGKILLs the replica the router just picked (through the supervisor's
injector) before forwarding, ``stall_replica:K`` registers but withholds
the next K forwards (the forward deadline recovers them), and
``refuse_connect:K`` fails the next K replica connection attempts — all
host-side, all counted, so every failover path is drivable
deterministically.

Observability: per-replica ``fleet.replica_up[i]`` gauges; exact
``fleet.forwarded`` / ``fleet.relayed`` / ``fleet.failover`` /
``fleet.resubmitted`` / ``fleet.shed`` / ``fleet.timeouts`` counters; a
windowed router-latency SLO histogram on the injectable clock; and a
``request/router`` span per relayed response, recorded under the
request's original trace id (trace continuity across failover is a
tested invariant).
"""
from __future__ import annotations

import itertools
import os
import socket
import threading
import time

from raft_tpu.obs import metrics as _metrics
from raft_tpu.obs import trace as _trace
from raft_tpu.obs.metrics import SlidingHistogram
from raft_tpu.resilience import faults
from raft_tpu.resilience.retry import RetryExhausted, retry_call
from raft_tpu.serve import protocol

#: router request-path functions under the GL3xx concurrency contracts
__graftlint_concurrent__ = (
    "_handle_conn", "_dispatch", "_admit", "_forward", "_pick_locked",
    "_relay", "_link_read_loop", "_fail_replica", "_resubmit",
    "probe_once", "_probe", "_try_admit", "_connect_link", "telemetry",
)

#: counters the telemetry snapshot surfaces (all owned by this process)
_COUNTERS = ("forwarded", "relayed", "failover", "resubmitted", "shed",
             "timeouts", "restart", "restart_suppressed")


class NoHealthyReplica(ConnectionError):
    """Every replica is down (or not yet admitted) — retried through the
    resubmission ladder; exhaustion answers the client with the typed
    error frame."""


class _Conn:
    """One client connection: the socket plus its write lock (relays
    arrive from several link-reader threads and control answers from the
    conn's own reader — frames must not interleave)."""

    def __init__(self, sock):
        self.sock = sock
        self.wlock = threading.Lock()

    def send(self, obj) -> bool:
        try:
            with self.wlock:
                protocol.send_msg(self.sock, obj)
            return True
        except (OSError, ValueError):
            return False          # client went away; its results drop

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class _Link:
    """The router's admitted connection to one replica: socket + write
    lock (forwards come from many conn readers and the resubmit path)."""

    def __init__(self, sock):
        self.sock = sock
        self.wlock = threading.Lock()

    def send(self, obj) -> bool:
        try:
            with self.wlock:
                protocol.send_msg(self.sock, obj)
            return True
        except (OSError, ValueError):
            return False

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class _ReplicaState:
    """Router-side view of one replica (all fields guarded by the
    router's lock except ``idx``/``socket_path``, frozen at build)."""

    def __init__(self, idx: int, socket_path: str):
        self.idx = idx
        self.socket_path = socket_path
        self.healthy = False
        self.link: _Link | None = None
        self.inflight = 0
        self.heat: dict = {}             # design label -> forwards routed
        self.outstanding: dict = {}      # forward id -> _Forward
        self.admissions = 0              # passed probes (re-admissions)


class _Forward:
    """One client request in flight through the fleet.  Ownership is the
    pop: exactly one path (relay, failover, forward deadline) may pop it
    from a replica's outstanding table, so the client is answered
    exactly once no matter how many replicas die under it."""

    __slots__ = ("conn", "client_id", "payload", "trace", "label", "fid",
                 "resubmits", "t0", "t_ns")

    def __init__(self, conn: _Conn, client_id, payload: dict, trace: str,
                 label: str, fid: str, t0: float, t_ns: int):
        self.conn = conn
        self.client_id = client_id
        self.payload = payload
        self.trace = trace
        self.label = label
        self.fid = fid
        self.resubmits = 0
        self.t0 = t0
        self.t_ns = t_ns


class FleetRouter:
    """See module docstring.  ``config`` is the arm-time
    :class:`~raft_tpu.serve.fleet.FleetConfig` snapshot (never re-read
    on the request path — the GL303 contract); ``replica_sockets`` fixes
    replica identity (index -> socket path, stable across restarts);
    ``injector`` is the supervisor hook ``kill_replica`` fires through;
    ``clock`` and ``sleep`` are injectable for the deterministic tests."""

    def __init__(self, config, replica_sockets, socket_path: str,
                 clock=time.monotonic, injector=None, on_shutdown=None,
                 sleep=time.sleep, slo_window_s: float = 60.0):
        self.config = config
        self.socket_path = socket_path
        self.clock = clock
        self._injector = injector
        self._on_shutdown = on_shutdown
        self._sleep = sleep
        self._replicas = [_ReplicaState(i, p)
                          for i, p in enumerate(replica_sockets)]
        self._lock = threading.Lock()     # replica states + affinity
        self._affinity: dict = {}         # design label -> replica idx
        self._fids = itertools.count()
        self.slo_window_s = float(slo_window_s)
        self._slo_lock = threading.Lock()
        self._slo = SlidingHistogram("fleet.latency_s",
                                     window_s=self.slo_window_s)
        self._listener = None
        self._threads: list = []
        self._stopping = threading.Event()
        self.t_armed = time.monotonic()

    # ---------------------------------------------------------- lifecycle
    def start(self) -> None:
        """Bind the front socket, admit every reachable replica, start
        the accept loop and (with a positive probe interval) the
        heartbeat loop."""
        try:
            os.unlink(self.socket_path)        # stale socket from a kill
        except FileNotFoundError:
            pass
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(self.socket_path)
        self._listener.listen(64)
        for st in self._replicas:
            self._try_admit(st)
        t_accept = threading.Thread(target=self._accept_loop,
                                    name="fleet-accept", daemon=True)
        self._threads.append(t_accept)
        t_accept.start()
        if self.config.probe_interval_s > 0:
            t_probe = threading.Thread(target=self._probe_loop,
                                       name="fleet-probe", daemon=True)
            self._threads.append(t_probe)
            t_probe.start()

    def stop(self, timeout: float = 10.0) -> None:
        """Stop intake, fail anything still in flight loudly, close the
        links.  The supervisor stops the replica processes themselves."""
        if self._stopping.is_set():
            return
        self._stopping.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:              # pragma: no cover
                pass
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
        orphans = []
        with self._lock:
            for st in self._replicas:
                st.healthy = False
                link, st.link = st.link, None
                if link is not None:
                    link.close()
                orphans.extend(st.outstanding.values())
                st.outstanding.clear()
                st.inflight = 0
        for fwd in orphans:
            fwd.conn.send(protocol.error_response(
                fwd.client_id, ConnectionError("router stopped")))

    def _probe_loop(self) -> None:
        while not self._stopping.wait(self.config.probe_interval_s):
            try:
                self.probe_once()
            except Exception:      # pragma: no cover - heartbeat must
                pass               # survive anything a probe can raise

    # ------------------------------------------------------- accept side
    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                sock, _ = self._listener.accept()
            except OSError:
                break                          # listener closed by stop()
            t = threading.Thread(target=self._handle_conn,
                                 args=(_Conn(sock),),
                                 name="fleet-conn", daemon=True)
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)
            t.start()

    def _handle_conn(self, conn: _Conn) -> None:
        try:
            while True:
                try:
                    obj = protocol.recv_msg(conn.sock)
                except protocol.PeerClosed:
                    return
                except protocol.ProtocolError as e:
                    if not conn.send(protocol.error_response(None, e)):
                        return
                    continue
                try:
                    req = protocol.parse_request(obj)
                except protocol.ProtocolError as e:
                    conn.send(protocol.error_response(
                        obj.get("id") if isinstance(obj, dict) else None, e))
                    continue
                op = req["op"]
                if op == "ping":
                    with self._lock:
                        n_h = sum(1 for s in self._replicas if s.healthy)
                    conn.send({
                        "id": req["id"], "ok": True, "op": "ping",
                        "router": True, "replicas": len(self._replicas),
                        "healthy": n_h,
                        "uptime_s": round(time.monotonic() - self.t_armed,
                                          3)})
                    continue
                if op == "stats":
                    conn.send({"id": req["id"], "ok": True, "op": "stats",
                               "router": self.telemetry()})
                    continue
                if op == "refresh":
                    conn.send(self._broadcast_refresh(req, obj))
                    continue
                if op == "shutdown":
                    conn.send({"id": req["id"], "ok": True,
                               "op": "shutdown", "router": True})
                    threading.Thread(
                        target=self._on_shutdown or self.stop,
                        name="fleet-shutdown", daemon=True).start()
                    return
                self._dispatch(conn, req, obj)
        finally:
            conn.close()

    # ----------------------------------------------------- request path
    def _dispatch(self, conn: _Conn, req: dict, raw: dict) -> None:
        """Admission-check one solve-kind request, then forward it (or
        shed it with the typed ``Overloaded`` frame)."""
        label = req["lanes"][0][1] if req["lanes"] else ""
        trace = req.get("trace") or _trace.new_trace_id()
        shed_reason = self._admit()
        if shed_reason is not None:
            _metrics.counter("fleet.shed").inc()
            conn.send(protocol.overloaded_response(
                req["id"], self.config.retry_after_ms, detail=shed_reason))
            return
        fwd = _Forward(conn=conn, client_id=req["id"], payload=raw,
                       trace=trace, label=label,
                       fid=f"f{next(self._fids)}", t0=self.clock(),
                       t_ns=time.perf_counter_ns())
        if self._injector is not None and faults.consume("kill_replica"):
            # kill the replica affinity is about to pick: a deterministic
            # mid-stream death right under this request's forward
            with self._lock:
                pick = self._pick_locked(label)
            if pick is not None:
                self._injector.kill(pick.idx)
        try:
            self._forward(fwd)
        except (ConnectionError, OSError):
            self._resubmit(fwd, reason="dispatch-time forward failed")

    def _admit(self) -> str | None:
        """Deterministic admission control; returns the shed reason, or
        None to admit.  Pure function of replica state, the in-flight
        total, and the windowed error budget at the router's clock."""
        now = self.clock()
        cfg = self.config
        with self._lock:
            n_h = sum(1 for s in self._replicas if s.healthy)
            inflight = sum(s.inflight for s in self._replicas)
        if n_h == 0:
            return "no healthy replica"
        if inflight + 1 > cfg.queue_max * n_h:
            return (f"in-flight capacity exhausted "
                    f"({inflight}/{cfg.queue_max * n_h})")
        with self._slo_lock:
            win = self._slo.window(now)
        events = win.get("count", 0) + win.get("errors", 0)
        if (events >= cfg.shed_min_events
                and win.get("error_rate", 0.0) > cfg.shed_error_rate):
            return (f"error budget exhausted (windowed error rate "
                    f"{win['error_rate']:.3f} > {cfg.shed_error_rate})")
        return None

    def _pick_locked(self, label: str):
        """Routing decision (caller holds the lock): bucket affinity by
        design label, least-loaded (ties -> lowest index) on a miss or
        when the pinned replica is down/saturated."""
        healthy = [s for s in self._replicas
                   if s.healthy and s.link is not None]
        if not healthy:
            return None
        idx = self._affinity.get(label)
        if idx is not None:
            aff = self._replicas[idx]
            if (aff.healthy and aff.link is not None
                    and aff.inflight < self.config.queue_max):
                return aff
        pick = min(healthy, key=lambda s: (s.inflight, s.idx))
        if label:
            self._affinity[label] = pick.idx
        return pick

    def _forward(self, fwd: _Forward) -> None:
        """Route one forward to a replica; raises on failure (the
        resubmission ladder is the retry discipline, not this)."""
        with self._lock:
            pick = self._pick_locked(fwd.label)
            if pick is None:
                raise NoHealthyReplica(
                    f"no healthy replica for request {fwd.client_id!r}")
            link = pick.link
            pick.outstanding[fwd.fid] = fwd
            pick.inflight += 1
            pick.heat[fwd.label] = pick.heat.get(fwd.label, 0) + 1
        _metrics.counter("fleet.forwarded").inc()
        if faults.consume("stall_replica"):
            return      # withheld frame: the forward deadline recovers it
        if not link.send({**fwd.payload, "id": fwd.fid,
                          "trace": fwd.trace}):
            with self._lock:
                still = pick.outstanding.pop(fwd.fid, None)
                if still is not None:
                    pick.inflight = max(0, pick.inflight - 1)
            if still is not None:       # not already claimed by failover
                raise ConnectionError(
                    f"send to replica {pick.idx} failed")

    def _resubmit(self, fwd: _Forward, reason: str) -> None:
        """Failover: re-route one orphaned forward through the bounded
        retry ladder (idempotent — solves are pure); ladder exhaustion
        answers the client with the typed error frame."""
        if self._stopping.is_set():
            fwd.conn.send(protocol.error_response(
                fwd.client_id, ConnectionError("router stopping")))
            return
        fwd.resubmits += 1
        cfg = self.config

        def attempt(_i):
            self._forward(fwd)

        try:
            retry_call(
                attempt, retries=cfg.resubmit_retries,
                backoff_s=cfg.resubmit_backoff_s, growth=2.0,
                max_backoff_s=max(cfg.resubmit_backoff_s, 1.0),
                retry_on=(ConnectionError, OSError),
                describe=(f"failover resubmit of request "
                          f"{fwd.client_id!r} ({reason})"),
                sleep=self._sleep)
            _metrics.counter("fleet.resubmitted").inc()
        except RetryExhausted as e:
            with self._slo_lock:
                self._slo.error(now=self.clock())
            fwd.conn.send(protocol.error_response(fwd.client_id, e))

    # -------------------------------------------------------- link side
    def _link_read_loop(self, state: _ReplicaState, link: _Link) -> None:
        try:
            while True:
                obj = protocol.recv_msg(link.sock)
                self._relay(state, obj)
        except (protocol.PeerClosed, protocol.ProtocolError, OSError):
            pass
        if self._stopping.is_set():
            return
        with self._lock:
            current = state.link is link
        if current:                 # a replaced link must not kill its
            self._fail_replica(state, "connection lost")   # successor

    def _relay(self, state: _ReplicaState, obj) -> None:
        """One replica response frame -> the owning client, exactly once
        (the outstanding-table pop is the ownership transfer; late
        frames for timed-out/failed-over forwards drop here)."""
        fid = obj.get("id") if isinstance(obj, dict) else None
        with self._lock:
            fwd = state.outstanding.pop(fid, None)
            if fwd is not None:
                state.inflight = max(0, state.inflight - 1)
        if fwd is None:
            return
        now = self.clock()
        ok = bool(obj.get("ok"))
        with self._slo_lock:
            if ok:
                self._slo.observe(max(0.0, now - fwd.t0), now=now)
            else:
                self._slo.error(now=now)
        out = {**obj, "id": fwd.client_id, "replica": state.idx}
        if fwd.resubmits:
            out["resubmits"] = fwd.resubmits
        if fwd.trace:
            # the router half of the request tree, under the ORIGINAL
            # trace id — failover resubmission must not break the tree
            _trace.record(
                "request/router", fwd.t_ns, time.perf_counter_ns(),
                attrs={"replica": state.idx, "resubmits": fwd.resubmits},
                trace=fwd.trace,
                tid=_trace.synthetic_tid(f"{fwd.trace}#router"),
                track=f"req {fwd.client_id} router")
        # count BEFORE the client-visible send: a caller that observes
        # the response and then snapshots telemetry must see this relay
        _metrics.counter("fleet.relayed").inc()
        fwd.conn.send(out)

    def _fail_replica(self, state: _ReplicaState, reason: str) -> None:
        """Mark one replica down and fail its in-flight forwards over to
        survivors.  Idempotent: concurrent detection paths (link EOF,
        heartbeat, send failure) race to the same state flip, and the
        orphan list is claimed under the lock exactly once."""
        with self._lock:
            link, state.link = state.link, None
            was_healthy, state.healthy = state.healthy, False
            orphans = list(state.outstanding.values())
            state.outstanding.clear()
            state.inflight = 0
        if link is not None:
            link.close()
        if not was_healthy and not orphans:
            return
        _metrics.gauge(f"fleet.replica_up[{state.idx}]").set(0)
        if orphans:
            _metrics.counter("fleet.failover").inc(len(orphans))
        for fwd in orphans:
            self._resubmit(fwd, reason=reason)

    # -------------------------------------------------- probe/admission
    def _connect_link(self, state: _ReplicaState):
        """One bounded connect-and-probe ladder to a replica socket;
        returns the probed socket (deadline already cleared) or raises.
        The ``refuse_connect`` counted fault fires here."""
        cfg = self.config

        def attempt(_i):
            if faults.consume("refuse_connect"):
                raise ConnectionRefusedError(
                    f"fault-injected refuse_connect to replica "
                    f"{state.idx}")
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(cfg.probe_timeout_s)
            try:
                s.connect(state.socket_path)
                protocol.send_msg(s, {"op": "ping",
                                      "id": f"admit-{state.idx}"})
                resp = protocol.recv_msg(s)
                if not (isinstance(resp, dict) and resp.get("ok")):
                    raise ConnectionError(
                        f"replica {state.idx} failed the admission "
                        f"probe: {resp!r}")
                s.settimeout(None)
                return s
            except Exception:
                s.close()
                raise

        return retry_call(
            attempt, retries=2, backoff_s=0.05, growth=2.0,
            max_backoff_s=0.5, deadline_s=2.0 * cfg.probe_timeout_s,
            retry_on=(OSError, ConnectionError),
            describe=f"admit replica {state.idx}", sleep=self._sleep)

    def _try_admit(self, state: _ReplicaState) -> bool:
        """(Re-)admit one down replica: connect + passing ping probe,
        then start its reader and mark it healthy.  Best-effort — an
        unreachable replica just stays down until the next probe tick."""
        try:
            sock = self._connect_link(state)
        except (RetryExhausted, OSError, ConnectionError):
            return False
        link = _Link(sock)
        with self._lock:
            state.link = link
            state.healthy = True
            state.inflight = 0
            state.admissions += 1
        _metrics.gauge(f"fleet.replica_up[{state.idx}]").set(1)
        t = threading.Thread(target=self._link_read_loop,
                             args=(state, link),
                             name=f"fleet-link-{state.idx}", daemon=True)
        self._threads.append(t)
        t.start()
        return True

    def _probe(self, state: _ReplicaState) -> bool:
        """Deadline-bounded heartbeat on a one-shot connection (the
        link's own stream belongs to its reader): a stalled replica
        accepts but never answers, and the deadline catches it."""
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(self.config.probe_timeout_s)
        try:
            s.connect(state.socket_path)
            protocol.send_msg(s, {"op": "ping",
                                  "id": f"probe-{state.idx}"})
            resp = protocol.recv_msg(s)
            return isinstance(resp, dict) and bool(resp.get("ok"))
        except (OSError, protocol.PeerClosed, protocol.ProtocolError):
            return False
        finally:
            try:
                s.close()
            except OSError:      # pragma: no cover
                pass

    def probe_once(self) -> dict:
        """One health sweep (the probe loop's body; tests call it
        directly on a virtual clock): expire overdue forwards into the
        resubmission ladder, heartbeat healthy replicas, try to re-admit
        down ones."""
        now = self.clock()
        overdue = []
        with self._lock:
            for st in self._replicas:
                for fid in [f for f, w in st.outstanding.items()
                            if now - w.t0 > self.config.request_timeout_s]:
                    overdue.append(st.outstanding.pop(fid))
                    st.inflight = max(0, st.inflight - 1)
        for fwd in overdue:
            _metrics.counter("fleet.timeouts").inc()
            self._resubmit(fwd, reason="forward deadline expired")
        summary = {"expired": len(overdue), "failed": [], "admitted": []}
        for st in self._replicas:
            if self._stopping.is_set():
                break
            with self._lock:
                healthy = st.healthy
            if healthy:
                if not self._probe(st):
                    summary["failed"].append(st.idx)
                    self._fail_replica(st, "heartbeat deadline")
            elif self._try_admit(st):
                summary["admitted"].append(st.idx)
        return summary

    # ---------------------------------------------------- control plane
    def _broadcast_refresh(self, req: dict, raw: dict) -> dict:
        """Forward a ``refresh`` to every healthy replica on one-shot
        connections; aggregate per-replica outcomes."""
        out: dict = {}
        for st in self._replicas:
            with self._lock:
                healthy = st.healthy
            if not healthy:
                out[str(st.idx)] = {"ok": False, "error": "replica down"}
                continue
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(self.config.probe_timeout_s)
            try:
                s.connect(st.socket_path)
                protocol.send_msg(s, {**raw, "id": f"refresh-{st.idx}"})
                resp = protocol.recv_msg(s)
                out[str(st.idx)] = {"ok": bool(resp.get("ok"))}
            except (OSError, protocol.PeerClosed,
                    protocol.ProtocolError) as e:
                out[str(st.idx)] = {"ok": False, "error": str(e)[-200:]}
            finally:
                try:
                    s.close()
                except OSError:      # pragma: no cover
                    pass
        return {"id": req["id"], "ok": all(v.get("ok") for v in
                                           out.values()),
                "op": "refresh", "replicas": out}

    # -------------------------------------------------------- telemetry
    def reset_telemetry(self) -> None:
        """Measurement-window boundary (the bench's warm vs measured
        pass): a fresh SLO window."""
        with self._slo_lock:
            self._slo = SlidingHistogram("fleet.latency_s",
                                         window_s=self.slo_window_s)

    def telemetry(self) -> dict:
        """Live fleet snapshot: per-replica health/in-flight/heat, the
        affinity map, the windowed router latency, and the exact
        failover/shed/restart counters.  Deterministic under a virtual
        clock."""
        now = self.clock()
        with self._lock:
            reps = [{"idx": s.idx, "healthy": s.healthy,
                     "inflight": s.inflight, "admissions": s.admissions,
                     "outstanding": len(s.outstanding),
                     "heat": dict(sorted(s.heat.items()))}
                    for s in self._replicas]
            affinity = dict(sorted(self._affinity.items()))
        with self._slo_lock:
            win = self._slo.window(now)
        return {
            "uptime_s": round(time.monotonic() - self.t_armed, 3),
            "replicas": reps,
            "healthy": sum(1 for r in reps if r["healthy"]),
            "affinity": affinity,
            "latency": win,
            "window_s": self.slo_window_s,
            "counters": {name: _metrics.counter(f"fleet.{name}").value
                         for name in _COUNTERS},
            "admission": {
                "queue_max": self.config.queue_max,
                "shed_error_rate": self.config.shed_error_rate,
                "shed_min_events": self.config.shed_min_events,
                "retry_after_ms": self.config.retry_after_ms,
                "request_timeout_s": self.config.request_timeout_s,
            },
        }
