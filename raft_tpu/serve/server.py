"""The resident solver daemon: socket front, batcher middle, warm core.

Thread layout (all state lock-owned or single-writer by construction):

* one ACCEPT thread — listens on the AF_UNIX socket, spawns a reader per
  connection;
* N CONNECTION READER threads — frame/parse/validate requests, stage
  each lane (memoized, see :meth:`~raft_tpu.serve.solver.SolverCore.
  stage_lane` — this is where a lane learns its bucket signature), and
  submit lanes to the :class:`~raft_tpu.serve.batcher.MicroBatcher`;
  control ops (``ping``/``stats``/``refresh``/``shutdown``) answer
  inline;
* ONE SOLVER LOOP thread — drains the batcher (deadline-or-capacity
  closes), solves each batch through :func:`~raft_tpu.serve.solver.
  solve_batch`, slices rows back to their owning requests, and sends
  each response the moment its last lane lands.

Graceful shutdown (``shutdown`` op or SIGTERM via ``python -m
raft_tpu.serve``): stop intake, flush every pending bucket (the batcher
drains closed), answer everything in flight, then exit — a client that
got its request in gets its response out.

Observability (armed by ``RAFT_TPU_OBS`` like every other subsystem):
per-bucket ``serve.queue_wait_s[SxNxW]`` latency histograms (submit ->
batch close), ``serve.batch_occupancy[SxNxW]`` gauges plus exact
``serve.lanes``/``serve.batches`` counters, and the solver's own
per-bucket dispatch histograms underneath.
"""
from __future__ import annotations

import os
import socket
import threading
import time

from raft_tpu.serve import protocol
from raft_tpu.serve.batcher import Lane, MicroBatcher
from raft_tpu.serve.config import ServeConfig
from raft_tpu.serve.solver import SolverCore, solve_batch

#: daemon request-path functions under the GL3xx concurrency contracts
__graftlint_concurrent__ = ("_handle_conn", "_solve_loop", "_deliver",
                            "_submit_lanes", "_control", "_bucket_label")


def _bucket_label(sig) -> str:
    return f"{sig.segments}x{sig.nodes}x{sig.nw}"


class _Conn:
    """One client connection: the socket plus its write lock (responses
    are sent from the solver loop AND control answers from the reader —
    frames must not interleave)."""

    def __init__(self, sock):
        self.sock = sock
        self.wlock = threading.Lock()

    def send(self, obj) -> bool:
        try:
            with self.wlock:
                protocol.send_msg(self.sock, obj)
            return True
        except (OSError, ValueError):
            return False          # client went away; its results drop


class _PendingRequest:
    """Fan-in state of one multi-lane request.  Rows are filled by the
    single solver-loop thread only; ``done`` counts under the server's
    requests lock (an error path may also finish a request)."""

    def __init__(self, conn: _Conn, req_id, n_lanes: int, clock):
        self.conn = conn
        self.id = req_id
        self.rows = [None] * n_lanes
        self.waits = [0.0] * n_lanes
        self.remaining = n_lanes
        self.error = None        # first batch failure poisons the request
        self.t0 = clock()


class SolverServer:
    """See module docstring.  ``config`` is the arm-time snapshot
    (:meth:`ServeConfig.from_env` — never re-read on the request path);
    ``clock`` is injectable for the deterministic tests."""

    def __init__(self, config: ServeConfig | None = None,
                 socket_path: str | None = None, clock=time.monotonic):
        self.config = config or ServeConfig.from_env()
        self.socket_path = socket_path or self.config.socket_path
        self.clock = clock
        self.core = SolverCore(self.config)
        self.batcher = MicroBatcher(self.config.batch_deadline_s,
                                    self.config.batch_max, clock=clock)
        self._lock = threading.Lock()    # guards _PendingRequest fan-in
        self._threads: list = []
        self._listener = None
        self._stopping = threading.Event()
        self._solver_done = threading.Event()
        self.t_armed = time.monotonic()

    # ----------------------------------------------------------- warmup
    def warmup(self, designs, Hs: float = 8.0, Tp: float = 12.0) -> dict:
        """Arm the service for a design list BEFORE accepting traffic:
        stage one lane per design and solve one padded batch per distinct
        bucket, so every executable is resolved (AOT disk load on a warm
        root, compile on a cold one) ahead of the first client.  Returns
        per-bucket arming info; ``ready-to-serve`` time in the smoke is
        measured through this."""
        by_sig: dict = {}
        for spec in designs:
            design, label = protocol.resolve_design(spec)
            sig, staged = self.core.stage_lane(design, Hs, Tp)
            by_sig.setdefault(sig, Lane(request_id=None, seq=0, label=label,
                                        staged=staged))
        info = {}
        for sig, lane in by_sig.items():
            _rows, binfo = solve_batch(self.core, sig, [lane])
            info[_bucket_label(sig)] = {"lanes": binfo["lanes"],
                                        "capacity": binfo["capacity"]}
        return info

    # ---------------------------------------------------------- control
    def start(self) -> None:
        """Bind the socket and start the accept + solver threads."""
        path = self.socket_path
        try:
            os.unlink(path)                   # stale socket from a kill
        except FileNotFoundError:
            pass
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(path)
        self._listener.listen(64)
        t_solve = threading.Thread(target=self._solve_loop,
                                   name="serve-solver", daemon=True)
        t_accept = threading.Thread(target=self._accept_loop,
                                    name="serve-accept", daemon=True)
        self._threads += [t_solve, t_accept]
        t_solve.start()
        t_accept.start()

    def stop(self, timeout: float = 30.0) -> None:
        """Graceful drain: stop intake, flush pending batches, answer
        in-flight requests, close the listener."""
        if self._stopping.is_set():
            return
        self._stopping.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        self.batcher.close()
        self._solver_done.wait(timeout)
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the solver loop has drained and exited."""
        return self._solver_done.wait(timeout)

    def serve_forever(self) -> None:
        """``start()`` then block until :meth:`stop` completes (the
        daemon entry point; ``python -m raft_tpu.serve`` wires SIGTERM to
        ``stop``)."""
        self.start()
        self._solver_done.wait()

    # ------------------------------------------------------ accept side
    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                sock, _ = self._listener.accept()
            except OSError:
                break                          # listener closed by stop()
            t = threading.Thread(target=self._handle_conn,
                                 args=(_Conn(sock),),
                                 name="serve-conn", daemon=True)
            # bounded bookkeeping in a long-lived daemon: drop handles of
            # connections that already hung up
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)
            t.start()

    def _handle_conn(self, conn: _Conn) -> None:
        try:
            while True:
                try:
                    obj = protocol.recv_msg(conn.sock)
                except protocol.PeerClosed:
                    return
                except protocol.ProtocolError as e:
                    if not conn.send(protocol.error_response(None, e)):
                        return
                    continue
                try:
                    req = protocol.parse_request(obj)
                except protocol.ProtocolError as e:
                    conn.send(protocol.error_response(
                        obj.get("id") if isinstance(obj, dict) else None, e))
                    continue
                if req["op"] in ("ping", "stats", "refresh", "shutdown"):
                    stop = self._control(conn, req, obj)
                    if stop:
                        return
                    continue
                try:
                    self._submit_lanes(conn, req)
                except Exception as e:         # staging/validation failure
                    conn.send(protocol.error_response(req["id"], e))
        finally:
            try:
                conn.sock.close()
            except OSError:
                pass

    def _control(self, conn: _Conn, req: dict, raw: dict) -> bool:
        """Answer a control op inline; returns True when the server
        should stop (shutdown)."""
        op = req["op"]
        if op == "ping":
            conn.send({"id": req["id"], "ok": True, "op": "ping",
                       "uptime_s": round(time.monotonic() - self.t_armed, 3)})
            return False
        if op == "stats":
            conn.send({"id": req["id"], "ok": True, "op": "stats",
                       "solver": self.core.stats(),
                       "queue": self.batcher.counters(),
                       "queue_depth": self.batcher.depth()})
            return False
        if op == "refresh":
            # operator-carried knob values (NOT an env re-read: the env
            # snapshot stays arm-time per GL303; explicit values in the
            # request are a configuration action, like restarting).
            # Validate BEFORE touching anything — a malformed value must
            # answer with an error, never kill the reader thread.
            try:
                new_deadline = raw.get("deadline_ms")
                new_max = raw.get("batch_max")
                if new_deadline is not None:
                    new_deadline = max(0.0, float(new_deadline)) / 1e3
                if new_max is not None:
                    new_max = int(new_max)
                    if new_max < 1:
                        raise ValueError("batch_max must be >= 1")
            except (TypeError, ValueError) as e:
                conn.send(protocol.error_response(req["id"], e))
                return False
            info = self.core.refresh()
            if new_deadline is not None:
                self.batcher.set_deadline(new_deadline)
            if new_max is not None:
                import dataclasses

                # config first, then the batcher (both under their own
                # locks): a batch popped during the transition may carry
                # the OLD capacity's lane count — solve_batch pads to
                # max(capacity, lanes), so either interleaving solves.
                # The new capacity is a new abstract batch signature, so
                # the next dispatch per bucket re-resolves its executable
                # (AOT disk or compile); nothing stale can be served.
                self.core.config = dataclasses.replace(
                    self.core.config, batch_max=new_max)
                self.batcher.set_batch_max(new_max)
            conn.send({"id": req["id"], "ok": True, "op": "refresh",
                       **info,
                       "batch_deadline_ms":
                           round(self.batcher.deadline_s * 1e3, 3),
                       "batch_max": self.batcher.batch_max})
            return False
        # shutdown: acknowledge, then drain gracefully.  The reader holds
        # THIS connection open until the solver loop finishes — the
        # requester (or anything sharing its connection) may still be
        # owed responses for queued lanes, and returning now would close
        # the socket underneath them.
        conn.send({"id": req["id"], "ok": True, "op": "shutdown"})
        threading.Thread(target=self.stop, name="serve-stop",
                         daemon=True).start()
        self._solver_done.wait(60.0)
        return True

    def _submit_lanes(self, conn: _Conn, req: dict) -> None:
        lanes = []
        for seq, (design, label, Hs, Tp) in enumerate(req["lanes"]):
            sig, staged = self.core.stage_lane(design, Hs, Tp)
            lanes.append((sig, Lane(request_id=None, seq=seq, label=label,
                                    staged=staged)))
        pend = _PendingRequest(conn, req["id"], len(lanes), self.clock)
        for _sig, lane in lanes:
            lane.request_id = pend
        try:
            for sig, lane in lanes:
                self.batcher.submit(sig, lane)
        except RuntimeError as e:              # raced shutdown
            conn.send(protocol.error_response(req["id"], e))

    # ------------------------------------------------------ solver side
    def _solve_loop(self) -> None:
        from raft_tpu import obs as _obs

        try:
            while True:
                item = self.batcher.next_batch()
                if item is None:
                    return
                sig, lanes = item
                label = _bucket_label(sig)
                now = self.clock()
                for ln in lanes:
                    _obs.metrics.histogram(
                        f"serve.queue_wait_s[{label}]").observe(
                            max(0.0, now - ln.t_submit))
                with _obs.trace.span("serve/batch",
                                     attrs={"sig": label,
                                            "lanes": len(lanes)}):
                    try:
                        rows, info = solve_batch(self.core, sig, lanes)
                    except Exception as e:     # a poisoned batch must not
                        self._fail_batch(lanes, e)   # kill the daemon
                        continue
                _obs.metrics.gauge(
                    f"serve.batch_occupancy[{label}]").set(info["occupancy"])
                _obs.metrics.counter("serve.batches").inc()
                _obs.metrics.counter("serve.lanes").inc(len(lanes))
                self._deliver(lanes, rows, now)
        finally:
            self._solver_done.set()

    def _fail_batch(self, lanes, exc) -> None:
        # a failed batch POISONS every request it carried lanes for: the
        # request answers with the error once its last lane lands, even
        # when its other lanes (in other batches) solved fine — a
        # multi-bucket sweep must never get ok:true with null rows
        finished = []
        with self._lock:
            for ln in lanes:
                pend = ln.request_id
                if pend.error is None:
                    pend.error = exc
                pend.remaining -= 1
                if pend.remaining <= 0:
                    finished.append(pend)
        for pend in finished:
            pend.conn.send(protocol.error_response(pend.id, pend.error))

    def _deliver(self, lanes, rows, t_close) -> None:
        finished = []
        with self._lock:
            for ln, row in zip(lanes, rows):
                pend = ln.request_id
                pend.rows[ln.seq] = row
                pend.waits[ln.seq] = round(max(0.0, t_close - ln.t_submit), 6)
                pend.remaining -= 1
                if pend.remaining <= 0:
                    finished.append(pend)
        for pend in finished:
            if pend.error is not None:     # another batch of this request
                pend.conn.send(            # failed earlier
                    protocol.error_response(pend.id, pend.error))
                continue
            pend.conn.send({
                "id": pend.id,
                "ok": True,
                "results": pend.rows,
                "t_queue_s": pend.waits,
                "t_total_s": round(self.clock() - pend.t0, 6),
            })
