"""The resident solver daemon: socket front, batcher middle, warm core.

Thread layout (all state lock-owned or single-writer by construction):

* one ACCEPT thread — listens on the AF_UNIX socket, spawns a reader per
  connection;
* N CONNECTION READER threads — frame/parse/validate requests, stage
  each lane (memoized, see :meth:`~raft_tpu.serve.solver.SolverCore.
  stage_lane` — this is where a lane learns its bucket signature), and
  submit lanes to the :class:`~raft_tpu.serve.batcher.MicroBatcher`;
  control ops (``ping``/``stats``/``refresh``/``shutdown``) answer
  inline;
* ONE SOLVER LOOP thread — drains the batcher (deadline-or-capacity
  closes), solves each batch through :func:`~raft_tpu.serve.solver.
  solve_batch`, slices rows back to their owning requests, and sends
  each response the moment its last lane lands.

Graceful shutdown (``shutdown`` op or SIGTERM via ``python -m
raft_tpu.serve``): stop intake, flush every pending bucket (the batcher
drains closed), answer everything in flight, dump the flight recorder
and flush the performance ledger, then exit — a client that got its
request in gets its response out.

Observability (armed by ``RAFT_TPU_OBS`` like every other subsystem):

* **request-scoped traces** — every solve-kind request runs under ONE
  trace id (client-minted or server-minted): the reader records
  ``request/server/stage`` on its own thread, the solver loop emits
  ``request/server/queue_wait`` / ``request/server/solve`` per lane on
  synthetic per-lane tracks (explicit-endpoint spans: overlapping
  requests never break per-track time containment), and delivery
  closes the ``request/server`` root — one Perfetto-loadable tree per
  request, spanning threads, thread-name metadata included;
* **live SLO windows** — a sliding-window request-latency histogram
  plus per-bucket queue-wait windows on the server's own (injectable)
  clock: the ``stats`` op returns windowed p50/p90/p99, error rate,
  occupancy, queue depth, and compile counts — deterministic under a
  virtual clock;
* **flight recorder** — the last-N completed request records (id, op,
  trace, buckets, per-stage timings, outcome), dumped atomically on
  batch failure, ``refresh``, and shutdown;
* the per-bucket ``serve.queue_wait_s[SxNxW]`` cumulative histograms,
  ``serve.batch_occupancy[SxNxW]`` gauges and exact
  ``serve.lanes``/``serve.batches`` counters, and the solver's own
  per-bucket dispatch histograms underneath.
"""
from __future__ import annotations

import os
import socket
import threading
import time

from raft_tpu.obs.flight import FlightRecorder
from raft_tpu.obs.metrics import SlidingHistogram
from raft_tpu.serve import protocol
from raft_tpu.serve.batcher import Lane, MicroBatcher
from raft_tpu.serve.config import ServeConfig
from raft_tpu.serve.solver import SolverCore, solve_batch

#: daemon request-path functions under the GL3xx concurrency contracts
__graftlint_concurrent__ = ("_handle_conn", "_solve_loop", "_deliver",
                            "_submit_lanes", "_control", "_bucket_label",
                            "_finish_records", "_wait_window")


def _bucket_label(sig) -> str:
    return f"{sig.segments}x{sig.nodes}x{sig.nw}"


class _Conn:
    """One client connection: the socket plus its write lock (responses
    are sent from the solver loop AND control answers from the reader —
    frames must not interleave)."""

    def __init__(self, sock):
        self.sock = sock
        self.wlock = threading.Lock()

    def send(self, obj) -> bool:
        try:
            with self.wlock:
                protocol.send_msg(self.sock, obj)
            return True
        except (OSError, ValueError):
            return False          # client went away; its results drop


class _PendingRequest:
    """Fan-in state of one multi-lane request.  Rows are filled by the
    single solver-loop thread only; ``done`` counts under the server's
    requests lock (an error path may also finish a request)."""

    def __init__(self, conn: _Conn, req_id, n_lanes: int, clock,
                 op: str = "solve", trace: str = "",
                 t_recv_ns: int = 0, stage_s: float = 0.0):
        self.conn = conn
        self.id = req_id
        self.op = op
        self.trace = trace
        self.rows = [None] * n_lanes
        self.waits = [0.0] * n_lanes
        self.solve_s = [0.0] * n_lanes
        self.sigs = [""] * n_lanes       # bucket label per lane
        self.remaining = n_lanes
        self.error = None        # first batch failure poisons the request
        self.t0 = clock()
        self.t_recv_ns = t_recv_ns or time.perf_counter_ns()
        self.stage_s = stage_s


class SolverServer:
    """See module docstring.  ``config`` is the arm-time snapshot
    (:meth:`ServeConfig.from_env` — never re-read on the request path);
    ``clock`` is injectable for the deterministic tests."""

    def __init__(self, config: ServeConfig | None = None,
                 socket_path: str | None = None, clock=time.monotonic,
                 slo_window_s: float = 60.0):
        self.config = config or ServeConfig.from_env()
        self.socket_path = socket_path or self.config.socket_path
        self.clock = clock
        self.core = SolverCore(self.config)
        self.batcher = MicroBatcher(self.config.batch_deadline_s,
                                    self.config.batch_max, clock=clock)
        self._lock = threading.Lock()    # guards _PendingRequest fan-in
        self._threads: list = []
        self._listener = None
        self._stopping = threading.Event()
        self._solver_done = threading.Event()
        self.t_armed = time.monotonic()
        # live SLO state, on the SERVER'S clock (virtual-clock
        # deterministic): one request-latency window, per-bucket
        # queue-wait windows (lazily created under their own lock), a
        # flight recorder, and exact request/error counters
        self.slo_window_s = float(slo_window_s)
        self.flight = FlightRecorder()
        self._slo_latency = SlidingHistogram("serve.latency_s",
                                             window_s=self.slo_window_s)
        self._slo_lock = threading.Lock()
        self._slo_wait: dict = {}        # bucket label -> SlidingHistogram
        self._req_done = 0
        self._req_err = 0

    # ----------------------------------------------------------- warmup
    def warmup(self, designs, Hs: float = 8.0, Tp: float = 12.0) -> dict:
        """Arm the service for a design list BEFORE accepting traffic:
        stage one lane per design and solve one padded batch per distinct
        bucket, so every executable is resolved (AOT disk load on a warm
        root, compile on a cold one) ahead of the first client.  Returns
        per-bucket arming info; ``ready-to-serve`` time in the smoke is
        measured through this."""
        by_sig: dict = {}
        for spec in designs:
            design, label = protocol.resolve_design(spec)
            sig, staged = self.core.stage_lane(design, Hs, Tp)
            by_sig.setdefault(sig, Lane(request_id=None, seq=0, label=label,
                                        staged=staged))
        info = {}
        for sig, lane in by_sig.items():
            _rows, binfo = solve_batch(self.core, sig, [lane])
            info[_bucket_label(sig)] = {"lanes": binfo["lanes"],
                                        "capacity": binfo["capacity"]}
        return info

    # ---------------------------------------------------------- control
    def start(self) -> None:
        """Bind the socket and start the accept + solver threads."""
        path = self.socket_path
        try:
            os.unlink(path)                   # stale socket from a kill
        except FileNotFoundError:
            pass
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(path)
        self._listener.listen(64)
        t_solve = threading.Thread(target=self._solve_loop,
                                   name="serve-solver", daemon=True)
        t_accept = threading.Thread(target=self._accept_loop,
                                    name="serve-accept", daemon=True)
        self._threads += [t_solve, t_accept]
        t_solve.start()
        t_accept.start()

    def stop(self, timeout: float = 30.0) -> None:
        """Graceful drain: stop intake, flush pending batches, answer
        in-flight requests, close the listener."""
        if self._stopping.is_set():
            return
        self._stopping.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        self.batcher.close()
        self._solver_done.wait(timeout)
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
        # post-drain telemetry publication: the flight recorder and the
        # measured-performance ledger survive the process (SIGTERM
        # included — ``python -m raft_tpu.serve`` routes it here), and a
        # final forced obs publish flushes the span ring past the
        # debounce.  All best-effort: telemetry never blocks shutdown.
        try:
            from raft_tpu import obs as _obs

            self.flight.dump(label="serve", reason="shutdown")
            _obs.ledger.flush()
            _obs.maybe_publish("serve", force=True)
        except Exception:              # pragma: no cover - e.g. a
            pass                       # malformed RAFT_TPU_ROOFLINE

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the solver loop has drained and exited."""
        return self._solver_done.wait(timeout)

    def serve_forever(self) -> None:
        """``start()`` then block until :meth:`stop` completes (the
        daemon entry point; ``python -m raft_tpu.serve`` wires SIGTERM to
        ``stop``)."""
        self.start()
        self._solver_done.wait()

    # ------------------------------------------------------ accept side
    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                sock, _ = self._listener.accept()
            except OSError:
                break                          # listener closed by stop()
            t = threading.Thread(target=self._handle_conn,
                                 args=(_Conn(sock),),
                                 name="serve-conn", daemon=True)
            # bounded bookkeeping in a long-lived daemon: drop handles of
            # connections that already hung up
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)
            t.start()

    def _handle_conn(self, conn: _Conn) -> None:
        try:
            while True:
                try:
                    obj = protocol.recv_msg(conn.sock)
                except protocol.PeerClosed:
                    return
                except protocol.ProtocolError as e:
                    if not conn.send(protocol.error_response(None, e)):
                        return
                    continue
                t_recv_ns = time.perf_counter_ns()
                try:
                    req = protocol.parse_request(obj)
                except protocol.ProtocolError as e:
                    conn.send(protocol.error_response(
                        obj.get("id") if isinstance(obj, dict) else None, e))
                    continue
                if req["op"] in ("ping", "stats", "refresh", "shutdown"):
                    stop = self._control(conn, req, obj)
                    if stop:
                        return
                    continue
                try:
                    self._submit_lanes(conn, req, t_recv_ns)
                except Exception as e:         # staging/validation failure
                    conn.send(protocol.error_response(req["id"], e))
        finally:
            try:
                conn.sock.close()
            except OSError:
                pass

    def _control(self, conn: _Conn, req: dict, raw: dict) -> bool:
        """Answer a control op inline; returns True when the server
        should stop (shutdown)."""
        op = req["op"]
        if op == "ping":
            conn.send({"id": req["id"], "ok": True, "op": "ping",
                       "uptime_s": round(time.monotonic() - self.t_armed, 3)})
            return False
        if op == "stats":
            conn.send({"id": req["id"], "ok": True, "op": "stats",
                       "solver": self.core.stats(),
                       "queue": self.batcher.counters(),
                       "queue_depth": self.batcher.depth(),
                       "telemetry": self.telemetry()})
            return False
        if op == "refresh":
            # operator-carried knob values (NOT an env re-read: the env
            # snapshot stays arm-time per GL303; explicit values in the
            # request are a configuration action, like restarting).
            # Validate BEFORE touching anything — a malformed value must
            # answer with an error, never kill the reader thread.
            try:
                new_deadline = raw.get("deadline_ms")
                new_max = raw.get("batch_max")
                if new_deadline is not None:
                    new_deadline = max(0.0, float(new_deadline)) / 1e3
                if new_max is not None:
                    new_max = int(new_max)
                    if new_max < 1:
                        raise ValueError("batch_max must be >= 1")
            except (TypeError, ValueError) as e:
                conn.send(protocol.error_response(req["id"], e))
                return False
            # a refresh is a natural post-mortem boundary: dump the
            # flight tail and flush the ledger BEFORE state turns over
            # (best-effort: telemetry must never fail the control op)
            try:
                from raft_tpu import obs as _obs

                self.flight.dump(label="serve", reason="refresh")
                _obs.ledger.flush()
            except Exception:          # pragma: no cover
                pass
            info = self.core.refresh()
            # fresh SLO windows: refreshed knobs define a new
            # measurement regime, and mixing regimes in one window
            # would misattribute the old deadline's latencies
            self.reset_telemetry()
            if new_deadline is not None:
                self.batcher.set_deadline(new_deadline)
            if new_max is not None:
                import dataclasses

                # config first, then the batcher (both under their own
                # locks): a batch popped during the transition may carry
                # the OLD capacity's lane count — solve_batch pads to
                # max(capacity, lanes), so either interleaving solves.
                # The new capacity is a new abstract batch signature, so
                # the next dispatch per bucket re-resolves its executable
                # (AOT disk or compile); nothing stale can be served.
                self.core.config = dataclasses.replace(
                    self.core.config, batch_max=new_max)
                self.batcher.set_batch_max(new_max)
            conn.send({"id": req["id"], "ok": True, "op": "refresh",
                       **info,
                       "batch_deadline_ms":
                           round(self.batcher.deadline_s * 1e3, 3),
                       "batch_max": self.batcher.batch_max})
            return False
        # shutdown: acknowledge, then drain gracefully.  The reader holds
        # THIS connection open until the solver loop finishes — the
        # requester (or anything sharing its connection) may still be
        # owed responses for queued lanes, and returning now would close
        # the socket underneath them.
        conn.send({"id": req["id"], "ok": True, "op": "shutdown"})
        threading.Thread(target=self.stop, name="serve-stop",
                         daemon=True).start()
        self._solver_done.wait(60.0)
        return True

    def _submit_lanes(self, conn: _Conn, req: dict,
                      t_recv_ns: int = 0) -> None:
        from raft_tpu.obs import trace as _trace

        trace_id = req.get("trace") or _trace.new_trace_id()
        t_recv_ns = t_recv_ns or time.perf_counter_ns()
        lanes = []
        # staging runs on THIS reader thread under the request's trace
        # context: the "request/server/stage" span lands on the reader's
        # own track, carrying the shared trace id
        with _trace.context(_trace.TraceContext(trace=trace_id,
                                                path="request/server")):
            t_stage0 = time.perf_counter_ns()
            with _trace.span("stage", attrs={"op": req["op"],
                                             "lanes": len(req["lanes"])}):
                for seq, (design, label, Hs, Tp) in enumerate(req["lanes"]):
                    sig, staged = self.core.stage_lane(design, Hs, Tp)
                    lanes.append((sig, Lane(request_id=None, seq=seq,
                                            label=label, staged=staged,
                                            trace=trace_id)))
            stage_s = (time.perf_counter_ns() - t_stage0) / 1e9
        pend = _PendingRequest(conn, req["id"], len(lanes), self.clock,
                               op=req["op"], trace=trace_id,
                               t_recv_ns=t_recv_ns, stage_s=stage_s)
        for seq, (sig, lane) in enumerate(lanes):
            lane.request_id = pend
            pend.sigs[seq] = _bucket_label(sig)
        try:
            for sig, lane in lanes:
                lane.t_submit_ns = time.perf_counter_ns()
                self.batcher.submit(sig, lane)
        except RuntimeError as e:              # raced shutdown
            conn.send(protocol.error_response(req["id"], e))

    # ------------------------------------------------------ solver side
    def _wait_window(self, label: str) -> SlidingHistogram:
        """The per-bucket queue-wait SLO window (lazily created; the
        bucket ladder bounds the cardinality by construction)."""
        with self._slo_lock:
            w = self._slo_wait.get(label)
            if w is None:
                w = self._slo_wait[label] = SlidingHistogram(
                    f"serve.queue_wait[{label}]",
                    window_s=self.slo_window_s)
            return w

    def _solve_loop(self) -> None:
        from raft_tpu import obs as _obs

        try:
            while True:
                item = self.batcher.next_batch()
                if item is None:
                    return
                sig, lanes = item
                label = _bucket_label(sig)
                now = self.clock()
                t_close_ns = time.perf_counter_ns()
                wait_win = self._wait_window(label)
                for ln in lanes:
                    # queue wait is measured on the BATCHER'S clock:
                    # close instant minus submit instant, exactly —
                    # deterministic under the virtual-clock tests
                    qw = max(0.0, now - ln.t_submit)
                    _obs.metrics.histogram(
                        f"serve.queue_wait_s[{label}]").observe(qw)
                    wait_win.observe(qw, now=now)
                with _obs.trace.span("serve/batch",
                                     attrs={"sig": label,
                                            "lanes": len(lanes)}):
                    try:
                        rows, info = solve_batch(self.core, sig, lanes)
                    except Exception as e:     # a poisoned batch must not
                        self._record_lane_spans(lanes, label, t_close_ns,
                                                time.perf_counter_ns(),
                                                solved=False)
                        self._fail_batch(lanes, e)   # kill the daemon
                        continue
                t_done_ns = time.perf_counter_ns()
                solve_s = (t_done_ns - t_close_ns) / 1e9
                with self._lock:
                    for ln in lanes:
                        ln.request_id.solve_s[ln.seq] = round(solve_s, 6)
                self._record_lane_spans(lanes, label, t_close_ns, t_done_ns)
                _obs.metrics.gauge(
                    f"serve.batch_occupancy[{label}]").set(info["occupancy"])
                _obs.metrics.counter("serve.batches").inc()
                _obs.metrics.counter("serve.lanes").inc(len(lanes))
                self._deliver(lanes, rows, now)
        finally:
            self._solver_done.set()

    def _record_lane_spans(self, lanes, label: str, t_close_ns: int,
                           t_done_ns: int, solved: bool = True) -> None:
        """Per-lane request-scoped spans, emitted by the solver loop on
        behalf of each lane's request: ``queue_wait`` (submit -> batch
        close) and ``solve`` (close -> materialized), both on a
        synthetic per-lane track so overlapping requests keep per-track
        time containment (the Perfetto invariant)."""
        from raft_tpu.obs import trace as _trace

        for ln in lanes:
            if not ln.trace:
                continue                 # warmup lanes trace nothing
            tid = _trace.synthetic_tid(f"{ln.trace}#{ln.seq}")
            track = f"req {ln.request_id.id} lane {ln.seq}"
            _trace.record("request/server/queue_wait", ln.t_submit_ns,
                          t_close_ns, depth=2, attrs={"sig": label},
                          trace=ln.trace, tid=tid, track=track)
            if solved:
                _trace.record("request/server/solve", t_close_ns,
                              t_done_ns, depth=2, attrs={"sig": label},
                              trace=ln.trace, tid=tid, track=track)

    def _fail_batch(self, lanes, exc) -> None:
        # a failed batch POISONS every request it carried lanes for: the
        # request answers with the error once its last lane lands, even
        # when its other lanes (in other batches) solved fine — a
        # multi-bucket sweep must never get ok:true with null rows
        finished = []
        with self._lock:
            for ln in lanes:
                pend = ln.request_id
                if pend.error is None:
                    pend.error = exc
                pend.remaining -= 1
                if pend.remaining <= 0:
                    finished.append(pend)
        # bookkeeping BEFORE the error frames go out (same contract as
        # _deliver: a client holding its response finds it counted, and
        # the server root span closes before the client's enclosing one)
        t_send_clk = self.clock()
        t_send_ns = time.perf_counter_ns()
        self._finish_records(finished, t_send_clk, t_send_ns)
        for pend in finished:
            pend.conn.send(protocol.error_response(pend.id, pend.error))
        if finished:
            # post-mortem trigger: the ring is dumped the moment a batch
            # poisons real requests (best-effort, atomic)
            self.flight.dump(label="serve", reason="batch_error")

    def _finish_records(self, finished, t_send_clk: float | None = None,
                        t_send_ns: int | None = None) -> None:
        """SLO + flight + trace bookkeeping for requests that just
        finished (ok or poisoned): one flight record each, the request
        latency observed into the sliding window (errors counted into
        the error budget instead), and the ``request/server`` root span
        closed on the request's synthetic track."""
        if not finished:
            return
        from raft_tpu.obs import trace as _trace

        t_send_clk = self.clock() if t_send_clk is None else t_send_clk
        t_send_ns = (time.perf_counter_ns() if t_send_ns is None
                     else t_send_ns)
        for pend in finished:
            ok = pend.error is None
            total_s = max(0.0, t_send_clk - pend.t0)
            if ok:
                self._slo_latency.observe(total_s, now=t_send_clk)
            else:
                self._slo_latency.error(now=t_send_clk)
            with self._lock:
                self._req_done += 1
                if not ok:
                    self._req_err += 1
            if pend.trace:
                _trace.record(
                    "request/server", pend.t_recv_ns, t_send_ns, depth=1,
                    attrs={"op": pend.op, "ok": ok},
                    trace=pend.trace,
                    tid=_trace.synthetic_tid(pend.trace),
                    track=f"req {pend.id}")
            self.flight.record({
                "id": pend.id,
                "op": pend.op,
                "trace": pend.trace,
                "buckets": list(pend.sigs),
                "stage_s": round(pend.stage_s, 6),
                "queue_wait_s": list(pend.waits),
                "solve_s": list(pend.solve_s),
                "total_s": round(total_s, 6),
                "outcome": ("ok" if ok else
                            f"error:{type(pend.error).__name__}"),
            })

    def _deliver(self, lanes, rows, t_close) -> None:
        finished = []
        with self._lock:
            for ln, row in zip(lanes, rows):
                pend = ln.request_id
                pend.rows[ln.seq] = row
                # EXACTLY batch close minus submit, on the batcher's
                # clock: the flight-recorder breakdown and t_queue_s
                # agree with the virtual-clock tests to the last bit
                pend.waits[ln.seq] = round(max(0.0, t_close - ln.t_submit), 6)
                pend.remaining -= 1
                if pend.remaining <= 0:
                    finished.append(pend)
        t_send_clk = self.clock()
        t_send_ns = time.perf_counter_ns()
        # SLO/flight/trace bookkeeping BEFORE the response frames go
        # out: a client that holds its response and immediately asks
        # for stats must find its own request already counted (and the
        # server root span must close before the client's enclosing
        # span does)
        self._finish_records(finished, t_send_clk, t_send_ns)
        for pend in finished:
            if pend.error is not None:     # another batch of this request
                pend.conn.send(            # failed earlier
                    protocol.error_response(pend.id, pend.error))
                continue
            pend.conn.send({
                "id": pend.id,
                "ok": True,
                "results": pend.rows,
                "t_queue_s": pend.waits,
                "t_total_s": round(t_send_clk - pend.t0, 6),
                **({"trace": pend.trace} if pend.trace else {}),
            })

    # -------------------------------------------------------- telemetry
    def reset_telemetry(self) -> None:
        """Measurement-window boundary (the bench's warm pass vs
        measured pass; the ``refresh`` op): fresh SLO windows and a
        zeroed error budget.  The flight recorder keeps its ring — a
        post-mortem wants history across boundaries, not a blank tape."""
        with self._slo_lock:
            self._slo_latency = SlidingHistogram(
                "serve.latency_s", window_s=self.slo_window_s)
            self._slo_wait = {}
        with self._lock:
            self._req_done = 0
            self._req_err = 0

    def telemetry(self) -> dict:
        """The live SLO snapshot the extended ``stats`` op returns:
        windowed request-latency quantiles + error rate, per-bucket
        queue-wait windows, occupancy, queue depth, exact error budget,
        compile count, flight-recorder counters, and the performance
        ledger summary.  All deterministic under a virtual clock."""
        from raft_tpu import cache as _cache
        from raft_tpu import obs as _obs

        now = self.clock()
        with self._slo_lock:
            waits = {label: w.window(now)
                     for label, w in sorted(self._slo_wait.items())}
        with self._lock:
            done, errs = self._req_done, self._req_err
        solver = self.core.stats()
        return {
            "uptime_s": round(time.monotonic() - self.t_armed, 3),
            "window_s": self.slo_window_s,
            "latency": self._slo_latency.window(now),
            "queue_wait": waits,
            "occupancy": {label: st["mean_occupancy"]
                          for label, st in solver["buckets"].items()},
            "queue_depth": self.batcher.depth(),
            "error_budget": {
                "requests": done,
                "errors": errs,
                "error_rate": round(errs / done, 6) if done else 0.0,
            },
            "compiles": solver["compiles"],
            "flight": self.flight.counts(),
            # lightweight by design: a polled stats op must not re-read
            # and re-parse every persisted ledger file (ledger.entries()
            # is the full-record accessor for offline consumers)
            "ledger": _obs.ledger.stat(),
            "cache_enabled": _cache.is_enabled(),
        }
