"""Length-prefixed JSON wire protocol of the resident solver service.

Framing: every message is a 4-byte big-endian unsigned length followed by
that many bytes of UTF-8 JSON (one object per frame).  Both directions
use the same framing; a frame larger than :data:`MAX_FRAME` is a
protocol error (a malformed or hostile peer must not make the daemon
allocate unbounded buffers).

Request schema (``op`` selects the kind)::

    {"op": "solve", "id": "...", "design": <name|path|dict>,
     "Hs": 8.0, "Tp": 12.0}                        -> 1 lane
    {"op": "dlc",   "id": "...", "design": ...,
     "cases": [[Hs, Tp], ...]}                     -> N lanes, one bucket
    {"op": "sweep", "id": "...", "designs": [...],
     "Hs": 8.0, "Tp": 12.0}                        -> N lanes, >= 1 buckets
    {"op": "ping"} | {"op": "stats"} | {"op": "refresh"}
                   | {"op": "shutdown"}

``design`` accepts a shipped-design alias (``"oc3"``, ``"oc4"``,
``"oc4_2"``, ``"volturnus"`` — case-insensitive, also the full YAML stem
like ``"OC3spar"``), an absolute YAML path, or an inline design dict
(the :func:`raft_tpu.model.load_design` passthrough).

Any solve-kind request may additionally carry a ``"trace"`` string: the
request-scoped trace id every span of its life is recorded under
(client submit, reader parse/stage, queue wait, batch solve, delivery).
The client mints one per request when the caller didn't
(:func:`raft_tpu.obs.trace.new_trace_id`); the server adopts it — so a
Perfetto trace exported on either side groups one request's spans
across processes AND threads by the same id.

Response: ``{"id": ..., "ok": true, "results": [<per-lane dict>, ...],
"health": {...}, "t_queue_s": [...], "trace": ..., "server": {...}}``
with one result row per requested lane, in request order — a multi-lane
request (``dlc``/``sweep``) answers once, after its last lane's batch
lands.  Errors: ``{"id": ..., "ok": false, "error": {"class": ...,
"detail": ...}}``.

Load shedding (the fleet router, :mod:`raft_tpu.serve.router`): a
request refused by admission control answers immediately with the typed
``overloaded`` error — ``{"id": ..., "ok": false, "shed": true,
"retry_after_ms": <hint>, "error": {"class": "Overloaded", "detail":
...}}``.  Solves are pure, so a shed request is safe to re-submit after
the hint; the single-daemon server never sheds (its micro-batch queue is
its own backpressure).

The ``stats`` op answers with the live telemetry snapshot::

    {"id": ..., "ok": true, "op": "stats",
     "solver": {...},                  # per-bucket batches/occupancy,
                                       # compiles, arm-time knobs
     "queue": {...}, "queue_depth": {...},
     "telemetry": {
        "uptime_s": ..., "window_s": ...,
        "latency": {count, p50, p90, p99, errors, error_rate, ...},
        "queue_wait": {"<SxNxW>": {...same windowed shape...}, ...},
        "error_budget": {"requests", "errors", "error_rate"},
        "flight": {"capacity", "size", "recorded", "errors"},
        "compiles": ..., "ledger": {...}}}

(the windowed quantiles are deterministic rank-walk values over the
sliding sub-window ring — see ``docs/observability.rst``).
"""
from __future__ import annotations

import json
import os
import struct

#: hard per-frame cap (requests are small; responses carry (6,) stats per
#: lane, not spectra — 32 MiB is orders of magnitude of headroom)
MAX_FRAME = 32 * 1024 * 1024

_LEN = struct.Struct(">I")

#: shipped-design aliases -> YAML stems under ``raft_tpu/designs/``
DESIGN_ALIASES = {
    "oc3": "OC3spar",
    "oc3spar": "OC3spar",
    "oc4": "OC4semi",
    "oc4semi": "OC4semi",
    "oc4_2": "OC4semi_2",
    "oc4semi_2": "OC4semi_2",
    "volturnus": "VolturnUS-S",
    "volturnus-s": "VolturnUS-S",
}

OPS = ("solve", "dlc", "sweep", "ping", "stats", "refresh", "shutdown")


class ProtocolError(ValueError):
    """Malformed frame or request — the connection answers with an error
    response (and stays up: one bad request must not drop a client whose
    other requests are already queued)."""


class PeerClosed(ConnectionError):
    """The peer closed the stream mid-frame (or before one started)."""


class Overloaded(RuntimeError):
    """Typed load-shed signal: the fleet refused admission (capacity or
    error budget).  Carried on the wire as ``error.class == "Overloaded"``
    plus a top-level ``retry_after_ms`` hint — solves are pure, so the
    client may simply re-submit after the hint."""


def send_msg(sock, obj) -> None:
    """Serialize ``obj`` and write one length-prefixed frame."""
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(payload) > MAX_FRAME:
        raise ProtocolError(f"frame of {len(payload)} bytes exceeds "
                            f"MAX_FRAME={MAX_FRAME}")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise PeerClosed(f"peer closed after {len(buf)}/{n} bytes")
        buf.extend(chunk)
    return bytes(buf)


def recv_msg(sock):
    """Read one length-prefixed JSON frame; raises :class:`PeerClosed` on
    EOF at a frame boundary, :class:`ProtocolError` on an oversized or
    non-JSON frame."""
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > MAX_FRAME:
        raise ProtocolError(f"peer announced a {n}-byte frame "
                            f"(MAX_FRAME={MAX_FRAME})")
    data = _recv_exact(sock, n)
    try:
        return json.loads(data.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ProtocolError(f"undecodable frame: {e}") from None


def resolve_design(spec):
    """A request's ``design`` field -> something
    :func:`raft_tpu.model.load_design` accepts, plus a short stable label
    for metrics/logs.  Aliases resolve to the shipped YAMLs."""
    if isinstance(spec, dict):
        return spec, "<inline>"
    if not isinstance(spec, str) or not spec:
        raise ProtocolError(f"design must be a name, path, or dict; got "
                            f"{type(spec).__name__}")
    stem = DESIGN_ALIASES.get(spec.strip().lower())
    if stem is not None:
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        return os.path.join(pkg, "designs", stem + ".yaml"), stem
    if os.path.isfile(spec):
        return spec, os.path.splitext(os.path.basename(spec))[0]
    raise ProtocolError(
        f"unknown design {spec!r}: not a shipped alias "
        f"({sorted(set(DESIGN_ALIASES))}) nor an existing YAML path")


def _sea_state(obj, key_hs="Hs", key_tp="Tp"):
    try:
        Hs, Tp = float(obj[key_hs]), float(obj[key_tp])
    except KeyError as e:
        raise ProtocolError(f"request is missing {e.args[0]!r}") from None
    except (TypeError, ValueError):
        raise ProtocolError(
            f"{key_hs}/{key_tp} must be numbers; got "
            f"{obj.get(key_hs)!r}/{obj.get(key_tp)!r}") from None
    if not (Hs >= 0.0):          # NaN fails this too
        raise ProtocolError(f"Hs must be >= 0, got {Hs!r}")
    return Hs, Tp


def parse_request(obj) -> dict:
    """Validate one inbound request object; returns a normalized dict
    ``{"op", "id", "lanes": [(design, label, Hs, Tp), ...]}`` (``lanes``
    empty for the control ops).  Raises :class:`ProtocolError` with a
    client-facing message on anything malformed."""
    if not isinstance(obj, dict):
        raise ProtocolError(f"request must be a JSON object, got "
                            f"{type(obj).__name__}")
    op = obj.get("op")
    if op not in OPS:
        raise ProtocolError(f"unknown op {op!r}; have {OPS}")
    tr = obj.get("trace")
    if tr is not None and not isinstance(tr, str):
        raise ProtocolError(f"'trace' must be a string; got "
                            f"{type(tr).__name__}")
    out = {"op": op, "id": obj.get("id"), "lanes": [],
           "trace": tr or None}
    if op in ("ping", "stats", "refresh", "shutdown"):
        return out
    if out["id"] is None:
        raise ProtocolError(f"{op!r} request needs an 'id'")
    if op == "solve":
        design, label = resolve_design(obj.get("design"))
        Hs, Tp = _sea_state(obj)
        out["lanes"] = [(design, label, Hs, Tp)]
    elif op == "dlc":
        design, label = resolve_design(obj.get("design"))
        cases = obj.get("cases")
        if not isinstance(cases, list) or not cases:
            raise ProtocolError("'dlc' needs a non-empty 'cases' list of "
                                "[Hs, Tp] rows")
        for row in cases:
            if not isinstance(row, (list, tuple)) or len(row) != 2:
                raise ProtocolError(f"'dlc' case rows are [Hs, Tp]; got "
                                    f"{row!r}")
            Hs, Tp = _sea_state({"Hs": row[0], "Tp": row[1]})
            out["lanes"].append((design, label, Hs, Tp))
    else:                                    # sweep
        designs = obj.get("designs")
        if not isinstance(designs, list) or not designs:
            raise ProtocolError("'sweep' needs a non-empty 'designs' list")
        Hs, Tp = _sea_state(obj)
        for spec in designs:
            design, label = resolve_design(spec)
            out["lanes"].append((design, label, Hs, Tp))
    return out


def error_response(req_id, exc) -> dict:
    return {"id": req_id, "ok": False,
            "error": {"class": type(exc).__name__,
                      "detail": str(exc)[-500:]}}


def overloaded_response(req_id, retry_after_ms: float,
                        detail: str = "") -> dict:
    """The typed shed response (see the module docstring): an
    ``Overloaded`` error frame with a ``retry_after_ms`` hint."""
    return {"id": req_id, "ok": False, "shed": True,
            "retry_after_ms": round(float(retry_after_ms), 3),
            "error": {"class": "Overloaded",
                      "detail": detail or "fleet admission refused; "
                                          "retry after the hint"}}
