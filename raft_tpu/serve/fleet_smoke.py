"""Fleet-smoke: cross-process proof of the fault-tolerant serving tier.

``python -m raft_tpu.serve fleet-smoke`` (``make fleet-smoke``, CI fast
job) runs the REAL fleet — supervisor + router in this process (both
JAX-free), real daemon children over real sockets — and proves the
robustness contract in three phases on ONE shared AOT cache root:

* **Phase A (reference)**: a cold 1-replica fleet serves the mixed
  3-design stream through the router; rows become the bit-identical
  reference, and the single replica pays exactly ``n_buckets`` compiles.
* **Phase B (failover)**: a 2-replica fleet arms entirely warm (both
  replicas ZERO compiles at ready, off the shared root).  Mid-stream,
  the counted ``kill_replica:1`` fault SIGKILLs the replica the router
  just picked — every request is still answered exactly once (zero
  lost: all futures resolve ok; zero duplicate: the router relays
  exactly one response per request), rows are bit-identical to Phase A,
  at least one response carries a ``resubmits`` count, the survivors
  pay zero compiles, and the supervisor restarts the dead replica warm
  (zero compiles at ready) with the router re-admitting it only after a
  passing probe.
* **Phase C (shed-then-recover)**: a 1-replica fleet with ``queue_max=1``
  and a short forward deadline; ``stall_replica:1`` wedges the first
  request in flight, so a burst of 7 more is deterministically shed with
  typed ``Overloaded`` responses carrying ``retry_after_ms`` hints.  The
  stalled request is recovered by the forward deadline (answered, with a
  resubmit), and every shed request succeeds on sequential re-submission
  — load shedding degrades, never loses.

Prints one JSON line; rc 0 iff all checks hold.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import time

from raft_tpu.resilience import faults
from raft_tpu.serve.smoke import (BATCH_MAX, DEADLINE_MS, N_ITER, NW,
                                  _child_env)

#: the mixed stream: 3 designs x 4 rounds = 12 solve requests, landing
#: in the stock ladder's buckets (the serve-smoke stream, one round up —
#: the kill fires mid-stream with work on both sides of it)
STREAM = [(d, 6.0 + 0.5 * (i % 3), 10.0 + 0.5 * (i % 2))
          for i, d in enumerate(["oc3", "oc4", "volturnus"] * 4)]

SERVE_ARGS = ["--nw", str(NW), "--n-iter", str(N_ITER),
              "--deadline-ms", str(DEADLINE_MS),
              "--batch-max", str(BATCH_MAX),
              "--warm", "oc3,oc4,volturnus"]


def _fleet_env(cache_dir: str) -> dict:
    """Replica child environment: shared cache root, CPU platform, no
    inherited fault arming (the parent arms faults for the ROUTER; a
    child inheriting them would double-fire)."""
    env = _child_env(cache_dir)
    env.pop("RAFT_TPU_FAULT_INJECT", None)
    return env


def _mk_fleet(cache_dir: str, tmp: str, tag: str, **cfg_overrides):
    from raft_tpu.serve.fleet import Fleet, FleetConfig

    cfg = FleetConfig.from_env(
        socket_path=os.path.join(tmp, f"fleet-{tag}.sock"),
        **cfg_overrides)
    run_dir = os.path.join(tmp, f"run-{tag}")
    os.makedirs(run_dir, exist_ok=True)
    return Fleet(cfg, serve_args=SERVE_ARGS, child_env=_fleet_env(cache_dir),
                 run_dir=run_dir)


def _counters(fleet) -> dict:
    return dict(fleet.router.telemetry()["counters"])


def _delta(after: dict, before: dict) -> dict:
    return {k: after[k] - before.get(k, 0) for k in after}


def _drive(sock: str, arm_kill_after: int | None = None):
    """Submit the stream open-loop through the router; optionally arm
    ``kill_replica:1`` after the first ``arm_kill_after`` responses have
    landed (so the kill strikes mid-stream, deterministically between
    two requests).  Returns (rows, responses)."""
    from raft_tpu.serve.client import SolveClient

    with SolveClient(sock, connect_timeout=30.0) as cl:
        head = STREAM if arm_kill_after is None else STREAM[:arm_kill_after]
        tail = [] if arm_kill_after is None else STREAM[arm_kill_after:]
        futs = [cl.submit({"op": "solve", "design": d, "Hs": Hs, "Tp": Tp})
                for d, Hs, Tp in head]
        resps = [f.result(180.0) for f in futs]
        if tail:
            faults.reset_counts()
            os.environ["RAFT_TPU_FAULT_INJECT"] = "kill_replica:1"
            try:
                futs = [cl.submit({"op": "solve", "design": d,
                                   "Hs": Hs, "Tp": Tp})
                        for d, Hs, Tp in tail]
                resps += [f.result(180.0) for f in futs]
            finally:
                os.environ.pop("RAFT_TPU_FAULT_INJECT", None)
                faults.reset_counts()
    bad = [r for r in resps if not r.get("ok")]
    if bad:
        raise RuntimeError(f"{len(bad)} requests failed: {bad[0]}")
    rows = [r["results"][0]["std_dev"] for r in resps]
    return rows, resps


def _replica_solver_stats(fleet) -> list:
    """Per-replica ``stats`` over a direct connection to each replica
    socket (compile counts are per-process truths the router can't
    fake)."""
    from raft_tpu.serve.client import SolveClient

    out = []
    for rep in fleet.telemetry()["supervisor"]["replicas"]:
        with SolveClient(rep["socket"], connect_timeout=10.0) as cl:
            out.append(cl.stats()["solver"])
    return out


def _wait_healthy(fleet, n: int, timeout_s: float = 120.0) -> bool:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        if fleet.router.telemetry()["healthy"] >= n:
            return True
        time.sleep(0.25)
    return False


def main(argv=None) -> int:
    t_all = time.perf_counter()
    keep = argv and "--keep" in argv
    tmp = tempfile.mkdtemp(prefix="raft_tpu_fleet_smoke_")
    cache_dir = os.path.join(tmp, "cache")
    checks: dict = {}
    info: dict = {}
    os.environ.pop("RAFT_TPU_FAULT_INJECT", None)
    faults.reset_counts()
    try:
        # ---- Phase A: cold 1-replica reference through the router ----
        fleet = _mk_fleet(cache_dir, tmp, "a", replicas=1)
        fleet.start()
        c0 = _counters(fleet)
        rows_ref, _ = _drive(fleet.router.socket_path)
        d = _delta(_counters(fleet), c0)
        solver_a = _replica_solver_stats(fleet)[0]
        n_buckets = len(solver_a["buckets"])
        fleet.stop()
        checks["cold_compiles_eq_buckets"] = (
            solver_a["compiles"] == n_buckets > 0)
        checks["phase_a_all_relayed"] = (
            d["relayed"] == len(STREAM) and d["failover"] == 0)
        info["n_buckets"] = n_buckets
        info["cold_compiles"] = solver_a["compiles"]

        # ---- Phase B: 2 replicas warm; kill one mid-stream ----
        fleet = _mk_fleet(cache_dir, tmp, "b", replicas=2)
        ready = fleet.start()
        warm_ready = [r.get("compiles_at_ready")
                      for r in ready["replicas"].values()]
        checks["warm_fleet_zero_compiles_at_ready"] = warm_ready == [0, 0]
        c0 = _counters(fleet)
        rows_b, resps_b = _drive(fleet.router.socket_path,
                                 arm_kill_after=4)
        d = _delta(_counters(fleet), c0)
        resubmitted = [r for r in resps_b if r.get("resubmits")]
        checks["kill_all_answered_exactly_once"] = (
            len(resps_b) == len(STREAM)
            and all(r.get("ok") for r in resps_b)
            and d["relayed"] == len(STREAM))
        checks["kill_failover_fired"] = (
            d["failover"] >= 1 and len(resubmitted) >= 1)
        checks["kill_rows_bit_identical"] = rows_b == rows_ref
        restarted = _wait_healthy(fleet, 2)
        checks["dead_replica_restarted_and_readmitted"] = restarted
        sup = fleet.telemetry()["supervisor"]["replicas"]
        restarts = {r["idx"]: fleet._replicas[r["idx"]].restarts
                    for r in sup}
        killed = [i for i, n in restarts.items() if n > 0]
        checks["restart_counter_fired"] = (
            _counters(fleet)["restart"] - c0["restart"] >= 1
            and len(killed) == 1)
        checks["restarted_replica_warm"] = all(
            fleet._replicas[i].ready.get("compiles_at_ready") == 0
            for i in killed)
        solver_b = _replica_solver_stats(fleet) if restarted else []
        checks["survivors_and_restart_zero_compiles"] = (
            bool(solver_b) and all(s["compiles"] == 0 for s in solver_b))
        fleet.stop()
        info["failover_requests"] = d["failover"]
        info["resubmitted_responses"] = len(resubmitted)
        info["killed_replica"] = killed

        # ---- Phase C: forced overload -> typed shed -> recover ----
        fleet = _mk_fleet(cache_dir, tmp, "c", replicas=1, queue_max=1,
                          request_timeout_s=2.0)
        fleet.start()
        c0 = _counters(fleet)
        from raft_tpu.serve.client import SolveClient

        with SolveClient(fleet.router.socket_path,
                         connect_timeout=30.0) as cl:
            faults.reset_counts()
            os.environ["RAFT_TPU_FAULT_INJECT"] = "stall_replica:1"
            try:
                stalled = cl.submit({"op": "solve", "design": "oc3",
                                     "Hs": 6.0, "Tp": 10.0})
                burst = [cl.submit({"op": "solve", "design": d,
                                    "Hs": Hs, "Tp": Tp})
                         for d, Hs, Tp in STREAM[1:8]]
                shed = [f.result(30.0) for f in burst]
            finally:
                os.environ.pop("RAFT_TPU_FAULT_INJECT", None)
                faults.reset_counts()
            checks["overload_sheds_typed"] = all(
                r.get("ok") is False and r.get("shed") is True
                and r.get("error", {}).get("class") == "Overloaded"
                and r.get("retry_after_ms", 0) > 0 for r in shed)
            # the stalled request is recovered by the forward deadline
            stalled_resp = stalled.result(60.0)
            checks["stalled_request_recovered"] = (
                stalled_resp.get("ok") is True
                and stalled_resp.get("resubmits", 0) >= 1)
            # shed-then-recover: every shed request succeeds re-submitted
            redo = [cl.call({"op": "solve", "design": d,
                             "Hs": Hs, "Tp": Tp}, timeout=60.0)
                    for d, Hs, Tp in STREAM[1:8]]
            checks["shed_requests_recover"] = all(
                r.get("ok") for r in redo)
        d = _delta(_counters(fleet), c0)
        # exactly the 7 burst requests shed (dispatch is sequential on
        # the conn reader, so admission sees each one's predecessor)
        checks["shed_counter_deterministic"] = d["shed"] == 7
        checks["forward_deadline_counter_fired"] = d["timeouts"] >= 1
        fleet.stop()
        info["shed_count"] = d["shed"]

        ok = all(checks.values())
        print(json.dumps({
            "ok": ok, **checks, **info,
            "n_requests": len(STREAM),
            "wall_s": round(time.perf_counter() - t_all, 2),
            **({"dir": tmp} if keep else {}),
        }))
        return 0 if ok else 1
    finally:
        os.environ.pop("RAFT_TPU_FAULT_INJECT", None)
        faults.reset_counts()
        if not keep:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":                                # pragma: no cover
    sys.exit(main())
