"""Model facade: the user-facing API of raft_tpu.

Mirrors the reference ``Model`` class surface (raft/raft.py:1227-1738) —
``setEnv`` / ``calcSystemProps`` / ``calcMooringAndOffsets`` / ``solveEigen``
/ ``solveDynamics`` / ``calcOutputs`` / ``plot`` and the ``results`` dict
with ``properties`` / ``means`` / ``eigen`` / ``response`` keys
(raft/raft.py:1290,1329,1364,1450,1590) — but is a thin host-side
orchestrator: every numeric step is a pure, jitted, vmappable function from
the lower layers, so the same pipeline also powers the batched design-sweep
API in :mod:`raft_tpu.parallel`.
"""
from __future__ import annotations

import dataclasses as _dataclasses

import numpy as np
import jax.numpy as jnp

from raft_tpu.build.members import build_member_set, build_rna
from raft_tpu.core.types import Env, WaveState
from raft_tpu.core.waves import jonswap, wave_number
from raft_tpu.hydro import (
    node_kinematics,
    strip_added_mass,
    strip_excitation,
)
from raft_tpu.mooring import (
    fairlead_tensions,
    mooring_force,
    mooring_stiffness,
    parse_mooring,
    solve_equilibrium,
    tension_jacobian,
)
from raft_tpu.solve import LinearCoeffs, solve_dynamics, solve_eigen
from raft_tpu.statics import assemble_statics
from raft_tpu.utils.profiling import phase

Array = jnp.ndarray

DOF_NAMES = ("surge", "sway", "heave", "roll", "pitch", "yaw")


class Model:
    """One mooring-coupled floating wind turbine analysis (cf. raft/raft.py:1230).

    Parameters mirror the reference constructor: ``design`` is the parsed
    YAML dict; ``w`` the frequency grid (default ``arange(0.05, 3, 0.05)``,
    raft/raft.py:1272); ``depth`` the water depth override.
    """

    def __new__(cls, design: dict = None, w=None, depth: float | None = None,
                nTurbines: int = 1, BEM=None, positions=None,
                pad_segments: int | None = None, pad_nodes: int | None = None):
        # N-turbine construction returns the stacked-axis ArrayModel (the
        # reference accepts nTurbines but hard-wires fowtList[0],
        # raft/raft.py:1292-1298; here arrays actually solve as 6N DOF)
        if nTurbines != 1:
            from raft_tpu.array import ArrayModel

            if positions is None:
                positions = (design or {}).get("array", {}).get("positions")
            return ArrayModel(design, positions=positions, w=w, depth=depth,
                              nT=nTurbines, BEM=BEM)
        return super().__new__(cls)

    def __init__(self, design: dict, w=None, depth: float | None = None,
                 nTurbines: int = 1, BEM=None, positions=None,
                 pad_segments: int | None = None, pad_nodes: int | None = None):
        if positions is not None:
            raise ValueError("positions is only meaningful with nTurbines > 1")
        self.design = design
        self.members = build_member_set(
            design, pad_segments=pad_segments, pad_nodes=pad_nodes
        )
        self.rna = build_rna(design)
        moor = design.get("mooring")
        yaw_stiff = float(design.get("turbine", {}).get("yaw_stiffness", 0.0))
        self.moor = parse_mooring(moor, yaw_stiffness=yaw_stiff) if moor else None
        if depth is None:
            depth = float(moor.get("water_depth", 300.0)) if moor else 300.0
        self.depth = float(depth)
        if w is None:
            w = np.arange(0.05, 3.0, 0.05)
        self.w = jnp.asarray(np.asarray(w, dtype=float))
        self.env = Env(depth=self.depth)
        self.wave: WaveState | None = None
        # BEM: None -> pure Morison (the reference snapshot's behavior,
        # A_BEM=0, raft/raft.py:1797-1800); a mode string -> mesh the
        # potMod members and run the panel solver ('native' forces the C++
        # host solver, 'jax' the on-device port, 'auto'/the historical
        # 'native'-as-default routes per RAFT_TPU_BEM); or a precomputed
        # (A[6,6,nw], B[6,6,nw], F[6,nw]) tuple (e.g. from WAMIT files via
        # hydro.bem_io.load_wamit_coeffs)
        if isinstance(BEM, str) and BEM not in ("native", "jax", "auto"):
            raise ValueError(
                f"BEM={BEM!r}: expected 'native', 'jax', 'auto', or a "
                "precomputed (A, B, F) tuple")
        self.bem_mode = BEM if isinstance(BEM, str) else None
        self.bem = BEM if not isinstance(BEM, str) else None
        self._bem_headings = None        # staged heading grid (calcBEM)
        self.statics = None
        self.A_morison = None
        self.F_morison = None
        self.kin = None
        self.C_moor0 = None
        self.F_moor0 = None
        self.C_moor = None
        self.F_moor = None
        self.r6_eq = None
        self.rao = None
        self.eigen = None
        self.results: dict = {}

    # ---------------------------------------------------------------- env

    def setEnv(self, Hs=8.0, Tp=12.0, V=10.0, beta=0.0, Fthrust=0.0,
               current=0.0, current_heading=0.0, current_exp=0.0):
        """Sea state + wind (cf. FOWT.setEnv, raft/raft.py:1804-1832), plus
        a steady current (speed / heading / power-law shear exponent) the
        reference has no model for: it adds a mean drag load to the offset
        equilibrium and shifts the drag linearization point
        (hydro/strip.py node_current / current_mean_force)."""
        # validate BEFORE mutating any state: a heading outside the staged
        # grid must leave the model exactly as it was
        F_beta = None
        if self._bem_headings is not None and self.bem is not None:
            F_beta = self._heading_excitation(float(beta))
        self.env = Env(
            Hs=float(Hs), Tp=float(Tp), V=float(V), beta=float(beta),
            depth=self.depth, current=float(current),
            current_heading=float(current_heading),
            current_exp=float(current_exp),
        )
        S = jonswap(self.w, Hs, Tp)
        self.wave = WaveState(
            w=self.w, k=wave_number(self.w, self.depth), zeta=jnp.sqrt(S)
        )
        self.Fthrust = float(Fthrust)
        hHub = float(self.rna.hHub)
        self.f6Ext = jnp.array(
            [self.Fthrust, 0.0, 0.0, 0.0, self.Fthrust * hHub, 0.0]
        )
        # environment changed: node kinematics and excitation are stale
        # (they depend on the wave field incl. heading); statics are not
        self.kin = None
        self.F_morison = None
        if F_beta is not None:
            # re-stage the excitation for the new heading from the grid --
            # no BEM re-solve (A, B are heading-independent)
            A, B = self._bem_headings[2], self._bem_headings[3]
            self.bem = (A, B, F_beta)

    # ------------------------------------------------------------- statics

    def calcBEM(self, dz_max: float = 3.0, da_max: float = 2.0,
                out_dir: str | None = None, irr: bool = False,
                headings=None):
        """Mesh potMod members and run the native BEM solver
        (cf. FOWT.calcBEM, raft/raft.py:2016-2073 — where the reference
        leaves the solve commented out, this one runs).

        ``irr=True`` adds interior waterplane lid panels and the extended
        boundary integral equation, removing irregular frequencies (the
        HAMS `irr` knob, hams/pyhams.py:200,284).  ``headings``: optional
        heading grid [rad]; the excitation is solved for every heading in
        one pass (the influence matrix factors once per frequency) and
        later ``setEnv(beta=...)`` calls re-stage the matching excitation
        by interpolation WITHOUT re-running the solver — the reference's
        HAMS heading-grid workflow (hams/pyhams.py:196-289) carried through
        the Model.  Writes HullMesh.pnl / platform.gdf when ``out_dir`` is
        given, matching the reference's on-disk artifacts.

        The solver itself routes per the key-salted ``RAFT_TPU_BEM`` knob
        (or an explicit ``Model(BEM="native"|"jax"|"auto")``): the native
        f64 host solver, or the on-device JAX port
        (:mod:`raft_tpu.hydro.jax_bem`) whose padded-shape executables
        make novel geometries pay only a device solve."""
        from raft_tpu.hydro.mesh import mesh_design, mesh_lid, write_gdf, write_pnl
        from raft_tpu.hydro.jax_bem import solve_bem_any

        with phase("calcBEM"):
            panels = mesh_design(self.design, dz_max=dz_max, da_max=da_max)
            if len(panels) == 0:
                return None
            if out_dir is not None:
                import os

                os.makedirs(out_dir, exist_ok=True)
                write_pnl(os.path.join(out_dir, "HullMesh.pnl"), panels)
                write_gdf(os.path.join(out_dir, "platform.gdf"), panels)
            lid = mesh_lid(self.design, da_max=da_max) if irr else None
            # finite-depth Green function below k0*depth = 10 (native
            # solver switches per frequency); deep water beyond
            if headings is not None:
                self._bem_headings, self.bem = solve_bem_heading_grid(
                    panels, self.w, float(self.env.rho), float(self.env.g),
                    self.depth, lid, headings, float(self.env.beta),
                    mode=self.bem_mode,
                )
            else:
                self.bem = solve_bem_any(
                    panels, np.asarray(self.w),
                    rho=float(self.env.rho), g=float(self.env.g),
                    beta=float(self.env.beta), depth=self.depth, lid=lid,
                    mode=self.bem_mode,
                )
                # only after a SUCCESSFUL solve: the fresh single-heading
                # result supersedes any staged grid (a failed solve must
                # leave the staged state untouched)
                self._bem_headings = None
        return self.bem

    def _heading_excitation(self, beta: float) -> np.ndarray:
        betas, F_all, _, _ = self._bem_headings
        return interp_heading_excitation(betas, F_all, beta)

    def calcSystemProps(self):
        """Statics + strip-theory hydro + undisplaced mooring stiffness
        (cf. Model.calcSystemProps, raft/raft.py:1315-1330)."""
        if self.wave is None:
            self.setEnv()
        if self.bem_mode is not None and self.bem is None:
            self.calcBEM()
        exclude = self.bem is not None
        with phase("statics"):
            self.statics = assemble_statics(self.members, self.rna, self.env)
        with phase("hydro-strip"):
            self.kin = node_kinematics(self.members, self.wave, self.env)
            self.A_morison = strip_added_mass(
                self.members, self.env, exclude_potmod=exclude
            )
            self.F_morison = strip_excitation(
                self.members, self.kin, self.env, exclude_potmod=exclude
            )
        with phase("mooring-stiffness"):
            if self.moor is not None:
                z6 = jnp.zeros(6)
                self.C_moor0 = mooring_stiffness(self.moor, z6)
                self.F_moor0 = mooring_force(self.moor, z6)
            else:
                self.C_moor0 = jnp.zeros((6, 6))
                self.F_moor0 = jnp.zeros(6)
        self.C_moor = self.C_moor0
        self.F_moor = self.F_moor0
        self.results["properties"] = self._properties()
        return self

    def _properties(self) -> dict:
        s = self.statics
        return {
            "total mass": float(s.mass),
            "total CG": np.asarray(s.rCG),
            "substructure mass": float(s.m_sub),
            "substructure CG": np.asarray(s.rCG_sub),
            "shell mass": float(s.m_shell),
            "ballast mass": float(s.m_ballast),
            "tower mass": float(s.m_tower),
            "tower CG": np.asarray(s.rCG_tower),
            "displacement": float(s.V),
            "center of buoyancy": np.asarray(s.rCB),
            "waterplane area": float(s.AWP),
            "metacentric height": float(s.zMeta - s.rCG[2]),
            "metacenter z": float(s.zMeta),
            "roll inertia at subCG": float(s.I44),
            "pitch inertia at subCG": float(s.I55),
            "yaw inertia at centerline": float(s.I66),
            "buoyancy (pgV)": float(self.env.rho * self.env.g * s.V),
            "C_stiffness": np.asarray(s.C_hydro + s.C_struc),
        }

    # ------------------------------------------------------------- mooring

    def calcMooringAndOffsets(self):
        """Mean offset + linearized mooring about it
        (cf. Model.calcMooringAndOffsets, raft/raft.py:1333-1367)."""
        if self.statics is None:
            self.calcSystemProps()
        if self.moor is None:
            self.r6_eq = jnp.zeros(6)
            self.results["means"] = {"platform offset": np.zeros(6)}
            return self
        s = self.statics
        F_const = s.W_struc + s.W_hydro + self.f6Ext
        if float(jnp.abs(self.env.current)) > 0:
            from raft_tpu.hydro import current_mean_force

            F_const = F_const + current_mean_force(self.members, self.env)
        C_body = s.C_struc + s.C_hydro
        with phase("mooring-equilibrium"):
            self.r6_eq, res = solve_equilibrium(self.moor, F_const, C_body)
            self.C_moor = mooring_stiffness(self.moor, self.r6_eq)
            self.F_moor = mooring_force(self.moor, self.r6_eq)
            T_mean = fairlead_tensions(self.moor, self.r6_eq)
        self.results["means"] = {
            "platform offset": np.asarray(self.r6_eq),
            "equilibrium residual": float(res),
            "mooring force": np.asarray(self.F_moor),
            "fairlead tensions": np.asarray(T_mean),
        }
        return self

    def solveStatics(self):
        """Mean static equilibrium (the reference declares this but leaves
        it a stub, raft/raft.py:1454-1466; here it is the working mooring-
        coupled equilibrium solve).  Alias of :meth:`calcMooringAndOffsets`,
        kept for reference API parity."""
        return self.calcMooringAndOffsets()

    # --------------------------------------------------------------- eigen

    def solveEigen(self, n_pass: int = 3):
        """Natural frequencies (cf. Model.solveEigen, raft/raft.py:1370-1452).

        With BEM coefficients staged, the frequency-dependent added mass is
        evaluated *at each mode's own natural frequency* by a small fixed
        point: solve with A(w_n) interpolated per mode, update w_n, repeat
        ``n_pass`` times (converges in 2-3 passes — A(w) varies slowly near
        the rigid-body modes).  The reference cannot do this: its BEM arrays
        are always zero (raft/raft.py:1380,1797-1800).

        Also reports the reference's per-DOF diagonal estimates with
        CG/mooring z-lever corrections (raft/raft.py:1422-1446) as the
        ``estimates`` key — the engineering cross-check output.
        """
        if self.statics is None:
            self.calcSystemProps()
        from raft_tpu.solve import diagonal_estimates, eigen_with_bem

        M_base = self.statics.M_struc + self.A_morison
        C_tot = self.statics.C_struc + self.statics.C_hydro + self.C_moor0
        with phase("eigen"):
            if self.bem is None:
                self.eigen = solve_eigen(M_base, C_tot)
                fns = np.asarray(self.eigen.fns)
                modes = np.asarray(self.eigen.modes)
                est = np.asarray(diagonal_estimates(M_base, C_tot))
            else:
                A_w = np.moveaxis(np.asarray(self.bem[0]), -1, 0)  # (nw,6,6)
                self.eigen, est = eigen_with_bem(
                    M_base, C_tot, A_w, np.asarray(self.w), n_pass=n_pass
                )
                fns = np.asarray(self.eigen.fns)
                modes = np.asarray(self.eigen.modes)
        self.results["eigen"] = {
            "frequencies": fns,
            "periods": np.asarray(1.0 / np.maximum(fns, 1e-12)),
            "modes": modes,
            "estimates": est,
        }
        return self

    # ------------------------------------------------------------ dynamics

    def _linear_coeffs(self) -> LinearCoeffs:
        nw = self.w.shape[0]
        s = self.statics
        M = jnp.broadcast_to(s.M_struc + self.A_morison, (nw, 6, 6))
        B = jnp.zeros((nw, 6, 6))
        C = s.C_struc + s.C_hydro + self.C_moor
        F = self.F_morison
        if self.bem is not None:
            A_bem, B_bem, F_bem = self.bem
            M = M + jnp.asarray(np.moveaxis(np.asarray(A_bem), -1, 0))
            B = B + jnp.asarray(np.moveaxis(np.asarray(B_bem), -1, 0))
            from raft_tpu.core.cplx import Cx

            # BEM excitation is per unit wave amplitude; the Morison
            # excitation is on the spectral-amplitude basis (wave kinematics
            # scale with zeta = sqrt(S), core/waves.py).  Scale by zeta per
            # frequency so the bases match before summing.
            Fb = np.moveaxis(np.asarray(F_bem), -1, 0)   # complex on host only
            zeta = np.asarray(self.wave.zeta)[:, None]
            F = F + Cx(jnp.asarray(zeta * Fb.real), jnp.asarray(zeta * Fb.imag))
        return LinearCoeffs(M=M, B=B, C=C, F=F)

    def solveDynamics(self, nIter: int = 40, tol: float = 0.01, method="while",
                      history: bool = False):
        # nIter default is above the reference's 15 (raft/raft.py:1469): the
        # OC4 semi needs ~22 iterations from the 0.1 seed; the early-exit
        # driver makes the higher cap free for fast-converging cases
        """RAO fixed-point solve (cf. Model.solveDynamics, raft/raft.py:1469).

        ``history=True`` records the per-iteration convergence error into
        ``results["response"]["iteration error history"]`` — the diagnostic
        the reference serves with per-iterate RAO plots
        (raft/raft.py:1536-1539), for inspecting a non-converging case.
        """
        if self.statics is None or self.kin is None:
            self.calcSystemProps()
        lin = self._linear_coeffs()
        with phase("rao-solve"):
            self.rao = solve_dynamics(
                self.members, self.kin, self.wave, self.env, lin,
                n_iter=nIter, tol=tol, method=method, history=history,
            )
        Xi = self.rao.Xi
        zeta = np.maximum(np.asarray(self.wave.zeta), 1e-12)
        dw = float(self.w[1] - self.w[0]) if len(self.w) > 1 else 1.0
        amp = np.asarray(Xi.abs())                       # (nw,6) spectral amp
        rao_mag = amp / zeta[:, None]
        sigma = np.sqrt((amp**2).sum(axis=0) * dw)
        self.results["response"] = {
            "w": np.asarray(self.w),
            "Xi": np.asarray(Xi.to_complex()),
            "RAO magnitude": rao_mag,
            "std dev": sigma,
            "converged": bool(self.rao.converged),
            "iterations": int(self.rao.n_iter),
        }
        if self.rao.err_hist is not None:
            self.results["response"]["iteration error history"] = np.asarray(
                self.rao.err_hist
            )
        return self

    # ------------------------------------------------------------- outputs

    def calcOutputs(self):
        """Derived outputs incl. nacelle acceleration RAO
        (cf. Model.calcOutputs, raft/raft.py:1602-1712)."""
        if self.rao is None:
            raise RuntimeError("run solveDynamics first")
        w = np.asarray(self.w)
        Xi = np.asarray(self.rao.Xi.to_complex())
        hHub = float(self.rna.hHub)
        # nacelle accel: -w^2 (Xi_surge + Xi_pitch * hHub) (raft/raft.py:1712)
        a_nac = -(w**2) * (Xi[:, 0] + Xi[:, 4] * hHub)
        zeta = np.maximum(np.asarray(self.wave.zeta), 1e-12)
        dw = float(w[1] - w[0]) if len(w) > 1 else 1.0
        self.results["response"]["nacelle acceleration"] = a_nac
        self.results["response"]["nacelle acceleration RAO"] = np.abs(a_nac) / zeta
        self.results["response"]["nacelle acceleration std dev"] = float(
            np.sqrt((np.abs(a_nac) ** 2).sum() * dw)
        )
        # fairlead tension RAOs: linearized line tension about the mean
        # offset (the reference's intended output, raft/raft.py:1655-1708)
        if self.moor is not None and self.r6_eq is not None:
            J = np.asarray(tension_jacobian(self.moor, self.r6_eq))  # (nl,6)
            T_amp = Xi @ J.T                                         # (nw,nl)
            self.results["response"]["fairlead tension amplitude"] = np.abs(T_amp)
            self.results["response"]["fairlead tension RAO"] = (
                np.abs(T_amp) / zeta[:, None]
            )
            self.results["response"]["fairlead tension std dev"] = np.sqrt(
                (np.abs(T_amp) ** 2).sum(axis=0) * dw
            )
        # design-constraint margins the reference carries only as
        # commented-out legacy code (raft/raft.py:1655-1698): slack-line
        # margin min_l(T_mean_l - 3 sigma_T_l) (negative = a line can go
        # slack at the 3-sigma excursion) and dynamic pitch
        # |static| + 3 sigma_pitch vs the 10 deg limit used there
        cons = {}
        if "fairlead tension std dev" in self.results["response"]:
            T_mean = np.asarray(self.results["means"]["fairlead tensions"])
            sig_T = self.results["response"]["fairlead tension std dev"]
            cons["slack line margin"] = float((T_mean - 3.0 * sig_T).min())
        sig_p = float(self.results["response"]["std dev"][4])
        static_p = (float(self.r6_eq[4]) if self.r6_eq is not None else 0.0)
        cons["dynamic pitch"] = float(np.rad2deg(abs(static_p) + 3.0 * sig_p))
        cons["dynamic pitch limit"] = 10.0
        self.results["constraints"] = cons
        return self.results

    def airgap(self, points, deck_z: float):
        """Relative wave elevation and air-gap margin at deck points.

        Linear-theory deck-clearance check (no analog in the reference):
        the relative elevation at plan point p = (x, y) is the incident
        elevation minus the structure's vertical motion there,
        ``eta_rel(w) = zeta e^{-i k (x cos beta + y sin beta)} - u_z(p, w)``
        with ``u_z = Xi_heave + Xi_roll y - Xi_pitch x`` (small-angle rigid
        body).  The 3-sigma air gap is ``deck_z - eta_mean_offset - 3
        sigma_rel``; negative means waves can reach the deck.

        ``points``: (np, 2) plan coordinates [m]; ``deck_z``: underside of
        deck above SWL [m].  Returns a dict with per-point sigma and
        margins, and stores it under ``results["airgap"]``.
        """
        if self.rao is None:
            raise RuntimeError("run solveDynamics first")
        pts = np.atleast_2d(np.asarray(points, dtype=float))
        if pts.ndim != 2 or pts.shape[-1] != 2:
            raise ValueError(
                f"points must be (np, 2) plan coordinates [x, y]; got shape "
                f"{pts.shape}"
            )
        w = np.asarray(self.w)
        k = np.asarray(self.wave.k)
        zeta = np.asarray(self.wave.zeta)
        beta = float(self.env.beta)
        Xi = np.asarray(self.rao.Xi.to_complex())            # (nw,6)
        dw = float(w[1] - w[0]) if len(w) > 1 else 1.0
        phase_lag = np.exp(-1j * k[None, :] * (
            pts[:, 0, None] * np.cos(beta) + pts[:, 1, None] * np.sin(beta)
        ))                                                   # (np,nw)
        eta = zeta[None, :] * phase_lag
        u_z = (Xi[None, :, 2]
               + Xi[None, :, 3] * pts[:, 1, None]
               - Xi[None, :, 4] * pts[:, 0, None])           # (np,nw)
        eta_rel = eta - u_z
        sigma = np.sqrt((np.abs(eta_rel) ** 2).sum(axis=1) * dw)   # (np,)
        # mean vertical offset of each deck point (heave/trim at the mean)
        z_off = np.zeros(len(pts))
        if self.r6_eq is not None:
            r6 = np.asarray(self.r6_eq)
            z_off = r6[2] + r6[3] * pts[:, 1] - r6[4] * pts[:, 0]
        out = {
            "points": pts,
            "sigma rel elevation": sigma,
            "margin 3 sigma": deck_z + z_off - 3.0 * sigma,
            "deck_z": float(deck_z),
        }
        self.results["airgap"] = out
        return out

    def print_report(self):
        """Human-readable property/results report (the reference prints this
        from calcOutputs, raft/raft.py:1606-1627)."""
        p = self.results.get("properties", {})
        print("=== raft_tpu analysis report ===")
        for key in (
            "total mass", "substructure mass", "shell mass", "ballast mass",
            "tower mass", "displacement", "buoyancy (pgV)", "waterplane area",
            "metacentric height",
        ):
            if key in p:
                print(f"  {key:<22} {p[key]:14.4g}")
        for key in ("total CG", "substructure CG", "center of buoyancy"):
            if key in p:
                v = p[key]
                print(f"  {key:<22} [{v[0]:9.3f} {v[1]:9.3f} {v[2]:9.3f}]")
        if "eigen" in self.results:
            fns = self.results["eigen"]["frequencies"]
            print("  natural frequencies [Hz] (surge..yaw):")
            print("   ", " ".join(f"{f:8.5f}" for f in fns))
            print("  natural periods [s]:")
            print("   ", " ".join(f"{t:8.2f}" for t in self.results["eigen"]["periods"]))
        if "means" in self.results:
            r6 = self.results["means"]["platform offset"]
            print(f"  mean offsets: surge {r6[0]:.2f} m, sway {r6[1]:.2f} m, "
                  f"heave {r6[2]:.2f} m, pitch {np.rad2deg(r6[4]):.2f} deg")
        if "response" in self.results:
            s = self.results["response"]["std dev"]
            print("  response std dev (surge..yaw):")
            print("   ", " ".join(f"{x:9.4g}" for x in s))
            if "nacelle acceleration std dev" in self.results["response"]:
                print(f"  nacelle accel std dev: "
                      f"{self.results['response']['nacelle acceleration std dev']:.3f} m/s^2")
        if "constraints" in self.results:
            c = self.results["constraints"]
            if "slack line margin" in c:
                print(f"  slack line margin (T - 3 sigma): "
                      f"{c['slack line margin']:.4g} N")
            print(f"  dynamic pitch (|static| + 3 sigma): "
                  f"{c['dynamic pitch']:.2f} deg "
                  f"(limit {c['dynamic pitch limit']:.0f})")
        print("================================")

    # ---------------------------------------------------------------- plot

    def plot(self, ax=None, hideGrid: bool = False, n_ring: int = 24):
        """3D wireframe of members + mooring lines: end rings and
        longitudinal edges per segment (cf. Member.plot raft/raft.py:799-856
        and Model.plot :1715-1738)."""
        import matplotlib.pyplot as plt

        if ax is None:
            fig = plt.figure(figsize=(8, 8))
            ax = fig.add_subplot(projection="3d")
        plot_member_wireframe(ax, self.members, n_ring=n_ring)
        if self.moor is not None:
            from raft_tpu.mooring import fairlead_positions, line_states

            r6 = self.r6_eq if self.r6_eq is not None else jnp.zeros(6)
            rf = np.asarray(fairlead_positions(self.moor, r6))
            ra = np.asarray(self.moor.r_anchor)
            st = line_states(self.moor, r6)
            for i in range(rf.shape[0]):
                self._plot_line(ax, ra[i], rf[i], st, i)
        if hideGrid:
            ax.set_axis_off()
        return ax

    def plot_raos(self, axes=None):
        """2x3 grid of RAO magnitude curves |Xi|/zeta per DOF vs frequency
        — the response view the reference renders per fixed-point iterate
        (raft/raft.py:1536-1539), here from the converged solve.  Run
        ``solveDynamics()`` first; returns the axes array."""
        if "response" not in self.results:
            raise RuntimeError("run solveDynamics() before plot_raos()")
        resp = self.results["response"]
        return plot_rao_grid(np.asarray(resp["w"]),
                             np.asarray(resp["RAO magnitude"])[None],
                             axes=axes)

    def _plot_line(self, ax, ra, rf, st, i):
        import numpy as np

        H, V = float(st.H[i]), float(st.V[i])
        L, w = float(self.moor.props.L[i]), float(self.moor.props.w[i])
        s = np.linspace(0, L, 50)
        Vv = np.maximum(V - w * (L - s), 0.0)
        T = np.sqrt(H**2 + Vv**2)
        dx = np.where(Vv > 0, H / T, 1.0)
        dz = np.where(Vv > 0, Vv / T, 0.0)
        x = np.concatenate([[0], np.cumsum(dx[:-1] * np.diff(s))])
        z = np.concatenate([[0], np.cumsum(dz[:-1] * np.diff(s))])
        # scale horizontal run to end exactly at the fairlead
        u = (rf[:2] - ra[:2]) / max(np.hypot(*(rf[:2] - ra[:2])), 1e-9)
        scale = np.hypot(*(rf[:2] - ra[:2])) / max(x[-1], 1e-9)
        pts = ra[None, :] + np.concatenate(
            [x[:, None] * scale * u[None, :], z[:, None]], axis=1
        )
        ax.plot(*pts.T, "b-", lw=0.8)


def plot_rao_grid(w, rao, axes=None, labels=None):
    """2x3 grid of per-DOF RAO magnitude curves, one line per leading-axis
    entry (turbines in an array; a single model passes ``rao[None]``).
    The ONE layout shared by ``Model.plot_raos`` and
    ``ArrayModel.plot_raos`` so the two views cannot drift apart.

    ``w``: (nw,) [rad/s]; ``rao``: (nT, nw, 6) magnitudes.  Returns the
    axes array."""
    import matplotlib.pyplot as plt

    f_hz = np.asarray(w) / (2.0 * np.pi)
    rao = np.asarray(rao)
    nT = rao.shape[0]
    if axes is None:
        _, axes = plt.subplots(2, 3, figsize=(12, 6), sharex=True)
    dof = ("surge [m/m]", "sway [m/m]", "heave [m/m]",
           "roll [rad/m]", "pitch [rad/m]", "yaw [rad/m]")
    flat = np.asarray(axes).ravel()
    if flat.size < 6:
        raise ValueError(f"plot_rao_grid needs 6 axes (one per DOF), "
                         f"got {flat.size}")
    for i, ax in enumerate(flat[:6]):
        for t in range(nT):
            lbl = (labels[t] if labels is not None
                   else f"T{t}" if nT > 1 else None)
            ax.plot(f_hz, rao[t, :, i], label=lbl if i == 0 else None)
        ax.set_ylabel(dof[i])
        ax.grid(True, alpha=0.3)
        if i >= 3:
            ax.set_xlabel("frequency [Hz]")
    if nT > 1 or labels is not None:
        flat[0].legend(fontsize=7)
    return axes


def plot_member_wireframe(ax, m, offset=(0.0, 0.0), n_ring: int = 24):
    """Wireframe of a MemberSet's segments on a 3D axes (shared by Model
    and ArrayModel plots): end rings + longitudinal edges per segment."""
    keep = np.asarray(m.seg_mask & ~m.seg_is_cap)
    off = np.array([offset[0], offset[1], 0.0])
    rA = np.asarray(m.seg_rA)[keep] + off
    q = np.asarray(m.seg_q)[keep]
    R = np.asarray(m.seg_R)[keep]
    L = np.asarray(m.seg_l)[keep]
    dA = np.asarray(m.seg_dA)[keep]
    dB = np.asarray(m.seg_dB)[keep]
    circ = np.asarray(m.seg_circ)[keep]
    th = np.linspace(0, 2 * np.pi, n_ring + 1)
    for i in range(len(rA)):
        rB_i = rA[i] + q[i] * L[i]
        p1, p2 = R[i][:, 0], R[i][:, 1]
        if circ[i]:
            ringA = rA[i] + 0.5 * dA[i, 0] * (
                np.outer(np.cos(th), p1) + np.outer(np.sin(th), p2)
            )
            ringB = rB_i + 0.5 * dB[i, 0] * (
                np.outer(np.cos(th), p1) + np.outer(np.sin(th), p2)
            )
        else:
            sq = np.array([[1, 1], [-1, 1], [-1, -1], [1, -1], [1, 1]]) * 0.5
            ringA = rA[i] + sq[:, :1] * dA[i, 0] * p1 + sq[:, 1:] * dA[i, 1] * p2
            ringB = rB_i + sq[:, :1] * dB[i, 0] * p1 + sq[:, 1:] * dB[i, 1] * p2
        ax.plot(*ringA.T, "k-", lw=0.6)
        ax.plot(*ringB.T, "k-", lw=0.6)
        step = max(1, len(ringA) // 8)
        for j in range(0, len(ringA), step):
            ax.plot(*np.stack([ringA[j], ringB[j]]).T, "k-", lw=0.4)


def solve_bem_heading_grid(panels, w, rho, g, depth, lid, headings, beta,
                           mode=None):
    """Solve radiation once + diffraction for a whole heading grid, and
    stage the excitation at the current heading.

    Shared staging protocol of Model.calcBEM and ArrayModel.calcBEM:
    returns ``(bem_headings, bem)`` where ``bem_headings = (betas,
    F_all[nb,6,nw], A, B)`` is the grid for later re-staging and ``bem``
    is the (A, B, F[6,nw]) tuple at ``beta``.  ``mode`` routes the
    solver (native host / on-device JAX / auto — see
    :func:`raft_tpu.hydro.jax_bem.solve_bem_any`); either way the
    influence matrix factors once per frequency and every extra heading
    is one extra back-substitution.
    """
    from raft_tpu.hydro.jax_bem import solve_bem_any

    betas = np.sort(np.asarray(headings, dtype=float))
    if not (betas[0] - 1e-9 <= beta <= betas[-1] + 1e-9):
        # fail BEFORE the (expensive) panel solve, not after
        raise ValueError(
            f"current heading {beta:.3f} rad outside the requested grid "
            f"[{betas[0]:.3f}, {betas[-1]:.3f}] — include it or setEnv first"
        )
    A, B, F_all = solve_bem_any(panels, np.asarray(w), rho=rho, g=g,
                                beta=betas, depth=depth, lid=lid, mode=mode)
    bem_headings = (betas, F_all, A, B)
    return bem_headings, (A, B, interp_heading_excitation(betas, F_all, beta))


def interp_heading_excitation(betas, F_all, beta: float) -> np.ndarray:
    """Excitation F[6,nw] at heading ``beta`` from a staged heading grid
    (linear interpolation in heading; shared by Model and ArrayModel
    re-staging).  Runs per sea-state case inside ``setEnv``, so it is one
    vectorized blend of the two bracketing heading slices, not a per-
    (component, frequency) loop."""
    betas = np.asarray(betas)
    # tolerance sized for float32 round-trips: a heading that passed
    # through a device array (e.g. WaveState.beta under default f32) can
    # differ from the staged grid value in the 7th decimal — that is the
    # same physical heading, not an out-of-grid request (1e-6 rad ~ 6e-5
    # deg)
    if beta < betas[0] - 1e-6 or beta > betas[-1] + 1e-6:
        raise ValueError(
            f"heading {beta:.3f} rad outside staged grid "
            f"[{betas[0]:.3f}, {betas[-1]:.3f}]"
        )
    beta = float(np.clip(beta, betas[0], betas[-1]))
    if len(betas) == 1:
        return np.asarray(F_all[0])
    j = int(np.clip(np.searchsorted(betas, beta), 1, len(betas) - 1))
    t = float(np.clip((beta - betas[j - 1]) / (betas[j] - betas[j - 1]), 0.0, 1.0))
    return (1.0 - t) * np.asarray(F_all[j - 1]) + t * np.asarray(F_all[j])


def load_design(fname) -> dict:
    """Parse a design YAML path — or pass a dict through unchanged, so
    every staging entry point accepts in-memory design variants (e.g.
    programmatically perturbed geometries) alongside files."""
    if isinstance(fname, dict):
        return fname
    import yaml

    with open(fname) as f:
        return yaml.safe_load(f)


def _staged_wave(nw: int, w_min: float, w_max: float, depth: float,
                 Hs: float, Tp: float, nw_pad: int | None = None) -> WaveState:
    """The ONE staged-grid recipe shared by :func:`stage_design_base` and
    :func:`stage_designs`: ``nw`` JONSWAP bins on [w_min, w_max], plus —
    when ``nw_pad`` exceeds ``nw`` — bucket padding that extends the grid
    past ``w_max`` at the same spacing with ``zeta = 0`` and a
    ``freq_mask`` marking the physical bins (the padded bins then carry
    exactly-zero response through the solve; see
    :mod:`raft_tpu.build.buckets`)."""
    nw = int(nw)
    nw_p = nw if nw_pad is None else int(nw_pad)
    if nw_p < nw:
        raise ValueError(f"nw_pad={nw_p} smaller than nw={nw}")
    if nw_p > nw and nw < 2:
        raise ValueError("frequency padding needs nw >= 2 to fix the spacing")
    w_host = np.linspace(w_min, w_max, nw)
    if nw_p > nw:
        dw = w_host[1] - w_host[0]
        w_host = np.concatenate(
            [w_host, w_host[-1] + dw * np.arange(1, nw_p - nw + 1)])
    w = jnp.asarray(w_host)
    zeta = jnp.sqrt(jonswap(w, Hs, Tp))
    mask = None
    if nw_p > nw:
        mask = jnp.asarray(np.arange(nw_p) < nw)
        zeta = zeta * mask                    # exact zeros at padded bins
    return WaveState(w=w, k=wave_number(w, depth), zeta=zeta,
                     freq_mask=mask)


def stage_design_base(fname, nw: int, Hs: float, Tp: float,
                      w_min: float, w_max: float,
                      with_mooring: bool = True, bucket=None):
    """One-call staging of a design to the forward-pipeline inputs:
    ``(design, members, rna, env, wave, C_moor)``.

    The shared recipe behind the driver entry (``__graft_entry__._base6``)
    and the trace-audit registry (``raft_tpu.lint.registry``) — one
    staging contract, so the audit's "mirror of the traced core" cannot
    drift from the program the driver actually compiles.

    ``with_mooring=False`` skips the mooring parse + linearized-stiffness
    solve (``C_moor`` is then None): the stiffness is a jitted
    forward-mode Jacobian through the catenary Newton solve, so call
    sites that bring their own mooring must not pay its compile.

    ``bucket``: ``None`` (default) builds the design at its exact shapes —
    the historical behavior, byte-identical.  ``True`` rounds the member
    axes and the frequency grid up to their shape-bucket classes
    (:func:`raft_tpu.build.buckets.bucketize`), and an explicit
    :class:`~raft_tpu.build.buckets.BucketSig` pins the class directly
    (self-healing promotion applies if the design outgrows it) — every
    design staged at one class shares one compiled shape.
    """
    design = load_design(fname)
    members, _sig, rna, env, wave, C_moor = _stage_design_one(
        design, nw, Hs, Tp, w_min, w_max, with_mooring, bucket)
    return design, members, rna, env, wave, C_moor


def _stage_design_one(design: dict, nw: int, Hs: float, Tp: float,
                      w_min: float, w_max: float, with_mooring: bool,
                      bucket):
    """The ONE per-design staging recipe shared by
    :func:`stage_design_base` and :func:`stage_designs`: member build
    (exact or bucket-padded), RNA, per-design-depth Env, (padded) wave
    grid, mooring stiffness — one body, so a solo-staged design and the
    same design staged inside a megabatch cannot drift.  ``bucket``:
    ``None`` exact shapes, ``True`` bucketize, or an explicit
    :class:`~raft_tpu.build.buckets.BucketSig`.  Returns
    ``(members, sig_or_None, rna, env, wave, C_moor)``."""
    nw_pad = None
    sig = None
    if bucket is None:
        members = build_member_set(design)
    else:
        from raft_tpu.build import buckets as _buckets

        if isinstance(bucket, _buckets.BucketSig):
            members, sig = _buckets.build_bucketed_member_set(design, bucket)
        else:
            members, sig = _buckets.build_bucketed_member_set(design, nw=nw)
        nw_pad = sig.nw
    rna = build_rna(design)
    depth = float(design["mooring"]["water_depth"])
    env = Env(Hs=Hs, Tp=Tp, depth=depth)
    wave = _staged_wave(nw, w_min, w_max, depth, Hs, Tp, nw_pad=nw_pad)
    C_moor = None
    if with_mooring:
        moor = parse_mooring(
            design["mooring"],
            yaw_stiffness=design["turbine"]["yaw_stiffness"])
        C_moor = mooring_stiffness(moor, jnp.zeros(6))
    return members, sig, rna, env, wave, C_moor


@_dataclasses.dataclass
class DesignBatch:
    """One shape bucket's worth of staged designs, stacked batch-leading.

    Every array pytree carries a leading lane axis of length
    ``len(fnames)``; a whole batch solves as ONE padded device dispatch
    (:func:`raft_tpu.parallel.sweep.sweep_designs`).  ``indices`` maps
    lanes back to positions in the caller's original design list.
    """

    sig: "object"            # raft_tpu.build.buckets.BucketSig
    fnames: list             # per-lane design identifiers (path or dict)
    indices: list            # per-lane position in the caller's list
    members: "object"        # MemberSet, (B, ...) stacked
    rna: "object"            # RNA, (B,) stacked scalars
    env: "object"            # Env, (B,) stacked scalars
    wave: "object"           # WaveState, (B, nw_pad)
    C_moor: "object"         # (B, 6, 6) or None (with_mooring=False)
    bem: "object" = None     # staged (A[B,nw,6,6], B[...], F Cx[B,nw,6]) or None
    nw: int = 0              # physical (unpadded) frequency-bin count
    promotions: int = 0      # class promotions THIS batch's staging performed


def _stack_trees(trees):
    """Stack a list of identical-structure pytrees batch-leading."""
    import jax

    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def stage_designs(fnames, nw: int, Hs: float, Tp: float,
                  w_min: float, w_max: float, with_mooring: bool = True,
                  bems=None) -> dict:
    """Stage a MIXED design list into shape buckets, stacked batch-leading.

    Each design (YAML path or dict) is bucketized
    (:func:`raft_tpu.build.buckets.bucketize`, honoring
    ``RAFT_TPU_BUCKETS``), built padded to its class (self-healing
    promotion included), staged with the shared
    :func:`stage_design_base` recipe — per-design water depth, mooring
    stiffness, padded frequency grid — and grouped by
    :class:`~raft_tpu.build.buckets.BucketSig`: the result maps each
    signature to a :class:`DesignBatch` whose members/RNA/env/wave/mooring
    (and optional BEM layouts) are stacked along a leading lane axis.
    One executable per bucket then serves ANY designs of that class —
    the designs are call *arguments*, not closure constants.

    ``bems``: optional per-design raw BEM tuples (``A[6,6,nw]``,
    ``B[6,6,nw]``, ``F[6,nw]`` complex, on the physical grid) — all
    designs or none (a bucket mixing BEM and strip-only lanes would need
    two programs).  Staged frequency-leading, zero-padded on the bucket
    grid, excitation zeta-scaled (zero at padded bins by construction).
    """
    from raft_tpu.build import buckets as _buckets

    fnames = list(fnames)
    if bems is not None:
        bems = list(bems)
        if len(bems) != len(fnames):
            raise ValueError(f"bems has {len(bems)} entries for "
                             f"{len(fnames)} designs")
        if any(b is None for b in bems):
            raise ValueError("bems must cover every design or be None: a "
                             "bucket mixing BEM and strip-only lanes would "
                             "need two different compiled programs")
    staged: dict = {}
    promo: dict = {}
    for i, fn in enumerate(fnames):
        design = load_design(fn)
        p0 = _buckets.promotion_count()
        members, sig, rna, env, wave, C_moor = _stage_design_one(
            design, nw, Hs, Tp, w_min, w_max, with_mooring, bucket=True)
        bem = None
        if bems is not None:
            bem = _stage_bem_padded(bems[i], wave, nw)
        staged.setdefault(sig, []).append(
            (i, fn, members, rna, env, wave, C_moor, bem))
        promo[sig] = promo.get(sig, 0) + (_buckets.promotion_count() - p0)

    out: dict = {}
    for sig, rows in staged.items():
        idx, names, ms, rnas, envs, waves, cms, bs = zip(*rows)
        out[sig] = DesignBatch(
            sig=sig,
            fnames=list(names),
            indices=list(idx),
            members=_stack_trees(ms),
            rna=_stack_trees(rnas),
            env=_stack_trees(envs),
            wave=_stack_trees(waves),
            C_moor=None if cms[0] is None else jnp.stack(cms),
            bem=None if bs[0] is None else _stack_trees(bs),
            nw=int(nw),
            promotions=promo[sig],
        )
    return out


def _stage_bem_padded(bem, wave: WaveState, nw: int):
    """One design's raw host BEM tuple -> the bucket grid's staged device
    layout.  Padding is the ONLY step owned here: the host arrays are
    zero-padded past the physical bins, then routed through the shared
    device-layout + zeta-scaling recipe behind :func:`raft_tpu.parallel.
    sweep.stage_bem` — one convention, so a bucketed BEM lane cannot
    drift from a solo ``stage_bem`` staging.  Padded-bin excitation is
    exactly zero by construction (zeta is zero there)."""
    from raft_tpu.parallel.sweep import _bem_device_layout, _stage_zeta

    A_h, B_h, F_h = (np.asarray(x) for x in bem)   # (6,6,nw)/(6,6,nw)/(6,nw)
    if A_h.shape[-1] != nw:
        raise ValueError(f"BEM arrays carry {A_h.shape[-1]} frequency bins; "
                         f"the staged grid has {nw} physical bins")
    nw_p = int(wave.w.shape[-1])
    if nw_p > nw:
        tail = ((0, 0),) * (A_h.ndim - 1) + ((0, nw_p - nw),)
        A_h = np.pad(A_h, tail)
        B_h = np.pad(B_h, tail)
        F_h = np.pad(F_h, ((0, 0), (0, nw_p - nw)))
    return _stage_zeta(_bem_device_layout((A_h, B_h, F_h)), wave.zeta)


def run_raft(fname_design: str, fname_env: str | None = None,
             plot: bool = False, w=None) -> dict:
    """End-to-end analysis recipe (cf. runRAFT, raft/runRAFT.py:23-82).

    ``fname_env``: optional environment YAML with ``Hs``/``Tp``/``V``/
    ``beta`` [deg]/``Fthrust`` keys.  The reference accepts this argument
    but never opens it (hard-coded sea state, raft/runRAFT.py:68); here it
    is honored, with the reference's defaults when absent."""
    design = load_design(fname_design)
    model = Model(design, w=w)
    turb = design.get("turbine", {})
    envd = load_design(fname_env) if fname_env else {}
    model.setEnv(
        Hs=float(envd.get("Hs", 8.0)),
        Tp=float(envd.get("Tp", 12.0)),
        V=float(envd.get("V", 10.0)),
        beta=float(np.deg2rad(envd.get("beta", 0.0))),
        Fthrust=float(envd.get("Fthrust", turb.get("Fthrust", 0.0))),
    )
    model.calcSystemProps()
    model.solveEigen()
    model.calcMooringAndOffsets()
    model.solveDynamics()
    model.calcOutputs()
    if plot:
        model.plot()
    return model.results
