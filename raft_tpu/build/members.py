"""Design dict -> :class:`~raft_tpu.core.types.MemberSet` (host-side).

This replaces the reference's per-object ``Member`` construction
(raft/raft.py:37-201) and heading-replication loop (raft/raft.py:1770-1783)
with a flat, stacked, masked-array build: the entire platform+tower becomes
one pytree of fixed-shape arrays, ready for ``jit``/``vmap``/``shard_map``.

Behavioral parity notes (validated against the reference's recipe):
  * Station positions are normalized to [0, l] exactly as raft/raft.py:86.
  * Heading rotation uses the reference's clockwise-convention matrix
    (raft/raft.py:71-77) so replicated member patterns land identically.
  * Strip discretization matches raft/raft.py:147-191: max spacing
    ``dls_max`` (reference hard-codes 10.0 m), node at each strip midpoint,
    a zero-length "end disk" node at end A, and zero-length nodes at flat
    transitions.  The reference has no end-B disk node; we reproduce that by
    default (``include_end_b=False``) for output parity — flip it on for
    flat-topped fully-submerged members where the missing top-face pressure
    term matters.
  * End caps/bulkheads become extra "cap segments" with the hole as inner
    dims, using the same interpolated-diameter rules as raft/raft.py:484-633.

Deviation from the reference (documented in DEVIATIONS.md): the reference
translates each cap's inertia matrix by the *previous submember's* center
instead of the cap's own center (stale variable at raft/raft.py:633); here
the cap's own center is used.
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from raft_tpu.io.schema import get_from_dict


@dataclass
class _Accum:
    """Plain-numpy accumulator for segment and node rows."""

    seg: dict = field(default_factory=lambda: {k: [] for k in _SEG_KEYS})
    node: dict = field(default_factory=lambda: {k: [] for k in _NODE_KEYS})


_SEG_KEYS = [
    "rA", "q", "R", "l", "dA", "dB", "diA", "diB",
    "l_fill", "rho_fill", "rho_shell", "circ", "is_cap", "member", "type",
]
_NODE_KEYS = [
    "r", "q", "p1", "p2", "ds", "drs", "dls",
    "Cd_q", "Cd_p1", "Cd_p2", "Cd_end", "Ca_q", "Ca_p1", "Ca_p2", "Ca_end",
    "circ", "member", "potmod",
]


def _orientation(rA, rB, gamma_deg):
    """q/p1/p2 unit vectors + Z1Y2Z3 rotation matrix (cf. raft/raft.py:205-242).

    float64 numpy twin of core.transforms.member_orientation — the host build
    must stay double precision regardless of the jax x64 flag, so it cannot
    route through jnp.  tests/test_build_members.py pins the two
    implementations against each other so they cannot diverge.
    """
    rAB = rB - rA
    l = np.linalg.norm(rAB)
    q = rAB / l
    beta = np.arctan2(q[1], q[0])
    phi = np.arctan2(np.sqrt(q[0] ** 2 + q[1] ** 2), q[2])
    s1, c1 = np.sin(beta), np.cos(beta)
    s2, c2 = np.sin(phi), np.cos(phi)
    g = np.deg2rad(gamma_deg)
    s3, c3 = np.sin(g), np.cos(g)
    R = np.array(
        [
            [c1 * c2 * c3 - s1 * s3, -c3 * s1 - c1 * c2 * s3, c1 * s2],
            [c1 * s3 + c2 * c3 * s1, c1 * c3 - c2 * s1 * s3, s1 * s2],
            [-c3 * s2, s2 * s3, c2],
        ]
    )
    p1 = R @ np.array([1.0, 0.0, 0.0])
    p2 = np.cross(q, p1)
    return q, p1, p2, R


def _as_pairs(d, n, circ):
    """Normalize a diameter spec to (n,2) side-length pairs.

    Follows the reference's semantics: circular members read 'd' as per-station
    diameters (shape=n, raft/raft.py:92); rectangular members read it as
    side-length pairs (shape=[n,2], raft/raft.py:99), where a single 1-D
    ``[len, wid]`` pair broadcasts to every station — so a length-2 list is a
    pair even when n == 2.
    """
    d = np.asarray(d, dtype=float)
    if circ:
        if d.ndim == 0:
            d = np.tile(d, n)
        if d.ndim == 1 and d.shape[0] == n:
            return np.stack([d, d], axis=-1)
        raise ValueError("circular member 'd' must be a scalar or per-station list")
    if d.ndim == 0:
        return np.tile(d, (n, 2))
    if d.ndim == 1 and d.shape[0] == 2:
        return np.tile(d, (n, 1))
    if d.shape == (n, 2):
        return d
    raise ValueError("rectangular member 'd' must be [len,wid] or an (n,2) list of pairs")


def _interp_pairs(x, xs, pairs):
    """Interpolate an (n,2) pair profile at scalar x."""
    return np.array(
        [np.interp(x, xs, pairs[:, 0]), np.interp(x, xs, pairs[:, 1])]
    )


def _cap_hole_pairs(d_in, ncap, circ):
    """Normalize 'cap_d_in' to (ncap,2) hole side-length pairs.

    Mirrors the `_as_pairs` convention: circular members read a 1-D list as
    per-cap hole diameters; rectangular members read a length-2 1-D list as
    one [len, wid] hole pair broadcast to every cap (a pair even when
    ncap == 2), or an (ncap,2) array of per-cap pairs.
    """
    d_in = np.asarray(d_in, dtype=float)
    if d_in.ndim == 0:
        return np.tile(d_in, (ncap, 2))
    if d_in.ndim == 1:
        if not circ and d_in.shape[0] == 2:
            return np.tile(d_in, (ncap, 1))
        if d_in.shape[0] == ncap:
            return np.stack([d_in, d_in], axis=-1)
        if d_in.shape[0] == 1:
            return np.tile(d_in[0], (ncap, 2))
        raise ValueError("'cap_d_in' must be scalar, per-cap, or a rect [len,wid] pair")
    if d_in.shape == (ncap, 2):
        return d_in
    raise ValueError("'cap_d_in' must be scalar, per-cap, or an (ncap,2) pair list")


def add_member(acc: _Accum, mi: dict, member_id: int, dls_max: float = 10.0,
               include_end_b: bool = False) -> None:
    """Parse one member dict (one heading already applied) into the accumulator."""
    mtype = int(mi["type"])
    rA = np.array(mi["rA"], dtype=float)
    rB = np.array(mi["rB"], dtype=float)
    shape_str = str(mi["shape"])
    circ = shape_str[0].lower() == "c"
    if not circ and shape_str[0].lower() != "r":
        raise ValueError("member 'shape' must start with 'c' (circular) or 'r' (rectangular)")

    heading = get_from_dict(mi, "heading", default=0.0)
    if heading != 0.0:
        c, s = np.cos(np.deg2rad(heading)), np.sin(np.deg2rad(heading))
        rot = np.array([[c, s, 0.0], [-s, c, 0.0], [0.0, 0.0, 1.0]])
        rA = rot @ rA
        rB = rot @ rB

    l = np.linalg.norm(rB - rA)
    stations_raw = np.array(mi["stations"], dtype=float)
    n = len(stations_raw)
    if n < 2:
        raise ValueError("at least two 'stations' entries are required")
    stations = (stations_raw - stations_raw[0]) / (stations_raw[-1] - stations_raw[0]) * l

    d = _as_pairs(mi["d"], n, circ)                         # (n,2) outer dims
    t = get_from_dict(mi, "t", shape=n)                     # (n,) wall thickness
    di = np.maximum(d - 2.0 * t[:, None], 0.0)              # (n,2) inner dims

    gamma = get_from_dict(mi, "gamma", default=0.0) if not circ else 0.0
    rho_shell = get_from_dict(mi, "rho_shell", default=8500.0)
    l_fill = get_from_dict(mi, "l_fill", shape=-1, default=0.0)
    rho_fill = get_from_dict(mi, "rho_fill", shape=-1, default=0.0)

    # hydro coefficient profiles (per station; interpolated onto nodes below).
    # 'Cd'/'Ca' apply to both transverse directions; the optional
    # 'Cd_p1'/'Cd_p2'/'Ca_p1'/'Ca_p2' keys override per direction (p1 is the
    # vertical transverse direction of a horizontal member) — needed for flat
    # rectangular pontoons whose vertical added mass far exceeds the lateral.
    Cd_q = get_from_dict(mi, "Cd_q", shape=n, default=0.0)
    Cd_p = get_from_dict(mi, "Cd", shape=n, default=0.6)
    Cd_p1 = get_from_dict(mi, "Cd_p1", shape=n, default=Cd_p)
    Cd_p2 = get_from_dict(mi, "Cd_p2", shape=n, default=Cd_p)
    Cd_end = get_from_dict(mi, "CdEnd", shape=n, default=0.6)
    Ca_q = get_from_dict(mi, "Ca_q", shape=n, default=0.0)
    Ca_p = get_from_dict(mi, "Ca", shape=n, default=0.97)
    Ca_p1 = get_from_dict(mi, "Ca_p1", shape=n, default=Ca_p)
    Ca_p2 = get_from_dict(mi, "Ca_p2", shape=n, default=Ca_p)
    Ca_end = get_from_dict(mi, "CaEnd", shape=n, default=0.6)

    q, p1, p2, R = _orientation(rA, rB, gamma)

    def push_seg(rA_s, l_s, dA, dB, diA, diB, lf, rf, is_cap):
        acc.seg["rA"].append(rA_s)
        acc.seg["q"].append(q)
        acc.seg["R"].append(R)
        acc.seg["l"].append(l_s)
        acc.seg["dA"].append(dA)
        acc.seg["dB"].append(dB)
        acc.seg["diA"].append(diA)
        acc.seg["diB"].append(diB)
        acc.seg["l_fill"].append(lf)
        acc.seg["rho_fill"].append(rf)
        acc.seg["rho_shell"].append(rho_shell)
        acc.seg["circ"].append(circ)
        acc.seg["is_cap"].append(is_cap)
        acc.seg["member"].append(member_id)
        acc.seg["type"].append(mtype)

    # ---- shell segments (station spans), cf. raft/raft.py:346-477 ----
    for i in range(1, n):
        l_s = stations[i] - stations[i - 1]
        if l_s <= 0.0:
            continue
        lf = l_fill if np.isscalar(l_fill) else l_fill[i - 1]
        rf = rho_fill if np.isscalar(rho_fill) else rho_fill[i - 1]
        push_seg(
            rA + q * stations[i - 1], l_s,
            d[i - 1], d[i], di[i - 1], di[i],
            float(lf), float(rf), False,
        )

    # ---- cap/bulkhead segments, cf. raft/raft.py:484-633 ----
    cap_stations_raw = get_from_dict(mi, "cap_stations", shape=-1, default=[])
    cap_stations_raw = np.atleast_1d(np.asarray(cap_stations_raw, dtype=float))
    if cap_stations_raw.size:
        ncap = cap_stations_raw.shape[0]
        cap_t = np.atleast_1d(get_from_dict(mi, "cap_t", shape=ncap))
        cap_d_in = _cap_hole_pairs(
            np.asarray(get_from_dict(mi, "cap_d_in", shape=-1, default=0.0), dtype=float),
            ncap, circ,
        )
        cap_L = (cap_stations_raw - stations_raw[0]) / (stations_raw[-1] - stations_raw[0]) * l

        for ci in range(cap_L.shape[0]):
            L, h = cap_L[ci], cap_t[ci]
            hole = cap_d_in[ci]
            # skip bulkheads within one thickness of either member end — the
            # interior-cap interpolation below would reach past the end.  The
            # reference has the same guard (raft/raft.py:504-508) but its
            # top-end clause is always-false (`L > stations[-1] + h`, should
            # be `- h`); the intended both-ends form is used here (DEVIATIONS.md).
            near_A = stations[0] < L < stations[0] + h and not np.isclose(L, stations[0])
            near_B = stations[-1] - h < L < stations[-1] and not np.isclose(L, stations[-1])
            if near_A or near_B:
                continue
            if np.isclose(L, stations[0]):
                dA_c = di[0]
                dB_c = _interp_pairs(L + h, stations, di)
                diA_c = hole
                diB_c = dB_c * np.divide(diA_c, dA_c, out=np.zeros(2), where=dA_c > 0)
                base = L
            elif np.isclose(L, stations[-1]):
                dA_c = _interp_pairs(L - h, stations, di)
                dB_c = di[-1]
                diB_c = hole
                diA_c = dA_c * np.divide(diB_c, dB_c, out=np.zeros(2), where=dB_c > 0)
                base = L - h
            else:
                dA_c = _interp_pairs(L - h / 2, stations, di)
                dB_c = _interp_pairs(L + h / 2, stations, di)
                dM = _interp_pairs(L, stations, di)
                frac = np.divide(hole, dM, out=np.zeros(2), where=dM > 0)
                diA_c = dA_c * frac
                diB_c = dB_c * frac
                base = L - h / 2
            push_seg(rA + q * base, float(h), dA_c, dB_c, diA_c, diB_c, 0.0, 0.0, True)

    # ---- strip-theory nodes, cf. raft/raft.py:147-191 ----
    ls = [0.0]
    dls = [0.0]
    ds = [0.5 * d[0]]
    drs = [0.5 * d[0]]
    for i in range(1, n):
        lstrip = stations[i] - stations[i - 1]
        if lstrip > 0.0:
            ns = int(np.ceil(lstrip / dls_max))
            dlstrip = lstrip / ns
            m = 0.5 * (d[i] - d[i - 1]) / dlstrip
            for j in range(ns):
                ls.append(stations[i - 1] + dlstrip * (0.5 + j))
                dls.append(dlstrip)
                ds.append(d[i - 1] + dlstrip * m * (0.5 + j))
                drs.append(dlstrip * m)
        else:
            ls.append(stations[i - 1])
            dls.append(0.0)
            ds.append(0.5 * (d[i - 1] + d[i]))
            drs.append(0.5 * (d[i] - d[i - 1]))
    if include_end_b:
        # end-B disk node (not present in the reference; see module docstring)
        ls.append(l)
        dls.append(0.0)
        ds.append(0.5 * d[-1])
        drs.append(-0.5 * d[-1])

    rAB = rB - rA
    for li, dlsi, dsi, drsi in zip(ls, dls, ds, drs):
        acc.node["r"].append(rA + (li / l) * rAB)
        acc.node["q"].append(q)
        acc.node["p1"].append(p1)
        acc.node["p2"].append(p2)
        acc.node["ds"].append(np.asarray(dsi, dtype=float).reshape(-1)[:2]
                              if np.ndim(dsi) else np.array([dsi, dsi]))
        acc.node["drs"].append(np.asarray(drsi, dtype=float).reshape(-1)[:2]
                               if np.ndim(drsi) else np.array([drsi, drsi]))
        acc.node["dls"].append(dlsi)
        acc.node["Cd_q"].append(np.interp(li, stations, Cd_q))
        acc.node["Cd_p1"].append(np.interp(li, stations, Cd_p1))
        acc.node["Cd_p2"].append(np.interp(li, stations, Cd_p2))
        acc.node["Cd_end"].append(np.interp(li, stations, Cd_end))
        acc.node["Ca_q"].append(np.interp(li, stations, Ca_q))
        acc.node["Ca_p1"].append(np.interp(li, stations, Ca_p1))
        acc.node["Ca_p2"].append(np.interp(li, stations, Ca_p2))
        acc.node["Ca_end"].append(np.interp(li, stations, Ca_end))
        acc.node["circ"].append(circ)
        acc.node["member"].append(member_id)
        acc.node["potmod"].append(bool(mi.get("potMod", False)))


def _accumulate(design: dict, dls_max: float = 10.0,
                include_end_b: bool = False) -> _Accum:
    """Heading-replicated platform+tower accumulation shared by
    :func:`build_member_set` and :func:`member_counts` — ONE parse of the
    member list, so the size a design is *bucketed* by can never drift
    from the size it is *built* at."""
    acc = _Accum()
    member_id = 0
    for mi in design["platform"]["members"]:
        headings = get_from_dict(mi, "heading", shape=-1, default=0.0)
        for heading in np.atleast_1d(headings):
            mi_h = dict(mi)
            mi_h["heading"] = float(heading)
            add_member(acc, mi_h, member_id, dls_max=dls_max, include_end_b=include_end_b)
            member_id += 1
    if "turbine" in design and "tower" in design["turbine"]:
        add_member(acc, design["turbine"]["tower"], member_id, dls_max=dls_max,
                   include_end_b=include_end_b)
        member_id += 1
    return acc


def member_counts(design: dict, dls_max: float = 10.0,
                  include_end_b: bool = False) -> tuple[int, int]:
    """Exact (segment, node) counts a design builds at — the quantity the
    shape-bucket ladder (:mod:`raft_tpu.build.buckets`) rounds up.  Pure
    host-side numpy, no device arrays."""
    acc = _accumulate(design, dls_max=dls_max, include_end_b=include_end_b)
    return len(acc.seg["l"]), len(acc.node["dls"])


def build_member_set(design: dict, dls_max: float = 10.0,
                     pad_segments: int | None = None, pad_nodes: int | None = None,
                     include_end_b: bool = False, dtype=None, _acc=None):
    """Build the full platform+tower :class:`MemberSet` from a design dict.

    Replicates members over their ``heading`` patterns (raft/raft.py:1770-1783)
    and appends the tower member.  ``pad_segments``/``pad_nodes`` fix the array
    sizes (masked padding) so a family of designs shares one compiled shape.
    A design that exceeds the requested padding raises ``ValueError``; the
    shape-bucket layer (:func:`raft_tpu.build.buckets.build_bucketed_member_set`)
    catches that and promotes the design to the next size class instead of
    failing the caller.  ``_acc``: a prebuilt :func:`_accumulate` result for
    THIS design (the bucket layer measures counts before building; passing
    its accumulator avoids parsing the member list twice).
    """
    import jax.numpy as jnp

    from raft_tpu.core.types import MemberSet

    acc = (_acc if _acc is not None
           else _accumulate(design, dls_max=dls_max,
                            include_end_b=include_end_b))

    S = len(acc.seg["l"])
    N = len(acc.node["dls"])
    Sp = pad_segments if pad_segments is not None else S
    Np = pad_nodes if pad_nodes is not None else N
    if Sp < S or Np < N:
        raise ValueError(f"padding too small: need >= ({S} segments, {N} nodes)")

    dtype = dtype or jnp.zeros(0).dtype

    def seg(key, shape_tail=(), dt=None, pad_val=0):
        arr = np.array(acc.seg[key])
        out = np.full((Sp, *shape_tail), pad_val, dtype=arr.dtype if dt is None else dt)
        out[:S] = arr
        return jnp.asarray(out, dtype=dt or dtype)

    def node(key, shape_tail=(), dt=None, pad_val=0):
        arr = np.array(acc.node[key])
        out = np.full((Np, *shape_tail), pad_val, dtype=arr.dtype if dt is None else dt)
        out[:N] = arr
        return jnp.asarray(out, dtype=dt or dtype)

    seg_mask = jnp.asarray(np.arange(Sp) < S)
    node_mask = jnp.asarray(np.arange(Np) < N)
    # padded segments get l=1 to keep divisions well-defined (masked out anyway)
    seg_l = np.ones(Sp)
    seg_l[:S] = np.array(acc.seg["l"])

    return MemberSet(
        seg_rA=seg("rA", (3,)),
        seg_q=seg("q", (3,)),
        seg_R=seg("R", (3, 3)),
        seg_l=jnp.asarray(seg_l, dtype=dtype),
        seg_dA=seg("dA", (2,)),
        seg_dB=seg("dB", (2,)),
        seg_diA=seg("diA", (2,)),
        seg_diB=seg("diB", (2,)),
        seg_l_fill=seg("l_fill"),
        seg_rho_fill=seg("rho_fill"),
        seg_rho_shell=seg("rho_shell"),
        seg_circ=seg("circ", dt=bool),
        seg_is_cap=seg("is_cap", dt=bool),
        seg_member=seg("member", dt=np.int32, pad_val=-1),
        seg_type=seg("type", dt=np.int32, pad_val=0),
        seg_mask=seg_mask,
        node_r=node("r", (3,)),
        node_q=node("q", (3,)),
        node_p1=node("p1", (3,)),
        node_p2=node("p2", (3,)),
        node_ds=node("ds", (2,)),
        node_drs=node("drs", (2,)),
        node_dls=node("dls"),
        node_Cd_q=node("Cd_q"),
        node_Cd_p1=node("Cd_p1"),
        node_Cd_p2=node("Cd_p2"),
        node_Cd_end=node("Cd_end"),
        node_Ca_q=node("Ca_q"),
        node_Ca_p1=node("Ca_p1"),
        node_Ca_p2=node("Ca_p2"),
        node_Ca_end=node("Ca_end"),
        node_circ=node("circ", dt=bool),
        node_member=node("member", dt=np.int32, pad_val=-1),
        node_mask=node_mask,
        node_potmod=node("potmod", dt=bool),
    )


def build_rna(design: dict):
    """Extract lumped RNA properties (cf. raft/raft.py:1790-1794, :1264-1268)."""
    from raft_tpu.core.types import RNA

    t = design["turbine"]
    yaw = t.get("yaw_stiffness", t.get("yaw stiffness", 0.0))
    return RNA(
        mRNA=float(t["mRNA"]),
        IxRNA=float(t["IxRNA"]),
        IrRNA=float(t["IrRNA"]),
        xCG_RNA=float(t["xCG_RNA"]),
        hHub=float(t["hHub"]),
        Fthrust=float(t.get("Fthrust", 0.0)),
        yaw_stiffness=float(yaw),
    )
