"""Hetero-smoke: prove the shape-bucket compile collapse cross-process.

``python -m raft_tpu.build.smoke`` stages a MIXED design stream — OC3
spar + VolturnUS-S + OC4 semi, three different member topologies — through
:func:`raft_tpu.parallel.sweep.sweep_designs` in TWO fresh processes
sharing one warm-start cache dir, and asserts:

* process 1 compiles exactly ``bucket count`` executables for the mixed
  stream (the AOT registry's own compile-event log), and that count is
  STRICTLY below the design count — the O(designs) -> O(buckets)
  collapse;
* the mixed-batch (padded, bucketed) results match per-design solo
  solves to a scale-relative 1e-5 — padding must not change the physics;
* process 2 compiles ZERO ``sweep_designs`` executables (every bucket is
  an AOT disk hit) and reproduces process 1's numbers bit-for-bit.

Exit code 0/1; prints one JSON line.  ``make hetero-smoke`` wraps it
(< 60 s CPU); runs in the CI fast job.

``python -m raft_tpu.build.smoke child`` is the per-process payload
(internal).
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

DESIGNS = ("OC3spar", "VolturnUS-S", "OC4semi")


def _child(argv) -> None:
    p = argparse.ArgumentParser(prog="raft_tpu.build.smoke child")
    p.add_argument("--nw", type=int, default=24)
    args = p.parse_args(argv)

    # the smoke must never dial a hardware backend: pin CPU before jax init
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from raft_tpu import cache
    from raft_tpu.model import stage_design_base
    from raft_tpu.parallel import forward_response, response_std, sweep_designs

    cache.enable()                      # RAFT_TPU_CACHE_DIR from the parent

    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    fnames = [os.path.join(pkg, "designs", n + ".yaml") for n in DESIGNS]
    kw = dict(nw=args.nw, Hs=8.0, Tp=12.0, w_min=0.05, w_max=2.95)

    out = sweep_designs(fnames, n_iter=30, return_xi=False, **kw)
    compiles = cache.compile_count("sweep_designs")

    # per-design solo reference (unpadded, un-bucketed) for the parity leg
    errs = []
    for i, fn in enumerate(fnames):
        _, m, rna, env, wv, C = stage_design_base(fn, **kw)
        o = forward_response(m, rna, env, wv, C, n_iter=30)
        sig = np.asarray(response_std(o.Xi.abs2(), wv.w))
        # scale-relative: unexcited symmetric DOFs are zero-mean float
        # noise in both runs (see bench.hetero_buckets)
        errs.append(float(np.max(np.abs(out["std dev"][i] - sig))
                          / np.max(np.abs(sig))))

    print(json.dumps({
        "n_designs": len(fnames),
        "n_buckets": out["buckets"]["n_buckets"],
        "signatures": out["buckets"]["signatures"],
        "promotions": out["buckets"]["promotions"],
        "compiles": compiles,
        "aot": cache.report().get("aot", {}),
        "solo_max_rel": max(errs),
        "sigma": np.asarray(out["std dev"]).tolist(),
    }))


def _run_child(cache_dir: str, nw: int) -> dict:
    env = dict(os.environ)
    env["RAFT_TPU_CACHE_DIR"] = cache_dir
    env["JAX_PLATFORMS"] = "cpu"
    # deterministic whatever environment launches it (cache-smoke precedent):
    # a caller's virtual-device mesh would change topology and the AOT keys
    env.pop("XLA_FLAGS", None)
    env.pop("RAFT_TPU_BUCKETS", None)   # the claim is about the default ladder
    r = subprocess.run(
        [sys.executable, "-m", "raft_tpu.build.smoke", "child",
         "--nw", str(nw)],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
    )
    if r.returncode != 0:
        raise SystemExit(
            f"hetero-smoke child failed (rc={r.returncode}):\n"
            + (r.stderr or r.stdout)[-2000:]
        )
    return json.loads(r.stdout.strip().splitlines()[-1])


def smoke(argv) -> int:
    p = argparse.ArgumentParser(prog="raft_tpu.build.smoke")
    p.add_argument("--nw", type=int, default=24, help="frequency bins")
    p.add_argument("--dir", default=None,
                   help="cache dir (default: fresh temp dir, removed after)")
    args = p.parse_args(argv)

    d = args.dir or tempfile.mkdtemp(prefix="raft_tpu_hetero_smoke_")
    try:
        cold = _run_child(d, args.nw)
        warm = _run_child(d, args.nw)
        checks = {
            # one compile per bucket, strictly fewer than designs
            "cold_compiles_eq_buckets":
                cold["compiles"] == cold["n_buckets"],
            "fewer_compiles_than_designs":
                cold["compiles"] < cold["n_designs"],
            # padding must not change the physics
            "solo_parity_1e5": cold["solo_max_rel"] <= 1e-5,
            # a warm process recompiles NOTHING for the mixed stream
            "warm_zero_compiles": warm["compiles"] == 0,
            "warm_disk_hits": warm["aot"].get("disk_hits", 0)
                              >= cold["n_buckets"],
            "results_identical": warm["sigma"] == cold["sigma"],
        }
        ok = all(checks.values())
        print(json.dumps({
            "ok": ok,
            **checks,
            "n_designs": cold["n_designs"],
            "n_buckets": cold["n_buckets"],
            "signatures": cold["signatures"],
            "cold_compiles": cold["compiles"],
            "warm_compiles": warm["compiles"],
            "warm_aot": warm["aot"],
            "solo_max_rel": cold["solo_max_rel"],
            "cache_dir": d,
        }))
        return 0 if ok else 1
    finally:
        if args.dir is None:
            shutil.rmtree(d, ignore_errors=True)


def main() -> int:
    argv = sys.argv[1:]
    if argv and argv[0] == "child":
        _child(argv[1:])
        return 0
    return smoke(argv)


if __name__ == "__main__":
    raise SystemExit(main())
