"""Host-side preprocessing: design dicts -> device-ready pytrees."""
from raft_tpu.build.members import (  # noqa: F401
    build_member_set,
    build_rna,
    member_counts,
)
from raft_tpu.build.buckets import (  # noqa: F401
    BucketSig,
    bucketize,
    build_bucketed_member_set,
    ladder,
    ladder_salt,
    promotion_count,
)
