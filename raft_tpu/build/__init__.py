"""Host-side preprocessing: design dicts -> device-ready pytrees."""
from raft_tpu.build.members import build_member_set, build_rna  # noqa: F401
