"""Shape-bucket ladder: heterogeneous designs -> a handful of padded signatures.

Every distinct design YAML (OC3, OC4, VolturnUS, user designs) has its own
member/segment/node counts, and those counts leak into the jitted shapes —
so a naive mixed request stream compiles one executable *per design* and
can never share a device batch.  This module rounds each shape axis UP to
a small ladder of size classes (masked padding does the rest): any design
lands in one of a handful of padded signatures, compile count collapses
from O(designs) to O(buckets), and a mixed batch of different platforms
solves as one padded device dispatch per bucket
(:func:`raft_tpu.parallel.sweep.sweep_designs`).

Three bucketed axes:

* ``segments`` / ``nodes`` — the :class:`~raft_tpu.core.types.MemberSet`
  axes, padded through the existing masked-padding path of
  :func:`raft_tpu.build.members.build_member_set` (``seg_mask`` /
  ``node_mask`` gate every padded row out of statics, hydrostatics and
  Morison sums).
* ``nw`` — the frequency-grid length.  Padded bins extend the grid beyond
  ``w_max`` at the same spacing with ``zeta = 0`` and a ``freq_mask`` on
  the :class:`~raft_tpu.core.types.WaveState` that zeroes the fixed-point
  seed at those bins, so they carry exactly-zero response through every
  iteration and perturb neither the drag linearization's spectral moment
  nor the convergence check (see docs/architecture.rst "Shape buckets &
  megabatching" for the invariant argument).

The default ladder is sized so the four shipped designs land in two
buckets (OC3 spar + VolturnUS-S share the small class, the two OC4 semis
the medium one).  ``RAFT_TPU_BUCKETS`` overrides it, e.g.::

    RAFT_TPU_BUCKETS="segments=16,48,96;nodes=64,128,256;nw=32,64,128"

The ladder (env-resolved, canonicalized) salts every AOT executable key a
bucketed sweep compiles (:func:`ladder_salt`), so changing the ladder can
never be served an executable padded for the old classes.
"""
from __future__ import annotations

import logging
import os
import threading
from typing import NamedTuple

from raft_tpu.build.members import _accumulate, build_member_set, member_counts

log = logging.getLogger(__name__)

ENV_VAR = "RAFT_TPU_BUCKETS"

DEFAULT_LADDER: dict = {
    "segments": (16, 48, 96, 192, 384),
    "nodes": (64, 128, 256, 512, 1024),
    "nw": (16, 32, 64, 128, 256, 512),
    # BEM panel-mesh size classes (hull + lid panels = the influence-
    # matrix dimension of hydro/jax_bem.py): padded with degenerate
    # zero-area panels so every mesh of a class shares one compiled
    # on-device solve — same contract as the member axes above.  Every
    # built-in class is a BEM_TILE multiple, so the tiled Pallas
    # assembly route (core/pallas_bem.py) engages for all of them; a
    # custom RAFT_TPU_BUCKETS override with a non-multiple class still
    # works (that class just falls back to the XLA assembly route).
    "panels": (64, 128, 256, 512, 768, 1024, 1536, 2048),
}

#: (panel_i, panel_j) tile edge of the Pallas BEM assembly kernels — the
#: influence-matrix grid is (n / BEM_TILE)^2 tiles with the wave-integral
#: tables VMEM-resident per tile.  The built-in ``panels`` ladder above is
#: aligned to it by construction.
BEM_TILE = 64

_AXES = tuple(DEFAULT_LADDER)


class BucketSig(NamedTuple):
    """One padded shape class: every design whose exact counts round up to
    the same ``BucketSig`` shares one compiled executable.  ``nw`` is None
    when only the member axes were bucketed (no frequency grid in play)."""

    segments: int
    nodes: int
    nw: int | None = None


class BucketOverflow(ValueError):
    """A design (or frequency grid) exceeds the top of the ladder on some
    axis — extend the ladder (``RAFT_TPU_BUCKETS``) to admit it."""


def ladder(env: str | None = None) -> dict:
    """The active size-class ladder: ``DEFAULT_LADDER`` unless
    ``RAFT_TPU_BUCKETS`` (or the explicit ``env`` string) overrides it.
    Each axis is a strictly-increasing tuple of admissible padded sizes;
    axes absent from the override keep their defaults."""
    spec = os.environ.get(ENV_VAR, "") if env is None else env
    spec = spec.strip()
    out = dict(DEFAULT_LADDER)
    if not spec:
        return out
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"{ENV_VAR}: expected 'axis=n1,n2,...' entries separated by "
                f"';', got {part!r}")
        axis, _, vals = part.partition("=")
        axis = axis.strip()
        if axis not in _AXES:
            raise ValueError(
                f"{ENV_VAR}: unknown axis {axis!r}; have {sorted(_AXES)}")
        try:
            classes = tuple(int(v) for v in vals.split(",") if v.strip())
        except ValueError:
            raise ValueError(
                f"{ENV_VAR}: non-integer class in {part!r}") from None
        if not classes or any(c <= 0 for c in classes):
            raise ValueError(f"{ENV_VAR}: {axis} needs positive classes")
        if list(classes) != sorted(set(classes)):
            raise ValueError(
                f"{ENV_VAR}: {axis} classes must be strictly increasing")
        out[axis] = classes
    return out


def ladder_salt(ld: dict | None = None) -> tuple:
    """Canonical AOT-key component naming the active ladder version —
    folded into every bucketed executable's key so a ladder change (env
    override or a future default bump) invalidates instead of serving an
    executable padded for the old classes."""
    ld = ld or ladder()
    return ("buckets",
            ";".join(f"{a}={','.join(map(str, ld[a]))}" for a in _AXES))


def round_up(value: int, axis: str, ld: dict | None = None) -> int:
    """Smallest ladder class >= ``value`` on ``axis``; raises
    :class:`BucketOverflow` past the ladder top."""
    classes = (ld or ladder())[axis]
    for c in classes:
        if value <= c:
            return c
    raise BucketOverflow(
        f"{axis}={value} exceeds the ladder top {classes[-1]}; extend "
        f"{ENV_VAR} (e.g. {axis}=...,{classes[-1]},{2 * classes[-1]})")


def bucketize(design: dict, nw: int | None = None, dls_max: float = 10.0,
              include_end_b: bool = False, ld: dict | None = None) -> BucketSig:
    """Round a design's exact (segment, node) counts — and, when given,
    the frequency-grid length — up to their ladder classes."""
    ld = ld or ladder()
    S, N = member_counts(design, dls_max=dls_max, include_end_b=include_end_b)
    return BucketSig(
        segments=round_up(S, "segments", ld),
        nodes=round_up(N, "nodes", ld),
        nw=None if nw is None else round_up(int(nw), "nw", ld),
    )


# ---------------------------------------------------------------- promotion

_lock = threading.Lock()
_promotions = 0


def promotion_count() -> int:
    """Process-wide count of class promotions the self-healing build has
    performed (a design exceeded its requested class and was bumped to the
    next one) — surfaced in the sweep's ``buckets`` stats block so silent
    ladder misfits are visible."""
    return _promotions


def _record_promotion(n: int = 1) -> None:
    global _promotions
    with _lock:
        _promotions += n
    from raft_tpu import obs as _obs

    _obs.metrics.counter("buckets.promotions").inc(n)


def reset_promotions() -> None:
    """Zero the promotion counter (tests)."""
    global _promotions
    with _lock:
        _promotions = 0


def build_bucketed_member_set(design: dict, sig: BucketSig | None = None,
                              nw: int | None = None, dls_max: float = 10.0,
                              include_end_b: bool = False, dtype=None):
    """Build a design's :class:`MemberSet` padded to its bucket class.

    ``sig``: the target class (member axes only are used; ``sig.nw`` rides
    along untouched).  Default: bucketize the design, rounding ``nw`` (when
    given) into the signature too.  The member list is parsed ONCE: the
    same accumulator measures the exact counts and feeds the padded array
    build, so bucketing a design costs no second parse.  If the design
    exceeds the requested class on either member axis — a caller reusing a
    stale ``sig``, or a ladder override that shrank between staging and
    build — the build SELF-HEALS: the failing axes are promoted to the
    class admitting the true count (logged + counted,
    :func:`promotion_count`) instead of raising.  Only past the ladder top
    does it raise (:class:`BucketOverflow`).

    Returns ``(members, sig)`` with ``sig`` reflecting any promotion.
    """
    ld = ladder()
    acc = _accumulate(design, dls_max=dls_max, include_end_b=include_end_b)
    S, N = len(acc.seg["l"]), len(acc.node["dls"])
    if sig is None:
        sig = BucketSig(
            segments=round_up(S, "segments", ld),
            nodes=round_up(N, "nodes", ld),
            nw=None if nw is None else round_up(int(nw), "nw", ld),
        )
    if S > sig.segments or N > sig.nodes:
        # promotion path: bump each insufficient axis to the class that
        # admits the true count (BucketOverflow past the ladder top)
        promoted = BucketSig(
            segments=round_up(S, "segments", ld) if S > sig.segments
            else sig.segments,
            nodes=round_up(N, "nodes", ld) if N > sig.nodes else sig.nodes,
            nw=sig.nw,
        )
        _record_promotion(int(promoted.segments > sig.segments)
                          + int(promoted.nodes > sig.nodes))
        log.info(
            "bucket promotion: design needs (%d segments, %d nodes) > class "
            "(%d, %d); promoted to (%d, %d) [total promotions: %d]",
            S, N, sig.segments, sig.nodes, promoted.segments, promoted.nodes,
            promotion_count())
        sig = promoted
    m = build_member_set(design, dls_max=dls_max,
                         pad_segments=sig.segments, pad_nodes=sig.nodes,
                         include_end_b=include_end_b, dtype=dtype, _acc=acc)
    return m, sig
