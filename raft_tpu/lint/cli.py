"""``python -m raft_tpu.lint``: the graftlint command line.

Modes (composable):

* default — static AST pass over the package (GL101-GL107 purity rules
  + GL201-GL204 contract rules), compared against the committed
  baseline; exit 1 on any NEW violation;
* ``--audit`` — additionally run the trace audit over the registered
  entry points (retrace / f64 / host-callback budgets) AND the
  compiled-artifact budget audit (cost/memory metrics vs the committed
  ``lint/budgets.json``); exit 1 on any budget breach;
* ``--write-baseline`` — regenerate the baseline from the current tree
  (triage mode) and exit 0;
* ``--write-budgets`` — AOT-lower the registered entries and refresh
  ``lint/budgets.json`` for the current backend platform, then exit 0;
* ``--json`` — emit one machine-readable JSON line (the form
  ``make evidence`` embeds in EVIDENCE.json) after the human output.

Paths default to the package + repo entry scripts + examples.  Tests
and fixture corpora are deliberately NOT linted: the suite runs x64 on
purpose, and ``tests/test_lint.py``'s fixtures must contain violations.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

DEFAULT_TARGETS = ("raft_tpu", "__graft_entry__.py", "bench.py", "examples")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _arm_audit_env() -> None:
    """Pin the audit's backend BEFORE anything initializes jax: CPU (the
    audit is a structural gate, not a perf run — no hardware required)
    with the virtual device count the sharded-lowering gate shards over.
    XLA parses its flags exactly once per process, so this must land
    ahead of the first ``jax.devices()`` anywhere; in-process callers
    that already initialized jax are handled by
    :func:`raft_tpu.parallel.spmd.force_cpu_devices` instead."""
    from raft_tpu.lint.audit import SHARDED_MESH_DEVICES
    from raft_tpu.parallel.spmd import with_host_device_flag

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["XLA_FLAGS"] = with_host_device_flag(
        os.environ.get("XLA_FLAGS", ""), SHARDED_MESH_DEVICES)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m raft_tpu.lint",
        description="graftlint: JAX-aware static analysis + trace audit")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: the package + "
                         "entry scripts)")
    ap.add_argument("--root", default=None,
                    help="repo root for relative paths (default: "
                         "autodetected)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (default: raft_tpu/lint/"
                         "baseline.json)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from the current tree")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every violation, ignoring the baseline")
    ap.add_argument("--audit", action="store_true",
                    help="also run the trace audit (registered entry "
                         "points)")
    ap.add_argument("--audit-only", action="store_true",
                    help="run only the trace audit")
    ap.add_argument("--audit-entries", default=None,
                    help="comma-separated registry entry names "
                         "(default: all)")
    ap.add_argument("--no-retrace-check", action="store_true",
                    help="audit jaxpr budgets only (skip the compile the "
                         "retrace check needs)")
    ap.add_argument("--no-budget-check", action="store_true",
                    help="skip the compiled-artifact budget audit")
    ap.add_argument("--budgets", default=None,
                    help="budgets JSON (default: raft_tpu/lint/"
                         "budgets.json)")
    ap.add_argument("--write-budgets", action="store_true",
                    help="AOT-lower the registered entries and refresh "
                         "the committed budgets for this platform")
    ap.add_argument("--json", action="store_true",
                    help="emit a final machine-readable JSON line")
    args = ap.parse_args(argv)

    root = args.root or repo_root()
    rc = 0
    summary: dict = {"tool": "graftlint"}

    if args.write_budgets:
        # budget refresh is its own mode: lower + measure, save, done
        _arm_audit_env()
        from raft_tpu.lint.audit import write_budgets

        names = (args.audit_entries.split(",")
                 if args.audit_entries else None)
        path, reports = write_budgets(names, args.budgets)
        for r in reports:
            print(r.summary())
        print(f"[graftlint] budgets written: {path} "
              f"({len(reports)} entries)")
        if args.json:
            print(json.dumps({"tool": "graftlint", "ok": True,
                              "budgets_written": len(reports)}))
        return 0

    if not args.audit_only:
        from raft_tpu.lint import baseline as bl
        from raft_tpu.lint.rules import RULES, lint_paths

        targets = list(args.paths) if args.paths else list(DEFAULT_TARGETS)
        try:
            violations = lint_paths(targets, root)
        except (FileNotFoundError, ValueError) as e:
            # a typo'd target must fail LOUD, not lint nothing and pass
            print(f"[graftlint] error: {e}")
            return 2
        if args.write_baseline:
            path = bl.save(violations, args.baseline)
            print(f"[graftlint] baseline written: {path} "
                  f"({len(violations)} violations triaged)")
            summary["static"] = {"violations": len(violations),
                                 "baseline_written": True}
        else:
            if args.no_baseline:
                fresh, absorbed = violations, 0
            else:
                fresh, absorbed = bl.filter_new(violations, args.baseline)
            for v in fresh:
                print(v.format())
            print(f"[graftlint] static: {len(fresh)} new violation(s), "
                  f"{absorbed} baselined, "
                  f"{len(violations)} total")
            summary["static"] = {"new": len(fresh), "baselined": absorbed,
                                 "total": len(violations)}
            # concurrency-contract summary (GL3xx): the daemon-readiness
            # gate, one key deep here and in EVIDENCE.json (evidence.py
            # lifts this block) — "new" must stay zero, "triaged" counts
            # the single-threaded-by-contract findings carried in the
            # baseline with their reasons
            gl3_rules = sorted(r for r in RULES if r.startswith("GL3"))
            gl3 = {}
            for r in gl3_rules:
                n_new = sum(1 for v in fresh if v.rule == r)
                n_total = sum(1 for v in violations if v.rule == r)
                gl3[r] = {"new": n_new, "triaged": n_total - n_new}
            summary["gl3xx"] = {
                "rules": gl3,
                "ok": all(c["new"] == 0 for c in gl3.values()),
            }
            print("[graftlint] gl3xx: " + "  ".join(
                f"{r}={c['new']} new/{c['triaged']} triaged"
                for r, c in gl3.items()))
            # SPMD-contract summary (GL4xx): the pod-readiness gate, same
            # shape as gl3xx — one key deep here and in EVIDENCE.json
            gl4_rules = sorted(r for r in RULES if r.startswith("GL4"))
            gl4 = {}
            for r in gl4_rules:
                n_new = sum(1 for v in fresh if v.rule == r)
                n_total = sum(1 for v in violations if v.rule == r)
                gl4[r] = {"new": n_new, "triaged": n_total - n_new}
            summary["gl4xx"] = {
                "rules": gl4,
                "ok": all(c["new"] == 0 for c in gl4.values()),
            }
            print("[graftlint] gl4xx: " + "  ".join(
                f"{r}={c['new']} new/{c['triaged']} triaged"
                for r, c in gl4.items()))
            if fresh:
                rc = 1

    if (args.audit or args.audit_only) and not args.write_baseline:
        _arm_audit_env()
        from raft_tpu.lint.audit import run_audit

        names = (args.audit_entries.split(",")
                 if args.audit_entries else None)
        reports = run_audit(names,
                            retrace_check=not args.no_retrace_check,
                            budget_check=not args.no_budget_check,
                            budgets_path=args.budgets)
        for r in reports:
            print(r.summary())
        bad = [r for r in reports if not r.ok]
        summary["audit"] = {"entries": [r.to_dict() for r in reports],
                            "failed": len(bad)}
        if not args.no_budget_check:
            # one-key-deep pass/fail + metrics for EVIDENCE.json
            summary["budgets"] = {
                "ok": all(r.budget_ok for r in reports),
                "entries": {r.name: {"ok": r.budget_ok,
                                     "metrics": r.metrics,
                                     "notes": r.budget_notes}
                            for r in reports},
            }
        if bad:
            rc = 1

    summary["ok"] = rc == 0
    if args.json:
        print(json.dumps(summary))
    return rc


if __name__ == "__main__":
    sys.exit(main())
