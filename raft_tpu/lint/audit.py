"""Trace audit: per-jaxpr budgets for the registered entry points.

For every :mod:`raft_tpu.lint.registry` entry the audit

1. traces the entry under ``jax.make_jaxpr`` **in x32 mode** (the TPU
   production mode; ``jax.experimental.disable_x64`` scopes it even when
   the enclosing test session runs x64) and walks the closed jaxpr —
   including every nested sub-jaxpr (pjit/scan/while/cond/shard_map
   bodies) — asserting

   * a **dtype budget**: zero ``float64``/``complex128`` avals.  A leak
     means some constant or op re-promoted the x32 pipeline — exactly the
     hazard class GL105 guards statically;
   * a **host-callback budget**: zero ``pure_callback``/``io_callback``/
     ``debug_callback`` equations.  A callback inside the hot loop syncs
     host<->device every iteration and makes the executable
     unserializable for the AOT registry (cache/aot.py);

2. runs a **retrace check**: ``jax.jit`` the entry, call it with two
   same-shape/same-dtype argument sets, and count actual traces via a
   counting wrapper.  The budget is ONE trace — a second trace for
   identical abstract signatures means something non-hashable or
   value-dependent leaked into the trace (the recompile hazard that
   erases the warm-start wins: PR 1 measured >94% of cold wall-clock in
   XLA compilation);

3. runs the **compiled-artifact budget audit**: AOT-lowers the entry
   (``jax.jit(fn).lower(*args).compile()``, still under x32) and records
   the compiler's own accounting — ``cost_analysis()`` flops and bytes
   accessed, ``memory_analysis()`` argument/output/temp byte sizes (the
   HBM peak proxy), plus the jaxpr equation and sub-jaxpr counts —
   against the committed ``lint/budgets.json``.  A trace audit alone
   cannot see a perf regression that only exists in the compiled
   artifact (an extra fusion barrier, a doubled temp buffer, a
   broadcast materialized in HBM); the budget gate can, ahead of any
   hardware run.  Budgets are per backend platform (CI pins CPU);
   ``--write-budgets`` refreshes them after an intentional change, and
   regressions beyond the stated tolerance fail ``make lint``.

``run_audit()`` returns one :class:`AuditReport` per entry;
``main``-level consumers (CLI ``--audit``, ``make lint``, the fast test
tier) fail on any ``ok=False`` report.
"""
from __future__ import annotations

import dataclasses
import json
import os
import time

_HOST_CALLBACK_PRIMS = {"pure_callback", "io_callback", "debug_callback",
                        "callback"}
_WIDE_DTYPES = ("float64", "complex128")

DEFAULT_BUDGETS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "budgets.json")
#: a metric may grow this fraction over its committed budget before the
#: gate fails (absorbs jax/XLA version wiggle without hiding a real
#: regression; per-entry "_tolerance" overrides)
DEFAULT_TOLERANCE = 0.25


@dataclasses.dataclass
class AuditReport:
    name: str
    public_api: str
    n_eqns: int                 # equations in the flattened jaxpr walk
    f64_leaves: int             # wide-dtype avals found (budget: 0)
    f64_examples: list          # first few offending aval descriptions
    host_callbacks: int         # callback eqns found (budget: 0)
    retraces: int               # extra traces on a same-shape call (0)
    trace_s: float
    ok: bool
    # compiled-artifact budget audit (None when not run)
    metrics: dict | None = None
    budget_ok: bool | None = None
    budget_notes: list = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["trace_s"] = round(d["trace_s"], 3)
        return d

    def summary(self) -> str:
        state = "ok" if self.ok else "FAIL"
        line = (f"[audit] {self.name}: {state} — {self.n_eqns} eqns, "
                f"f64 leaves {self.f64_leaves}, host callbacks "
                f"{self.host_callbacks}, retraces {self.retraces} "
                f"({self.trace_s:.2f}s)")
        if self.budget_ok is not None:
            m = self.metrics or {}
            line += (f"\n[audit]   budget: "
                     f"{'ok' if self.budget_ok else 'FAIL'} — "
                     f"flops {m.get('flops', '?')}, bytes "
                     f"{m.get('bytes_accessed', '?')}, temp "
                     f"{m.get('temp_bytes', '?')}")
            for note in self.budget_notes:
                line += f"\n[audit]     {note}"
        return line


def _iter_jaxprs(jaxpr):
    """Yield ``jaxpr`` and every sub-jaxpr reachable through eqn params
    (pjit/scan/while/cond/shard_map/custom_vjp bodies, remat, ...)."""
    import jax.core as jcore

    seen: set[int] = set()
    stack = [jaxpr]
    while stack:
        j = stack.pop()
        if id(j) in seen:
            continue
        seen.add(id(j))
        yield j
        for eqn in j.eqns:
            for val in eqn.params.values():
                stack.extend(_extract_jaxprs(val, jcore))


def _extract_jaxprs(val, jcore):
    out = []
    if isinstance(val, jcore.ClosedJaxpr):
        out.append(val.jaxpr)
    elif isinstance(val, jcore.Jaxpr):
        out.append(val)
    elif isinstance(val, (list, tuple)):
        for v in val:
            out.extend(_extract_jaxprs(v, jcore))
    return out


def _aval_is_wide(aval) -> bool:
    dt = getattr(aval, "dtype", None)
    return dt is not None and str(dt) in _WIDE_DTYPES


def audit_jaxpr(closed_jaxpr):
    """(n_eqns, f64_leaves, f64_examples, host_callbacks) over the full
    nested-jaxpr walk."""
    n_eqns = 0
    wide = 0
    examples: list[str] = []
    callbacks = 0
    for j in _iter_jaxprs(closed_jaxpr.jaxpr):
        for var in list(j.invars) + list(j.constvars) + list(j.outvars):
            aval = getattr(var, "aval", None)
            if aval is not None and _aval_is_wide(aval):
                wide += 1
                if len(examples) < 4:
                    examples.append(f"var {aval}")
        for eqn in j.eqns:
            n_eqns += 1
            if eqn.primitive.name in _HOST_CALLBACK_PRIMS:
                callbacks += 1
            for var in eqn.outvars:
                aval = getattr(var, "aval", None)
                if aval is not None and _aval_is_wide(aval):
                    wide += 1
                    if len(examples) < 4:
                        examples.append(f"{eqn.primitive.name} -> {aval}")
    # consts of the top-level closed jaxpr (closure-captured arrays)
    for c in closed_jaxpr.consts:
        dt = getattr(c, "dtype", None)
        if dt is not None and str(dt) in _WIDE_DTYPES:
            wide += 1
            if len(examples) < 4:
                examples.append(f"const {dt}{getattr(c, 'shape', ())}")
    return n_eqns, wide, examples, callbacks


def _count_retraces(fn, args, args2) -> int:
    """Extra traces beyond the first when calling a fresh ``jax.jit`` of
    ``fn`` with two same-structure argument sets."""
    import jax

    traces = [0]

    def counted(*a):
        traces[0] += 1
        return fn(*a)

    jf = jax.jit(counted)
    r1 = jf(*args)
    r2 = jf(*args2)
    jax.block_until_ready((r1, r2))
    return traces[0] - 1


def compiled_metrics(compiled, n_eqns: int, n_jaxprs: int) -> dict:
    """Compiler-side accounting of one AOT-compiled executable, keyed by
    stable metric names.  Metrics a backend does not report are simply
    absent — the budget check treats a committed-but-unavailable metric
    as a failure (a gate that silently stops measuring is no gate)."""
    m: dict = {"n_eqns": int(n_eqns), "n_jaxprs": int(n_jaxprs)}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            for src, dst in (("flops", "flops"),
                             ("bytes accessed", "bytes_accessed"),
                             ("transcendentals", "transcendentals")):
                v = ca.get(src)
                if v is not None and float(v) == float(v):
                    m[dst] = float(v)
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            for attr, dst in (
                    ("temp_size_in_bytes", "temp_bytes"),
                    ("argument_size_in_bytes", "argument_bytes"),
                    ("output_size_in_bytes", "output_bytes"),
                    ("generated_code_size_in_bytes", "code_bytes")):
                v = getattr(ma, attr, None)
                if isinstance(v, (int, float)):
                    m[dst] = int(v)
            if all(k in m for k in ("temp_bytes", "argument_bytes",
                                    "output_bytes")):
                # HBM peak proxy: everything the executable holds live
                m["peak_bytes"] = (m["temp_bytes"] + m["argument_bytes"]
                                   + m["output_bytes"])
    except Exception:
        pass
    return m


def load_budgets(path: str | None = None) -> dict:
    path = path or DEFAULT_BUDGETS
    if not os.path.exists(path):
        return {"tolerance": DEFAULT_TOLERANCE, "platforms": {}}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    data.setdefault("tolerance", DEFAULT_TOLERANCE)
    data.setdefault("platforms", {})
    return data


def save_budgets(reports, path: str | None = None,
                 platform: str | None = None) -> str:
    """Merge ``reports``' metrics into the budgets file for ``platform``
    (default: the current jax backend).  Other platforms' committed
    budgets are preserved."""
    import jax

    path = path or DEFAULT_BUDGETS
    platform = platform or jax.default_backend()
    data = load_budgets(path)
    plat = data["platforms"].setdefault(platform, {})
    for r in reports:
        if r.metrics:
            fresh = {k: r.metrics[k] for k in sorted(r.metrics)}
            # a refresh replaces the MEASURED values only: "_"-prefixed
            # keys ("_tolerance" overrides, annotations) are maintainer
            # state and survive the rewrite
            fresh.update({k: v for k, v in plat.get(r.name, {}).items()
                          if k.startswith("_")})
            plat[r.name] = fresh
    data["_comment"] = (
        "graftlint compiled-artifact budgets: per-platform, per-entry "
        "cost_analysis()/memory_analysis() metrics of the registered "
        "audit entries; the gate fails when a metric regresses beyond "
        "'tolerance'. Refresh with `python -m raft_tpu.lint "
        "--write-budgets` and review the diff like any code change.")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def check_budget(name: str, metrics: dict | None, budgets: dict,
                 platform: str) -> tuple:
    """(ok, notes) of one entry's metrics against the committed budget.

    Fails on: no committed budget for (platform, entry), a committed
    metric the current run cannot measure, or a committed metric grown
    beyond tolerance.  Shrinking beyond tolerance is reported as a
    non-failing note — refresh the budgets to bank the improvement."""
    plat = budgets.get("platforms", {}).get(platform)
    if not plat or name not in plat:
        return False, [f"no committed budget for entry {name!r} on "
                       f"platform {platform!r} — run `python -m "
                       f"raft_tpu.lint --write-budgets`"]
    entry_budget = plat[name]
    tol = float(entry_budget.get("_tolerance",
                                 budgets.get("tolerance",
                                             DEFAULT_TOLERANCE)))
    ok = True
    notes: list = []
    for metric, bv in sorted(entry_budget.items()):
        if metric.startswith("_"):
            continue
        cur = (metrics or {}).get(metric)
        if cur is None:
            ok = False
            notes.append(f"{metric}: committed {bv} but unavailable in "
                         f"this run — the gate cannot verify it")
        elif cur > bv * (1.0 + tol) and cur > bv + 1:
            ok = False
            notes.append(f"{metric}: {cur} exceeds budget {bv} "
                         f"(+{100.0 * (cur / bv - 1.0) if bv else 100.0:.1f}%"
                         f" > tol {100.0 * tol:.0f}%) — a compiled-artifact "
                         f"regression; if intentional, refresh with "
                         f"--write-budgets")
        elif bv and cur < bv * (1.0 - tol):
            notes.append(f"note: {metric}: {cur} is far below budget {bv} "
                         f"— refresh budgets to bank the improvement")
    return ok, notes


#: devices in the forced virtual CPU mesh the sharded-lowering gate runs
#: on — matches the test session's virtual device count and the SPMD
#: smoke's global mesh (4 local devices x 2 processes)
SHARDED_MESH_DEVICES = 8
#: the sharded-lowering bound: per-device peak under the batch-sharded
#: lowering must not exceed 1/N of the replicated lowering's per-device
#: peak by more than this fraction (padding, replicated small operands,
#: and partitioner bookkeeping live inside the slack)
SHARDED_TOLERANCE = 0.25
#: lanes per device the gate tiles each entry's batch up to before
#: lowering: at 1 lane/device the per-device FIXED footprint (closure
#: constants, scan bookkeeping) swamps the batch term the bound is
#: about; at 8 the batch-proportional memory dominates and the 1/N
#: scaling claim is actually measurable
SHARDED_MIN_LANES_PER_DEVICE = 16


def _sharded_mesh(axis: str = "batch"):
    """The forced virtual CPU mesh the sharded gate lowers on —
    :func:`raft_tpu.parallel.spmd.forced_cpu_mesh`, the same construction
    the SPMD smoke and the driver dry run use, so device count and axis
    name cannot drift between them."""
    from raft_tpu.parallel import spmd

    _, mesh = spmd.forced_cpu_mesh(SHARDED_MESH_DEVICES, axis=axis)
    return mesh


def sharded_metrics(entry, mesh) -> dict:
    """Dual-lower one ``sharded=True`` entry over ``mesh`` (x32) and
    return the sharded-gate metric block.

    The entry's batch-leading leaves (leading dim == the first array
    leaf's) are tiled to a mesh-divisible lane count, then the SAME
    argument set is AOT-lowered twice: once fully replicated, once with
    the batch axis sharded over the mesh.  ``memory_analysis`` sizes are
    PER-DEVICE, so the pair pins the claim that matters on a pod: a
    batch-sharded dispatch holds ~1/N of the replicated footprint per
    device — an executable that silently materializes the full batch on
    every device (a lost sharding annotation, a gather the partitioner
    inserted) breaks ``sharded_peak_bytes`` against its committed budget
    AND the ratio bound in :func:`check_sharded`."""
    import math

    import jax
    import jax.numpy as jnp
    from jax.experimental import disable_x64
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis = mesh.axis_names[0]
    n = int(mesh.devices.size)
    with disable_x64():
        fn, args, _ = entry.build()
        leaves, treedef = jax.tree_util.tree_flatten(args)
        batch = next(l.shape[0] for l in leaves
                     if getattr(l, "ndim", 0) >= 1)
        # tile whole batches up to >= SHARDED_MIN_LANES_PER_DEVICE * n
        # lanes while keeping the count a multiple of both the batch and
        # the mesh size
        base = math.lcm(batch, n)
        k = max(1, -(-(SHARDED_MIN_LANES_PER_DEVICE * n) // base))
        reps = base * k // batch
        tiled, specs = [], []
        for leaf in leaves:
            if getattr(leaf, "ndim", 0) >= 1 and leaf.shape[0] == batch:
                tiled.append(jnp.concatenate([leaf] * reps, axis=0)
                             if reps > 1 else leaf)
                specs.append(P(axis))
            else:
                tiled.append(leaf)
                specs.append(P())
        targs = jax.tree_util.tree_unflatten(treedef, tiled)

        def lower(spec_list):
            sh = jax.tree_util.tree_unflatten(
                treedef, [NamedSharding(mesh, s) for s in spec_list])
            return jax.jit(fn, in_shardings=sh).lower(*targs).compile()

        rep = compiled_metrics(lower([P()] * len(specs)), 0, 0)
        shd = compiled_metrics(lower(specs), 0, 0)
    out = {"sharded_mesh_devices": n,
           "sharded_batch_lanes": int(batch * reps)}
    if "peak_bytes" in rep:
        out["replicated_peak_bytes"] = rep["peak_bytes"]
    if "peak_bytes" in shd:
        out["sharded_peak_bytes"] = shd["peak_bytes"]
    return out


def check_sharded(name: str, metrics: dict | None) -> tuple:
    """(ok, notes) of one sharded entry's ratio bound: per-device peak
    under the batch-sharded lowering <= replicated / mesh_size x
    (1 + SHARDED_TOLERANCE).  Missing metrics fail — a gate that stops
    measuring is no gate."""
    m = metrics or {}
    rep, shd = m.get("replicated_peak_bytes"), m.get("sharded_peak_bytes")
    n = m.get("sharded_mesh_devices")
    if not rep or shd is None or not n:
        return False, [f"sharded gate: entry {name!r} is sharded=True but "
                       "the dual lowering produced no peak_bytes pair — "
                       "the per-device bound cannot be verified"]
    bound = rep / n * (1.0 + SHARDED_TOLERANCE)
    if shd > bound:
        return False, [
            f"sharded_peak_bytes {shd} exceeds replicated/{n} x "
            f"{1.0 + SHARDED_TOLERANCE:.2f} = {bound:.0f} (replicated "
            f"{rep}) — the batch-sharded lowering is materializing "
            f"(nearly) the full batch per device"]
    return True, []


def audit_entry(entry, retrace_check: bool = True,
                collect_metrics: bool = False) -> AuditReport:
    """Run all budgets for one registry entry **in x32 mode**."""
    import jax
    from jax.experimental import disable_x64

    t0 = time.perf_counter()
    metrics = None
    with disable_x64():
        fn, args, args2 = entry.build()
        jaxpr = jax.make_jaxpr(fn)(*args)
        n_eqns, wide, examples, callbacks = audit_jaxpr(jaxpr)
        if collect_metrics:
            n_jaxprs = sum(1 for _ in _iter_jaxprs(jaxpr.jaxpr))
            compiled = jax.jit(fn).lower(*args).compile()
            metrics = compiled_metrics(compiled, n_eqns, n_jaxprs)
        retraces = (_count_retraces(fn, args, args2)
                    if retrace_check else 0)
    return AuditReport(
        name=entry.name,
        public_api=entry.public_api,
        n_eqns=n_eqns,
        f64_leaves=wide,
        f64_examples=examples,
        host_callbacks=callbacks,
        retraces=retraces,
        trace_s=time.perf_counter() - t0,
        ok=(wide == 0 and callbacks == 0 and retraces == 0),
        metrics=metrics,
    )


def run_audit(names=None, retrace_check: bool = True,
              budget_check: bool = True,
              budgets_path: str | None = None) -> list[AuditReport]:
    """Audit the named entries (default: every registered entry).  With
    ``budget_check`` each entry is additionally AOT-lowered and its
    compiled-artifact metrics held to the committed budgets; a budget
    breach (or a missing budget) marks the report ``ok=False``."""
    import jax

    from raft_tpu.lint.registry import get_entries

    entries = get_entries(names)
    # force the virtual mesh BEFORE the first entry builds (backend init
    # order: the mesh setup must land before jax stages any arrays)
    mesh = (_sharded_mesh() if budget_check
            and any(e.sharded for e in entries) else None)
    reports = [audit_entry(e, retrace_check=retrace_check,
                           collect_metrics=budget_check)
               for e in entries]
    if budget_check:
        budgets = load_budgets(budgets_path)
        platform = jax.default_backend()
        for e, r in zip(entries, reports):
            sh_ok, sh_notes = True, []
            if e.sharded:
                r.metrics = {**(r.metrics or {}),
                             **sharded_metrics(e, mesh)}
                sh_ok, sh_notes = check_sharded(r.name, r.metrics)
            r.budget_ok, notes = check_budget(
                r.name, r.metrics, budgets, platform)
            r.budget_ok = r.budget_ok and sh_ok
            r.budget_notes.extend(sh_notes + notes)
            r.ok = r.ok and r.budget_ok
    return reports


def write_budgets(names=None, path: str | None = None) -> tuple:
    """Collect metrics for the named entries (default: all), including
    the sharded-lowering pair for ``sharded=True`` entries, and merge
    them into the budgets file.  Returns (path, reports)."""
    from raft_tpu.lint.registry import get_entries

    entries = get_entries(names)
    mesh = (_sharded_mesh() if any(e.sharded for e in entries) else None)
    reports = [audit_entry(e, retrace_check=False, collect_metrics=True)
               for e in entries]
    for e, r in zip(entries, reports):
        if e.sharded:
            r.metrics = {**(r.metrics or {}), **sharded_metrics(e, mesh)}
    return save_budgets(reports, path), reports
