"""Trace audit: per-jaxpr budgets for the registered entry points.

For every :mod:`raft_tpu.lint.registry` entry the audit

1. traces the entry under ``jax.make_jaxpr`` **in x32 mode** (the TPU
   production mode; ``jax.experimental.disable_x64`` scopes it even when
   the enclosing test session runs x64) and walks the closed jaxpr —
   including every nested sub-jaxpr (pjit/scan/while/cond/shard_map
   bodies) — asserting

   * a **dtype budget**: zero ``float64``/``complex128`` avals.  A leak
     means some constant or op re-promoted the x32 pipeline — exactly the
     hazard class GL105 guards statically;
   * a **host-callback budget**: zero ``pure_callback``/``io_callback``/
     ``debug_callback`` equations.  A callback inside the hot loop syncs
     host<->device every iteration and makes the executable
     unserializable for the AOT registry (cache/aot.py);

2. runs a **retrace check**: ``jax.jit`` the entry, call it with two
   same-shape/same-dtype argument sets, and count actual traces via a
   counting wrapper.  The budget is ONE trace — a second trace for
   identical abstract signatures means something non-hashable or
   value-dependent leaked into the trace (the recompile hazard that
   erases the warm-start wins: PR 1 measured >94% of cold wall-clock in
   XLA compilation).

``run_audit()`` returns one :class:`AuditReport` per entry;
``main``-level consumers (CLI ``--audit``, ``make lint``, the fast test
tier) fail on any ``ok=False`` report.
"""
from __future__ import annotations

import dataclasses
import time

_HOST_CALLBACK_PRIMS = {"pure_callback", "io_callback", "debug_callback",
                        "callback"}
_WIDE_DTYPES = ("float64", "complex128")


@dataclasses.dataclass
class AuditReport:
    name: str
    public_api: str
    n_eqns: int                 # equations in the flattened jaxpr walk
    f64_leaves: int             # wide-dtype avals found (budget: 0)
    f64_examples: list          # first few offending aval descriptions
    host_callbacks: int         # callback eqns found (budget: 0)
    retraces: int               # extra traces on a same-shape call (0)
    trace_s: float
    ok: bool

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["trace_s"] = round(d["trace_s"], 3)
        return d

    def summary(self) -> str:
        state = "ok" if self.ok else "FAIL"
        return (f"[audit] {self.name}: {state} — {self.n_eqns} eqns, "
                f"f64 leaves {self.f64_leaves}, host callbacks "
                f"{self.host_callbacks}, retraces {self.retraces} "
                f"({self.trace_s:.2f}s)")


def _iter_jaxprs(jaxpr):
    """Yield ``jaxpr`` and every sub-jaxpr reachable through eqn params
    (pjit/scan/while/cond/shard_map/custom_vjp bodies, remat, ...)."""
    import jax.core as jcore

    seen: set[int] = set()
    stack = [jaxpr]
    while stack:
        j = stack.pop()
        if id(j) in seen:
            continue
        seen.add(id(j))
        yield j
        for eqn in j.eqns:
            for val in eqn.params.values():
                stack.extend(_extract_jaxprs(val, jcore))


def _extract_jaxprs(val, jcore):
    out = []
    if isinstance(val, jcore.ClosedJaxpr):
        out.append(val.jaxpr)
    elif isinstance(val, jcore.Jaxpr):
        out.append(val)
    elif isinstance(val, (list, tuple)):
        for v in val:
            out.extend(_extract_jaxprs(v, jcore))
    return out


def _aval_is_wide(aval) -> bool:
    dt = getattr(aval, "dtype", None)
    return dt is not None and str(dt) in _WIDE_DTYPES


def audit_jaxpr(closed_jaxpr):
    """(n_eqns, f64_leaves, f64_examples, host_callbacks) over the full
    nested-jaxpr walk."""
    n_eqns = 0
    wide = 0
    examples: list[str] = []
    callbacks = 0
    for j in _iter_jaxprs(closed_jaxpr.jaxpr):
        for var in list(j.invars) + list(j.constvars) + list(j.outvars):
            aval = getattr(var, "aval", None)
            if aval is not None and _aval_is_wide(aval):
                wide += 1
                if len(examples) < 4:
                    examples.append(f"var {aval}")
        for eqn in j.eqns:
            n_eqns += 1
            if eqn.primitive.name in _HOST_CALLBACK_PRIMS:
                callbacks += 1
            for var in eqn.outvars:
                aval = getattr(var, "aval", None)
                if aval is not None and _aval_is_wide(aval):
                    wide += 1
                    if len(examples) < 4:
                        examples.append(f"{eqn.primitive.name} -> {aval}")
    # consts of the top-level closed jaxpr (closure-captured arrays)
    for c in closed_jaxpr.consts:
        dt = getattr(c, "dtype", None)
        if dt is not None and str(dt) in _WIDE_DTYPES:
            wide += 1
            if len(examples) < 4:
                examples.append(f"const {dt}{getattr(c, 'shape', ())}")
    return n_eqns, wide, examples, callbacks


def _count_retraces(fn, args, args2) -> int:
    """Extra traces beyond the first when calling a fresh ``jax.jit`` of
    ``fn`` with two same-structure argument sets."""
    import jax

    traces = [0]

    def counted(*a):
        traces[0] += 1
        return fn(*a)

    jf = jax.jit(counted)
    r1 = jf(*args)
    r2 = jf(*args2)
    jax.block_until_ready((r1, r2))
    return traces[0] - 1


def audit_entry(entry, retrace_check: bool = True) -> AuditReport:
    """Run all budgets for one registry entry **in x32 mode**."""
    import jax
    from jax.experimental import disable_x64

    t0 = time.perf_counter()
    with disable_x64():
        fn, args, args2 = entry.build()
        jaxpr = jax.make_jaxpr(fn)(*args)
        n_eqns, wide, examples, callbacks = audit_jaxpr(jaxpr)
        retraces = (_count_retraces(fn, args, args2)
                    if retrace_check else 0)
    return AuditReport(
        name=entry.name,
        public_api=entry.public_api,
        n_eqns=n_eqns,
        f64_leaves=wide,
        f64_examples=examples,
        host_callbacks=callbacks,
        retraces=retraces,
        trace_s=time.perf_counter() - t0,
        ok=(wide == 0 and callbacks == 0 and retraces == 0),
    )


def run_audit(names=None, retrace_check: bool = True) -> list[AuditReport]:
    """Audit the named entries (default: every registered entry)."""
    from raft_tpu.lint.registry import get_entries

    return [audit_entry(e, retrace_check=retrace_check)
            for e in get_entries(names)]
