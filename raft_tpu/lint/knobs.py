"""Machine-readable registry of every environment knob raft_tpu reads.

Each ``RAFT_TPU_*`` / ``JAX_*`` / ``XLA_FLAGS`` read in the package is a
contract with the warm-start subsystem: a knob that changes the *traced
program* (kernel routing, donation, padding ladder, backend) MUST be
folded into the AOT executable keys, or a warm process can silently be
served an executable compiled under the other setting — exactly the
lambda-salt cache defeat fixed by hand in PR 2.  A knob that only steers
*host-side* behavior (schedules, roots, timeouts) must stay out of the
keys, or flipping it would needlessly recompile.  This registry writes
that classification down once, machine-readably, and three consumers
enforce it:

* rule **GL201** (:mod:`raft_tpu.lint.rules`): every matching env read in
  linted code must name a registered knob, and a read reachable from
  jit-traced code must be classified ``aot_key``;
* the **docs table** in ``docs/usage.rst`` is generated from this file
  (:func:`rst_table`; ``python -m raft_tpu.lint.knobs`` rewrites it
  between the AUTOGEN markers) — a drift test pins file == registry;
* a **salt-site test** (``tests/test_lint.py``) asserts each ``aot_key``
  knob's ``salt_token`` really appears in the source of its declared
  ``salted_via`` function, so the classification cannot rot into a claim.

Classifications:

``aot_key``
    The knob changes the traced/compiled program; its resolved value is
    folded into every AOT executable key (``salted_via`` names the salt
    function, ``salt_token`` the source fragment carrying the knob).
``host``
    Host-side orchestration only (cache roots, schedules, timeouts,
    strictness): never alters a traced program, never keyed.
``fault``
    Deterministic fault injection (:mod:`raft_tpu.resilience.faults`):
    host-side by contract, exercised only by the resilience harness.
"""
from __future__ import annotations

import dataclasses
import os
import re

#: env names GL201 (and the drift test) consider knob reads
ENV_READ_RE = re.compile(r"^(?:RAFT_TPU_[A-Z0-9_]+|JAX_[A-Z0-9_]+|XLA_FLAGS)$")

AOT_KEY = "aot_key"
HOST = "host"
FAULT = "fault"


@dataclasses.dataclass(frozen=True)
class Knob:
    name: str
    default: str            # human-readable default, for the docs table
    layer: str              # owning subsystem (module that parses it)
    classification: str     # AOT_KEY / HOST / FAULT
    description: str        # one line, for the docs table
    salted_via: str | None = None    # dotted function folding it into keys
    salt_token: str | None = None    # source fragment proving the salt


KNOBS: tuple[Knob, ...] = (
    # ------------------------------------------------ program-shaping ----
    Knob("RAFT_TPU_PALLAS", "auto (on iff TPU)", "core.pallas6", AOT_KEY,
         "Route the batched 6x6 RAO solves through the Pallas kernel",
         salted_via="raft_tpu.cache.aot._solver_salts",
         salt_token="pallas6.enabled()"),
    Knob("RAFT_TPU_DONATE", "on", "parallel.pipeline", AOT_KEY,
         "Buffer donation at the donating call sites (chunked DLC staging)",
         salted_via="raft_tpu.cache.aot.donation_salt",
         salt_token="donate_argnums"),
    Knob("RAFT_TPU_BUCKETS", "built-in ladder", "build.buckets", AOT_KEY,
         "Size-class ladder for shape-bucketed mixed-design megabatches "
         "(incl. the BEM panels axis)",
         salted_via="raft_tpu.build.buckets.ladder_salt",
         salt_token="buckets"),
    Knob("RAFT_TPU_BEM", "auto (jax iff TPU)", "hydro.jax_bem", AOT_KEY,
         "Panel-solver routing: native host C++, on-device JAX, or auto",
         salted_via="raft_tpu.cache.aot._solver_salts",
         salt_token="bem_mode"),
    Knob("RAFT_TPU_BEM_ASSEMBLY", "auto (pallas iff TPU)", "hydro.jax_bem",
         AOT_KEY,
         "BEM influence-matrix assembly route: tiled Pallas kernels or the "
         "bit-comparable XLA fallback",
         salted_via="raft_tpu.cache.aot._solver_salts",
         salt_token="resolved_assembly()"),
    Knob("RAFT_TPU_BEM_PRECISION", "f32", "hydro.jax_bem", AOT_KEY,
         "BEM assembly precision (f32, or bf16 assembly with f32 factor + "
         "refinement; the f64 host oracle is untouched)",
         salted_via="raft_tpu.cache.aot._solver_salts",
         salt_token="bem_precision()"),
    Knob("XLA_FLAGS", "unset", "cache.aot", AOT_KEY,
         "Raw XLA compiler flags (device counts, HLO dumps, ...)",
         salted_via="raft_tpu.cache.aot._solver_salts",
         salt_token="XLA_FLAGS"),
    Knob("JAX_PLATFORMS", "unset (jax default)", "cache.aot", AOT_KEY,
         "Backend platform pin; keyed via the device topology",
         salted_via="raft_tpu.cache.aot._topology",
         salt_token="default_backend()"),
    # ------------------------------------------------------- host-only ----
    Knob("RAFT_TPU_CACHE_DIR", "~/.cache/raft_tpu", "cache.config", HOST,
         "Warm-start cache root; 'off' disables every warm layer"),
    Knob("RAFT_TPU_CKPT", "off", "resilience.checkpoint", HOST,
         "Durable chunk checkpoint store ('1' = cache root, or a path)"),
    Knob("RAFT_TPU_OBS", "off", "obs.export", HOST,
         "Observability export sink ('1' = cache root obs/, or a directory)"),
    # Snapshotted ONCE at first use (the arm-time contract): the
    # concurrent sweep/serve paths reach maybe_publish / ledger.flush,
    # and neither may re-read the environment mid-process.
    Knob("RAFT_TPU_OBS_FLUSH_MS", "1000 ms", "obs.export", HOST,
         "Monotonic-clock debounce of per-sweep auto-publish (forced "
         "publishes at phase ends always write)"),
    Knob("RAFT_TPU_ROOFLINE", "built-in per-device table", "obs.ledger",
         HOST,
         "Peak '<flops>:<bytes/s>' override for the measured-performance "
         "ledger's roofline fractions"),
    Knob("RAFT_TPU_PIPELINE_DEPTH", "2", "parallel.pipeline", HOST,
         "Dispatch-ahead window of the chunked executor (min 1)"),
    Knob("RAFT_TPU_STRICT", "on", "resilience.health", HOST,
         "Fail loud after reporting a degraded bench/sweep result"),
    Knob("RAFT_TPU_BUILD_TIMEOUT", "300 s", "resilience.retry", HOST,
         "Hard timeout for the native BEM g++ build subprocess"),
    Knob("RAFT_TPU_PROBE_TIMEOUT", "60 s", "bench", HOST,
         "Device probe child timeout in bench.py"),
    Knob("RAFT_TPU_PROBE_RETRIES", "2", "bench", HOST,
         "Device probe retry budget in bench.py"),
    Knob("RAFT_TPU_BENCH_BUDGET", "1500 s", "bench", HOST,
         "Wall-clock budget bench.py divides between its phases"),
    Knob("RAFT_TPU_BENCH_ASSUME_DEVICE", "unset", "bench", HOST,
         "Internal: marks the re-exec'd device bench child"),
    Knob("RAFT_TPU_DRYRUN_NO_REEXEC", "unset", "__graft_entry__", HOST,
         "Internal: recursion guard of the dryrun subprocess fallback"),
    # ------------------------------------------------- solver service ----
    # Snapshotted ONCE at daemon arm time (ServeConfig.from_env — the
    # GL303 contract); the request path never re-reads them.  BATCH_MAX
    # fixes the padded lane capacity, which reaches every serve
    # executable's key through the abstract batch signature the AOT
    # registry always hashes — no separate salt site needed.
    Knob("RAFT_TPU_SERVE_BATCH_DEADLINE_MS", "25 ms", "serve.config", HOST,
         "Micro-batch close deadline of the resident solver service"),
    Knob("RAFT_TPU_SERVE_BATCH_MAX", "8", "serve.config", HOST,
         "Fixed padded lane capacity per bucket batch (keyed via the "
         "abstract batch signature)"),
    Knob("RAFT_TPU_SERVE_SOCKET", "per-uid tmp path", "serve.config", HOST,
         "Default AF_UNIX socket path of the solver daemon"),
    # ------------------------------------------------------ serving fleet ----
    # Snapshotted ONCE at fleet arm time (FleetConfig.from_env — the
    # GL303 contract); the router's concurrent request path only ever
    # sees the frozen snapshot.  All host-side: replica daemons inherit
    # their own RAFT_TPU_SERVE_* knobs; nothing here touches a traced
    # program or an AOT key.
    Knob("RAFT_TPU_FLEET_REPLICAS", "2", "serve.fleet", HOST,
         "Replica daemon count of the supervised serving fleet"),
    Knob("RAFT_TPU_FLEET_PROBE_MS", "500 ms", "serve.fleet", HOST,
         "Heartbeat cadence of the router's replica health probes (and "
         "the supervisor's babysit sweep)"),
    Knob("RAFT_TPU_FLEET_PROBE_TIMEOUT_MS", "2000 ms", "serve.fleet", HOST,
         "Deadline on each ping probe / admission / refresh connection"),
    Knob("RAFT_TPU_FLEET_QUEUE_MAX", "32", "serve.fleet", HOST,
         "Per-replica in-flight cap; admission sheds past queue_max x "
         "healthy replicas"),
    Knob("RAFT_TPU_FLEET_SHED_ERROR_RATE", "0.5", "serve.fleet", HOST,
         "Windowed SLO error rate above which admission sheds (typed "
         "Overloaded responses with a retry-after hint)"),
    Knob("RAFT_TPU_FLEET_RESTART_MAX", "3", "serve.fleet", HOST,
         "Restart-storm bound: max restarts per replica per window"),
    Knob("RAFT_TPU_FLEET_RESTART_WINDOW_S", "30 s", "serve.fleet", HOST,
         "Sliding window of the restart-storm bound"),
    Knob("RAFT_TPU_FLEET_SOCKET", "per-uid tmp path", "serve.fleet", HOST,
         "Front-end AF_UNIX socket path of the fleet router"),
    # ------------------------------------------------- fault injection ----
    Knob("RAFT_TPU_FAULT_INJECT", "unset", "resilience.faults", FAULT,
         "Deterministic host-side fault specs (nan_chunk:K, kill, ...)"),
)

_BY_NAME = {k.name: k for k in KNOBS}


def get(name: str) -> Knob | None:
    return _BY_NAME.get(name)


def names() -> frozenset:
    return frozenset(_BY_NAME)


def classification(name: str) -> str | None:
    k = _BY_NAME.get(name)
    return k.classification if k else None


# ------------------------------------------------------------------ docs --

#: markers bounding the generated block in docs/usage.rst
BEGIN_MARK = ".. BEGIN AUTOGEN KNOB TABLE (python -m raft_tpu.lint.knobs)"
END_MARK = ".. END AUTOGEN KNOB TABLE"

_AOT_LABEL = {AOT_KEY: "key-salted", HOST: "host-only", FAULT: "fault-inj"}


def rst_table(names=None) -> str:
    """The env-knob reference as an RST grid table (list-table), generated
    so the docs can never drift from the registry.  ``names`` filters to a
    subset (the serving guide renders only the ``RAFT_TPU_SERVE_*`` rows;
    ``docs/usage.rst`` carries the full table)."""
    rows = (KNOBS if names is None
            else tuple(k for k in KNOBS if k.name in set(names)))
    lines = [
        ".. list-table:: Environment knobs (generated from "
        "``raft_tpu/lint/knobs.py``)",
        "   :header-rows: 1",
        "   :widths: 28 18 16 12 40",
        "",
        "   * - Knob",
        "     - Default",
        "     - Layer",
        "     - AOT key",
        "     - Effect",
    ]
    for k in sorted(rows, key=lambda k: (k.classification != AOT_KEY,
                                         k.name)):
        lines += [
            f"   * - ``{k.name}``",
            f"     - {k.default}",
            f"     - ``{k.layer}``",
            f"     - {_AOT_LABEL[k.classification]}",
            f"     - {k.description}",
        ]
    return "\n".join(lines) + "\n"


def serve_knob_names() -> tuple:
    """The serving-tier knobs — single daemon plus fleet (the
    ``docs/serving.rst`` autogen subset)."""
    return tuple(k.name for k in KNOBS
                 if k.name.startswith(("RAFT_TPU_SERVE_",
                                       "RAFT_TPU_FLEET_")))


def _docs_path(fname: str) -> str:
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(here, "docs", fname)


def _usage_path() -> str:
    return _docs_path("usage.rst")


def rendered_docs_block(text: str) -> str | None:
    """The current generated block of ``text`` (between the markers,
    exclusive), or None when the markers are absent/malformed."""
    try:
        head, rest = text.split(BEGIN_MARK, 1)
        block, _tail = rest.split(END_MARK, 1)
    except ValueError:
        return None
    return block.strip("\n") + "\n"


def rewrite_docs(path: str | None = None, names=None) -> bool:
    """Regenerate the table between the markers in one docs file
    (default ``docs/usage.rst``, full registry).  Returns True when the
    file changed."""
    path = path or _usage_path()
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    if BEGIN_MARK not in text or END_MARK not in text:
        raise RuntimeError(f"AUTOGEN markers missing from {path}")
    head, rest = text.split(BEGIN_MARK, 1)
    _old, tail = rest.split(END_MARK, 1)
    new = (head + BEGIN_MARK + "\n\n" + rst_table(names) + "\n"
           + END_MARK + tail)
    if new == text:
        return False
    with open(path, "w", encoding="utf-8") as f:
        f.write(new)
    return True


def rewrite_all_docs() -> list:
    """Every autogen knob table in the docs tree: the full table in
    ``usage.rst`` plus the serve subset in ``serving.rst``.  Returns the
    files that changed (drift tests pin each against the registry)."""
    changed = []
    if rewrite_docs(_usage_path()):
        changed.append("usage.rst")
    serving = _docs_path("serving.rst")
    if os.path.exists(serving) and rewrite_docs(serving,
                                                serve_knob_names()):
        changed.append("serving.rst")
    return changed


if __name__ == "__main__":
    changed = rewrite_all_docs()
    print(f"[knobs] docs tables "
          f"{'updated: ' + ', '.join(changed) if changed else 'up to date'}"
          f" ({len(KNOBS)} knobs)")
